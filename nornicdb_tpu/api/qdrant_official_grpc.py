"""Official Qdrant gRPC wire contract served over the QdrantCompat layer.

Reference: pkg/qdrantgrpc (COMPAT.md: "official qdrant proto, 100% SDK
compat"; collections_service.go, points_service.go). The proto subset in
``api/proto/qdrant.proto`` replicates the upstream package (`qdrant`),
service names (`qdrant.Collections`, `qdrant.Points`), method names, and
field numbers, so official qdrant client SDKs speak to this server
without modification; handlers are registered generically (no
grpc_python_plugin in this image).

Serving path (this is the reference's highest-throughput surface, 29k
ops/s in its e2e bench): handlers are ``grpc.aio`` coroutines on ONE
event loop — no per-RPC thread handoff — and registered raw
(deserializer/serializer = None), so the server moves request/response
*bytes*:

- hot reads (Search/Scroll/Count/Get/collection info) probe a shared
  :class:`~nornicdb_tpu.cache.WireCache` first: identical request bytes
  against an unchanged generation return the cached serialized response
  inline on the loop — zero protobuf, zero allocation, zero handoff;
- misses and writes run on a small executor so a storage scan can never
  stall the loop's cache hits, and concurrent Search/Upsert point ops
  coalesce there through the compat layer's MicroBatcher/BatchCoalescer
  (power-of-two bucketed batches, one device dispatch per convoy);
- fixed-shape acks (Upsert/Delete) are pre-serialized protobuf
  templates: the only per-reply work is appending the 9-byte ``time``
  field.
"""

from __future__ import annotations

import asyncio
import contextvars
import os
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import grpc

from nornicdb_tpu import admission as _adm
from nornicdb_tpu import obs
from nornicdb_tpu.obs import tenant as _tenant
from nornicdb_tpu.api.proto import qdrant_pb2 as q
from nornicdb_tpu.api.qdrant import QdrantError, _match_filter

# per-surface, per-method request latency (tentpole: real histograms,
# not gauges). Method label cardinality is bounded by the proto surface.
_GRPC_H = obs.REGISTRY.histogram(
    "nornicdb_grpc_request_seconds",
    "gRPC request latency by method (both aio surfaces)",
    labels=("method",))

# large-response serialization runs on THIS dedicated pool, not the
# shared compute executor and never the event loop (ISSUE 11): a 10MB
# Scroll page flattening to bytes must not occupy a coalescing compute
# thread nor stall the loop's cache hits. Lazily built; responses under
# the threshold keep serializing inline in their compute hop.
_ser_pool = None
_ser_lock = threading.Lock()


def _serializer_pool():
    global _ser_pool
    if _ser_pool is None:
        with _ser_lock:
            if _ser_pool is None:
                from concurrent import futures

                _ser_pool = futures.ThreadPoolExecutor(
                    max_workers=2, thread_name_prefix="grpc-serialize")
    return _ser_pool


def serialize_offload_threshold() -> int:
    """Responses whose ``ByteSize()`` exceeds this serialize on the
    dedicated serializer pool (``NORNICDB_WIRE_SERIALIZE_OFFLOAD_BYTES``,
    default 256KB; 0 offloads everything, -1 disables offload)."""
    try:
        return int(os.environ.get(
            "NORNICDB_WIRE_SERIALIZE_OFFLOAD_BYTES", str(256 * 1024)))
    except ValueError:
        return 256 * 1024


def _serialize_timed(out) -> bytes:
    t0 = time.perf_counter()
    data_out = out.SerializeToString()
    obs.record_stage("grpc", "serialize", time.perf_counter() - t0)
    return data_out


def _iter_matching_points(compat, name: str, flt: Optional[Dict[str, Any]],
                          with_payload: bool = True,
                          with_vector: bool = False):
    """Stream points of a collection in scroll (id) order, filtered.
    Pages through scroll_points so no full-collection materialization or
    silent cap is involved."""
    offset = None
    while True:
        page = compat.scroll_points(name, offset=offset, limit=10_000,
                                    with_payload=True,
                                    with_vector=with_vector)
        for d in page["points"]:
            if flt is None or _match_filter(
                d.get("payload") or {}, flt, point_id=d["id"]
            ):
                if not with_payload:
                    d = {**d, "payload": None}
                yield d
        offset = page.get("next_page_offset")
        if offset is None:
            return


# -- value conversion -----------------------------------------------------


def value_to_py(v: "q.Value") -> Any:
    kind = v.WhichOneof("kind")
    if kind is None or kind == "null_value":
        return None
    if kind == "struct_value":
        return {k: value_to_py(x) for k, x in v.struct_value.fields.items()}
    if kind == "list_value":
        return [value_to_py(x) for x in v.list_value.values]
    return getattr(v, kind)


def py_to_value(x: Any) -> "q.Value":
    v = q.Value()
    if x is None:
        v.null_value = q.NULL_VALUE
    elif isinstance(x, bool):
        v.bool_value = x
    elif isinstance(x, int):
        v.integer_value = x
    elif isinstance(x, float):
        v.double_value = x
    elif isinstance(x, str):
        v.string_value = x
    elif isinstance(x, dict):
        for k, item in x.items():
            v.struct_value.fields[str(k)].CopyFrom(py_to_value(item))
    elif isinstance(x, (list, tuple)):
        v.list_value.values.extend(py_to_value(i) for i in x)
    else:
        v.string_value = str(x)
    return v


def point_id_to_py(pid: "q.PointId") -> Any:
    which = pid.WhichOneof("point_id_options")
    if which == "num":
        return int(pid.num)
    return pid.uuid


def py_to_point_id(x: Any) -> "q.PointId":
    pid = q.PointId()
    # stored point ids round-trip as strings; numeric strings go back out
    # as the numeric id form the client upserted
    try:
        pid.num = int(x)
    except (TypeError, ValueError):
        pid.uuid = str(x)
    return pid


def filter_to_dict(flt: "q.Filter") -> Optional[Dict[str, Any]]:
    if not (flt.must or flt.should or flt.must_not):
        return None

    def cond_to_dict(c: "q.Condition") -> Dict[str, Any]:
        which = c.WhichOneof("condition_one_of")
        if which == "field":
            fc = c.field
            out: Dict[str, Any] = {"key": fc.key}
            mwhich = fc.match.WhichOneof("match_value")
            if mwhich == "keyword":
                out["match"] = {"value": fc.match.keyword}
            elif mwhich == "integer":
                out["match"] = {"value": int(fc.match.integer)}
            elif mwhich == "boolean":
                out["match"] = {"value": fc.match.boolean}
            elif mwhich == "text":
                out["match"] = {"text": fc.match.text}
            elif mwhich is not None:
                raise QdrantError(f"unsupported match kind {mwhich!r}")
            rng = {}
            for field in ("lt", "gt", "gte", "lte"):
                if fc.range.HasField(field):
                    rng[field] = getattr(fc.range, field)
            if rng:
                out["range"] = rng
            if "match" not in out and "range" not in out:
                raise QdrantError(
                    f"field condition on {fc.key!r} has no supported "
                    "match or range clause")
            return out
        if which == "has_id":
            ids = [point_id_to_py(p) for p in c.has_id.has_id]
            return {"has_id": ids}
        if which == "filter":
            return {"filter": filter_to_dict(c.filter) or {}}
        if which == "is_null":
            return {"is_null": c.is_null.key}
        if which == "is_empty":
            return {"is_empty": c.is_empty.key}
        raise QdrantError(f"unsupported filter condition {which!r}")

    return {
        "must": [cond_to_dict(c) for c in flt.must],
        "should": [cond_to_dict(c) for c in flt.should],
        "must_not": [cond_to_dict(c) for c in flt.must_not],
    }


def _with_payload(sel: "q.WithPayloadSelector") -> bool:
    which = sel.WhichOneof("selector_options")
    if which is None:
        return True  # qdrant default for search is payload on
    if which == "enable":
        return sel.enable
    return True  # include/exclude subset: return full payload


def _with_vectors(msg, field: str = "with_vectors") -> bool:
    if not msg.HasField(field):
        return False
    sel = getattr(msg, field)
    which = sel.WhichOneof("selector_options")
    if which == "enable":
        return sel.enable
    return which is not None


def grpc_status_of(e: Exception) -> grpc.StatusCode:
    if isinstance(e, QdrantError) and getattr(e, "status", 400) == 404:
        return grpc.StatusCode.NOT_FOUND
    if getattr(e, "status", 400) == 503:
        return grpc.StatusCode.UNAVAILABLE
    return grpc.StatusCode.INVALID_ARGUMENT


# methods that perform admitted WORK (device dispatch, storage scans,
# merged applies) and therefore pass through admission control; cheap
# metadata reads are never shed. Resolved once per handler BUILD —
# the per-request path pays one `is not None` check (ISSUE 15).
_SHED_METHODS = ("Search", "Query", "Hybrid", "Upsert", "Scroll",
                 "Recommend", "Count", "Delete", "SetPayload")


def _shed_lane_of(method: str) -> Optional[str]:
    tail = method.rsplit("/", 1)[-1]
    if not any(tail.startswith(m) for m in _SHED_METHODS):
        return None
    # bulk upsert convoys and point deletes ride the BACKGROUND lane
    # (ISSUE 15: interactive > replay > background rebuild/bulk upsert
    # convoy) — under pressure, writes shed before reads
    if tail.startswith(("Upsert", "Delete", "SetPayload")):
        return _adm.LANE_BACKGROUND
    return _adm.LANE_INTERACTIVE


# -- aio handler plumbing (shared with api/grpc_server.py) ----------------


def _fresh_time_tag(resp_cls):
    """(1-byte protobuf tag, unit scale) of the response's ``time``/
    ``took_ms`` double field, if it has one — used to stamp cache hits
    with THIS request's serving time (scalar fields are last-wins, so
    appending overrides the stale value frozen into the cached bytes).
    ``time`` is seconds (qdrant contract); ``took_ms`` milliseconds."""
    if resp_cls is None:
        return None
    for fname, scale in (("time", 1.0), ("took_ms", 1e3)):
        fd = resp_cls.DESCRIPTOR.fields_by_name.get(fname)
        if fd is not None and fd.type == fd.TYPE_DOUBLE and fd.number < 16:
            return bytes([(fd.number << 3) | 1]), scale  # wire type 1
    return None


def aio_unary_raw(
    fn: Callable[[bytes], Any],
    *,
    method: str = "",
    wire=None,
    gen: Optional[Callable[[], int]] = None,
    executor=None,
    error_cls=QdrantError,
    resp_cls=None,
):
    """Raw-bytes aio unary handler around ``fn(request_bytes) -> response
    message | bytes``.

    Wire-cache hits return serialized bytes inline on the event loop (no
    protobuf, no executor hop); when ``resp_cls`` exposes a time/took_ms
    double, the hit gets a fresh 9-byte time field appended so clients
    see THIS request's latency, not the miss's. Everything else runs on
    ``executor`` so a slow compute can't stall the loop. ``error_cls``
    exceptions map to gRPC status via :func:`grpc_status_of`."""
    time_tag = scale = None
    cached_served = None
    cached_surf = None
    if wire is not None:
        tagged = _fresh_time_tag(resp_cls)
        if tagged is not None:
            time_tag, scale = tagged
        if "Search" in method or "Query" in method \
                or method.endswith("/Hybrid"):
            # serving-tier mix (ISSUE 10): a wire-cache hit on a search
            # RPC answered from cached bytes — no ladder rung executed.
            # Surface by RPC semantics: only the nornic Hybrid RPC is
            # the hybrid surface; every other search-shaped method
            # (/qdrant.Points/Search, nornic QdrantService points ops,
            # nornic SearchService/Search) is a vector search. Child
            # resolved once per handler build; hit path pays one
            # striped inc, no labels() probe.
            surf = "hybrid" if method.endswith("/Hybrid") else "vector"
            cached_served = obs.audit.served_counter(surf, "cached")
            cached_surf = surf

    # the offload threshold is resolved ONCE per handler build (server
    # construction), not per response: a per-query os.environ read on
    # the hottest surface costs real throughput (the PR 9 maybe_device
    # pre-gate measured the same pattern at 8-12% of a 50us path)
    def serve(data: bytes, _threshold=serialize_offload_threshold()):
        out = fn(data)
        if isinstance(out, bytes):
            return out
        # serialize stage: message -> wire bytes (the parse stage is
        # timed symmetrically in _parse); pre-serialized ack templates
        # and cache hits return bytes above and skip both. LARGE
        # responses return the message unflattened — the handler hops
        # them to the dedicated serializer pool so neither the event
        # loop nor a coalescing compute thread pays for the flatten.
        if _threshold >= 0 and out.ByteSize() > _threshold:
            return out
        return _serialize_timed(out)

    latency = _GRPC_H.labels(method or "unknown")
    # admission pre-gate (ISSUE 15): which lane this method sheds on,
    # resolved once per handler build. None = never shed (cheap
    # metadata reads); cache HITS are served even under overload —
    # a hit costs nothing and is pure goodput.
    shed_lane = _shed_lane_of(method) if method else None

    async def handler(data: bytes, context):
        g = 0
        t0 = time.time()
        # tenant resolution (ISSUE 18): explicit x-nornic-tenant
        # metadata, else the namespace default — a non-explicit tenant
        # is refined by the qdrant collection->tenant mapping once the
        # op resolves its collection (the contextvar cell crosses the
        # executor hop with copy_context below)
        try:
            md = dict(context.invocation_metadata() or ())
            ten_hdr = md.get(_tenant.GRPC_METADATA_KEY)
        except Exception:  # noqa: BLE001 — metadata API absent in tests
            ten_hdr = None
        ten, ten_explicit = _tenant.resolve(ten_hdr, None, None)
        # root span per RPC: grpc.aio runs each handler in its own
        # asyncio task (own contextvar context), so concurrent RPCs
        # never share a current-span slot
        with _tenant.tenant_scope(ten, explicit=ten_explicit), \
                obs.trace("wire", method=method,
                          transport="grpc") as root:
            if wire is not None:
                g = gen()
                hit = wire.get(method, data, g)
                if hit is not None:
                    root.annotate(cache="hit")
                    if cached_served is not None:
                        root.annotate(served_by="cached")
                        cached_served.inc()
                        # the plane-wide counter above bypasses
                        # audit.record_served, so the per-tenant side
                        # records here — a cache hit is still this
                        # tenant's request (attribution completeness)
                        _tenant.record_served(cached_surf, "cached",
                                              seconds=time.time() - t0)
                    latency.observe(time.time() - t0)
                    if time_tag is not None:
                        return (hit + time_tag + struct.pack(
                            "<d", (time.time() - t0) * scale))
                    return hit
            # deadline budget minted at ingress (ISSUE 15): the
            # client's gRPC deadline when one rode the RPC, else the
            # surface default derived from the SLO objective; visible
            # on the trace root (acceptance: budget at ingress)
            try:
                budget = context.time_remaining()
            except Exception:  # noqa: BLE001 — context without deadline API
                budget = None
            dl, explicit = _adm.mint_deadline("grpc", budget, now=t0)
            root.annotate(deadline_ms=round((dl - t0) * 1e3, 1))
            # the lane the shed verdict resolved also binds the scope,
            # so per-lane in-flight/drain accounting sees the same
            # lane the verdict used (a write flood counts as
            # background pressure, not interactive)
            with _adm.request_scope("grpc", dl, lane_name=shed_lane,
                                    explicit=explicit):
                if shed_lane is not None:
                    try:
                        _adm.check("grpc", shed_lane)
                    except _adm.ShedError as e:
                        latency.observe(time.time() - t0)
                        # honest backpressure: RESOURCE_EXHAUSTED with
                        # retry-pushback metadata derived from the
                        # lane's drain rate (the gRPC analog of
                        # HTTP 429 + Retry-After)
                        await context.abort(
                            grpc.StatusCode.RESOURCE_EXHAUSTED, str(e),
                            trailing_metadata=(
                                ("grpc-retry-pushback-ms",
                                 str(int(e.retry_after_s * 1e3))),))
                try:
                    if executor is not None:
                        # copy_context carries the root span AND the
                        # admission context into the executor thread,
                        # so spans opened by the compute (coalesce
                        # wait, device dispatch) land in THIS request's
                        # trace and the batcher sees its budget/lane.
                        # The executor-queue delay is a measured wait
                        # observation for the admission controller —
                        # under overload THIS is where the queue lives.
                        ctx = contextvars.copy_context()
                        t_q = time.time()

                        def _serve_queued(data=data, t_q=t_q):
                            _adm.CONTROLLER.note_wait(
                                _adm.lane(), time.time() - t_q)
                            return serve(data)

                        out = await asyncio.get_running_loop(
                            ).run_in_executor(executor, ctx.run,
                                              _serve_queued)
                    else:
                        out = serve(data)
                    if not isinstance(out, bytes):
                        # over-threshold response: flatten on the
                        # serializer pool — the loop awaits, it never
                        # serializes (pinned by the 10MB loop-block
                        # test)
                        ctx = contextvars.copy_context()
                        out = await asyncio.get_running_loop(
                            ).run_in_executor(_serializer_pool(),
                                              ctx.run,
                                              _serialize_timed, out)
                except _adm.DeadlineExceeded as e:
                    # the budget expired in queue: failed fast, honest
                    # DEADLINE_EXCEEDED instead of a late answer
                    latency.observe(time.time() - t0)
                    await context.abort(
                        grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
                except error_cls as e:
                    latency.observe(time.time() - t0)
                    await context.abort(grpc_status_of(e), str(e))
                if wire is not None:
                    wire.put(method, data, g, out)
                latency.observe(time.time() - t0)
                return out

    # no request_deserializer / response_serializer: the server hands us
    # the wire bytes and sends back exactly the bytes we return
    return grpc.unary_unary_rpc_method_handler(handler)


def _parse(fn: Callable[[Any], Any], req_cls) -> Callable[[bytes], Any]:
    def parse_then(data: bytes):
        # wire/parse stage of the request's latency decomposition
        t0 = time.perf_counter()
        req = req_cls.FromString(data)
        obs.record_stage("grpc", "parse", time.perf_counter() - t0)
        return fn(req)

    return parse_then


class _AckTemplate:
    """Pre-serialized fixed-shape reply + trailing ``time`` field.

    Protobuf fields may be emitted in any order, so a response whose
    only variable field is ``time`` (double, field 2 in every qdrant
    *OperationResponse) serializes as <template bytes> + <0x11> +
    <8-byte LE double> — no message object, no SerializeToString."""

    __slots__ = ("prefix", "tag")

    def __init__(self, message):
        self.prefix = message.SerializeToString()
        num = message.DESCRIPTOR.fields_by_name["time"].number
        if num >= 16:  # pragma: no cover — upstream proto pins time=2
            raise ValueError("time field number too large for 1-byte tag")
        self.tag = bytes([(num << 3) | 1])  # wire type 1: 64-bit

    def render(self, t0: float) -> bytes:
        return self.prefix + self.tag + struct.pack("<d", time.time() - t0)


_POINTS_ACK = _AckTemplate(q.PointsOperationResponse(
    result=q.UpdateResult(operation_id=0, status=q.Completed)))
_COLLECTION_OK = _AckTemplate(q.CollectionOperationResponse(result=True))


_DISTANCE_NAMES = {
    q.Cosine: "Cosine", q.Euclid: "Euclid", q.Dot: "Dot",
    q.Manhattan: "Manhattan", q.UnknownDistance: "Cosine",
}
_DISTANCE_ENUMS = {
    "Cosine": q.Cosine, "Euclid": q.Euclid, "Dot": q.Dot,
    "Manhattan": q.Manhattan,
}


class OfficialCollectionsServicer:
    """qdrant.Collections (reference: collections_service.go).

    Methods are plain ``request -> response`` translations raising
    QdrantError; the aio wire layer (handlers()) adds byte caching,
    executor offload and status mapping."""

    def __init__(self, compat):
        self.compat = compat

    def Get(self, request):
        t0 = time.time()
        info = self.compat.get_collection(request.collection_name)
        vec_cfg = info["config"]["params"].get("vectors", {})
        resp = q.GetCollectionInfoResponse(
            result=q.CollectionInfo(
                status=q.Green,
                vectors_count=int(info.get("indexed_vectors_count", 0)),
                segments_count=int(info.get("segments_count", 1)),
                points_count=int(info.get("points_count", 0)),
            ),
            time=time.time() - t0,
        )
        if vec_cfg:
            params = q.VectorParams(
                size=int(vec_cfg.get("size", 0)),
                distance=_DISTANCE_ENUMS.get(
                    vec_cfg.get("distance", "Cosine"), q.Cosine),
            )
            resp.result.config.params.vectors_config.params.CopyFrom(params)
        return resp

    def List(self, request):
        t0 = time.time()
        return q.ListCollectionsResponse(
            collections=[
                q.CollectionDescription(name=n)
                for n in self.compat.list_collections()
            ],
            time=time.time() - t0,
        )

    def Create(self, request):
        t0 = time.time()
        size = 0
        distance = "Cosine"
        if request.HasField("vectors_config"):
            which = request.vectors_config.WhichOneof("config")
            if which == "params":
                p = request.vectors_config.params
                size = int(p.size)
                distance = _DISTANCE_NAMES.get(p.distance, "Cosine")
            elif which == "params_map":
                # single-vector subset: first named vector wins
                for _name, p in request.vectors_config.params_map.map.items():
                    size = int(p.size)
                    distance = _DISTANCE_NAMES.get(p.distance, "Cosine")
                    break
        ok = self.compat.create_collection(
            request.collection_name,
            {"size": size, "distance": distance},
        )
        return q.CollectionOperationResponse(result=ok, time=time.time() - t0)

    def Delete(self, request):
        t0 = time.time()
        ok = self.compat.delete_collection(request.collection_name)
        return q.CollectionOperationResponse(result=ok, time=time.time() - t0)

    def CollectionExists(self, request):
        t0 = time.time()
        exists = request.collection_name in self.compat.list_collections()
        return q.CollectionExistsResponse(
            result=q.CollectionExists(exists=exists), time=time.time() - t0)

    def UpdateAliases(self, request):
        t0 = time.time()
        actions = []
        for op in request.actions:
            which = op.WhichOneof("action")
            if which == "create_alias":
                actions.append({"create": {
                    "alias": op.create_alias.alias_name,
                    "collection": op.create_alias.collection_name}})
            elif which == "rename_alias":
                actions.append({"rename": {
                    "old": op.rename_alias.old_alias_name,
                    "new": op.rename_alias.new_alias_name}})
            elif which == "delete_alias":
                actions.append({"delete": {
                    "alias": op.delete_alias.alias_name}})
        self.compat.update_aliases(actions)
        return _COLLECTION_OK.render(t0)

    def ListCollectionAliases(self, request):
        t0 = time.time()
        return q.ListAliasesResponse(
            aliases=[q.AliasDescription(**d) for d in
                     self.compat.list_aliases(request.collection_name)],
            time=time.time() - t0)

    def ListAliases(self, request):
        t0 = time.time()
        return q.ListAliasesResponse(
            aliases=[q.AliasDescription(**d)
                     for d in self.compat.list_aliases()],
            time=time.time() - t0)

    def handlers(self, wire=None, executor=None):
        gen = lambda: self.compat.cache_gen  # noqa: E731
        svc = "qdrant.Collections"

        def unary(name, fn, req_cls, resp_cls=None):
            return aio_unary_raw(
                _parse(fn, req_cls), method=f"/{svc}/{name}",
                wire=wire if resp_cls is not None else None, gen=gen,
                executor=executor, resp_cls=resp_cls)

        return grpc.method_handlers_generic_handler(svc, {
            "Get": unary("Get", self.Get, q.GetCollectionInfoRequest,
                         q.GetCollectionInfoResponse),
            "List": unary("List", self.List, q.ListCollectionsRequest,
                          q.ListCollectionsResponse),
            "Create": unary("Create", self.Create, q.CreateCollection),
            "Delete": unary("Delete", self.Delete, q.DeleteCollection),
            "CollectionExists": unary(
                "CollectionExists", self.CollectionExists,
                q.CollectionExistsRequest, q.CollectionExistsResponse),
            "UpdateAliases": unary(
                "UpdateAliases", self.UpdateAliases, q.ChangeAliases),
            "ListCollectionAliases": unary(
                "ListCollectionAliases", self.ListCollectionAliases,
                q.ListCollectionAliasesRequest, q.ListAliasesResponse),
            "ListAliases": unary(
                "ListAliases", self.ListAliases, q.ListAliasesRequest,
                q.ListAliasesResponse),
        })


class OfficialSnapshotsServicer:
    """qdrant.Snapshots (reference: snapshots_service.go — Create/List/
    Delete per collection + CreateFull/ListFull/DeleteFull). Snapshot
    files are JSON in ``snapshot_dir`` (the TPU build's own format; the
    reference likewise writes NornicDB-native snapshots, not qdrant's
    tar format). Never wire-cached: filesystem state is not generation-
    tracked."""

    def __init__(self, compat, snapshot_dir: str):
        self.compat = compat
        self.snapshot_dir = snapshot_dir

    @staticmethod
    def _desc(d):
        return q.SnapshotDescription(
            name=d["name"], creation_time=d["creation_time"],
            size=d["size"])

    def Create(self, request):
        t0 = time.time()
        d = self.compat.create_snapshot(
            request.collection_name, self.snapshot_dir)
        return q.CreateSnapshotResponse(
            snapshot_description=self._desc(d), time=time.time() - t0)

    def List(self, request):
        t0 = time.time()
        return q.ListSnapshotsResponse(
            snapshot_descriptions=[
                self._desc(d) for d in self.compat.list_snapshots(
                    request.collection_name, self.snapshot_dir)],
            time=time.time() - t0)

    def Delete(self, request):
        t0 = time.time()
        self.compat.delete_snapshot(
            request.collection_name, request.snapshot_name,
            self.snapshot_dir)
        return q.DeleteSnapshotResponse(time=time.time() - t0)

    def CreateFull(self, request):
        t0 = time.time()
        d = self.compat.create_full_snapshot(self.snapshot_dir)
        return q.CreateSnapshotResponse(
            snapshot_description=self._desc(d), time=time.time() - t0)

    def ListFull(self, request):
        t0 = time.time()
        return q.ListSnapshotsResponse(
            snapshot_descriptions=[
                self._desc(d) for d in
                self.compat.list_full_snapshots(self.snapshot_dir)],
            time=time.time() - t0)

    def DeleteFull(self, request):
        t0 = time.time()
        self.compat.delete_full_snapshot(
            request.snapshot_name, self.snapshot_dir)
        return q.DeleteSnapshotResponse(time=time.time() - t0)

    def handlers(self, wire=None, executor=None):
        svc = "qdrant.Snapshots"

        def unary(name, fn, req_cls):
            return aio_unary_raw(_parse(fn, req_cls),
                                 method=f"/{svc}/{name}", executor=executor)

        return grpc.method_handlers_generic_handler(svc, {
            "Create": unary("Create", self.Create, q.CreateSnapshotRequest),
            "List": unary("List", self.List, q.ListSnapshotsRequest),
            "Delete": unary("Delete", self.Delete, q.DeleteSnapshotRequest),
            "CreateFull": unary(
                "CreateFull", self.CreateFull, q.CreateFullSnapshotRequest),
            "ListFull": unary(
                "ListFull", self.ListFull, q.ListFullSnapshotsRequest),
            "DeleteFull": unary(
                "DeleteFull", self.DeleteFull, q.DeleteFullSnapshotRequest),
        })


class OfficialPointsServicer:
    """qdrant.Points (reference: points_service.go).

    The former per-servicer raw-bytes Search cache is replaced by the
    server-wide shared WireCache (cache.py) covering Search/Scroll/
    Count/Get — validated against the compat layer's cache generation,
    which every write surface bumps (point ops here, Cypher writes via
    the db.py mutation listener, alias/collection ops)."""

    def __init__(self, compat):
        self.compat = compat

    # -- helpers --------------------------------------------------------

    def _scored(self, d: Dict[str, Any]) -> "q.ScoredPoint":
        sp = q.ScoredPoint(
            id=py_to_point_id(d["id"]),
            score=float(d.get("score", 0.0)),
            version=0,
        )
        for k, v in (d.get("payload") or {}).items():
            sp.payload[k].CopyFrom(py_to_value(v))
        if d.get("vector") is not None:
            sp.vectors.vector.data.extend(float(x) for x in d["vector"])
        return sp

    def _retrieved(self, d: Dict[str, Any]) -> "q.RetrievedPoint":
        rp = q.RetrievedPoint(id=py_to_point_id(d["id"]))
        for k, v in (d.get("payload") or {}).items():
            rp.payload[k].CopyFrom(py_to_value(v))
        if d.get("vector") is not None:
            rp.vectors.vector.data.extend(float(x) for x in d["vector"])
        return rp

    # -- rpcs -----------------------------------------------------------

    def Upsert(self, request):
        t0 = time.time()
        points = []
        for p in request.points:
            vec: List[float] = []
            if p.HasField("vectors"):
                which = p.vectors.WhichOneof("vectors_options")
                if which == "vector":
                    vec = list(p.vectors.vector.data)
                elif which == "vectors":
                    for _name, v in p.vectors.vectors.vectors.items():
                        vec = list(v.data)
                        break
            points.append({
                "id": point_id_to_py(p.id),
                "vector": vec,
                "payload": {k: value_to_py(v) for k, v in p.payload.items()},
            })
        try:
            # convoy-coalesced: concurrent Upserts merge into one apply
            self.compat.upsert_points_coalesced(
                request.collection_name, points)
        except QdrantError:
            raise
        except (ValueError, TypeError) as e:
            raise QdrantError(str(e))
        return _POINTS_ACK.render(t0)

    def Delete(self, request):
        t0 = time.time()
        which = request.points.WhichOneof("points_selector_one_of")
        if which == "points":
            ids = [point_id_to_py(p) for p in request.points.points.ids]
            self.compat.delete_points(request.collection_name, ids)
        elif which == "filter":
            flt = filter_to_dict(request.points.filter)
            doomed = [
                d["id"] for d in _iter_matching_points(
                    self.compat, request.collection_name, flt)
            ]
            self.compat.delete_points(request.collection_name, doomed)
        return _POINTS_ACK.render(t0)

    def Get(self, request):
        t0 = time.time()
        ids = [point_id_to_py(p) for p in request.ids]
        points = self.compat.retrieve_points(
            request.collection_name, ids,
            with_payload=_with_payload(request.with_payload),
            with_vector=_with_vectors(request),
        )
        return q.GetResponse(
            result=[self._retrieved(d) for d in points],
            time=time.time() - t0,
        )

    def Search(self, request):
        t0 = time.time()
        offset = int(request.offset) if request.HasField("offset") else 0
        hits = self.compat.search_points(
            request.collection_name,
            list(request.vector),
            limit=(int(request.limit) or 10) + offset,
            with_payload=_with_payload(request.with_payload),
            with_vector=_with_vectors(request),
            score_threshold=(
                request.score_threshold
                if request.HasField("score_threshold") else None),
            query_filter=filter_to_dict(request.filter),
        )
        return q.SearchResponse(
            result=[self._scored(d) for d in hits[offset:]],
            time=time.time() - t0,
        )

    def Scroll(self, request):
        t0 = time.time()
        offset = None
        if request.HasField("offset"):
            offset = point_id_to_py(request.offset)
        limit = int(request.limit) if request.HasField("limit") else 10
        flt = filter_to_dict(request.filter)
        if flt is None:
            page = self.compat.scroll_points(
                request.collection_name,
                offset=offset,
                limit=limit,
                with_payload=_with_payload(request.with_payload),
                with_vector=_with_vectors(request),
            )
            points = page["points"]
            next_offset = page.get("next_page_offset")
        else:
            # qdrant semantics: a page holds up to `limit` MATCHING
            # points; next_page_offset is the following match's id
            points = []
            next_offset = None
            for d in _iter_matching_points(
                self.compat, request.collection_name, flt,
                with_payload=_with_payload(request.with_payload),
                with_vector=_with_vectors(request),
            ):
                if offset is not None and str(d["id"]) < str(offset):
                    continue
                if len(points) == limit:
                    next_offset = d["id"]
                    break
                points.append(d)
        resp = q.ScrollResponse(
            result=[self._retrieved(d) for d in points],
            time=time.time() - t0,
        )
        if next_offset is not None:
            resp.next_page_offset.CopyFrom(py_to_point_id(next_offset))
        return resp

    def Count(self, request):
        t0 = time.time()
        flt = filter_to_dict(request.filter)
        if flt is None:
            n = self.compat.count_points(request.collection_name)
        else:
            n = sum(1 for _ in _iter_matching_points(
                self.compat, request.collection_name, flt))
        return q.CountResponse(
            result=q.CountResult(count=n), time=time.time() - t0)

    def handlers(self, wire=None, executor=None):
        gen = lambda: self.compat.cache_gen  # noqa: E731
        svc = "qdrant.Points"

        def unary(name, fn, req_cls, resp_cls=None):
            return aio_unary_raw(
                _parse(fn, req_cls), method=f"/{svc}/{name}",
                wire=wire if resp_cls is not None else None, gen=gen,
                executor=executor, resp_cls=resp_cls)

        return grpc.method_handlers_generic_handler(svc, {
            "Upsert": unary("Upsert", self.Upsert, q.UpsertPoints),
            "Delete": unary("Delete", self.Delete, q.DeletePoints),
            "Get": unary("Get", self.Get, q.GetPoints, q.GetResponse),
            "Search": unary("Search", self.Search, q.SearchPoints,
                            q.SearchResponse),
            "Scroll": unary("Scroll", self.Scroll, q.ScrollPoints,
                            q.ScrollResponse),
            "Count": unary("Count", self.Count, q.CountPoints,
                           q.CountResponse),
        })
