"""CLI entrypoint: serve / version / import / export / eval.

Reference: cmd/nornicdb (cobra CLI, main.go:75-1296 — serve with port,
data-dir, embedding and accelerator flags) and cmd/eval (search-quality
eval harness CLI). Run as ``python -m nornicdb_tpu.cli <command>``.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
from typing import Any, Dict, List, Optional

VERSION = "0.1.0"


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nornicdb-tpu",
        description="TPU-native NornicDB-capability graph database",
    )
    sub = p.add_subparsers(dest="command")

    serve = sub.add_parser("serve", help="start the server")
    serve.add_argument("--data-dir", default=None,
                       help="persistent data directory (in-memory if unset)")
    serve.add_argument("--http-port", type=int, default=7474)
    serve.add_argument("--bolt-port", type=int, default=7687)
    serve.add_argument("--grpc-port", type=int, default=0,
                       help="gRPC port (0 = disabled)")
    serve.add_argument("--grpc-auth-token", default=None,
                       help="require this bearer token on every gRPC "
                            "call (aio interceptor; parity with the "
                            "REST surface's write authorization)")
    serve.add_argument("--host", default="127.0.0.1")
    serve.add_argument("--database", default="neo4j")
    serve.add_argument("--plugins-dir", default=None)
    serve.add_argument("--ann-quality", default=None,
                       choices=["fast", "balanced", "accurate",
                                "compressed"])

    sub.add_parser("version", help="print version")

    imp = sub.add_parser("import", help="import nodes/edges from JSONL")
    imp.add_argument("file")
    imp.add_argument("--data-dir", default=None)
    imp.add_argument("--database", default="neo4j")

    exp = sub.add_parser("export", help="export the graph as JSONL")
    exp.add_argument("file")
    exp.add_argument("--data-dir", default=None)
    exp.add_argument("--database", default="neo4j")

    oauth = sub.add_parser(
        "oauth-provider",
        help="start the standalone OAuth 2.0 provider (reference: "
             "cmd/oauth-provider)")
    oauth.add_argument("--port", type=int, default=8888)
    oauth.add_argument("--host", default="127.0.0.1")
    oauth.add_argument("--client-id", default="nornicdb")
    oauth.add_argument("--client-secret", default="nornicdb-secret")
    oauth.add_argument("--issuer", default=None)

    ev = sub.add_parser("eval", help="run a search-quality eval suite")
    ev.add_argument("suite", help="JSONL suite file")
    ev.add_argument("--data-dir", default=None)
    ev.add_argument("--corpus", default=None,
                    help="JSONL corpus to ingest before evaluating")
    ev.add_argument("--precision", type=float, default=0.5)
    ev.add_argument("--recall", type=float, default=0.5)
    ev.add_argument("--mrr", type=float, default=0.5)
    return p


def _open_db(data_dir: Optional[str], database: str = "neo4j"):
    import nornicdb_tpu

    return nornicdb_tpu.open(data_dir, database=database)


def cmd_serve(args) -> int:
    import os

    if args.ann_quality:
        os.environ["NORNICDB_VECTOR_ANN_QUALITY"] = args.ann_quality
    db = _open_db(args.data_dir, args.database)
    from nornicdb_tpu.api.bolt import BoltServer
    from nornicdb_tpu.api.http_server import HttpServer

    http = HttpServer(db, host=args.host, port=args.http_port,
                      database_manager=db.multidb_manager()).start()
    bolt = BoltServer(db, host=args.host, port=args.bolt_port).start()
    grpc_srv = None
    if args.grpc_port:
        from nornicdb_tpu.api.grpc_server import GrpcServer

        grpc_srv = GrpcServer(db, host=args.host, port=args.grpc_port,
                              auth_token=args.grpc_auth_token).start()
    if args.plugins_dir:
        from nornicdb_tpu.plugins import install_plugins

        loaded = install_plugins(db, args.plugins_dir)
        for p in loaded:
            status = p.error or f"{p.kind}, {len(p.functions)} functions"
            print(f"plugin {p.name}: {status}")
    print(f"nornicdb-tpu {VERSION}")
    print(f"  http  : http://{args.host}:{http.port}")
    print(f"  bolt  : bolt://{args.host}:{bolt.port}")
    if grpc_srv is not None:
        auth = " (bearer auth)" if args.grpc_auth_token else ""
        print(f"  grpc  : {grpc_srv.address} (aio){auth}")
    print(f"  data  : {args.data_dir or '(in-memory)'}")
    stop = threading.Event()

    def _sig(*_):
        stop.set()

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    stop.wait()
    print("shutting down")
    if grpc_srv is not None:
        grpc_srv.stop()
    bolt.stop()
    http.stop()
    db.close()
    return 0


def cmd_import(args) -> int:
    """JSONL rows: {"type": "node", "id", "labels", "properties",
    "embedding"} or {"type": "edge", "id", "start", "end", "edge_type",
    "properties"}."""
    from nornicdb_tpu.storage.types import Edge, Node

    db = _open_db(args.data_dir, args.database)
    nodes = edges = 0
    try:
        with open(args.file, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                from nornicdb_tpu.query.temporal_types import decode_map

                row = json.loads(line, object_hook=decode_map)
                if row.get("type", "node") == "node":
                    db.storage.create_node(Node(
                        id=row["id"], labels=row.get("labels", []),
                        properties=row.get("properties", {}),
                        embedding=row.get("embedding")))
                    nodes += 1
                else:
                    db.storage.create_edge(Edge(
                        id=row["id"], start_node=row["start"],
                        end_node=row["end"],
                        type=row.get("edge_type", "RELATED"),
                        properties=row.get("properties", {})))
                    edges += 1
        print(f"imported {nodes} nodes, {edges} edges")
        return 0
    finally:
        db.close()


def cmd_export(args) -> int:
    db = _open_db(args.data_dir, args.database)
    from nornicdb_tpu.query.temporal_types import encode_value

    def _default(v):
        # typed property values keep their tag; anything else becomes str
        try:
            return encode_value(v)
        except TypeError:
            return str(v)

    try:
        with open(args.file, "w", encoding="utf-8") as f:
            n = e = 0
            for node in db.storage.all_nodes():
                row: Dict[str, Any] = {
                    "type": "node", "id": node.id, "labels": node.labels,
                    "properties": node.properties,
                }
                if node.embedding is not None:
                    row["embedding"] = node.embedding
                f.write(json.dumps(row, default=_default) + "\n")
                n += 1
            for edge in db.storage.all_edges():
                f.write(json.dumps({
                    "type": "edge", "id": edge.id,
                    "start": edge.start_node, "end": edge.end_node,
                    "edge_type": edge.type,
                    "properties": edge.properties,
                }, default=_default) + "\n")
                e += 1
        print(f"exported {n} nodes, {e} edges")
        return 0
    finally:
        db.close()


def cmd_eval(args) -> int:
    from nornicdb_tpu.eval import Thresholds, harness_for_db

    db = _open_db(args.data_dir)
    try:
        if args.corpus:
            from nornicdb_tpu.storage.types import Node

            with open(args.corpus, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    from nornicdb_tpu.query.temporal_types import decode_map

                    row = json.loads(line, object_hook=decode_map)
                    node = Node(id=row["id"],
                                labels=row.get("labels", []),
                                properties=row.get("properties", {}),
                                embedding=row.get("embedding"))
                    db.storage.create_node(node)
            db.search.build_indexes()
        harness = harness_for_db(db, Thresholds(
            precision=args.precision, recall=args.recall, mrr=args.mrr))
        result = harness.run_file(args.suite)
        print(json.dumps(result.to_dict(), indent=2))
        return 0 if result.passed else 1
    finally:
        db.close()


def main(argv: Optional[List[str]] = None) -> int:
    parser = _build_parser()
    args = parser.parse_args(argv)
    if args.command == "serve":
        return cmd_serve(args)
    if args.command == "version":
        print(f"nornicdb-tpu {VERSION}")
        return 0
    if args.command == "import":
        return cmd_import(args)
    if args.command == "export":
        return cmd_export(args)
    if args.command == "eval":
        return cmd_eval(args)
    if args.command == "oauth-provider":
        from nornicdb_tpu.api.oauth_provider import OAuthProvider

        provider = OAuthProvider(
            port=args.port, host=args.host, client_id=args.client_id,
            client_secret=args.client_secret, issuer=args.issuer).start()
        print(f"oauth-provider listening on {provider.issuer}")
        try:
            import time as _t

            while True:
                _t.sleep(3600)
        except KeyboardInterrupt:
            provider.stop()
        return 0
    parser.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
