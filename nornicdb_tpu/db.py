"""DB facade: Open/Store/Recall/Cypher over the composed engine chain.

Reference: pkg/nornicdb/db.go:742 ``Open`` and the public API surface
(Store :1951, Recall :2107, Remember :2026, Link :2251, Neighbors :2299,
Forget :2378, Cypher :2222). Round-1 facade — search/cypher services are
wired in as those layers land.
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

from nornicdb_tpu.storage import (
    AsyncEngine,
    DurableEngine,
    Direction,
    Edge,
    Engine,
    ListenableEngine,
    MemoryEngine,
    MutationListener,
    NamespacedEngine,
    Node,
)


class _QdrantInvalidationListener(MutationListener):
    """Routes node mutations from ANY surface into the qdrant layer's
    cache invalidation (qdrant.py _on_external_mutation — the layer's
    own writes are filtered out there by a thread-local guard)."""

    def __init__(self, compat):
        self._compat = compat

    def on_node_upsert(self, node: Node) -> None:
        self._compat._on_external_mutation(node.id)

    def on_node_delete(self, node_id: str) -> None:
        self._compat._on_external_mutation(node_id)


class DB:
    """One logical NornicDB-style database instance."""

    def __init__(
        self,
        data_dir: Optional[str] = None,
        database: str = "neo4j",
        async_writes: bool = False,
        sync_every_write: bool = False,
        embedder: Optional[Any] = None,
        auto_embed: bool = True,
        engine: str = "auto",  # auto | native | python | memory
        replication: Optional[Any] = None,  # ReplicationConfig
        passphrase: Optional[str] = None,  # at-rest AES-256-GCM encryption
    ):
        # engine chain: Disk/Durable/Memory -> [Async] -> Namespaced ->
        # Listenable (reference chain order: db.go:742-947; the listener
        # layer sits on top so mutation callbacks carry LOGICAL node ids)
        if engine not in ("auto", "native", "python", "memory"):
            raise ValueError(f"unknown engine {engine!r}")
        if engine in ("native", "python") and not data_dir:
            raise ValueError(f"engine={engine!r} requires data_dir")
        self._data_dir = data_dir if engine != "memory" else None
        if data_dir and engine != "memory":
            # at-rest encryption: PBKDF2-derived key + salt file in the
            # data dir (reference: db.go:776-805 DeriveKey + salt)
            from nornicdb_tpu.encryption import make_encryptor

            encryptor = make_encryptor(passphrase, data_dir)
            if engine == "python":
                base: Engine = DurableEngine(
                    data_dir, sync_every_write=sync_every_write,
                    encryptor=encryptor,
                )
            elif engine == "native":
                from nornicdb_tpu.storage.disk import DiskEngine

                base = DiskEngine(data_dir, sync_every_write=sync_every_write,
                                  encryptor=encryptor)
            else:
                from nornicdb_tpu.storage import make_persistent_engine

                base = make_persistent_engine(
                    data_dir, sync_every_write=sync_every_write,
                    encryptor=encryptor,
                )
        else:
            base = MemoryEngine()
        self._base = base
        chain: Engine = base
        if async_writes:
            chain = AsyncEngine(chain)
        self.replicator = None
        self._cluster_transport = None
        if replication is not None and replication.mode != "standalone":
            try:
                chain = self._enable_replication(chain, replication)
            except Exception:
                # don't leak the already-open engine chain (file locks,
                # async flush thread) when replication wiring fails
                chain.close()
                raise
        self._chain = chain  # pre-namespace engine: multidb roots here
        self._listenable = ListenableEngine(NamespacedEngine(chain, database))
        self.storage = self._listenable
        self.database = database
        self._lock = threading.Lock()
        self._closed = False
        self._db_manager = None

        # lazily-built services (per logical DB)
        self._executor = None
        self._search = None
        if embedder is None:
            try:
                embedder = self._default_embedder()
            except Exception:
                # don't leak the already-open engine chain (file locks,
                # async flush thread) when e.g. the embedder sidecar is
                # corrupt — same discipline as the replication path above
                self._listenable.close()
                raise
        self._embedder = embedder
        self._embed_queue = None
        self._decay = None
        self._temporal = None
        self._inference = None
        if auto_embed:
            self._start_embed_queue()
        rep = getattr(self, "_deferred_rep_start", None)
        if rep is not None:
            self._deferred_rep_start = None
            rep.start()

    def _default_embedder(self):
        """Default local embedder (reference default: local embedding
        always on, embed.go — a real bge-m3 via llama.cpp). Here: the
        committed contrastively-trained mini encoder (models/pretrain.py)
        behind an LRU; HashEmbedder when the checkpoint is absent or
        forced (NORNICDB_TPU_EMBEDDER=hash).

        The chosen embedder identity (kind + dims) is PERSISTED with
        disk-backed stores (``embedder.json`` sidecar) and honored on
        reopen, so an existing database keeps its embedding space even
        when the default changes across versions — mixing spaces would
        silently break recall (advisor r3: db.py:117)."""
        import io as _io  # builtins.open is shadowed by module-level open()
        import json as _json
        import logging

        from nornicdb_tpu.embed.embedder import CachedEmbedder, HashEmbedder

        log = logging.getLogger("nornicdb_tpu.db")
        sidecar = (
            os.path.join(self._data_dir, "embedder.json")
            if self._data_dir else None
        )
        recorded = None
        sidecar_unreadable = False
        if sidecar and os.path.exists(sidecar):
            try:
                with _io.open(sidecar, encoding="utf-8") as f:
                    recorded = _json.load(f)
            except Exception as exc:
                # a corrupt sidecar must NOT be treated as "no recorded
                # identity": the default embedder could then write new
                # vectors into a different space before anyone notices.
                # Fail loudly (the data damage would be done by the time
                # a log line is read); NORNICDB_TPU_EMBEDDER=hash stays
                # available as the explicit escape hatch.
                sidecar_unreadable = True
                if os.environ.get("NORNICDB_TPU_EMBEDDER", "") != "hash":
                    raise ValueError(
                        f"embedder sidecar {sidecar} is unreadable "
                        f"({exc}); fix or remove the file to re-bind the "
                        "store's embedding space, or force "
                        "NORNICDB_TPU_EMBEDDER=hash to open anyway"
                    ) from exc
                log.error(
                    "embedder sidecar %s is unreadable (%s); forced hash "
                    "embedder is active — the recorded identity is NOT "
                    "re-written", sidecar, exc,
                )

        from nornicdb_tpu.models.hf_import import default_model_dir

        def build(kind):
            if kind == "hf":
                from nornicdb_tpu.models.hf_import import HFEncoderEmbedder

                d = default_model_dir()
                if d is None:
                    raise FileNotFoundError(
                        "NORNICDB_TPU_MODEL_DIR not set or not a model "
                        "dir, but the store was created with an "
                        "imported-weights embedder")
                return HFEncoderEmbedder(d)
            if kind == "encoder-mini":
                from nornicdb_tpu.models.pretrain import load_default_embedder

                inner = load_default_embedder()
                if inner is None:
                    raise FileNotFoundError("encoder checkpoint missing")
                return inner
            return HashEmbedder(
                # recorded dims only apply if the store really was hash:
                # another kind's dims would silently change hash's space
                dims=int(recorded.get("dims", 256))
                if recorded and recorded.get("kind") == "hash" else 256
            )

        env_force = os.environ.get("NORNICDB_TPU_EMBEDDER", "")
        if env_force == "hash":
            # the explicit escape hatch ALWAYS wins — it exists for when
            # the jax backend cannot even initialize (e.g. a hung TPU
            # tunnel), so no recorded preference may route around it
            want = "hash"
        elif default_model_dir() is not None:
            want = "hf"  # real imported weights beat the mini encoder
        else:
            want = "encoder-mini"
        kind = want
        if recorded and env_force != "hash":
            kind = recorded.get("kind", want)
        try:
            inner = build(kind)
        except Exception:
            if kind != "hash":
                log.warning(
                    "default embedder %r unavailable; falling back to "
                    "hash embedder — embeddings written now will be in a "
                    "different space", kind,
                )
            kind = "hash"
            inner = build("hash")
        if recorded and recorded.get("kind") != kind:
            log.warning(
                "store was created with embedder %r but %r is active; "
                "existing embeddings are in the recorded space — reindex "
                "to migrate", recorded.get("kind"), kind,
            )
        if sidecar and recorded is None and not sidecar_unreadable:
            try:
                with _io.open(sidecar, "w", encoding="utf-8") as f:
                    _json.dump({"kind": kind, "dims": inner.dims}, f)
            except OSError:
                pass
        return CachedEmbedder(inner)

    def _enable_replication(self, chain: Engine, cfg: Any) -> Engine:
        """Insert the ReplicatedEngine into the chain (reference:
        maybeEnableReplication, db.go:931,1261 — chain position
        …→[Async]→[Replicated]→Namespaced). HA modes stream the base
        WALEngine's log; Raft applies committed entries to the chain."""
        from nornicdb_tpu.replication import (
            ClusterTransport,
            HAPrimary,
            HAStandby,
            RaftNode,
            ReplicatedEngine,
        )
        from nornicdb_tpu.replication.replicator import decode_op_args
        from nornicdb_tpu.storage.wal_engine import WALEngine

        if getattr(cfg, "data_listen", None) is not None:
            # two-plane endpoint (ISSUE 16): heartbeats/fences on the
            # control channel, WAL batches and snapshot ships on a
            # separate bulk socket so replication volume never delays
            # failure detection
            from nornicdb_tpu.replication.transport import DualPlaneTransport

            transport = DualPlaneTransport(
                cfg.node_id, cfg.listen, cfg.data_listen)
        else:
            transport = ClusterTransport(cfg.node_id, cfg.listen)
        transport.start()
        self._cluster_transport = transport
        if cfg.mode == "multi_region":
            from nornicdb_tpu.replication import MultiRegionNode

            def mr_apply_fn(op, data, _chain=chain):
                getattr(_chain, op)(*decode_op_args(op, data))

            rep = MultiRegionNode(transport, cfg, mr_apply_fn)
            rep.start()
            self.replicator = rep
            return ReplicatedEngine(chain, rep)
        if cfg.mode == "ha_standby":
            if not isinstance(self._base, WALEngine):
                transport.close()
                raise ValueError(
                    f"replication mode {cfg.mode!r} requires a WAL-backed "
                    "engine (open with data_dir and engine='python')"
                )
            if not isinstance(chain, WALEngine):
                # HA replicators write to the base WALEngine directly;
                # an AsyncEngine overlay would be silently bypassed
                transport.close()
                raise ValueError(
                    "async_writes cannot be combined with HA replication "
                    "(writes route through the WAL primary directly)"
                )
            primary_cls = getattr(cfg, "primary_cls", None) or HAPrimary
            standby_cls = getattr(cfg, "standby_cls", None) or HAStandby
            if cfg.ha_role == "primary":
                rep = primary_cls(self._base, transport, cfg)
                rep.start()
            else:
                rep = standby_cls(
                    self._base, transport, cfg,
                    primary_addr=cfg.primary_addr,
                    on_promote=getattr(cfg, "on_promote", None),
                )
                # monitor start is DEFERRED to the end of __init__: the
                # failover clock must not tick while this facade is
                # still loading its embedder/services — a standby that
                # auto-promotes because its own open was slow fences
                # the healthy primary (split-brain at boot)
                self._deferred_rep_start = rep
        elif cfg.mode == "raft":
            def apply_fn(op, data, _chain=chain):
                getattr(_chain, op)(*decode_op_args(op, data))

            rep = RaftNode(transport, cfg, apply_fn)
            rep.start()
        else:
            transport.close()
            raise ValueError(f"unknown replication mode {cfg.mode!r}")
        self.replicator = rep
        return ReplicatedEngine(chain, rep)

    # -- service accessors ----------------------------------------------

    @property
    def executor(self):
        if self._executor is None:
            from nornicdb_tpu.query.executor import CypherExecutor

            self._executor = CypherExecutor(self.storage)
            if self._search is not None:
                self._executor.set_search_service(self._search)
            # Writes arriving outside Cypher (Store/Link, embed queue,
            # replication apply) must invalidate the executor's read
            # cache + columnar snapshot (reference: cache_policy.go).
            ex = self._executor

            class _CacheInvalidator(MutationListener):
                def on_node_upsert(self, node):
                    ex.on_external_node_upsert(node)

                def on_node_delete(self, node_id):
                    ex.on_external_mutation()

                def on_edge_upsert(self, edge):
                    ex.on_external_mutation()

                def on_edge_delete(self, edge_id):
                    ex.on_external_mutation()

            self._listenable.add_listener(_CacheInvalidator())
        return self._executor

    @property
    def search(self):
        if self._search is None:
            from nornicdb_tpu.search.service import SearchService

            import os as _os

            svc = SearchService(
                self.storage, embedder=self._embedder,
                persist_dir=(_os.path.join(self._data_dir, "search")
                             if self._data_dir else None),
                # read replicas tag their service (read_fleet.py sets
                # _search_resource_name before first access) so an
                # in-process fleet's per-node gauges never collide
                resource_name=getattr(self, "_search_resource_name",
                                      None),
            )
            # publish BEFORE backfill so a concurrently-finishing embed
            # lands via _on_embedded instead of being dropped (index_node
            # is idempotent, double-index is harmless)
            self._search = svc
            try:
                svc.build_indexes()  # nodes stored before first search
            except BaseException:
                # un-publish: a half-built index must not be served for
                # the life of the process; next access retries backfill
                self._search = None
                raise
            if self._executor is not None:
                self._executor.set_search_service(self._search)
        return self._search

    @property
    def qdrant_compat(self):
        """Single shared Qdrant translation layer per DB — the REST and
        gRPC surfaces must share one per-collection index cache or
        cross-surface writes go stale."""
        if getattr(self, "_qdrant_compat", None) is None:
            from nornicdb_tpu.api.qdrant import QdrantCompat

            compat = QdrantCompat(self.storage)
            # qdrant points are ordinary storage nodes: a Cypher
            # SET/DELETE (or GDPR delete) over any surface must
            # invalidate the per-collection index + search caches, not
            # just qdrant's own ops
            listener = _QdrantInvalidationListener(compat)
            if hasattr(self.storage, "add_listener"):
                self.storage.add_listener(listener)
            self._qdrant_compat = compat
        return self._qdrant_compat

    @property
    def decay(self):
        if self._decay is None:
            from nornicdb_tpu.decay import DecayManager

            self._decay = DecayManager(self.storage)
        return self._decay

    @property
    def temporal(self):
        if self._temporal is None:
            from nornicdb_tpu.temporal import TemporalTracker

            self._temporal = TemporalTracker()
        return self._temporal

    @property
    def inference(self):
        if self._inference is None:
            from nornicdb_tpu.inference import EvidenceBuffer, InferenceEngine

            # co-access edges materialize only after accumulated evidence
            # (reference wiring: evidence buffer ahead of Auto-TLP edges)
            self._inference = InferenceEngine(
                self.storage, self.search, evidence=EvidenceBuffer())
        return self._inference

    def _start_embed_queue(self):
        from nornicdb_tpu.embed.queue import EmbedQueue

        self._embed_queue = EmbedQueue(
            self.storage, self._embedder, on_embedded=self._on_embedded
        )
        self._listenable.add_listener(self._embed_queue)
        self._embed_queue.start()

    def _on_embedded(self, node: Node) -> None:
        if self._search is not None:
            self._search.index_node(node)

    # -- public API ------------------------------------------------------

    def store(
        self,
        content: str,
        labels: Optional[Sequence[str]] = None,
        properties: Optional[Dict[str, Any]] = None,
        node_id: Optional[str] = None,
        embedding: Optional[List[float]] = None,
        auto_link: bool = False,
    ) -> Node:
        """Store a memory node (reference: db.go:1951 Store)."""
        nid = node_id or str(uuid.uuid4())
        props = dict(properties or {})
        props.setdefault("content", content)
        node = Node(
            id=nid,
            labels=list(labels or ["Memory"]),
            properties=props,
            embedding=embedding,
        )
        self.storage.create_node(node)
        if embedding is not None and self._search is not None:
            # explicit-embedding stores bypass the embed queue (its
            # listener only enqueues un-embedded nodes), so an already
            # built search service must index them here — otherwise a
            # node stored after the first recall() is invisible to
            # every vector surface (recall/similar/graph_vector_search).
            # Best-effort: the node is durably stored either way, and a
            # dims-mismatched explicit embedding was never indexable
            # (it stays recallable by text, exactly as before).
            try:
                self._search.index_node(self.storage.get_node(nid))
            except Exception:  # noqa: BLE001
                pass
        if auto_link and embedding is not None:
            self.inference.on_store(node)
        return self.storage.get_node(nid)

    def recall(self, query: str, limit: int = 10, **kw) -> List[Dict[str, Any]]:
        """Hybrid search over stored memories (reference: db.go:2107 Recall)."""
        return self.search.search(query, limit=limit, **kw)

    def remember(self, node_id: str) -> Node:
        """Fetch a node and record the access for decay/temporal tracking;
        repeated co-access accumulates evidence toward inferred edges
        (reference: db.go:2026 Remember + inference.OnAccess :778)."""
        node = self.storage.get_node(node_id)
        self.decay.record_access(node_id)
        self.temporal.record_access(node_id)
        # evidence-gated co-access inference. Only once the inference
        # engine exists (store/auto-link path created it) — building the
        # whole search stack as a side effect of a read would surprise
        # pure-KV users on large stores.
        if self._inference is not None:
            try:
                self._inference.on_access(self._temporal, node_id)
            except Exception:
                pass  # inference must never fail a read
        return node

    def link(
        self,
        from_id: str,
        to_id: str,
        rel_type: str = "RELATES_TO",
        properties: Optional[Dict[str, Any]] = None,
        edge_id: Optional[str] = None,
    ) -> Edge:
        eid = edge_id or str(uuid.uuid4())
        edge = Edge(
            id=eid,
            type=rel_type,
            start_node=from_id,
            end_node=to_id,
            properties=dict(properties or {}),
        )
        self.storage.create_edge(edge)
        return self.storage.get_edge(eid)

    def neighbors(self, node_id: str, direction: str = Direction.BOTH) -> List[Node]:
        ids = self.storage.neighbors(node_id, direction)
        return [n for n in self.storage.batch_get_nodes(ids) if n is not None]

    def forget(self, node_id: str) -> None:
        self.storage.delete_node(node_id)
        if self._search is not None:
            self._search.remove_node(node_id)

    def cypher(
        self, query: str, params: Optional[Dict[str, Any]] = None
    ) -> "Any":
        """Execute a Cypher query (reference: db.go:2222 Cypher)."""
        return self.executor.execute(query, params or {})

    def graph_vector_search(
        self,
        anchor_id: str,
        hops: Sequence[Any],
        query_vector: Sequence[float],
        k: int = 10,
    ) -> List[Tuple[str, float]]:
        """Fused graph+vector query (the scenario-frontier workload of
        ROADMAP item 5): expand ``hops`` — an (etype, direction)
        sequence, 1 or 2 stages; a bare string means outgoing — from
        the anchor node, then rank the DISTINCT frontier nodes by
        cosine similarity to ``query_vector`` over the search service's
        vector index. Top-k ``(node_id, score)``, score descending.

        With the device graph plane gated on (``NORNICDB_GRAPH_DEVICE``)
        the traversal, frontier dedup, vector gather, scoring and top-k
        run as ONE compiled dispatch; any freshness gap or gate-off
        serves the identical-contract host fallback instead."""
        import numpy as np

        ex = self.executor
        cat = ex.columnar
        hops_n: List[Tuple[str, str]] = []
        for h in hops:
            if isinstance(h, str):
                hops_n.append((h, "out"))
            elif isinstance(h, (list, tuple)) and len(h) == 2:
                etype, direction = h
                if direction not in ("out", "in"):
                    raise ValueError(f"bad hop direction {direction!r}")
                hops_n.append((str(etype), direction))
            else:
                raise ValueError(
                    "each hop must be a relationship type or a "
                    "[type, 'in'|'out'] pair")
        if not hops_n or len(hops_n) > 2:
            raise ValueError("graph_vector_search supports 1 or 2 hops")
        row = cat.node_row(anchor_id)
        if row is None:
            return []
        q = np.asarray(query_vector, dtype=np.float32)
        if q.ndim != 1 or q.size == 0:
            raise ValueError("query_vector must be a flat float vector")
        index = self.search.vectors
        dims = getattr(index, "dims", None)
        if dims and q.shape[0] != dims:
            raise ValueError(
                f"query_vector has {q.shape[0]} dims, index has {dims}")
        q = q[None, :]
        plane = ex.device_graph
        from nornicdb_tpu.obs import audit as _audit
        import time as _time

        t0 = _time.time()
        hits = plane.traverse_rank([row], hops_n, q, k, index)
        if hits is None:
            hits = plane.traverse_rank_host([row], hops_n, q, k, index)
            _audit.record_served("graph", "host",
                                 seconds=_time.time() - t0)
        else:
            _audit.record_served("graph", "graph_traverse_rank_device",
                                 seconds=_time.time() - t0)
            if _audit.sampling_active():
                # shadow-parity: replay the identical-contract host
                # fallback on the audit worker and compare row ids

                def versions_now():
                    return {"catalog_version": cat.version,
                            "index_mutations":
                            getattr(index, "mutations", 0)}

                _audit.maybe_sample(
                    "graph", "graph_traverse_rank_device",
                    [r for r, _ in hits[0]], k=min(10, k),
                    ref=lambda: [r for r, _ in plane.traverse_rank_host(
                        [row], hops_n, q, k, index)[0]],
                    versions=versions_now(), versions_now=versions_now,
                    query={"anchor": anchor_id, "hops": hops_n, "k": k})
        nodes = cat.nodes()
        return [(nodes[r].id, s) for r, s in hits[0]]

    def multidb_manager(self, max_databases: int = 64):
        """Lazily-built multi-database manager rooted on the same engine
        chain this facade namespaces — CREATE/DROP DATABASE and per-DB
        storage views share durability with the default database
        (reference: cmd wires pkg/multidb into every server surface)."""
        with self._lock:
            if self._db_manager is None:
                from nornicdb_tpu.multidb import DatabaseManager

                self._db_manager = DatabaseManager(
                    self._chain, default_database=self.database,
                    max_databases=max_databases)
            return self._db_manager

    def flush(self) -> None:
        if self._embed_queue is not None:
            self._embed_queue.drain()
        self.storage.flush()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self._embed_queue is not None:
            self._embed_queue.stop()
        if self._search is not None:
            self._search.close()  # final index snapshot (search.go:496)
        if self._decay is not None:
            self._decay.stop()
        if self.replicator is not None:
            self.replicator.close()
        if self._cluster_transport is not None:
            self._cluster_transport.close()
        self.storage.close()

    def __enter__(self) -> "DB":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def open(data_dir: Optional[str] = None, **kw) -> DB:  # noqa: A001
    """Open a database (reference: pkg/nornicdb/db.go:742 Open)."""
    return DB(data_dir=data_dir, **kw)
