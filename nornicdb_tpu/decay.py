"""Tiered memory decay.

Reference: pkg/decay — tiers with half-lives EPISODIC 7d / SEMANTIC 69d /
PROCEDURAL 693d (decay.go:77 Tier, :977 HalfLife), score =
recency x frequency x importance weights (:329 Manager), promotion between
tiers, archive threshold, Kalman-smoothed scores (kalman_adapter.go).
Wired into the DB at open (reference db.go:1011-1028).
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from nornicdb_tpu.filters import KalmanFilter
from nornicdb_tpu.storage.types import Engine, Node, now_ms

DAY_MS = 86_400_000


class Tier:
    EPISODIC = "EPISODIC"
    SEMANTIC = "SEMANTIC"
    PROCEDURAL = "PROCEDURAL"


HALF_LIFE_MS = {
    Tier.EPISODIC: 7 * DAY_MS,
    Tier.SEMANTIC: 69 * DAY_MS,
    Tier.PROCEDURAL: 693 * DAY_MS,
}

# promotion: access count thresholds to climb tiers (reference promotion)
PROMOTE_ACCESSES = {Tier.EPISODIC: 5, Tier.SEMANTIC: 25}


@dataclass
class DecayScore:
    node_id: str
    score: float
    recency: float
    frequency: float
    importance: float
    tier: str


@dataclass
class _NodeState:
    tier: str = Tier.EPISODIC
    access_count: int = 0
    last_access_ms: int = 0
    kalman: KalmanFilter = field(default_factory=lambda: KalmanFilter())


class DecayManager:
    """Computes decay scores and archives below-threshold memories."""

    def __init__(
        self,
        storage: Engine,
        recency_weight: float = 0.5,
        frequency_weight: float = 0.3,
        importance_weight: float = 0.2,
        archive_threshold: float = 0.05,
        use_kalman: bool = True,
        half_life_ms: Optional[Dict[str, int]] = None,
    ):
        self.storage = storage
        self.w_recency = recency_weight
        self.w_frequency = frequency_weight
        self.w_importance = importance_weight
        self.archive_threshold = archive_threshold
        self.use_kalman = use_kalman
        self.half_life_ms = dict(half_life_ms or HALF_LIFE_MS)
        self._state: Dict[str, _NodeState] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # ISSUE 19: a BackgroundDevicePlane attaches itself here; when
        # present, sweep() runs as ONE vmapped device pass (host loop
        # stays the fallback for every degrade)
        self.device_plane = None

    # -- access tracking ---------------------------------------------------

    def record_access(self, node_id: str, at_ms: Optional[int] = None) -> None:
        at = at_ms if at_ms is not None else now_ms()
        with self._lock:
            st = self._state.setdefault(node_id, _NodeState())
            st.access_count += 1
            st.last_access_ms = at
            self._maybe_promote(st)

    def _maybe_promote(self, st: _NodeState) -> None:
        """Frequently-accessed memories climb tiers (longer half-lives)."""
        if st.tier == Tier.EPISODIC and st.access_count >= PROMOTE_ACCESSES[Tier.EPISODIC]:
            st.tier = Tier.SEMANTIC
        elif st.tier == Tier.SEMANTIC and st.access_count >= PROMOTE_ACCESSES[Tier.SEMANTIC]:
            st.tier = Tier.PROCEDURAL

    def tier_of(self, node_id: str) -> str:
        with self._lock:
            return self._state.get(node_id, _NodeState()).tier

    # -- scoring -----------------------------------------------------------

    def score(self, node: Node, now: Optional[int] = None) -> DecayScore:
        now = now if now is not None else now_ms()
        with self._lock:
            st = self._state.setdefault(node.id, _NodeState())
            last = st.last_access_ms or node.updated_at or node.created_at or now
            age_ms = max(now - last, 0)
            half_life = self.half_life_ms[st.tier]
            recency = math.pow(0.5, age_ms / half_life)
            frequency = 1.0 - math.exp(-st.access_count / 10.0)
            try:
                importance = float(node.properties.get("importance", 0.5))
            except (TypeError, ValueError):
                importance = 0.5  # non-numeric importance must not abort sweeps
            importance = min(max(importance, 0.0), 1.0)
            raw = (
                self.w_recency * recency
                + self.w_frequency * frequency
                + self.w_importance * importance
            )
            if self.use_kalman:
                raw = st.kalman.update(raw)
            return DecayScore(
                node_id=node.id, score=raw, recency=recency,
                frequency=frequency, importance=importance, tier=st.tier,
            )

    def scores(self, now: Optional[int] = None) -> List[DecayScore]:
        return [self.score(n, now) for n in self.storage.all_nodes()]

    # -- archive sweep -------------------------------------------------------

    def sweep(self, now: Optional[int] = None) -> Tuple[int, int]:
        """Mark below-threshold nodes archived (property flag — the
        reference archives rather than deletes). Returns (scored, archived).

        Runs on the BACKGROUND admission lane (ISSUE 15): a whole-graph
        scoring sweep must never convoy interactive traffic through the
        shared write/index machinery. With a device plane attached
        (ISSUE 19) the sweep is one vectorized score-and-promote pass;
        any guard trip inside the plane returns None and the host loop
        below serves — verdict parity is the plane's contract."""
        from nornicdb_tpu import admission as _adm

        plane = self.device_plane
        if plane is not None:
            res = plane.decay_sweep(now)
            if res is not None:
                return res
        with _adm.lane_scope(_adm.LANE_BACKGROUND):
            return self._sweep_background(now)

    def _sweep_background(self, now: Optional[int]) -> Tuple[int, int]:
        scored = archived = 0
        for node in self.storage.all_nodes():
            s = self.score(node, now)
            scored += 1
            if s.score < self.archive_threshold and not node.properties.get("_archived"):
                node.properties["_archived"] = True
                node.properties["_archived_at"] = now or now_ms()
                try:
                    self.storage.update_node(node)
                    archived += 1
                except KeyError:
                    pass
        return scored, archived

    def half_life(self, tier: str) -> int:
        """Reference: HalfLife (decay.go:977)."""
        return self.half_life_ms[tier]

    def stop(self) -> None:
        self._stop.set()
