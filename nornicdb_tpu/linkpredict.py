"""Topology-based link prediction over an adjacency snapshot.

Reference: pkg/linkpredict — topology.go:95-624 (CommonNeighbors, Jaccard,
AdamicAdar, PreferentialAttachment, ResourceAllocation),
graph_builder.go:144, hybrid.go (topology + embedding blend).
"""

from __future__ import annotations

import math
import threading
import weakref
from typing import Dict, List, Optional, Sequence, Set, Tuple

from nornicdb_tpu.storage.types import Direction, Engine


class AdjacencySnapshot:
    """Undirected neighbor sets captured once per prediction run
    (reference: graph_builder.go)."""

    def __init__(self, storage: Engine):
        self.neighbors: Dict[str, Set[str]] = {}
        for e in storage.all_edges():
            self.neighbors.setdefault(e.start_node, set()).add(e.end_node)
            self.neighbors.setdefault(e.end_node, set()).add(e.start_node)

    def of(self, node_id: str) -> Set[str]:
        return self.neighbors.get(node_id, set())

    def degree(self, node_id: str) -> int:
        return len(self.of(node_id))


# snapshot cache keyed on the columnar catalog (ISSUE 19): rebuilding
# the neighbor sets from storage.all_edges() on EVERY predict_links
# call is O(E) per prediction; with a catalog in hand the snapshot
# stays live until the catalog version moves. WeakKey so a dropped
# catalog never pins its snapshot.
_SNAP_LOCK = threading.Lock()
_SNAP_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def adjacency_snapshot(storage: Engine,
                       catalog=None) -> AdjacencySnapshot:
    """The run's adjacency snapshot. With ``catalog`` (a
    ``query.columnar.ColumnarCatalog``), cached per catalog version —
    repeat predictions between writes reuse ONE build, and the device
    background plane's host-parity re-scoring shares the same object
    (bitwise-identical set iteration, background/device_plane.py).
    Without a catalog the legacy build-per-call behavior stands."""
    if catalog is None:
        return AdjacencySnapshot(storage)
    v = catalog.version
    with _SNAP_LOCK:
        hit = _SNAP_CACHE.get(catalog)
    if hit is not None and hit[0] == v:
        return hit[1]
    snap = AdjacencySnapshot(storage)
    with _SNAP_LOCK:
        if catalog.version == v:
            _SNAP_CACHE[catalog] = (v, snap)
    return snap


def common_neighbors(snap: AdjacencySnapshot, a: str, b: str) -> float:
    return float(len(snap.of(a) & snap.of(b)))


def jaccard(snap: AdjacencySnapshot, a: str, b: str) -> float:
    na, nb = snap.of(a), snap.of(b)
    union = na | nb
    if not union:
        return 0.0
    return len(na & nb) / len(union)


def adamic_adar(snap: AdjacencySnapshot, a: str, b: str) -> float:
    total = 0.0
    for z in snap.of(a) & snap.of(b):
        d = snap.degree(z)
        if d > 1:
            total += 1.0 / math.log(d)
    return total


def preferential_attachment(snap: AdjacencySnapshot, a: str, b: str) -> float:
    return float(snap.degree(a) * snap.degree(b))


def resource_allocation(snap: AdjacencySnapshot, a: str, b: str) -> float:
    total = 0.0
    for z in snap.of(a) & snap.of(b):
        d = snap.degree(z)
        if d > 0:
            total += 1.0 / d
    return total


SCORERS = {
    "common_neighbors": common_neighbors,
    "jaccard": jaccard,
    "adamic_adar": adamic_adar,
    "preferential_attachment": preferential_attachment,
    "resource_allocation": resource_allocation,
}


def predict_links(
    storage: Engine,
    node_id: str,
    method: str = "adamic_adar",
    limit: int = 10,
    candidates: Optional[Sequence[str]] = None,
    catalog=None,
) -> List[Tuple[str, float]]:
    """Rank non-neighbor candidate nodes by topological affinity.
    ``catalog`` enables the per-version snapshot cache (the host path
    gets faster between writes even with the device plane off)."""
    snap = adjacency_snapshot(storage, catalog)
    scorer = SCORERS.get(method)
    if scorer is None:
        raise ValueError(f"unknown link prediction method {method!r}")
    existing = snap.of(node_id) | {node_id}
    if candidates is None:
        # 2-hop neighborhood is the sensible default candidate pool
        pool: Set[str] = set()
        for n in snap.of(node_id):
            pool |= snap.of(n)
        pool -= existing
    else:
        pool = set(candidates) - existing
    scored = [(c, scorer(snap, node_id, c)) for c in pool]
    scored = [(c, s) for c, s in scored if s > 0]
    scored.sort(key=lambda kv: (-kv[1], kv[0]))
    return scored[:limit]


def hybrid_predict(
    storage: Engine,
    search_service,
    node_id: str,
    topology_weight: float = 0.5,
    limit: int = 10,
) -> List[Tuple[str, float, float, float]]:
    """Blend topology score with embedding similarity
    (reference: hybrid.go). Returns (node_id, blended_score,
    topology_score, semantic_score) so callers can decompose the blend:
    blended == w*topology + (1-w)*semantic exactly."""
    topo = dict(predict_links(storage, node_id, limit=limit * 3))
    emb: Dict[str, float] = {}
    try:
        node = storage.get_node(node_id)
    except KeyError:
        return []
    if node.embedding is not None and search_service is not None:
        for nid, score in search_service.vector_search_candidates(
            node.embedding, k=limit * 3
        ):
            if nid != node_id:
                emb[nid] = max(score, 0.0)
    # normalize topology scores to [0, 1]
    tmax = max(topo.values(), default=1.0) or 1.0
    out: Dict[str, Tuple[float, float, float]] = {}
    for nid in set(topo) | set(emb):
        t = topo.get(nid, 0.0) / tmax
        s = emb.get(nid, 0.0)
        out[nid] = (topology_weight * t + (1.0 - topology_weight) * s, t, s)
    ranked = sorted(out.items(), key=lambda kv: (-kv[1][0], kv[0]))
    return [(nid, sc, t, s) for nid, (sc, t, s) in ranked[:limit]]
