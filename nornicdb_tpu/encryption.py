"""At-rest and field-level encryption.

Reference: pkg/encryption — AES-256-GCM with PBKDF2-derived keys
(600k iterations, random salt persisted beside the data dir; key
derivation wired at pkg/nornicdb/db.go:776-805) plus field-level
property encryption (db_privacy.go). Uses the baked-in ``cryptography``
package's AESGCM primitive.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import os
import secrets
from typing import Any, Dict, Iterable, Optional

try:  # optional dependency: only ENCRYPTED stores need the primitive.
    # Importing this module must not fail on a build without the
    # ``cryptography`` package — ``make_encryptor(None, ...)`` (every
    # unencrypted disk store, incl. WAL-shipping read replicas) never
    # touches AESGCM, so the import is gated and the error surfaces
    # only when an Encryptor is actually constructed.
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # pragma: no cover - exercised on slim containers
    AESGCM = None

PBKDF2_ITERS = 600_000
SALT_FILE = "encryption.salt"
_PREFIX = "enc:v1:"


class EncryptionError(Exception):
    pass


def derive_key(passphrase: str, salt: bytes,
               iterations: int = PBKDF2_ITERS) -> bytes:
    """PBKDF2-SHA256 -> 32-byte AES-256 key (reference: DeriveKey used at
    db.go:800)."""
    return hashlib.pbkdf2_hmac("sha256", passphrase.encode(), salt, iterations, 32)


def load_or_create_salt(data_dir: str) -> bytes:
    """Salt file persisted beside the store (reference: salt file in the
    data dir)."""
    os.makedirs(data_dir, exist_ok=True)
    path = os.path.join(data_dir, SALT_FILE)
    if os.path.exists(path):
        with open(path, "rb") as f:
            salt = f.read()
        if len(salt) != 16:
            raise EncryptionError(f"corrupt salt file: {path}")
        return salt
    salt = secrets.token_bytes(16)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(salt)
    os.replace(tmp, path)
    return salt


class Encryptor:
    """AES-256-GCM encrypt/decrypt for byte payloads and node property
    fields."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise EncryptionError("key must be 32 bytes (AES-256)")
        if AESGCM is None:
            raise EncryptionError(
                "the 'cryptography' package is not available in this "
                "build; encrypted stores cannot be opened")
        self._aead = AESGCM(key)

    @classmethod
    def from_passphrase(cls, passphrase: str, data_dir: str,
                        iterations: int = PBKDF2_ITERS) -> "Encryptor":
        salt = load_or_create_salt(data_dir)
        return cls(derive_key(passphrase, salt, iterations))

    # -- bytes -----------------------------------------------------------

    def encrypt(self, plaintext: bytes, aad: bytes = b"") -> bytes:
        nonce = secrets.token_bytes(12)
        return nonce + self._aead.encrypt(nonce, plaintext, aad or None)

    def decrypt(self, blob: bytes, aad: bytes = b"") -> bytes:
        if len(blob) < 13:
            raise EncryptionError("ciphertext too short")
        try:
            return self._aead.decrypt(blob[:12], blob[12:], aad or None)
        except Exception as e:
            raise EncryptionError("decryption failed") from e

    # -- field-level (reference: db_privacy.go encryptProperties) --------

    def encrypt_field(self, value: str) -> str:
        blob = self.encrypt(value.encode())
        return _PREFIX + base64.b64encode(blob).decode()

    def decrypt_field(self, value: str) -> str:
        if not value.startswith(_PREFIX):
            return value
        try:
            blob = base64.b64decode(value[len(_PREFIX):], validate=True)
        except (ValueError, binascii.Error) as e:
            raise EncryptionError("malformed ciphertext encoding") from e
        return self.decrypt(blob).decode("utf-8", errors="replace")

    @staticmethod
    def is_encrypted_field(value: Any) -> bool:
        return isinstance(value, str) and value.startswith(_PREFIX)

    def encrypt_properties(self, props: Dict[str, Any],
                           fields: Iterable[str]) -> Dict[str, Any]:
        """Encrypt the named string fields in-place-style (returns a new
        dict); non-string/missing fields pass through untouched."""
        out = dict(props)
        for f in fields:
            v = out.get(f)
            if isinstance(v, str) and not self.is_encrypted_field(v):
                out[f] = self.encrypt_field(v)
        return out

    def decrypt_properties(self, props: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(props)
        for k, v in out.items():
            if self.is_encrypted_field(v):
                try:
                    out[k] = self.decrypt_field(v)
                except EncryptionError:
                    pass  # wrong key: leave ciphertext visible, don't crash
        return out


def make_encryptor(passphrase: Optional[str], data_dir: str,
                   iterations: int = PBKDF2_ITERS) -> Optional[Encryptor]:
    if not passphrase:
        return None
    return Encryptor.from_passphrase(passphrase, data_dir, iterations)
