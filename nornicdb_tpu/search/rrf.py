"""Reciprocal Rank Fusion of BM25 and vector result lists.

Reference: pkg/search RRF fusion inside Service.Search (search.go:2841).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

DEFAULT_RRF_K = 60


def rrf_fuse(
    result_lists: Sequence[List[Tuple[str, float]]],
    weights: Sequence[float] = (),
    k: int = DEFAULT_RRF_K,
    limit: int = 10,
) -> List[Tuple[str, float]]:
    """Fuse ranked lists of (id, score) by reciprocal rank.

    score(id) = sum_i w_i / (k + rank_i(id)); ids absent from a list
    contribute nothing for it. Returns top ``limit`` by fused score."""
    if not weights:
        weights = [1.0] * len(result_lists)
    fused: Dict[str, float] = {}
    for w, results in zip(weights, result_lists):
        for rank, (doc_id, _score) in enumerate(results):
            fused[doc_id] = fused.get(doc_id, 0.0) + w / (k + rank + 1)
    ranked = sorted(fused.items(), key=lambda kv: (-kv[1], kv[0]))
    return ranked[:limit]
