"""Reciprocal Rank Fusion of BM25 and vector result lists.

Reference: pkg/search RRF fusion inside Service.Search (search.go:2841),
including the weighted variant Service.Search exposes per source.

Tie-breaking is DETERMINISTIC and matches the device fusion kernel
(search/hybrid_fused.py): candidates with equal fused scores order by
their first occurrence across (source index, rank within source), then
id — exactly the concat layout the device top-k resolves ties by, so
host and device fusion agree rank-for-rank.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

DEFAULT_RRF_K = 60


def rrf_fuse(
    result_lists: Sequence[List[Tuple[str, float]]],
    weights: Sequence[float] = (),
    k: int = DEFAULT_RRF_K,
    limit: int = 10,
) -> List[Tuple[str, float]]:
    """Fuse ranked lists of (id, score) by reciprocal rank.

    score(id) = sum_i w_i / (k + rank_i(id)); ids absent from a list
    contribute nothing for it. ``weights`` defaults to 1.0 per source
    (reference: weighted fusion in Service.Search). Returns top
    ``limit`` by fused score, ties broken by first occurrence
    (source order, then rank, then id)."""
    import numpy as np

    if not weights:
        weights = [1.0] * len(result_lists)
    # float32 accumulation, source-major: the exact arithmetic (and
    # addition order) of the device fusion kernel, so the two paths
    # produce bitwise-identical fused scores on identical input lists
    fused: Dict[str, np.float32] = {}
    first_seen: Dict[str, Tuple[int, int]] = {}
    for src, (w, results) in enumerate(zip(weights, result_lists)):
        w32 = np.float32(w)
        for rank, (doc_id, _score) in enumerate(results):
            contrib = w32 / np.float32(k + rank + 1)
            fused[doc_id] = np.float32(
                fused.get(doc_id, np.float32(0.0)) + contrib)
            if doc_id not in first_seen:
                first_seen[doc_id] = (src, rank)
    ranked = sorted(
        fused.items(),
        key=lambda kv: (-kv[1], first_seen[kv[0]], kv[0]),
    )
    return [(doc_id, float(s)) for doc_id, s in ranked[:limit]]
