"""Stage-2 reranking after RRF fusion.

Reference: pkg/search rerank.go / local_rerank.go / llm_rerank.go — a
second-stage reranker over the fused candidate list: a local
cross-encoder (GGUF in the reference; a device-scored cross signal
here) or a fail-open LLM reranker (errors leave the original order
untouched).
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


class LocalReranker:
    """Cross signal scorer: blends embedding cosine with lexical term
    overlap (the device part is one matmul over the candidate matrix —
    the analog of the reference's local cross-encoder pass)."""

    def __init__(self, embedder=None, alpha: float = 0.7):
        self.embedder = embedder
        self.alpha = alpha

    @staticmethod
    def _terms(text: str) -> set:
        return set(re.findall(r"[a-z0-9]+", text.lower()))

    def rerank(
        self,
        query: str,
        candidates: List[Dict[str, Any]],
        query_embedding: Optional[Sequence[float]] = None,
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        if not candidates:
            return candidates
        q_terms = self._terms(query)
        lex = np.zeros(len(candidates), dtype=np.float32)
        for i, c in enumerate(candidates):
            props = c.get("properties") or {}
            text = " ".join(str(v) for v in props.values())
            terms = self._terms(text)
            if q_terms and terms:
                lex[i] = len(q_terms & terms) / len(q_terms)
        cos = np.zeros(len(candidates), dtype=np.float32)
        qv = None
        if query_embedding is not None:
            qv = np.asarray(query_embedding, dtype=np.float32)
        elif self.embedder is not None and query:
            try:
                qv = np.asarray(self.embedder.embed(query),
                                dtype=np.float32)
            except Exception:
                qv = None  # fail-open: lexical-only rerank
        if qv is not None:
            qv = qv / max(np.linalg.norm(qv), 1e-12)
            for i, c in enumerate(candidates):
                v = c.get("_embedding")
                if v is None:
                    cos[i] = float(c.get("vector_score") or 0.0)
                else:
                    v = np.asarray(v, dtype=np.float32)
                    v = v / max(np.linalg.norm(v), 1e-12)
                    cos[i] = float(v @ qv)
        scores = self.alpha * cos + (1.0 - self.alpha) * lex
        order = np.argsort(-scores)
        out = []
        for rank, i in enumerate(order):
            c = dict(candidates[int(i)])
            c["rerank_score"] = float(scores[int(i)])
            out.append(c)
        return out[: limit or len(out)]


class LLMReranker:
    """Fail-open LLM reranker (reference: llm_rerank.go) — asks a
    Heimdall generator to order candidate ids; any failure (bad output,
    backend error) leaves the original order untouched."""

    def __init__(self, manager, model: Optional[str] = None):
        self.manager = manager
        self.model = model

    def rerank(
        self,
        query: str,
        candidates: List[Dict[str, Any]],
        query_embedding: Optional[Sequence[float]] = None,  # unused; API parity
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        if len(candidates) < 2:
            return candidates[: limit or len(candidates)]
        listing = "\n".join(
            f"{c.get('id')}: "
            f"{json.dumps(c.get('properties') or {}, default=str)[:300]}"
            for c in candidates
        )
        prompt = (
            "Rank these documents by relevance to the query. Reply with "
            "ONLY a JSON array of ids, best first.\n"
            f"Query: {query}\nDocuments:\n{listing}\nRanking:"
        )
        try:
            result = self.manager.generate(prompt, model=self.model,
                                           max_tokens=256)
            m = re.search(r"\[.*?\]", result.text, re.DOTALL)
            ranked_ids = json.loads(m.group(0)) if m else None
        except Exception:
            ranked_ids = None
        if not ranked_ids:
            return candidates[: limit or len(candidates)]  # fail-open
        by_id = {str(c.get("id")): c for c in candidates}
        out = [by_id[str(i)] for i in ranked_ids if str(i) in by_id]
        # anything the model forgot keeps its original relative order
        seen = {str(c.get("id")) for c in out}
        out += [c for c in candidates if str(c.get("id")) not in seen]
        return out[: limit or len(out)]
