"""Brute-force exact kNN index with a device-resident matrix.

The TPU analog of the reference's GPUEmbeddingIndex
(pkg/gpu/accelerator.go:290-843 Add/Sync/Search): a host NumPy mirror is
the source of truth; a capacity-padded [C,D] normalized matrix is synced
to device HBM lazily (dirty-flag) and queried with one MXU matmul + top-k
(nornicdb_tpu.ops.similarity). Growth re-pads to the next power-of-two
capacity so jit never sees a new shape per insert.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from nornicdb_tpu.obs import cost as _cost
from nornicdb_tpu.ops.similarity import (
    CHUNKED_THRESHOLD,
    cosine_topk,
    cosine_topk_auto,
    cosine_topk_chunked,
    l2_normalize,
    pad_dim,
)


def _use_pallas() -> bool:
    """Opt-in fused Pallas top-k (NORNICDB_PALLAS_TOPK=1). Off by
    default: on the single-chip bench the XLA matmul+top_k path is
    dispatch-bound and already optimal; the fused kernel targets
    large-batch / large-corpus servers."""
    import os

    return os.environ.get("NORNICDB_PALLAS_TOPK", "0") == "1"


class BruteForceIndex:
    """Exact cosine kNN over (id -> vector). Thread-safe."""

    def __init__(
        self,
        dims: Optional[int] = None,
        use_device: bool = True,
        compact_min_dead: int = 1024,
        compact_dead_frac: float = 0.5,
    ):
        self.dims = dims
        self.use_device = use_device
        # compaction policy: once dead (tombstoned) slots exceed BOTH
        # the absolute floor and the fraction of used slots, live rows
        # are re-packed and capacity re-padded — long-lived collections
        # with churn stop scanning (and shipping to HBM) garbage rows
        self.compact_min_dead = compact_min_dead
        self.compact_dead_frac = compact_dead_frac
        self._lock = threading.RLock()
        self._capacity = 0
        self._count = 0  # high-water mark of used slots
        self._matrix: Optional[np.ndarray] = None  # [cap, D] normalized f32
        self._valid: Optional[np.ndarray] = None  # [cap] bool
        self._ext_ids: List[Optional[str]] = []
        self._slot_of: Dict[str, int] = {}
        self._free: List[int] = []  # recycled slots (deletes)
        self._n_alive = 0
        # write-generation counter: bumped on every add/remove/compact.
        # Derived indexes (search/cagra.py graphs) key their staleness
        # off it instead of subscribing to individual mutations.
        self.mutations = 0
        self.compactions = 0
        # changelog of (mutation seq, ext_id) for adds/updates — derived
        # indexes exact-score these between rebuilds (read-your-writes).
        # Length-capped; _changelog_floor marks how far back it reaches.
        self._changelog: List[Tuple[int, str]] = []
        self._changelog_floor = 0
        # device cache
        self._dev_matrix = None
        self._dev_valid = None
        self._dirty = True
        # (mutations, ext_ids copy) memo for device_view consumers
        self._view_ids_cache = None
        # quantized serving plane (search/device_quant.py), created
        # lazily when NORNICDB_VECTOR_QUANT != off and the corpus
        # clears the quant floor — HBM then holds int8/PQ codes while
        # this host matrix stays the float32 source of truth
        self._quant = None
        # tiered serving plane (search/tiered_store.py), created lazily
        # when NORNICDB_VECTOR_TIERED is on and the corpus clears the
        # tiered floor — HBM then holds PQ slabs for the RESIDENT
        # partitions only; cold partitions spill to disk and this host
        # matrix serves exact reranks + cold side-scans
        self._tiered = None

    def __len__(self) -> int:
        return self._n_alive

    def __contains__(self, ext_id: str) -> bool:
        with self._lock:
            return ext_id in self._slot_of

    def contains_many(self, ext_ids) -> set:
        """Live members of ``ext_ids`` under ONE lock hold — bulk
        membership for decode-path filters (per-id ``in`` would take
        the lock once per candidate and convoy with writers)."""
        with self._lock:
            return {e for e in ext_ids if e in self._slot_of}

    def ids(self) -> List[str]:
        """Live external ids under one lock hold — the maintenance
        sweep (SearchService.prune_missing, replica bulk-delete replay)
        reconciles these against storage."""
        with self._lock:
            return list(self._slot_of.keys())

    @staticmethod
    def _normalize(v: np.ndarray) -> np.ndarray:
        n = np.linalg.norm(v)
        return v / n if n > 1e-12 else v

    def _ensure_capacity_locked(self, needed: int, dims: int) -> None:
        if self.dims is None:
            self.dims = dims
        if dims != self.dims:
            raise ValueError(f"dims mismatch: index={self.dims}, vector={dims}")
        if needed <= self._capacity:
            return
        new_cap = pad_dim(needed)
        new_m = np.zeros((new_cap, self.dims), dtype=np.float32)
        new_v = np.zeros((new_cap,), dtype=bool)
        if self._matrix is not None:
            new_m[: self._capacity] = self._matrix
            new_v[: self._capacity] = self._valid
        self._matrix = new_m
        self._valid = new_v
        self._ext_ids.extend([None] * (new_cap - len(self._ext_ids)))
        self._capacity = new_cap
        self._dirty = True

    # -- mutation ---------------------------------------------------------

    def add(self, ext_id: str, vector: Sequence[float]) -> None:
        v = np.asarray(vector, dtype=np.float32)
        with self._lock:
            if ext_id in self._slot_of:
                slot = self._slot_of[ext_id]
                self._matrix[slot] = self._normalize(v)
                self._dirty = True
                self.mutations += 1
                self._log_change_locked(ext_id)
                return
            self._ensure_capacity_locked(self._count + (0 if self._free else 1), v.shape[0])
            if self._free:
                slot = self._free.pop()
            else:
                slot = self._count
                self._count += 1
            self._matrix[slot] = self._normalize(v)
            self._valid[slot] = True
            self._ext_ids[slot] = ext_id
            self._slot_of[ext_id] = slot
            self._n_alive += 1
            self._dirty = True
            self.mutations += 1
            self._log_change_locked(ext_id)

    def _log_change_locked(self, ext_id: str) -> None:
        self._changelog.append((self.mutations, ext_id))
        # cap well above any derived index's rebuild threshold (10% of
        # corpus churn) so changed_since() can always reach a live
        # build marker; beyond the cap the floor advances and consumers
        # fall back to a full rebuild/exact path
        limit = self.changelog_cap()
        if len(self._changelog) > limit:
            cut = len(self._changelog) - limit
            self._changelog_floor = self._changelog[cut - 1][0]
            del self._changelog[:cut]

    def changelog_cap(self) -> int:
        """Current changelog length cap (same formula as the trim in
        _log_change_locked) — the accounting layer reports depth vs cap
        so near-overrun is visible before the device paths degrade."""
        return max(4096, self._capacity // 4)

    def resource_stats(self) -> Dict[str, float]:
        """Memory + freshness accounting for obs/resources.py: the
        device/host footprint of the matrix and its mirrors, tombstone
        pressure, and changelog depth vs cap. One short lock hold."""
        with self._lock:
            dims = self.dims or 0
            matrix_b = self._capacity * dims * 4  # float32
            valid_b = self._capacity  # bool
            dev = self._dev_matrix
            dev_b = 0
            if dev is not None:
                dev_b = int(getattr(dev, "nbytes", 0)) + int(
                    getattr(self._dev_valid, "nbytes", 0) or 0)
            used = max(self._count, 1)
            quant = self._quant
            tiered = self._tiered
            stats = {
                "rows": self._n_alive,
                "capacity": self._capacity,
                "device_bytes": dev_b,
                # host mirror + the ext-id slot table (pointer-sized
                # slots; string payloads are shared with callers)
                "host_bytes": matrix_b + valid_b + 8 * len(self._ext_ids),
                "dead_fraction": round(
                    (self._count - self._n_alive) / used, 6),
                "changelog_depth": len(self._changelog),
                "changelog_cap": self.changelog_cap(),
                "mutations": self.mutations,
            }
        if quant is not None:
            # outside the index lock: the plane takes no brute locks in
            # resource_stats_extra, but keep lock ordering trivial
            stats.update(quant.resource_stats_extra())
        if tiered is not None:
            stats.update(tiered.resource_stats_extra())
        return stats

    def changed_since(self, seq: int) -> Optional[List[str]]:
        """ext_ids added or UPDATED after mutation ``seq`` (latest first,
        deduped). Deletes are not reported — consumers live-filter those.
        Returns None when the changelog has been trimmed past ``seq``
        (consumer should rebuild or take an exact path instead)."""
        with self._lock:
            if seq < self._changelog_floor:
                return None
            out: List[str] = []
            for s, eid in reversed(self._changelog):
                if s <= seq:
                    break
                out.append(eid)
        return list(dict.fromkeys(out))

    def add_batch(self, items: Sequence[Tuple[str, Sequence[float]]]) -> None:
        with self._lock:
            for ext_id, vec in items:
                self.add(ext_id, vec)

    def remove(self, ext_id: str) -> bool:
        with self._lock:
            slot = self._slot_of.pop(ext_id, None)
            if slot is None:
                return False
            self._valid[slot] = False
            self._ext_ids[slot] = None
            self._free.append(slot)
            self._n_alive -= 1
            self._dirty = True
            self.mutations += 1
            self._maybe_compact_locked()
            return True

    def _maybe_compact_locked(self) -> None:
        dead = self._count - self._n_alive
        if (dead < self.compact_min_dead
                or dead < self.compact_dead_frac * max(self._count, 1)):
            return
        self._compact_locked()

    def compact(self) -> bool:
        """Re-pack live rows and re-pad capacity. Normally triggered by
        the remove-path policy; public for tests and admin tooling."""
        with self._lock:
            if self._count == self._n_alive:
                return False
            self._compact_locked()
            return True

    def _compact_locked(self) -> None:
        """Drop tombstoned rows: live rows move to the front (insertion
        order preserved) and capacity shrinks to pad_dim(n_alive), so
        search matmuls — and the HBM mirror — stop paying for deletes.
        Slot ids are remapped; _slot_of is the only consumer."""
        if self._n_alive == 0:
            self._capacity = 0
            self._count = 0
            self._matrix = None
            self._valid = None
            self._ext_ids = []
            self._slot_of = {}
            self._free = []
        else:
            rows = [i for i, e in enumerate(self._ext_ids)
                    if e is not None and self._valid[i]]
            new_cap = pad_dim(len(rows))
            new_m = np.zeros((new_cap, self.dims), dtype=np.float32)
            new_m[: len(rows)] = self._matrix[rows]
            new_v = np.zeros((new_cap,), dtype=bool)
            new_v[: len(rows)] = True
            self._ext_ids = ([self._ext_ids[i] for i in rows]
                             + [None] * (new_cap - len(rows)))
            self._slot_of = {e: s for s, e in enumerate(self._ext_ids)
                             if e is not None}
            self._matrix = new_m
            self._valid = new_v
            self._capacity = new_cap
            self._count = len(rows)
            self._free = []
        self._dirty = True
        self.mutations += 1
        self.compactions += 1

    def get(self, ext_id: str) -> Optional[np.ndarray]:
        with self._lock:
            slot = self._slot_of.get(ext_id)
            if slot is None:
                return None
            return self._matrix[slot].copy()

    def delta_vectors(self, ext_ids):
        """(ids, rows f32 [n, D] or None) for changelog delta ids under
        ONE lock hold, skipping ids removed since logging — the
        exact-float32 side-scan gather every quantized serving path
        shares (rows are CURRENT matrix values: read-your-writes)."""
        with self._lock:
            ids: List[str] = []
            rows = []
            for eid in ext_ids:
                slot = self._slot_of.get(eid)
                if slot is None:
                    continue
                ids.append(eid)
                rows.append(self._matrix[slot].copy())
        return ids, (np.stack(rows) if ids else None)

    def slots_of(
        self, ext_ids: Sequence[str],
        expect_mutations: Optional[int] = None,
    ) -> Optional[List[int]]:
        """Current matrix slot per ext id (-1 when absent). Slot ids
        only mean anything relative to a specific matrix state, so the
        read and the staleness check share one lock hold: when
        ``expect_mutations`` no longer matches (a write or compaction
        landed since the caller captured its device view), returns None
        — joining fresh slots against an older matrix would mis-join."""
        with self._lock:
            if expect_mutations is not None \
                    and self.mutations != expect_mutations:
                return None
            return [self._slot_of.get(e, -1) for e in ext_ids]

    def rows_for_slots(
        self, slots, expect_compactions: Optional[int] = None,
    ):
        """(rows f32 [n, D] copy, alive [n] bool, ext_ids [n]) for the
        given slot ids under ONE lock hold — the exact-rerank gather of
        the quantized plane. Rows are the CURRENT matrix values, so an
        in-place update reranks fresh automatically. Returns None when
        ``expect_compactions`` no longer matches (a compaction remapped
        the slot space since the caller's plane was built — slot-keyed
        reads can no longer be trusted) or a slot is out of range."""
        with self._lock:
            if expect_compactions is not None \
                    and self.compactions != expect_compactions:
                return None
            if self._matrix is None:
                return None
            sl = np.asarray(slots, dtype=np.int64)
            if sl.size and (sl.min() < 0 or sl.max() >= self._capacity):
                return None
            return (self._matrix[sl].copy(), self._valid[sl].copy(),
                    [self._ext_ids[int(i)] for i in sl])

    # -- search -----------------------------------------------------------

    def _device_arrays_locked(self):
        if self._dirty or self._dev_matrix is None:
            self._dev_matrix = jnp.asarray(self._matrix)
            self._dev_valid = jnp.asarray(self._valid)
            self._dirty = False
        return self._dev_matrix, self._dev_valid

    def view_meta(self):
        """(mutations, compactions) — or None while the index is empty
        — WITHOUT forcing the device arrays current. The walk tier
        only needs the mutation counter for its freshness gate; after
        a write burst, :meth:`device_view` would re-ship the whole
        matrix to device and re-copy the capacity-sized ext-id list,
        a per-write tax the walk dispatch never uses."""
        with self._lock:
            if self._n_alive == 0 or self._matrix is None:
                return None
            return self.mutations, self.compactions

    def ids_meta(self):
        """(ext_ids copy, mutations, compactions) — or None while
        empty — WITHOUT forcing the device arrays current. The
        quantized fused tier joins/decodes against slot ids and must
        not pay the float32 matrix re-ship that :meth:`device_view`
        implies after a write burst. Shares device_view's per-
        generation ids memo."""
        with self._lock:
            if self._n_alive == 0 or self._matrix is None:
                return None
            cached = self._view_ids_cache
            if cached is None or cached[0] != self.mutations:
                cached = (self.mutations, list(self._ext_ids))
                self._view_ids_cache = cached
            return cached[1], self.mutations, self.compactions

    def device_view(self):
        """Consistent device-side view for external batched kernels (the
        fused hybrid pipeline): (matrix[C,D], valid[C], ext_ids,
        mutations, compactions) captured atomically, or None while the
        index is empty. The matrix/valid arrays are the same lazily
        synced device cache ``search_batch`` dispatches against; the
        ext_ids copy is memoized per mutation generation so a steady
        read stream doesn't re-copy a capacity-sized list per batch."""
        with self._lock:
            if self._n_alive == 0 or self._matrix is None:
                return None
            m, valid = self._device_arrays_locked()
            cached = self._view_ids_cache
            if cached is None or cached[0] != self.mutations:
                cached = (self.mutations, list(self._ext_ids))
                self._view_ids_cache = cached
            return m, valid, cached[1], self.mutations, \
                self.compactions

    def search(
        self, query: Sequence[float], k: int = 10
    ) -> List[Tuple[str, float]]:
        return self.search_batch(np.asarray([query], dtype=np.float32), k)[0]

    @staticmethod
    def _search_host(queries, m, valid, ext_ids, k_eff):
        qn = queries / np.maximum(
            np.linalg.norm(queries, axis=1, keepdims=True), 1e-12)
        scores = qn @ m.T
        scores[:, ~valid] = -np.inf
        out: List[List[Tuple[str, float]]] = []
        for row in range(scores.shape[0]):
            top = np.argpartition(-scores[row], k_eff - 1)[:k_eff]
            # exact-tie order is lower-slot-first, matching lax.top_k on
            # the device path (hybrid parity relies on it); lexsort's
            # primary key is the last one
            top = top[np.lexsort((top, -scores[row][top]))]
            hits = []
            for idx in top:
                if not np.isfinite(scores[row, idx]):
                    break
                eid = ext_ids[int(idx)]
                if eid is not None:
                    hits.append((eid, float(scores[row, idx])))
            out.append(hits)
        return out

    # below this many matrix cells, host numpy beats a device dispatch
    # (jit-call overhead alone is ~100us; through a TPU tunnel the
    # transfer round-trip is ms) — small qdrant collections and early
    # index life live here
    _SMALL_HOST = 1 << 18

    def quant_plane(self):
        """The lazily-created quantized serving plane when
        NORNICDB_VECTOR_QUANT is configured and the corpus clears the
        quant floor, else None. ONE plane per index — direct kNN
        serving and the fused hybrid tier share it (one compressed copy
        in HBM, one rebuild cadence)."""
        from nornicdb_tpu.search.device_quant import (
            quant_min_n,
            quant_mode,
        )

        if quant_mode() == "off" or self._n_alive < quant_min_n():
            return None
        plane = self._quant
        if plane is None:
            from nornicdb_tpu.config import env_bool, env_int
            from nornicdb_tpu.search.device_quant import (
                QuantizedBrutePlane,
            )

            with self._lock:
                plane = self._quant
                if plane is None:
                    plane = QuantizedBrutePlane(
                        self,
                        n_shards=max(1, env_int("QUANT_SHARDS", 1)),
                        build_inline=env_bool("QUANT_INLINE_BUILD",
                                              False),
                        overfetch=max(1, env_int("QUANT_OVERFETCH", 8)),
                        min_pool=max(1, env_int("QUANT_MIN_POOL", 128)))
                    self._quant = plane
        return plane

    def tiered_plane(self):
        """The lazily-created tiered serving plane when
        NORNICDB_VECTOR_TIERED is on and the corpus clears the tiered
        floor, else None. ONE plane per index: one partition layout,
        one residency LRU, one disk spill store. All NORNICDB_TIERED_*
        knobs are read HERE, once, at plane creation — the per-request
        path (route/search_batch) is environment-free by the PR 14
        hot-path contract."""
        from nornicdb_tpu.search.tiered_store import (
            tiered_enabled,
            tiered_min_n,
        )

        if not tiered_enabled() or self._n_alive < tiered_min_n():
            return None
        plane = self._tiered
        if plane is None:
            from nornicdb_tpu.config import (
                env_bool,
                env_float,
                env_int,
                env_str,
            )
            from nornicdb_tpu.search.tiered_store import TieredStore

            with self._lock:
                plane = self._tiered
                if plane is None:
                    plane = TieredStore(
                        self,
                        nprobe=max(1, env_int("TIERED_NPROBE", 8)),
                        parts=max(0, env_int("TIERED_PARTS", 0)),
                        resident_max=max(
                            0, env_int("TIERED_RESIDENT", 0)),
                        part_rows=max(
                            256, env_int("TIERED_PART_ROWS", 4096)),
                        lex_bonus=env_float("TIERED_LEX_BONUS", 0.15),
                        build_inline=env_bool("TIERED_INLINE_BUILD",
                                              False),
                        overfetch=max(
                            1, env_int("TIERED_OVERFETCH", 8)),
                        min_pool=max(
                            1, env_int("TIERED_MIN_POOL", 128)),
                        root_dir=env_str("TIERED_DIR", "") or None)
                    self._tiered = plane
        return plane

    def _tiered_search_batch(self, queries, k, lex_hints=None):
        """Tiered cluster-routed serving (tiered_store.py) when
        NORNICDB_VECTOR_TIERED is on and the corpus clears the tiered
        floor. None = the quant/float32 rungs serve this batch — the
        ladder is tiered -> quant -> f32 -> host, never a wrong
        answer. Fail-open like the quant plane."""
        plane = self.tiered_plane()
        if plane is None:
            return None
        try:
            return plane.search_batch(
                np.asarray(queries, dtype=np.float32), k,
                lex_hints=lex_hints)
        except Exception:  # noqa: BLE001 — degrade, never fail
            from nornicdb_tpu.obs import audit as _audit
            from nornicdb_tpu.search.tiered_store import _TIERED_C

            _TIERED_C.labels("degrade_error").inc()
            _audit.record_degrade(
                "vector", "vector_tiered", "vector_brute_f32",
                "error", index=_cost.cost_name(self))
            return None

    def _quant_search_batch(self, queries, k):
        """Quantized coarse-then-exact serving (device_quant.py) when
        NORNICDB_VECTOR_QUANT is set and the corpus clears the quant
        floor. None = the float32 tier serves this batch — the degrade
        ladder is quantized -> float32 -> host, never a wrong answer.
        Fail-open: any plane error degrades, never fails a search."""
        plane = self.quant_plane()
        if plane is None:
            return None
        try:
            return plane.search_batch(
                np.asarray(queries, dtype=np.float32), k)
        except Exception:  # noqa: BLE001 — degrade, never fail
            # counted: a persistent plane bug silently eating the
            # compression win must show up in quant_events_total
            from nornicdb_tpu.obs import audit as _audit
            from nornicdb_tpu.search.device_quant import (
                _QUANT_C,
                quant_mode,
            )

            _QUANT_C.labels("degrade_error").inc()
            _audit.record_degrade(
                "vector", f"vector_{quant_mode()}", "vector_brute_f32",
                "error", index=_cost.cost_name(self))
            return None

    def search_batch(
        self, queries: np.ndarray, k: int = 10, exact: bool = False
    ) -> List[List[Tuple[str, float]]]:
        """Batched exact search; returns per-query [(ext_id, cosine)].
        With ``NORNICDB_VECTOR_QUANT`` set, large corpora serve through
        the quantized coarse+exact-rerank plane instead (answers remain
        exact-rescored float32; ``exact=True`` bypasses the plane for
        callers whose contract is exhaustive recall)."""
        from nornicdb_tpu.obs import audit as _audit

        if not exact:
            # capacity rung first (beyond-HBM corpora), then the
            # device-resident quant rung
            out = self._tiered_search_batch(queries, k)
            if out is not None:
                return out
            out = self._quant_search_batch(queries, k)
            if out is not None:
                return out
        # serving-tier note for the batch leader (ISSUE 10): every
        # return below — small-host numpy, XLA matmul, empty answer —
        # is the exact float32 brute tier (the quant plane notes its
        # own tier before returning above)
        _audit.note_batch_tier("vector_brute_f32")
        with self._lock:
            if self._n_alive == 0:
                return [[] for _ in range(len(queries))]
            k_eff = min(k, self._n_alive)
            # per-query cost accounting: the brute scan's price is its
            # known shapes — B queries against the capacity-padded
            # [C, D] matrix (host or device, the arithmetic is the same)
            if _cost.pricing_enabled():
                flops, byts = _cost.price_brute(
                    len(queries), self._capacity, self.dims or 1)
                _cost.record_query_cost("brute", _cost.cost_name(self),
                                        len(queries), flops, byts)
            if self._capacity * (self.dims or 1) <= self._SMALL_HOST:
                # no defensive copies: the whole host search runs under
                # the lock and only reads the matrix/valid/ext_ids
                return self._search_host(
                    np.asarray(queries, np.float32), self._matrix,
                    self._valid, self._ext_ids, k_eff)
            m, valid = self._device_arrays_locked()
            ext_ids = list(self._ext_ids)
        q = l2_normalize(jnp.asarray(queries, dtype=jnp.float32))
        if _use_pallas():
            from nornicdb_tpu.ops.pallas_topk import fused_cosine_topk

            s, i = fused_cosine_topk(q, m, valid, k_eff)
        else:
            s, i = cosine_topk_auto(q, m, valid, k_eff)
        s = np.asarray(s)
        i = np.asarray(i)
        out: List[List[Tuple[str, float]]] = []
        for row in range(s.shape[0]):
            hits = []
            for col in range(s.shape[1]):
                if s[row, col] < -1e29:
                    break
                eid = ext_ids[int(i[row, col])]
                if eid is not None:
                    hits.append((eid, float(s[row, col])))
            out.append(hits)
        return out

    # -- bulk access (for HNSW/kmeans builds) ------------------------------

    def snapshot(self) -> Tuple[np.ndarray, np.ndarray, List[Optional[str]]]:
        """(matrix[cap,D], valid[cap], ext_ids) — normalized, host-side.
        An empty index (never populated, or compacted to empty) yields
        zero-row arrays rather than crashing its graph/HNSW builders."""
        with self._lock:
            if self._matrix is None:
                return (np.zeros((0, self.dims or 0), np.float32),
                        np.zeros((0,), bool), [])
            return self._matrix.copy(), self._valid.copy(), list(self._ext_ids)

    def ids(self) -> List[str]:
        with self._lock:
            return [e for e in self._ext_ids if e is not None]

    # -- persistence (reference: vector store save/load, search.go:496) --

    def save(self, path: str) -> None:
        """Snapshot live rows to an .npz (compacted: dead slots dropped)."""
        with self._lock:
            if self._matrix is None or self._n_alive == 0:
                ids = np.asarray([], dtype="U1")
                matrix = np.zeros((0, 0), np.float32)
            else:
                rows = [i for i, e in enumerate(self._ext_ids)
                        if e is not None and self._valid[i]]
                ids = np.asarray([self._ext_ids[i] for i in rows])
                matrix = self._matrix[rows]
        # write through a file object — np.savez would append ".npz" to a
        # bare path, breaking the caller's atomic tmp-then-rename publish
        with open(path, "wb") as f:
            np.savez_compressed(f, ids=ids, matrix=matrix)

    @classmethod
    def load(cls, path: str, use_device: bool = True) -> "BruteForceIndex":
        """Exact restore: rows go back verbatim (no re-normalization — a
        second normalize of float32 rows drifts ~1e-7 and reorders
        equal-score ties vs the saved index)."""
        data = np.load(path, allow_pickle=False)
        idx = cls(use_device=use_device)
        ids = data["ids"]
        matrix = np.asarray(data["matrix"], np.float32)
        n = len(ids)
        if n == 0:
            return idx
        idx._ensure_capacity_locked(n, matrix.shape[1])
        idx._matrix[:n] = matrix
        idx._valid[:n] = True
        for i in range(n):
            eid = str(ids[i])
            idx._ext_ids[i] = eid
            idx._slot_of[eid] = i
        idx._count = n
        idx._n_alive = n
        idx._dirty = True
        return idx
