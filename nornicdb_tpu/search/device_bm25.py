"""Device-resident BM25: batched Okapi scoring over CSR postings in HBM.

``BM25Index.search`` is a single-query NumPy loop under the index lock —
every hybrid query serializes behind it and none of the lexical math
ever touches the accelerator. This module closes that host/device
boundary (the dominant hybrid-search bottleneck per the GPU
vector-search taxonomy, arXiv:2602.16719) the same way ``cagra.py``
closed it for graph ANN:

- **Layout**: the live postings flatten into device-resident CSR
  columns — per-term offset ranges over ``(doc_row, tf)`` pairs — plus
  ``doc_len`` and ``alive`` vectors over a dense, capacity-padded row
  space. Terms are sorted so host and device accumulate per-doc scores
  in the same order.
- **Scoring** (one jitted program per pow2 bucket): the host plans a
  query batch by flattening each query's term posting ranges into
  ``(posting_ptr, query_row, idf)`` entry columns (idf comes from the
  index's *incremental live-df counters*, so deletes correct df without
  touching the snapshot); the device gathers postings, applies the
  vectorized Okapi tf normalization, segment-sums into a dense
  ``[B, C]`` score matrix and takes one top-k. Batch, entry count and k
  pad to power-of-two buckets (``microbatch.pow2_bucket``) so the XLA
  compile universe stays bounded.
- **Sharding** (``shard_map``): postings, doc vectors and the planned
  entry columns row-shard over the ``data`` mesh axis; each shard
  scores its local rows, then one all-gather + top-k merges shard-local
  winners — bit-identical to the single-device reference merge
  (``ops.similarity.concat_topk``), same collective pattern as
  ``cagra`` and ``parallel.mesh.sharded_cosine_topk``.
- **Freshness** (PR 2 discipline): the snapshot records the index's
  mutation generation; churn beyond ``rebuild_stale_frac`` kicks a
  background rebuild while the stale snapshot keeps serving. Tombstones
  are live-filtered through a per-slot alive refresh (df corrected via
  the live counters), and adds/updates ride the index's capped
  changelog into an exact host delta side-scan — read-your-writes
  without a rebuild. A trimmed changelog or a slot-remapping compaction
  falls back to the host index until the fresh snapshot lands.
"""

from __future__ import annotations

import functools
import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nornicdb_tpu.obs import REGISTRY, declare_kind, record_dispatch
from nornicdb_tpu.ops.similarity import NEG_INF, concat_topk, pad_dim
from nornicdb_tpu.search.bm25 import B, K1, BM25Index, tokenize
from nornicdb_tpu.search.microbatch import pow2_bucket

# lifecycle + freshness decisions of the device lexical snapshot — the
# same observability contract the cagra tier established
_LEX_C = REGISTRY.counter(
    "nornicdb_device_bm25_events_total",
    "Device BM25 snapshot lifecycle and per-search freshness decisions",
    labels=("event",))

declare_kind("bm25_score")


class PlanOverflow(Exception):
    """The (U+1)*C segment-id space of a planned batch would exceed
    int32 (jax's default index width; segment_sum silently DROPS
    out-of-range ids). Callers serve the batch host-exact instead."""


class SnapshotStale(Exception):
    """A compaction remapped the host slot space after this snapshot's
    freshness checks began — slot-keyed reads can no longer be trusted
    and the caller must serve host-exact (a rebuild is already due)."""


# ---------------------------------------------------------------------------
# pure scoring kernels (shared with the fused hybrid pipeline)
# ---------------------------------------------------------------------------


def bm25_dense_scores(
    ptr: jnp.ndarray,  # [P] int32 indices into post_doc/post_tf
    urow: jnp.ndarray,  # [P] int32 unique-term row per entry
    sel: jnp.ndarray,  # [B, U] f32 idf-weighted term-selection matrix
    post_doc: jnp.ndarray,  # [Pcap] int32 doc row per posting
    post_tf: jnp.ndarray,  # [Pcap] f32 OR uint16 term freq per posting
    doc_len: jnp.ndarray,  # [C] f32 OR uint16
    alive_f: jnp.ndarray,  # [C] f32 {0,1}
    avgdl: jnp.ndarray,  # scalar f32
) -> jnp.ndarray:
    """Dense BM25 scores [B, C]; rows with no matching live term (and
    padding entries, whose sel columns are all-zero) come out NEG_INF.

    The aggregation is term-deduplicated across the batch: postings
    scatter ONCE per unique query term into a [U, C] tf-norm matrix
    (unique indices — each posting owns its (term, doc) cell), and the
    per-query accumulation is one idf-weighted [B,U]x[U,C] matmul. A
    coalesced batch whose queries share terms — the common case under
    zipfian traffic — thus pays the scatter once per term, not once per
    (query, term): the device dispatch gets CHEAPER per query as the
    MicroBatcher coalesces harder. Okapi contributions are strictly
    positive, so `score > 0` IS the touched-by-a-query-term mask."""
    u = sel.shape[1]
    c = doc_len.shape[0]
    # cast AFTER the gather: tf and doc-len are integer counts, so the
    # quantized (uint16) CSR columns are exactly lossless below 65536 —
    # HBM holds 2-byte columns, the Okapi arithmetic stays float32
    # bit-identical (PR 8 headroom; f32 columns pass through unchanged)
    d = post_doc[ptr]
    tf = post_tf[ptr].astype(jnp.float32)
    dl = doc_len[d].astype(jnp.float32)
    tf_norm = tf * (K1 + 1.0) / (tf + K1 * (1.0 - B + B * dl / avgdl))
    # padding entries carry urow == U and land in a discarded overflow
    # row, so they can never corrupt a real (term, doc) cell
    seg = urow * c + d
    m = jax.ops.segment_sum(tf_norm, seg, num_segments=(u + 1) * c)
    dense = sel @ m.reshape(u + 1, c)[:u]
    return jnp.where((alive_f[None, :] > 0.0) & (dense > 0.0),
                     dense, NEG_INF)


@functools.partial(jax.jit, static_argnames=("k",))
def _bm25_topk(ptr, urow, sel, post_doc, post_tf, doc_len, alive_f,
               avgdl, k):
    dense = bm25_dense_scores(ptr, urow, sel, post_doc, post_tf,
                              doc_len, alive_f, avgdl)
    return jax.lax.top_k(dense, k)


@functools.partial(jax.jit, static_argnames=("k_local",))
def _bm25_local_topk(ptr, urow, sel, post_doc, post_tf, doc_len,
                     alive_f, avgdl, row_offset, k_local):
    """One shard's local top-k with globalized row ids — the building
    block of the single-device reference merge."""
    dense = bm25_dense_scores(ptr, urow, sel, post_doc, post_tf,
                              doc_len, alive_f, avgdl)
    s, i = jax.lax.top_k(dense, k_local)
    return s, i + row_offset


@functools.partial(
    jax.jit, static_argnames=("k", "mesh_holder"))
def _sharded_bm25_impl(ptr, urow, sel, post_doc, post_tf, doc_len,
                       alive_f, avgdl, k, mesh_holder):
    from jax.sharding import PartitionSpec as P

    from nornicdb_tpu.parallel.mesh import compat_shard_map

    mesh = mesh_holder.mesh
    n_shards = mesh.shape["data"]
    c_local = doc_len.shape[0] // n_shards
    k_local = min(k, c_local)

    def local_fn(ptr_s, urow_s, sel_r, pd_s, pt_s, dl_s, al_s, avg_r):
        dense = bm25_dense_scores(ptr_s, urow_s, sel_r, pd_s, pt_s,
                                  dl_s, al_s, avg_r)
        s, i = jax.lax.top_k(dense, k_local)
        shard = jax.lax.axis_index("data")
        gi = i + shard * c_local
        all_s = jax.lax.all_gather(s, "data", axis=1, tiled=True)
        all_i = jax.lax.all_gather(gi, "data", axis=1, tiled=True)
        top_s, pos = jax.lax.top_k(all_s, k)
        return top_s, jnp.take_along_axis(all_i, pos, axis=1)

    return compat_shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P(), P("data"), P("data"),
                  P("data"), P("data"), P()),
        out_specs=(P(), P()),
    )(ptr, urow, sel, post_doc, post_tf, doc_len, alive_f, avgdl)


# ---------------------------------------------------------------------------
# the device snapshot index
# ---------------------------------------------------------------------------


class DeviceBM25:
    """Batched device BM25 over a wrapped (host) :class:`BM25Index`.

    The host index stays the mutable source of truth; the device
    snapshot is an immutable CSR build over it, kept fresh by alive
    refreshes + exact delta side-scans and rebuilt in the background
    once churn crosses ``rebuild_stale_frac``. Below ``min_n`` live
    docs search serves from the host index (one lock-held NumPy pass
    beats any device dispatch at tiny N)."""

    def __init__(
        self,
        bm25: BM25Index,
        n_shards: int = 1,
        min_n: int = 256,
        rebuild_stale_frac: float = 0.1,
        build_inline: bool = True,
        quant_cols: Optional[bool] = None,
    ):
        self.bm25 = bm25
        self.n_shards = max(1, n_shards)
        self.min_n = min_n
        if quant_cols is None:
            # captured once at construction (init-time env read, PR 14
            # hot-path contract): store the tf/doc-len CSR columns as
            # uint16 — exactly lossless for integer counts below 65536;
            # a corpus exceeding that falls back to f32 per column
            from nornicdb_tpu.config import env_bool

            quant_cols = env_bool("BM25_QUANT", True)
        self.quant_cols = bool(quant_cols)
        self.rebuild_stale_frac = rebuild_stale_frac
        # build_inline=False defers even the first build to a background
        # thread (read-path wiring: the host index serves until the
        # snapshot is ready); True blocks once — the right call in
        # tests/benches needing determinism.
        self.build_inline = build_inline
        self._snap: Optional[Dict[str, Any]] = None
        self._build_lock = threading.Lock()
        self._rebuilding = False
        self._rebuild_started = 0.0  # backlog age for /readyz + gauges
        self._rebuild_flag_lock = threading.Lock()
        self._alive_lock = threading.Lock()
        self._map_lock = threading.Lock()
        self._delta_cache: Optional[Tuple] = None
        # per-thread (nnz, unique_terms) from the latest plan() on this
        # thread — cost pricing reads it instead of re-deriving the
        # unique-term set and df stats on the hot path
        self._plan_cost = threading.local()
        self.builds = 0

    # -- build ------------------------------------------------------------

    def build(self) -> bool:
        """(Re)build the device snapshot. False when below ``min_n``
        (search stays on the host index)."""
        with self._build_lock:
            return self._build_locked()

    def _build_locked(self) -> bool:
        gen = self.bm25.mut_gen
        snap = self._snap
        if snap is not None and snap["built_gen"] == gen:
            return True  # raced another builder; already fresh
        base = self.bm25.csr_snapshot()
        n = len(base["row_ids"])
        if n < self.min_n:
            self._snap = None
            return False
        s_n = self.n_shards
        base_rows = -(-n // s_n)  # ceil
        c_local = pad_dim(base_rows)
        offsets = base["offsets"]
        post_doc = base["post_doc"]
        post_tf = base["post_tf"]
        n_terms = len(base["terms"])

        if s_n == 1:
            off_sh = offsets[None, :]
            doc_parts = [post_doc]
            tf_parts = [post_tf]
        else:
            # split every term's (ascending-row) posting range at the
            # shard boundaries; rows become shard-local
            off_sh = np.zeros((s_n, n_terms + 1), dtype=np.int64)
            doc_lists: List[List[np.ndarray]] = [[] for _ in range(s_n)]
            tf_lists: List[List[np.ndarray]] = [[] for _ in range(s_n)]
            edges = np.asarray(
                [sh * base_rows for sh in range(s_n + 1)], dtype=np.int64)
            for ti in range(n_terms):
                lo, hi = offsets[ti], offsets[ti + 1]
                docs = post_doc[lo:hi]
                tfs = post_tf[lo:hi]
                bounds = np.searchsorted(docs, edges)
                for sh in range(s_n):
                    a, bnd = bounds[sh], bounds[sh + 1]
                    doc_lists[sh].append(docs[a:bnd] - sh * base_rows)
                    tf_lists[sh].append(tfs[a:bnd])
                    off_sh[sh, ti + 1] = off_sh[sh, ti] + (bnd - a)
            doc_parts = [
                np.concatenate(dl) if dl else np.zeros(0, np.int32)
                for dl in doc_lists]
            tf_parts = [
                np.concatenate(tl) if tl else np.zeros(0, np.float32)
                for tl in tf_lists]

        p_cap = pad_dim(max(max(len(d) for d in doc_parts), 1))
        pd_all = np.zeros((s_n, p_cap), dtype=np.int32)
        pt_all = np.zeros((s_n, p_cap), dtype=np.float32)
        for sh in range(s_n):
            pd_all[sh, : len(doc_parts[sh])] = doc_parts[sh]
            pt_all[sh, : len(tf_parts[sh])] = tf_parts[sh]

        doc_len_all = np.zeros(s_n * c_local, dtype=np.float32)
        alive_all = np.zeros(s_n * c_local, dtype=np.float32)
        row_ids_all: List[Optional[str]] = [None] * (s_n * c_local)
        slot_all = np.full(s_n * c_local, -1, dtype=np.int64)
        for sh in range(s_n):
            lo, hi = sh * base_rows, min((sh + 1) * base_rows, n)
            if lo >= hi:
                continue
            cnt = hi - lo
            doc_len_all[sh * c_local: sh * c_local + cnt] = \
                base["doc_len"][lo:hi]
            alive_all[sh * c_local: sh * c_local + cnt] = 1.0
            row_ids_all[sh * c_local: sh * c_local + cnt] = \
                base["row_ids"][lo:hi]
            slot_all[sh * c_local: sh * c_local + cnt] = \
                base["slots"][lo:hi]

        # quantized CSR columns (PR 8 headroom): tf and doc-len are
        # integer counts, so uint16 storage is EXACTLY lossless below
        # 65536 (the kernel casts to f32 after the gather; idf stays
        # exact from the host plan's live-df counters). A column whose
        # max clears the range keeps f32 — degrade is per column and
        # the score arithmetic is bit-identical either way.
        tf_dtype = np.float32
        dl_dtype = np.float32
        if self.quant_cols:
            if not pt_all.size or float(pt_all.max()) < 65536.0:
                tf_dtype = np.uint16
            if not doc_len_all.size or float(doc_len_all.max()) < 65536.0:
                dl_dtype = np.uint16
            if tf_dtype is np.uint16 or dl_dtype is np.uint16:
                _LEX_C.labels("quant_cols").inc()
        snap = {
            "n": n,
            "shards": s_n,
            "c_local": c_local,
            "built_compactions": base["compactions"],
            "vocab": base["vocab"],
            "off_sh": off_sh,
            "post_doc": jnp.asarray(pd_all.reshape(-1)),
            "post_tf": jnp.asarray(pt_all.reshape(-1).astype(tf_dtype)),
            "doc_len": jnp.asarray(doc_len_all.astype(dl_dtype)),
            "alive_np": alive_all,
            "alive": jnp.asarray(alive_all),
            "alive_gen": gen,
            "row_ids": row_ids_all,
            "slots": slot_all,
            "built_gen": gen,
            "cols_quant": 1.0 if (tf_dtype is np.uint16
                                  or dl_dtype is np.uint16) else 0.0,
        }
        if s_n > 1 and len(jax.devices()) >= s_n:
            # place the snapshot on the mesh ONCE (cagra discipline): a
            # persistent serving index never re-ships postings per batch
            from jax.sharding import NamedSharding, PartitionSpec

            from nornicdb_tpu.parallel.mesh import data_mesh

            mesh = data_mesh(s_n)
            snap["mesh"] = mesh
            sh1 = NamedSharding(mesh, PartitionSpec("data"))
            for key in ("post_doc", "post_tf", "doc_len", "alive"):
                snap[key] = jax.device_put(snap[key], sh1)
        self._snap = snap
        self.builds += 1
        _LEX_C.labels("build").inc()
        return True

    def _kick_background_rebuild(self) -> None:
        with self._rebuild_flag_lock:
            if self._rebuilding:
                return
            self._rebuilding = True
            self._rebuild_started = time.time()
        _LEX_C.labels("background_rebuild").inc()

        def run():
            from nornicdb_tpu import admission as _adm

            try:
                # background maintenance lane (ISSUE 15): any coalescer
                # ride from this thread seals behind interactive work
                with _adm.lane_scope(_adm.LANE_BACKGROUND):
                    self.build()
            finally:
                # same lock as the set above: an unguarded clear can
                # interleave with a concurrent kick's read-then-set
                with self._rebuild_flag_lock:
                    self._rebuilding = False
                    self._rebuild_started = 0.0

        t = threading.Thread(target=run, name="device-bm25-rebuild",
                             daemon=True)
        t.start()

    def ensure_snapshot(self) -> Optional[Dict[str, Any]]:
        """Current snapshot (possibly stale-but-correct), or None while
        the host index must serve. Mirrors cagra._ensure_graph."""
        snap = self._snap
        gen = self.bm25.mut_gen
        if snap is not None:
            churn = gen - snap["built_gen"]
            if churn > self.rebuild_stale_frac * max(snap["n"], 1):
                self._kick_background_rebuild()
            return snap
        if len(self.bm25) < self.min_n:
            return None
        if not self.build_inline:
            self._kick_background_rebuild()
            return self._snap
        self.build()
        return self._snap

    @property
    def snapshot_built(self) -> bool:
        return self._snap is not None

    def stats(self) -> Dict[str, Any]:
        snap = self._snap
        return {
            "n_alive": len(self.bm25),
            "snapshot_built": snap is not None,
            "snapshot_n": snap["n"] if snap else 0,
            "shards": snap["shards"] if snap else 0,
            "builds": self.builds,
            "cols_quant": snap.get("cols_quant", 0.0) if snap else 0.0,
        }

    def resource_stats(self) -> Dict[str, Any]:
        """Memory + freshness accounting for obs/resources.py: device
        bytes of the CSR columns (postings doc/tf + doc-len/alive
        vectors), the mutation-generation gap between the live host
        index and the snapshot, and the rebuild backlog state."""
        snap = self._snap
        dev_b = 0
        rows = 0
        capacity = 0
        if snap is not None:
            for key in ("post_doc", "post_tf", "doc_len", "alive"):
                dev_b += int(getattr(snap[key], "nbytes", 0) or 0)
            rows = snap["n"]
            capacity = snap["shards"] * snap["c_local"]
        gen = self.bm25.mut_gen
        gap = (gen - snap["built_gen"]) if snap is not None else 0
        started = self._rebuild_started
        return {
            "rows": rows,
            "capacity": capacity,
            "device_bytes": dev_b,
            # host-side offset table + row-id/slot columns
            "host_bytes": (
                (snap["off_sh"].nbytes + snap["slots"].nbytes
                 + 8 * len(snap["row_ids"])) if snap is not None else 0),
            "mutation_gap": gap,
            "rebuild_in_flight": 1.0 if self._rebuilding else 0.0,
            "rebuild_backlog_s": (
                round(time.time() - started, 3)
                if self._rebuilding and started else 0.0),
            "builds": self.builds,
        }

    # -- shared snapshot plumbing -----------------------------------------

    def row_map(self, snap: Dict[str, Any], name: str, token: Any,
                derive) -> Optional[jnp.ndarray]:
        """Memoized ``snapshot lex row -> foreign row`` device map.

        The fused hybrid tiers join lexical candidates to another
        index's row space — the brute slot space (``l2v``, matmul tier)
        or the CAGRA graph row space (``l2g``, walk tier). Both maps
        live ON the snapshot dict under one lock, keyed by ``token``
        (the foreign index's generation: brute mutation counter, graph
        build sequence — MONOTONE integers, which is what lets the
        publish step below refuse cross-generation overwrites), so a
        snapshot rebuild drops every map with it and a foreign rebuild
        rebinds on the next batch instead of surviving stale.
        ``derive()`` returns the int32 host column or None when the
        foreign index moved mid-derivation (the caller retries next
        batch — a stale map can never mis-join silently).
        """
        with self._map_lock:
            maps = snap.setdefault("row_maps", {})
            cur = maps.get(name)
            if cur is not None and cur[0] == token:
                return cur[1]
        # derive OUTSIDE the lock: the l2g derivation is O(corpus)
        # host work + a device transfer, and holding the lock for it
        # would convoy every concurrent batch that only needs to READ
        # an already-cached map. Racing derivers duplicate rare work;
        # the double-check below keeps one winner.
        raw = derive()
        if raw is None:
            return None
        dev = jnp.asarray(np.asarray(raw, dtype=np.int32))
        if "mesh" in snap:
            from jax.sharding import NamedSharding, PartitionSpec

            dev = jax.device_put(
                dev, NamedSharding(snap["mesh"],
                                   PartitionSpec("data")))
        with self._map_lock:
            maps = snap.setdefault("row_maps", {})
            cur = maps.get(name)
            if cur is not None and cur[0] == token:
                return cur[1]  # raced another deriver; theirs serves
            if cur is not None and cur[0] > token:
                # a newer-generation map was published while we
                # derived: OUR batch still needs the map matching its
                # captured view, but storing it would evict the newer
                # one and force the next batch to re-derive
                return dev
            maps[name] = (token, dev)
            return dev

    # -- freshness --------------------------------------------------------

    def refresh_alive(self, snap: Dict[str, Any]) -> None:
        """Re-derive the device alive vector from per-SLOT liveness when
        the host index mutated. Slot-level (not ext-id) membership is
        load-bearing: a re-indexed doc tombstones its old slot while the
        ext id stays live — the old row must die here and the new one
        arrives via the delta side-scan, or results would carry both.
        Raises :class:`SnapshotStale` when a compaction remapped the
        slot space mid-request (the liveness read and the compaction
        check share one lock hold, so a resurrected slot id can never
        slip through)."""
        gen = self.bm25.mut_gen
        if snap["alive_gen"] == gen:
            return
        with self._alive_lock:
            if snap["alive_gen"] == gen:
                return
            alive = snap["alive_np"].copy()
            rows = np.nonzero(alive)[0]
            if rows.size:
                live = self.bm25.alive_slots(
                    snap["slots"][rows],
                    expect_compactions=snap["built_compactions"])
                if live is None:
                    raise SnapshotStale
                alive[rows] = live.astype(np.float32)
            dev = jnp.asarray(alive)
            if "mesh" in snap:
                from jax.sharding import NamedSharding, PartitionSpec

                dev = jax.device_put(
                    dev, NamedSharding(snap["mesh"],
                                       PartitionSpec("data")))
            snap["alive"] = dev
            snap["alive_gen"] = gen

    def delta_block(self, snap: Dict[str, Any]) -> Optional[List[str]]:
        """ext ids added/updated since the snapshot build (host
        side-scan scores them exactly). None = changelog trimmed or
        slots remapped — caller must serve host-exact and a rebuild is
        kicked. Memoized on the mutation counter."""
        m = self.bm25.mut_gen
        cached = self._delta_cache
        if cached is not None and cached[0] == m \
                and cached[1] == snap["built_gen"]:
            return cached[2]
        ids = self.bm25.changed_since(snap["built_gen"])
        self._delta_cache = (m, snap["built_gen"], ids)
        return ids

    # -- planning (host side of a batch) ----------------------------------

    def plan(
        self,
        snap: Dict[str, Any],
        token_rows: Sequence[Sequence[str]],
        b_bucket: int,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.float32]:
        """Flatten a tokenized query batch into pow2-padded entry
        columns (ptr, unique-term row) sharded like the snapshot, plus
        the [B, U] idf-weighted selection matrix and current avgdl.

        Terms are DEDUPED across the whole batch (each unique term's
        postings flatten once, however many coalesced queries share it)
        and idf comes from the incremental live-df counters, so deletes
        correct df without a rebuild."""
        vocab = snap["vocab"]
        off_sh = snap["off_sh"]
        s_n = snap["shards"]
        uniq_all = sorted({t for row in token_rows for t in row})
        dfs, n_alive, avgdl = self.bm25.term_stats(uniq_all)
        self._plan_cost.stats = (float(sum(dfs.values())),
                                 len(uniq_all))
        n = max(n_alive, 1)
        # unique scoring terms, in sorted order (the host accumulation
        # order); their idf rides the selection matrix
        terms: List[str] = []
        idfs: List[np.float32] = []
        u_of: Dict[str, int] = {}
        for t in uniq_all:
            df = dfs.get(t, 0)
            if df > 0 and t in vocab:
                u_of[t] = len(terms)
                terms.append(t)
                idfs.append(np.float32(
                    math.log(1.0 + (n - df + 0.5) / (df + 0.5))))
        u_b = pow2_bucket(max(len(terms), 1))
        # the device segment id is urow * C + doc in int32 (jax default
        # index width; segment_sum silently drops out-of-range ids) —
        # refuse to plan a batch whose id space would wrap
        if (u_b + 1) * snap["c_local"] > 2**31 - 1:
            raise PlanOverflow
        sel = np.zeros((b_bucket, u_b), dtype=np.float32)
        for qi, row in enumerate(token_rows):
            for t in set(row):
                ui = u_of.get(t)
                if ui is not None:
                    sel[qi, ui] = idfs[ui]
        ptr_lists: List[List[np.ndarray]] = [[] for _ in range(s_n)]
        urow_lists: List[List[int]] = [[] for _ in range(s_n)]
        cnt_lists: List[List[int]] = [[] for _ in range(s_n)]
        for ui, t in enumerate(terms):
            ti = vocab[t]
            for sh in range(s_n):
                a, bnd = int(off_sh[sh, ti]), int(off_sh[sh, ti + 1])
                if bnd > a:
                    ptr_lists[sh].append(
                        np.arange(a, bnd, dtype=np.int32))
                    urow_lists[sh].append(ui)
                    cnt_lists[sh].append(bnd - a)
        totals = [sum(c) for c in cnt_lists]
        p_b = pow2_bucket(max(max(totals), 1) if totals else 1)
        ptr = np.zeros((s_n, p_b), dtype=np.int32)
        # pad entries target the overflow row U (discarded on device)
        urow = np.full((s_n, p_b), u_b, dtype=np.int32)
        for sh in range(s_n):
            if not cnt_lists[sh]:
                continue
            ptr[sh, : totals[sh]] = np.concatenate(ptr_lists[sh])
            urow[sh, : totals[sh]] = np.repeat(
                np.asarray(urow_lists[sh], dtype=np.int32),
                np.asarray(cnt_lists[sh]))
        return (ptr.reshape(-1), urow.reshape(-1), sel,
                np.float32(avgdl))

    # -- dispatch ---------------------------------------------------------

    def topk_device(
        self,
        snap: Dict[str, Any],
        token_rows: Sequence[Sequence[str]],
        k: int,
        b_bucket: int,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Scores + global row ids [b_bucket, k] for a tokenized batch
        (rows beyond len(token_rows) are planning no-ops)."""
        ptr, urow, sel, avgdl = self.plan(snap, token_rows, b_bucket)
        args = (jnp.asarray(ptr), jnp.asarray(urow), jnp.asarray(sel),
                snap["post_doc"], snap["post_tf"], snap["doc_len"],
                snap["alive"], jnp.float32(avgdl))
        s_n = snap["shards"]
        if s_n == 1:
            s, i = _bm25_topk(*args, k=k)
        elif "mesh" in snap and len(jax.devices()) >= s_n:
            from nornicdb_tpu.parallel.mesh import _MeshHolder

            s, i = _sharded_bm25_impl(
                *args, k=k, mesh_holder=_MeshHolder(snap["mesh"]))
        else:
            s, i = self._topk_shards_single_device(snap, args, k)
        return np.asarray(s), np.asarray(i)

    def _topk_shards_single_device(self, snap, args, k):
        """Reference merge for the sharded layout on one device: score
        each shard's local rows, concatenate shard-local winners in
        shard order (exactly the all-gather layout) and take one global
        top-k. The mesh path must be bit-identical to this."""
        ptr, urow, sel, pd, pt, dl, al, avgdl = args
        s_n = snap["shards"]
        c_local = snap["c_local"]
        p_b = ptr.shape[0] // s_n
        p_cap = pd.shape[0] // s_n
        k_local = min(k, c_local)
        parts_s, parts_i = [], []
        for sh in range(s_n):
            s, i = _bm25_local_topk(
                ptr[sh * p_b:(sh + 1) * p_b],
                urow[sh * p_b:(sh + 1) * p_b],
                sel,
                pd[sh * p_cap:(sh + 1) * p_cap],
                pt[sh * p_cap:(sh + 1) * p_cap],
                dl[sh * c_local:(sh + 1) * c_local],
                al[sh * c_local:(sh + 1) * c_local],
                avgdl, jnp.int32(sh * c_local),
                k_local=k_local)
            parts_s.append(s)
            parts_i.append(i)
        return concat_topk(parts_s, parts_i, k)

    # -- search -----------------------------------------------------------

    def search(self, query: str, k: int = 10) -> List[Tuple[str, float]]:
        return self.search_batch([query], k)[0]

    def search_batch(
        self, queries: Sequence[str], k: int = 10
    ) -> List[List[Tuple[str, float]]]:
        """Batched BM25 top-k; same contract as
        :meth:`BM25Index.search_batch`, so callers swap host and device
        paths freely. Serves host-exact whenever the snapshot is
        missing or its changelog was overrun."""
        queries = list(queries)
        if not queries:
            return []
        snap = self.ensure_snapshot()
        if snap is None:
            return self.bm25.search_batch(queries, k)
        delta = self.delta_block(snap)
        if delta is None:
            _LEX_C.labels("host_fallback_changelog").inc()
            self._kick_background_rebuild()
            return self.bm25.search_batch(queries, k)
        token_rows = [tokenize(q) for q in queries]
        b = len(queries)
        bb = pow2_bucket(b)
        c_total = snap["shards"] * snap["c_local"]
        kb = min(pow2_bucket(max(min(k, snap["n"]), 1)), c_total)
        t0 = time.time()
        try:
            self.refresh_alive(snap)
            s, i = self.topk_device(snap, token_rows, kb, bb)
        except SnapshotStale:
            _LEX_C.labels("host_fallback_compaction").inc()
            self._kick_background_rebuild()
            return self.bm25.search_batch(queries, k)
        except PlanOverflow:
            _LEX_C.labels("host_fallback_overflow").inc()
            return self.bm25.search_batch(queries, k)
        record_dispatch("bm25_score", bb, kb, time.time() - t0)
        # per-query cost: the CSR nnz actually gathered is the batch's
        # unique-term posting mass (the scatter runs once per unique
        # term), plus the [B, U] x [U, C] idf-weighted score matmul.
        # Best-effort and gated — pricing must never fail or slow a
        # search with telemetry off
        from nornicdb_tpu.obs import cost as _cost

        if _cost.pricing_enabled():
            try:
                nnz, u = self._plan_cost.stats  # stashed by plan()
                flops, byts = _cost.price_bm25(bb, nnz, u, c_total)
                _cost.record_query_cost(
                    "bm25_score", _cost.cost_name(self), b, flops, byts)
            except Exception:  # noqa: BLE001
                pass
        out = self._resolve(snap, s[:b], i[:b], min(k, kb))
        if delta:
            _LEX_C.labels("delta_merge").inc()
            out = self._merge_delta(out, delta, token_rows, k)
        return out

    def _resolve(self, snap, s, i, k_eff):
        row_ids = snap["row_ids"]
        out: List[List[Tuple[str, float]]] = []
        for r in range(s.shape[0]):
            hits: List[Tuple[str, float]] = []
            for c in range(s.shape[1]):
                if s[r, c] < 0.5 * NEG_INF:
                    break
                eid = row_ids[int(i[r, c])]
                if eid is None:
                    continue
                hits.append((eid, float(s[r, c])))
                if len(hits) >= k_eff:
                    break
            out.append(hits)
        return out

    def _merge_delta(self, rows, delta_ids, token_rows, k):
        """Exact-score docs indexed since the snapshot and merge them in
        (read-your-writes). An updated doc's old row died in the alive
        refresh, so drop any same-id device entry defensively and let
        the fresh host score stand. Stable sort keeps device-rank order
        on exact ties, matching the host reference's slot order."""
        dset = set(delta_ids)
        out: List[List[Tuple[str, float]]] = []
        for qi, hits in enumerate(rows):
            fresh = self.bm25.score_docs(token_rows[qi], delta_ids)
            merged = [(eid, sc) for eid, sc in hits if eid not in dset]
            merged.extend(sorted(fresh.items()))
            merged.sort(key=lambda kv: -kv[1])
            out.append(merged[:k])
        return out
