"""Fused hybrid search: BM25 + vector + RRF in one compiled pipeline.

Reference: pkg/search Service.Search (search.go:2841) fuses BM25 and
vector candidate lists with (weighted) RRF — in this repo that fusion,
and the whole lexical half, ran as host Python under the BM25 lock.
This module executes the complete hybrid read path on device: one
jitted program takes a query batch's embeddings and planned lexical
entries and emits the RRF-fused top-k, with the per-source candidate
lists along for the ride (the service's min_score gates and result
payloads need the raw scores).

Pipeline (single compile per pow2 ``(B, k)`` bucket):

1. **lexical** — ``device_bm25.bm25_dense_scores`` over the CSR
   snapshot -> top-k rows;
2. **vector** — one MXU matmul over the brute index's device matrix
   (the same lazily-synced arrays ``BruteForceIndex.search_batch``
   dispatches against, so the vector side is always write-fresh) ->
   top-k slots;
3. **fuse** — the two candidate lists join on a device-resident
   ``lexical row -> vector slot`` map (docs in both sources must merge
   into ONE fused candidate), reciprocal-rank weights accumulate in
   float32 in source-major order — bit-identical to the host
   ``rrf.rrf_fuse`` — and one final top-k emits the fused ranking.
   Ties resolve by concatenated position = (source, rank), exactly the
   host fuse's deterministic ordering.

Sharding row-shards BOTH corpora over the ``data`` mesh axis: each
shard scores its lexical rows and vector slots locally, one all-gather
+ top-k per source merges shard winners, and the fuse then runs
replicated — bit-identical to the single-device shard-loop reference
(same collective pattern as cagra and ``mesh.sharded_cosine_topk``).

Freshness composes the PR 2 ladder: the lexical snapshot rebuilds in
the background on churn with tombstones alive-filtered (df corrected)
and adds/updates exact-scored by the host delta side-scan; the vector
side needs no snapshot (the brute matrix is the live index); the
row->slot join map re-derives whenever the brute index mutates, so
compactions can never mis-join. Any freshness gap degrades to the
host path — never to a wrong answer.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nornicdb_tpu.obs import REGISTRY, record_dispatch
from nornicdb_tpu.ops.similarity import NEG_INF, l2_normalize
from nornicdb_tpu.search.bm25 import BM25Index
from nornicdb_tpu.search.device_bm25 import (
    DeviceBM25,
    PlanOverflow,
    SnapshotStale,
    bm25_dense_scores,
)
from nornicdb_tpu.search.microbatch import pow2_bucket
from nornicdb_tpu.search.rrf import DEFAULT_RRF_K, rrf_fuse
from nornicdb_tpu.search.vector_index import BruteForceIndex

_HYB_C = REGISTRY.counter(
    "nornicdb_hybrid_fused_events_total",
    "Fused hybrid pipeline dispatches and freshness decisions",
    labels=("event",))


# ---------------------------------------------------------------------------
# pure device fusion
# ---------------------------------------------------------------------------


def _pad_cols(x: jnp.ndarray, k: int, fill) -> jnp.ndarray:
    if x.shape[1] >= k:
        return x
    pad = jnp.full((x.shape[0], k - x.shape[1]), fill, dtype=x.dtype)
    return jnp.concatenate([x, pad], axis=1)


def rrf_fuse_device(
    ls: jnp.ndarray,  # [B, kq] lexical scores, NEG_INF padded
    lid: jnp.ndarray,  # [B, kq] vector slot per lexical hit (-1 = none)
    lgrow: jnp.ndarray,  # [B, kq] global lexical row ids
    vs: jnp.ndarray,  # [B, kq] vector scores
    vi: jnp.ndarray,  # [B, kq] vector slots
    n_cand: jnp.ndarray,  # [B] per-request candidate depth (overfetch)
    w_lex: jnp.ndarray,  # [B]
    w_vec: jnp.ndarray,  # [B]
    rrf_k: int,
    c_vec: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Weighted RRF over the concatenated candidate lists. Docs present
    in both sources join via ``lid`` and keep their FIRST (lexical)
    position; per-candidate sums accumulate float32 source-major, so the
    result is bit-identical to host ``rrf_fuse`` on the same lists.
    Returns (fused scores [B, 2kq], concat positions [B, 2kq])."""
    b, kq = ls.shape
    r = jnp.arange(kq)
    in_cand = r[None, :] < n_cand[:, None]
    lval = (ls > 0.5 * NEG_INF) & in_cand
    vval = (vs > 0.5 * NEG_INF) & in_cand
    # shared candidate id space: vector slot when the lexical doc has a
    # vector, else a unique id past the vector capacity
    cid = jnp.concatenate(
        [jnp.where(lid >= 0, lid, c_vec + lgrow), vi], axis=1)
    val = jnp.concatenate([lval, vval], axis=1)
    inv = (rrf_k + 1.0 + r).astype(jnp.float32)
    w = jnp.concatenate(
        [w_lex[:, None] / inv[None, :], w_vec[:, None] / inv[None, :]],
        axis=1)
    w = jnp.where(val, w, 0.0)
    match = (cid[:, :, None] == cid[:, None, :]) \
        & val[:, :, None] & val[:, None, :]
    # each row of `match` has at most two hits (one per source), so the
    # einsum sum is a plain two-term float32 add — no reassociation
    fused = jnp.einsum("bij,bj->bi", match.astype(jnp.float32), w)
    m2 = jnp.arange(2 * kq)
    dup = jnp.any(match & (m2[None, None, :] < m2[None, :, None]), axis=2)
    fused = jnp.where(val & ~dup, fused, NEG_INF)
    return jax.lax.top_k(fused, 2 * kq)


@functools.partial(jax.jit, static_argnames=("kq", "rrf_k"))
def _fused_single(ptr, urow, sel, post_doc, post_tf, doc_len, alive_f,
                  l2v, avgdl, qn, vmatrix, vvalid, n_cand, w_lex, w_vec,
                  kq, rrf_k):
    c_lex = doc_len.shape[0]
    c_vec = vmatrix.shape[0]
    dense = bm25_dense_scores(ptr, urow, sel, post_doc, post_tf,
                              doc_len, alive_f, avgdl)
    ls, li = jax.lax.top_k(dense, min(kq, c_lex))
    vsc = qn @ vmatrix.T
    vsc = jnp.where(vvalid[None, :], vsc, NEG_INF)
    vs, vi = jax.lax.top_k(vsc, min(kq, c_vec))
    ls = _pad_cols(ls, kq, NEG_INF)
    li = _pad_cols(li, kq, 0)
    vs = _pad_cols(vs, kq, NEG_INF)
    vi = _pad_cols(vi, kq, 0)
    fs, fpos = rrf_fuse_device(ls, l2v[li], li, vs, vi, n_cand,
                               w_lex, w_vec, rrf_k, c_vec)
    return ls, li, vs, vi, fs, fpos


def _local_parts_impl(ptr, urow, sel, post_doc, post_tf, doc_len,
                      alive_f, l2v, avgdl, qn, vmatrix, vvalid, lex_off,
                      vec_off, kq):
    """One shard's per-source top-k with globalized ids — the building
    block of both the single-device reference loop and the mesh path."""
    c_lex = doc_len.shape[0]
    c_vec = vmatrix.shape[0]
    dense = bm25_dense_scores(ptr, urow, sel, post_doc, post_tf,
                              doc_len, alive_f, avgdl)
    ls, li = jax.lax.top_k(dense, min(kq, c_lex))
    vsc = qn @ vmatrix.T
    vsc = jnp.where(vvalid[None, :], vsc, NEG_INF)
    vs, vi = jax.lax.top_k(vsc, min(kq, c_vec))
    return ls, l2v[li], li + lex_off, vs, vi + vec_off


_local_parts = functools.partial(
    jax.jit, static_argnames=("kq",))(_local_parts_impl)


def _merge_parts(parts, kq):
    """Concat per-shard (scores, aux...) blocks in shard order and take
    one top-k, gathering every aux column by the winning positions —
    the all-gather-equivalent merge layout."""
    all_s = jnp.concatenate([p[0] for p in parts], axis=1)
    auxes = [jnp.concatenate([p[j] for p in parts], axis=1)
             for j in range(1, len(parts[0]))]
    k = min(kq, all_s.shape[1])
    top_s, pos = jax.lax.top_k(all_s, k)
    out = [_pad_cols(top_s, kq, NEG_INF)]
    for a in auxes:
        out.append(_pad_cols(jnp.take_along_axis(a, pos, axis=1), kq, 0))
    return out


@functools.partial(
    jax.jit, static_argnames=("kq", "rrf_k", "c_vec_total"))
def _fuse_merged(ls, lid, lgrow, vs, vi, n_cand, w_lex, w_vec, kq,
                 rrf_k, c_vec_total):
    return rrf_fuse_device(ls, lid, lgrow, vs, vi, n_cand, w_lex, w_vec,
                           rrf_k, c_vec_total)


@functools.partial(
    jax.jit, static_argnames=("kq", "rrf_k", "mesh_holder"))
def _fused_sharded_impl(ptr, urow, sel, post_doc, post_tf, doc_len,
                        alive_f, l2v, avgdl, qn, vmatrix, vvalid,
                        n_cand, w_lex, w_vec, kq, rrf_k, mesh_holder):
    from jax.sharding import PartitionSpec as P

    from nornicdb_tpu.parallel.mesh import compat_shard_map

    mesh = mesh_holder.mesh
    s_n = mesh.shape["data"]
    c_lex_local = doc_len.shape[0] // s_n
    c_vec_local = vmatrix.shape[0] // s_n
    c_vec_total = vmatrix.shape[0]

    def local_fn(ptr_s, urow_s, sel_r, pd_s, pt_s, dl_s, al_s, l2v_s,
                 avg_r, qn_r, vm_s, vv_s, nc_r, wl_r, wv_r):
        sh = jax.lax.axis_index("data")
        ls, lid, lgrow, vs, gvi = _local_parts_impl(
            ptr_s, urow_s, sel_r, pd_s, pt_s, dl_s, al_s, l2v_s, avg_r,
            qn_r, vm_s, vv_s, sh * c_lex_local, sh * c_vec_local,
            kq=kq)

        def gat(x):
            return jax.lax.all_gather(x, "data", axis=1, tiled=True)

        ls2, lid2, lgrow2 = _merge_parts(
            [(gat(ls), gat(lid), gat(lgrow))], kq)
        vs2, vi2 = _merge_parts([(gat(vs), gat(gvi))], kq)
        fs, fpos = rrf_fuse_device(ls2, lid2, lgrow2, vs2, vi2, nc_r,
                                   wl_r, wv_r, rrf_k, c_vec_total)
        return ls2, lgrow2, vs2, vi2, fs, fpos

    return compat_shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P(), P("data"), P("data"),
                  P("data"), P("data"), P("data"), P(), P(),
                  P("data", None), P("data"), P(), P(), P()),
        out_specs=(P(), P(), P(), P(), P(), P()),
    )(ptr, urow, sel, post_doc, post_tf, doc_len, alive_f, l2v,
      avgdl, qn, vmatrix, vvalid, n_cand, w_lex, w_vec)


# ---------------------------------------------------------------------------
# the pipeline object
# ---------------------------------------------------------------------------


class FusedHybrid:
    """Device-fused hybrid search over a (BM25Index, BruteForceIndex)
    pair. Stateless beyond the lexical snapshot (owned by
    :class:`DeviceBM25`) and the lexical-row -> vector-slot join map;
    both re-derive from the live host indexes, which remain the
    mutable sources of truth."""

    def __init__(
        self,
        bm25: BM25Index,
        brute: BruteForceIndex,
        n_shards: int = 1,
        min_n: int = 256,
        rebuild_stale_frac: float = 0.1,
        build_inline: bool = True,
        rrf_k: int = DEFAULT_RRF_K,
    ):
        self.bm25 = bm25
        self.brute = brute
        self.rrf_k = rrf_k
        self.n_shards = max(1, n_shards)
        self.lex = DeviceBM25(
            bm25, n_shards=self.n_shards, min_n=min_n,
            rebuild_stale_frac=rebuild_stale_frac,
            build_inline=build_inline)
        self._map_lock = threading.Lock()
        # sharded placement cache for the brute device arrays, keyed on
        # the array object identity (BruteForceIndex recreates it on
        # mutation) — a persistent serving index never re-ships the
        # corpus across devices per batch
        self._vec_placed: Optional[Tuple] = None

    def build(self) -> bool:
        return self.lex.build()

    @property
    def ready(self) -> bool:
        return self.lex.snapshot_built

    def ensure(self) -> bool:
        """Have (or start building) a lexical snapshot; False while the
        host path must serve."""
        return self.lex.ensure_snapshot() is not None

    # -- freshness helpers ------------------------------------------------

    def _ensure_map(self, snap: Dict[str, Any], mutations: int):
        """Device lex-row -> vector-slot map consistent with the brute
        matrix at generation ``mutations``, or None when a concurrent
        write/compaction moved the matrix on from the captured view —
        slots_of pins the read to the expected generation under the
        brute lock, so a remap can never mis-join silently."""
        with self._map_lock:
            if snap.get("l2v_mut") == mutations and "l2v" in snap:
                return snap["l2v"]
            ids = ["" if e is None else e for e in snap["row_ids"]]
            raw = self.brute.slots_of(ids, expect_mutations=mutations)
            if raw is None:
                return None
            slots = np.asarray(raw, dtype=np.int32)
            dev = jnp.asarray(slots)
            if "mesh" in snap:
                from jax.sharding import NamedSharding, PartitionSpec

                dev = jax.device_put(
                    dev, NamedSharding(snap["mesh"],
                                       PartitionSpec("data")))
            snap["l2v"] = dev
            snap["l2v_mut"] = mutations
            return dev

    def _vec_arrays(self, m, valid, snap):
        if snap["shards"] == 1 or "mesh" not in snap:
            return m, valid
        if m.shape[0] % snap["shards"] != 0:
            return None, None  # capacity not shardable; caller falls back
        cached = self._vec_placed
        if cached is not None and cached[0] is m:
            return cached[1], cached[2]
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = snap["mesh"]
        mp = jax.device_put(m, NamedSharding(mesh, P("data", None)))
        vp = jax.device_put(valid, NamedSharding(mesh, P("data")))
        self._vec_placed = (m, mp, vp)
        return mp, vp

    # -- search -----------------------------------------------------------

    def search_batch(
        self,
        queries_emb: np.ndarray,
        kq: int,
        extras: Sequence[Dict[str, Any]],
    ) -> List[Optional[Dict[str, Any]]]:
        """One fused dispatch for a coalesced hybrid batch.

        ``extras[i]`` carries the non-stackable half of request i:
        ``tokens`` (tokenized query), ``n_cand`` (its overfetch depth)
        and ``w`` ((w_lex, w_vec) fusion weights). Returns one row per
        query: dict with ``lex``/``vec``/``fused`` ranked lists and the
        shared stage ``times``, or None when the device path must not
        serve this batch (caller falls back to the host path)."""
        b = len(queries_emb)
        none_rows: List[Optional[Dict[str, Any]]] = [None] * b
        snap = self.lex.ensure_snapshot()
        if snap is None:
            return none_rows
        delta = self.lex.delta_block(snap)
        if delta is None:
            _HYB_C.labels("host_fallback_changelog").inc()
            self.lex._kick_background_rebuild()
            return none_rows
        view = self.brute.device_view()
        if view is None:
            return none_rows
        t_plan0 = time.time()
        m, valid, vec_ext, mutations, _compactions = view
        try:
            l2v = self._ensure_map(snap, mutations)
            if l2v is None:
                # a write/compaction moved the brute matrix between the
                # view capture and the map read — retry next batch
                _HYB_C.labels("host_fallback_vec_race").inc()
                return none_rows
            self.lex.refresh_alive(snap)
            token_rows = [e["tokens"] for e in extras]
            ptr, urow, sel, avgdl = self.lex.plan(snap, token_rows, b)
        except SnapshotStale:
            _HYB_C.labels("host_fallback_compaction").inc()
            self.lex._kick_background_rebuild()
            return none_rows
        except PlanOverflow:
            _HYB_C.labels("host_fallback_overflow").inc()
            return none_rows
        n_cand = np.asarray(
            [int(e["n_cand"]) for e in extras], dtype=np.int32)
        w_lex = np.asarray([e["w"][0] for e in extras], dtype=np.float32)
        w_vec = np.asarray([e["w"][1] for e in extras], dtype=np.float32)
        qn = l2_normalize(jnp.asarray(queries_emb, dtype=jnp.float32))
        args = (jnp.asarray(ptr), jnp.asarray(urow), jnp.asarray(sel),
                snap["post_doc"], snap["post_tf"], snap["doc_len"],
                snap["alive"], l2v, jnp.float32(avgdl), qn)
        tail = (jnp.asarray(n_cand), jnp.asarray(w_lex),
                jnp.asarray(w_vec))
        t0 = time.time()
        if snap["shards"] == 1:
            ls, li, vs, vi, fs, fpos = _fused_single(
                *args, jnp.asarray(m), jnp.asarray(valid), *tail,
                kq=kq, rrf_k=self.rrf_k)
            lgrow = li
        elif "mesh" in snap and len(jax.devices()) >= snap["shards"]:
            mp, vp = self._vec_arrays(m, valid, snap)
            if mp is None:
                _HYB_C.labels("host_fallback_unshardable").inc()
                return none_rows
            ls, lgrow, vs, vi, fs, fpos = _fused_sharded_impl(
                *args, mp, vp, *tail, kq=kq, rrf_k=self.rrf_k,
                mesh_holder=_holder(snap["mesh"]))
        else:
            ls, lgrow, vs, vi, fs, fpos = self._shard_loop(
                snap, args, m, valid, tail, kq)
        # force to host inside the timed window (async dispatch)
        ls, lgrow = np.asarray(ls), np.asarray(lgrow)
        vs, vi = np.asarray(vs), np.asarray(vi)
        fs, fpos = np.asarray(fs), np.asarray(fpos)
        t1 = time.time()
        record_dispatch("hybrid_fused", pow2_bucket(b), kq, t1 - t0)
        _HYB_C.labels("dispatch").inc()
        out = self._decode(snap, vec_ext, delta, token_rows, extras,
                           ls, lgrow, vs, vi, fs, fpos, kq)
        times = {"plan_s": t0 - t_plan0, "device_t0": t0,
                 "device_t1": t1, "decode_s": time.time() - t1}
        for row in out:
            if row is not None:
                row["times"] = times
        return out

    def _shard_loop(self, snap, args, m, valid, tail, kq):
        """Single-device reference for the sharded layout: run every
        shard's local parts, merge in shard order (the all-gather
        layout), fuse once. The mesh path must match this bit-for-bit."""
        ptr, urow, sel, pd, pt, dl, al, l2v, avgdl, qn = args
        n_cand, w_lex, w_vec = tail
        s_n = snap["shards"]
        c_local = snap["c_local"]
        p_b = ptr.shape[0] // s_n
        p_cap = pd.shape[0] // s_n
        mj, vj = jnp.asarray(m), jnp.asarray(valid)
        c_vec_local = mj.shape[0] // s_n
        lex_parts, vec_parts = [], []
        for sh in range(s_n):
            ls, lid, lgrow, vvs, gvi = _local_parts(
                ptr[sh * p_b:(sh + 1) * p_b],
                urow[sh * p_b:(sh + 1) * p_b],
                sel,
                pd[sh * p_cap:(sh + 1) * p_cap],
                pt[sh * p_cap:(sh + 1) * p_cap],
                dl[sh * c_local:(sh + 1) * c_local],
                al[sh * c_local:(sh + 1) * c_local],
                l2v[sh * c_local:(sh + 1) * c_local],
                avgdl, qn,
                mj[sh * c_vec_local:(sh + 1) * c_vec_local],
                vj[sh * c_vec_local:(sh + 1) * c_vec_local],
                jnp.int32(sh * c_local), jnp.int32(sh * c_vec_local),
                kq=kq)
            lex_parts.append((ls, lid, lgrow))
            vec_parts.append((vvs, gvi))
        ls2, lid2, lgrow2 = _merge_parts(lex_parts, kq)
        vs2, vi2 = _merge_parts(vec_parts, kq)
        fs, fpos = _fuse_merged(ls2, lid2, lgrow2, vs2, vi2, n_cand,
                                w_lex, w_vec, kq=kq, rrf_k=self.rrf_k,
                                c_vec_total=int(mj.shape[0]))
        return ls2, lgrow2, vs2, vi2, fs, fpos

    def _decode(self, snap, vec_ext, delta, token_rows, extras,
                ls, lgrow, vs, vi, fs, fpos, kq):
        row_ids = snap["row_ids"]
        out: List[Optional[Dict[str, Any]]] = []
        for r in range(len(extras)):
            n_cand = int(extras[r]["n_cand"])
            lex_hits: List[Tuple[str, float]] = []
            lex_by_pos: Dict[int, str] = {}
            for c in range(min(kq, ls.shape[1])):
                if ls[r, c] < 0.5 * NEG_INF or len(lex_hits) >= n_cand:
                    break
                eid = row_ids[int(lgrow[r, c])]
                if eid is None:
                    continue
                lex_by_pos[c] = eid
                lex_hits.append((eid, float(ls[r, c])))
            vec_hits: List[Tuple[str, float]] = []
            vec_by_pos: Dict[int, str] = {}
            for c in range(min(kq, vs.shape[1])):
                if vs[r, c] < 0.5 * NEG_INF or len(vec_hits) >= n_cand:
                    break
                eid = vec_ext[int(vi[r, c])]
                if eid is None:
                    continue
                vec_by_pos[c] = eid
                vec_hits.append((eid, float(vs[r, c])))
            if delta:
                # read-your-writes: exact host scores for post-snapshot
                # docs, then the (bit-compatible) host fuse over the
                # merged lists
                _HYB_C.labels("delta_merge").inc()
                dset = set(delta)
                fresh = self.bm25.score_docs(token_rows[r], delta)
                merged = [(e, s) for e, s in lex_hits if e not in dset]
                merged.extend(sorted(fresh.items()))
                merged.sort(key=lambda kv: -kv[1])
                lex_hits = merged[:n_cand]
                fused = rrf_fuse([lex_hits, vec_hits],
                                 weights=list(extras[r]["w"]),
                                 k=self.rrf_k, limit=n_cand)
            else:
                fused = []
                for c in range(fs.shape[1]):
                    if fs[r, c] < 0.5 * NEG_INF or len(fused) >= n_cand:
                        break
                    pos = int(fpos[r, c])
                    eid = (lex_by_pos.get(pos) if pos < kq
                           else vec_by_pos.get(pos - kq))
                    if eid is None:
                        continue
                    fused.append((eid, float(fs[r, c])))
            out.append({"lex": lex_hits, "vec": vec_hits,
                        "fused": fused})
        return out


def _holder(mesh):
    from nornicdb_tpu.parallel.mesh import _MeshHolder

    return _MeshHolder(mesh)
