"""Fused hybrid search: BM25 + vector + RRF in one compiled pipeline.

Reference: pkg/search Service.Search (search.go:2841) fuses BM25 and
vector candidate lists with (weighted) RRF — in this repo that fusion,
and the whole lexical half, ran as host Python under the BM25 lock.
This module executes the complete hybrid read path on device: one
jitted program takes a query batch's embeddings and planned lexical
entries and emits the RRF-fused top-k, with the per-source candidate
lists along for the ride (the service's min_score gates and result
payloads need the raw scores).

Pipeline (single compile per pow2 ``(B, k)`` bucket):

1. **lexical** — ``device_bm25.bm25_dense_scores`` over the CSR
   snapshot -> top-k rows;
2. **vector** — one of two tiers. The **brute tier**: one MXU matmul
   over the brute index's device matrix (the same lazily-synced arrays
   ``BruteForceIndex.search_batch`` dispatches against, so the vector
   side is always write-fresh) -> top-k slots. The **walk tier**
   (above ``walk_min_n`` live vectors): the jitted CAGRA greedy walk
   (``cagra._walk_body`` — fixed iterations, fixed ``itopk`` pool)
   over the device graph — sub-linear per query, which is what moves
   the corpus ceiling at which fusion wins (arXiv:2308.15136; the
   fused lexical+graph-ANN+fusion pipeline is the open frontier named
   by arXiv:2602.16719 §research-directions);
3. **fuse** — the two candidate lists join on a device-resident
   ``lexical row -> vector row`` map (brute slots for the matmul tier,
   graph rows for the walk tier; docs in both sources must merge into
   ONE fused candidate), reciprocal-rank weights accumulate in float32
   in source-major order — bit-identical to the host ``rrf.rrf_fuse``
   — and one final top-k emits the fused ranking. Ties resolve by
   concatenated position = (source, rank), exactly the host fuse's
   deterministic ordering.

Parity contract per tier: the brute tier is **rank-identical** to the
host hybrid path (the PR 4 parity corpus). The walk tier is
approximate by construction, so its contract is **walk-parity**: the
fused top-k must stay within recall@k tolerance of the host hybrid
ranking (bench + sentinel gate recall@10 >= 0.95 absolute), and every
freshness gap degrades DOWN the ladder — walk-fused -> brute-fused ->
host — never to a wrong answer.

Sharding row-shards BOTH corpora over the ``data`` mesh axis: each
shard scores its lexical rows and vector slots locally, one all-gather
+ top-k per source merges shard winners, and the fuse then runs
replicated — bit-identical to the single-device shard-loop reference
(same collective pattern as cagra and ``mesh.sharded_cosine_topk``).

Freshness composes the PR 2 ladder: the lexical snapshot rebuilds in
the background on churn with tombstones alive-filtered (df corrected)
and adds/updates exact-scored by the host delta side-scan; the vector
side needs no snapshot (the brute matrix is the live index); the
row->slot join map re-derives whenever the brute index mutates, so
compactions can never mis-join. Any freshness gap degrades to the
host path — never to a wrong answer.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nornicdb_tpu.obs import REGISTRY, declare_kind, record_dispatch
from nornicdb_tpu.obs import audit as _audit
from nornicdb_tpu.obs import cost as _cost
from nornicdb_tpu.ops.similarity import NEG_INF, l2_normalize
from nornicdb_tpu.search.bm25 import BM25Index
from nornicdb_tpu.search.cagra import (
    CagraIndex,
    _cagra_walk,
    _walk_body,
    merge_delta_hits,
)
from nornicdb_tpu.search.device_bm25 import (
    DeviceBM25,
    PlanOverflow,
    SnapshotStale,
    bm25_dense_scores,
)
from nornicdb_tpu.search.microbatch import pow2_bucket
from nornicdb_tpu.search.rrf import DEFAULT_RRF_K, rrf_fuse
from nornicdb_tpu.search.vector_index import BruteForceIndex

_HYB_C = REGISTRY.counter(
    "nornicdb_hybrid_fused_events_total",
    "Fused hybrid pipeline dispatches and freshness decisions",
    labels=("event",))

declare_kind("hybrid_fused")
declare_kind("hybrid_walk_fused")
declare_kind("hybrid_fused_quant")
declare_kind("hybrid_walk_fused_quant")

# canonical serving-tier names (obs/audit taxonomy) for the pipeline's
# rungs; every decoded row carries `served_by` — per ROW, because one
# rider's freshness correction (host re-fuse) must not relabel its
# batch-mates (ISSUE 10 rider accuracy)
TIER_BRUTE_F32 = "hybrid_brute_f32"
TIER_WALK_F32 = "hybrid_walk_f32"
TIER_WALK_QUANT = "hybrid_walk_quant"


def quant_tier(mode: str) -> str:
    return f"hybrid_brute_{mode}"


# ---------------------------------------------------------------------------
# pure device fusion
# ---------------------------------------------------------------------------


def _pad_cols(x: jnp.ndarray, k: int, fill) -> jnp.ndarray:
    if x.shape[1] >= k:
        return x
    pad = jnp.full((x.shape[0], k - x.shape[1]), fill, dtype=x.dtype)
    return jnp.concatenate([x, pad], axis=1)


def rrf_fuse_device(
    ls: jnp.ndarray,  # [B, kq] lexical scores, NEG_INF padded
    lid: jnp.ndarray,  # [B, kq] vector slot per lexical hit (-1 = none)
    lgrow: jnp.ndarray,  # [B, kq] global lexical row ids
    vs: jnp.ndarray,  # [B, kq] vector scores
    vi: jnp.ndarray,  # [B, kq] vector slots
    n_cand: jnp.ndarray,  # [B] per-request candidate depth (overfetch)
    w_lex: jnp.ndarray,  # [B]
    w_vec: jnp.ndarray,  # [B]
    rrf_k: int,
    c_vec: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Weighted RRF over the concatenated candidate lists. Docs present
    in both sources join via ``lid`` and keep their FIRST (lexical)
    position; per-candidate sums accumulate float32 source-major, so the
    result is bit-identical to host ``rrf_fuse`` on the same lists.
    Returns (fused scores [B, 2kq], concat positions [B, 2kq])."""
    b, kq = ls.shape
    r = jnp.arange(kq)
    in_cand = r[None, :] < n_cand[:, None]
    lval = (ls > 0.5 * NEG_INF) & in_cand
    vval = (vs > 0.5 * NEG_INF) & in_cand
    # shared candidate id space: vector slot when the lexical doc has a
    # vector, else a unique id past the vector capacity
    cid = jnp.concatenate(
        [jnp.where(lid >= 0, lid, c_vec + lgrow), vi], axis=1)
    val = jnp.concatenate([lval, vval], axis=1)
    inv = (rrf_k + 1.0 + r).astype(jnp.float32)
    w = jnp.concatenate(
        [w_lex[:, None] / inv[None, :], w_vec[:, None] / inv[None, :]],
        axis=1)
    w = jnp.where(val, w, 0.0)
    match = (cid[:, :, None] == cid[:, None, :]) \
        & val[:, :, None] & val[:, None, :]
    # each row of `match` has at most two hits (one per source), so the
    # einsum sum is a plain two-term float32 add — no reassociation
    fused = jnp.einsum("bij,bj->bi", match.astype(jnp.float32), w)
    m2 = jnp.arange(2 * kq)
    dup = jnp.any(match & (m2[None, None, :] < m2[None, :, None]), axis=2)
    fused = jnp.where(val & ~dup, fused, NEG_INF)
    return jax.lax.top_k(fused, 2 * kq)


@functools.partial(jax.jit, static_argnames=("kq", "rrf_k"))
def _fused_single(ptr, urow, sel, post_doc, post_tf, doc_len, alive_f,
                  l2v, avgdl, qn, vmatrix, vvalid, n_cand, w_lex, w_vec,
                  kq, rrf_k):
    c_vec = vmatrix.shape[0]
    ls, lid, lgrow, vs, vi = _local_parts_impl(
        ptr, urow, sel, post_doc, post_tf, doc_len, alive_f, l2v,
        avgdl, qn, vmatrix, vvalid, jnp.int32(0), jnp.int32(0), kq=kq)
    ls = _pad_cols(ls, kq, NEG_INF)
    lid = _pad_cols(lid, kq, 0)
    lgrow = _pad_cols(lgrow, kq, 0)
    vs = _pad_cols(vs, kq, NEG_INF)
    vi = _pad_cols(vi, kq, 0)
    fs, fpos = rrf_fuse_device(ls, lid, lgrow, vs, vi, n_cand,
                               w_lex, w_vec, rrf_k, c_vec)
    return ls, lgrow, vs, vi, fs, fpos


def _lex_parts_impl(ptr, urow, sel, post_doc, post_tf, doc_len,
                    alive_f, l2map, avgdl, lex_off, kq):
    """One shard's lexical top-k with globalized row ids plus the
    joined foreign-row column (brute slot for the matmul tier, graph
    row for the walk tier) — the lexical half of every shard path."""
    c_lex = doc_len.shape[0]
    dense = bm25_dense_scores(ptr, urow, sel, post_doc, post_tf,
                              doc_len, alive_f, avgdl)
    ls, li = jax.lax.top_k(dense, min(kq, c_lex))
    return ls, l2map[li], li + lex_off


_lex_parts = functools.partial(
    jax.jit, static_argnames=("kq",))(_lex_parts_impl)


def _local_parts_impl(ptr, urow, sel, post_doc, post_tf, doc_len,
                      alive_f, l2v, avgdl, qn, vmatrix, vvalid, lex_off,
                      vec_off, kq):
    """One shard's per-source top-k with globalized ids — the building
    block of both the single-device reference loop and the mesh path."""
    c_vec = vmatrix.shape[0]
    ls, lid, lgrow = _lex_parts_impl(ptr, urow, sel, post_doc, post_tf,
                                     doc_len, alive_f, l2v, avgdl,
                                     lex_off, kq)
    vsc = qn @ vmatrix.T
    vsc = jnp.where(vvalid[None, :], vsc, NEG_INF)
    vs, vi = jax.lax.top_k(vsc, min(kq, c_vec))
    return ls, lid, lgrow, vs, vi + vec_off


_local_parts = functools.partial(
    jax.jit, static_argnames=("kq",))(_local_parts_impl)


def _merge_parts(parts, kq):
    """Concat per-shard (scores, aux...) blocks in shard order and take
    one top-k, gathering every aux column by the winning positions —
    the all-gather-equivalent merge layout."""
    all_s = jnp.concatenate([p[0] for p in parts], axis=1)
    auxes = [jnp.concatenate([p[j] for p in parts], axis=1)
             for j in range(1, len(parts[0]))]
    k = min(kq, all_s.shape[1])
    top_s, pos = jax.lax.top_k(all_s, k)
    out = [_pad_cols(top_s, kq, NEG_INF)]
    for a in auxes:
        out.append(_pad_cols(jnp.take_along_axis(a, pos, axis=1), kq, 0))
    return out


@functools.partial(
    jax.jit, static_argnames=("kq", "rrf_k", "c_vec_total"))
def _fuse_merged(ls, lid, lgrow, vs, vi, n_cand, w_lex, w_vec, kq,
                 rrf_k, c_vec_total):
    return rrf_fuse_device(ls, lid, lgrow, vs, vi, n_cand, w_lex, w_vec,
                           rrf_k, c_vec_total)


@functools.partial(
    jax.jit, static_argnames=("kq", "rrf_k", "mesh_holder"))
def _fused_sharded_impl(ptr, urow, sel, post_doc, post_tf, doc_len,
                        alive_f, l2v, avgdl, qn, vmatrix, vvalid,
                        n_cand, w_lex, w_vec, kq, rrf_k, mesh_holder):
    from jax.sharding import PartitionSpec as P

    from nornicdb_tpu.parallel.mesh import compat_shard_map

    mesh = mesh_holder.mesh
    s_n = mesh.shape["data"]
    c_lex_local = doc_len.shape[0] // s_n
    c_vec_local = vmatrix.shape[0] // s_n
    c_vec_total = vmatrix.shape[0]

    def local_fn(ptr_s, urow_s, sel_r, pd_s, pt_s, dl_s, al_s, l2v_s,
                 avg_r, qn_r, vm_s, vv_s, nc_r, wl_r, wv_r):
        sh = jax.lax.axis_index("data")
        ls, lid, lgrow, vs, gvi = _local_parts_impl(
            ptr_s, urow_s, sel_r, pd_s, pt_s, dl_s, al_s, l2v_s, avg_r,
            qn_r, vm_s, vv_s, sh * c_lex_local, sh * c_vec_local,
            kq=kq)

        def gat(x):
            return jax.lax.all_gather(x, "data", axis=1, tiled=True)

        ls2, lid2, lgrow2 = _merge_parts(
            [(gat(ls), gat(lid), gat(lgrow))], kq)
        vs2, vi2 = _merge_parts([(gat(vs), gat(gvi))], kq)
        fs, fpos = rrf_fuse_device(ls2, lid2, lgrow2, vs2, vi2, nc_r,
                                   wl_r, wv_r, rrf_k, c_vec_total)
        return ls2, lgrow2, vs2, vi2, fs, fpos

    return compat_shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P(), P("data"), P("data"),
                  P("data"), P("data"), P("data"), P(), P(),
                  P("data", None), P("data"), P(), P(), P()),
        out_specs=(P(), P(), P(), P(), P(), P()),
    )(ptr, urow, sel, post_doc, post_tf, doc_len, alive_f, l2v,
      avgdl, qn, vmatrix, vvalid, n_cand, w_lex, w_vec)


# ---------------------------------------------------------------------------
# quantized vector halves (device_quant): int8/PQ coarse scoring inside
# the same compiled program; the decode exact-reranks the vector
# candidates on host float32 rows and re-fuses through the
# bit-compatible host rrf_fuse — compressed scores rank the POOL, never
# an answer
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("kq", "pool", "mode"))
def _fused_single_quant(ptr, urow, sel, post_doc, post_tf, doc_len,
                        alive_f, l2v, avgdl, qn, codes_t, aux,
                        vvalid, kq, pool, mode):
    """Lexical CSR scoring + quantized coarse vector top-``pool`` in
    one compiled program; ``mode`` picks the coarse scorer (``aux`` is
    the int8 per-row scales or the PQ codebooks). No device fuse: the
    quant decode exact-reranks the pool and re-fuses through the
    bit-compatible host rrf_fuse, so a device fuse over COARSE scores
    would be discarded anyway — and skipping it frees the vector half
    to overfetch ``pool`` > kq candidates (the rerank's recall slack,
    same policy as the standalone plane)."""
    from nornicdb_tpu.search.device_quant import (
        _int8_scores,
        _pq_adc_scores,
    )

    c_vec = codes_t.shape[1]
    ls, _lid, lgrow = _lex_parts_impl(ptr, urow, sel, post_doc,
                                      post_tf, doc_len, alive_f, l2v,
                                      avgdl, jnp.int32(0), kq=kq)
    if mode == "int8":
        vsc = _int8_scores(qn, codes_t, aux)
    else:
        vsc = _pq_adc_scores(qn, codes_t, aux)
    vsc = jnp.where(vvalid[None, :], vsc, NEG_INF)
    vs, vi = jax.lax.top_k(vsc, min(pool, c_vec))
    ls = _pad_cols(ls, kq, NEG_INF)
    lgrow = _pad_cols(lgrow, kq, 0)
    vs = _pad_cols(vs, pool, NEG_INF)
    vi = _pad_cols(vi, pool, 0)
    return ls, lgrow, vs, vi


@functools.partial(jax.jit, static_argnames=(
    "kq", "iters", "width", "itopk", "hash_bits", "n_seeds", "keep"))
def _walk_fused_single_q(ptr, urow, sel, post_doc, post_tf, doc_len,
                         alive_f, l2g, avgdl, qp, codes, codes_head,
                         scale, gadj, gvalidf, kq, iters, width, itopk,
                         hash_bits, n_seeds, keep):
    """Walk tier over a QUANTIZED graph base: the two-stage
    (head-prefilter -> full int8 dot) greedy walk replaces the float32
    walk inside the same compiled program. ``qp`` is the PCA-projected
    query batch (rotation is orthogonal, so dots are preserved). The
    walk's whole itopk pool rides out for the exact rerank; the host
    re-fuse replaces the device fuse (see _fused_single_quant)."""
    from nornicdb_tpu.search.device_quant import _walk_body_quant

    ls, _lid, lgrow = _lex_parts_impl(ptr, urow, sel, post_doc,
                                      post_tf, doc_len, alive_f, l2g,
                                      avgdl, jnp.int32(0), kq=kq)
    vs, vi = _walk_body_quant(qp, codes, codes_head, scale, gadj,
                              gvalidf, itopk, iters, width,
                              itopk, hash_bits, n_seeds, keep)
    ls = _pad_cols(ls, kq, NEG_INF)
    lgrow = _pad_cols(lgrow, kq, 0)
    return ls, lgrow, vs, vi


@functools.partial(jax.jit, static_argnames=(
    "kq", "iters", "width", "itopk", "hash_bits", "n_seeds"))
def _walk_fused_single_pq(ptr, urow, sel, post_doc, post_tf, doc_len,
                          alive_f, l2g, avgdl, qn, codes, codebooks,
                          gadj, gvalidf, kq, iters, width, itopk,
                          hash_bits, n_seeds):
    """Walk tier over a PQ graph base (ISSUE 17 satellite): the
    codes-only ADC walk replaces the int8 two-stage walk inside the
    same compiled program — HBM holds M bytes per graph row. The pool
    rides out for the exact host rerank and the host re-fuse replaces
    the device fuse, exactly as in :func:`_walk_fused_single_q`."""
    from nornicdb_tpu.search.device_quant import _walk_body_pq

    ls, _lid, lgrow = _lex_parts_impl(ptr, urow, sel, post_doc,
                                      post_tf, doc_len, alive_f, l2g,
                                      avgdl, jnp.int32(0), kq=kq)
    vs, vi = _walk_body_pq(qn, codes, codebooks, gadj, gvalidf,
                           itopk, iters, width, itopk, hash_bits,
                           n_seeds)
    ls = _pad_cols(ls, kq, NEG_INF)
    lgrow = _pad_cols(lgrow, kq, 0)
    return ls, lgrow, vs, vi


# ---------------------------------------------------------------------------
# the walk tier: CAGRA greedy walk instead of the brute matmul
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=(
    "kq", "rrf_k", "iters", "width", "itopk", "hash_bits", "n_seeds"))
def _walk_fused_single(ptr, urow, sel, post_doc, post_tf, doc_len,
                       alive_f, l2g, avgdl, qn, gmatrix, gadj, gvalidf,
                       n_cand, w_lex, w_vec, kq, rrf_k, iters, width,
                       itopk, hash_bits, n_seeds):
    """One compiled program for the walk tier: CSR lexical scoring,
    the fixed-iteration CAGRA greedy walk over the device graph, and
    device RRF joining on the ``lexical row -> graph row`` map. Same
    pow2 ``(B, kq)`` compile-bucket discipline as the brute tier —
    the walk's own statics (iters/width/itopk) are per-graph-build
    constants, not per-request knobs."""
    c_g = gmatrix.shape[0]
    ls, lid, lgrow = _lex_parts_impl(ptr, urow, sel, post_doc, post_tf,
                                     doc_len, alive_f, l2g, avgdl,
                                     jnp.int32(0), kq=kq)
    vs, vi = _walk_body(qn, gmatrix, gadj, gvalidf, min(kq, itopk),
                        iters, width, itopk, hash_bits, n_seeds)
    ls = _pad_cols(ls, kq, NEG_INF)
    lid = _pad_cols(lid, kq, 0)
    lgrow = _pad_cols(lgrow, kq, 0)
    vs = _pad_cols(vs, kq, NEG_INF)
    vi = _pad_cols(vi, kq, 0)
    fs, fpos = rrf_fuse_device(ls, lid, lgrow, vs, vi, n_cand,
                               w_lex, w_vec, rrf_k, c_g)
    return ls, lgrow, vs, vi, fs, fpos


@functools.partial(jax.jit, static_argnames=(
    "kq", "rrf_k", "iters", "width", "itopk", "hash_bits", "n_seeds",
    "mesh_holder"))
def _walk_fused_sharded_impl(ptr, urow, sel, post_doc, post_tf,
                             doc_len, alive_f, l2g, avgdl, qn, gmatrix,
                             gadj, gvalidf, n_cand, w_lex, w_vec, kq,
                             rrf_k, iters, width, itopk, hash_bits,
                             n_seeds, mesh_holder):
    """Mesh walk tier: both corpora row-sharded over ``data``; each
    shard scores its lexical rows and walks its local subgraph, one
    all-gather + top-k per source merges shard winners, and the fuse
    runs replicated — the same collective pattern as the brute-fused
    mesh path and ``cagra.sharded_cagra_walk``."""
    from jax.sharding import PartitionSpec as P

    from nornicdb_tpu.parallel.mesh import compat_shard_map

    mesh = mesh_holder.mesh
    s_n = mesh.shape["data"]
    c_lex_local = doc_len.shape[0] // s_n
    g_local = gmatrix.shape[0] // s_n
    c_g_total = gmatrix.shape[0]
    kw = min(kq, itopk)

    def local_fn(ptr_s, urow_s, sel_r, pd_s, pt_s, dl_s, al_s, l2g_s,
                 avg_r, qn_r, gm_s, ga_s, gv_s, nc_r, wl_r, wv_r):
        sh = jax.lax.axis_index("data")
        ls, lid, lgrow = _lex_parts_impl(
            ptr_s, urow_s, sel_r, pd_s, pt_s, dl_s, al_s, l2g_s,
            avg_r, sh * c_lex_local, kq=kq)
        ws, wi = _walk_body(qn_r, gm_s, ga_s, gv_s, kw, iters, width,
                            itopk, hash_bits, n_seeds)
        gwi = wi + sh * g_local

        def gat(x):
            return jax.lax.all_gather(x, "data", axis=1, tiled=True)

        ls2, lid2, lgrow2 = _merge_parts(
            [(gat(ls), gat(lid), gat(lgrow))], kq)
        vs2, vi2 = _merge_parts([(gat(ws), gat(gwi))], kq)
        fs, fpos = rrf_fuse_device(ls2, lid2, lgrow2, vs2, vi2, nc_r,
                                   wl_r, wv_r, rrf_k, c_g_total)
        return ls2, lgrow2, vs2, vi2, fs, fpos

    return compat_shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P(), P("data"), P("data"),
                  P("data"), P("data"), P("data"), P(), P(),
                  P("data", None), P("data", None), P("data"), P(),
                  P(), P()),
        out_specs=(P(), P(), P(), P(), P(), P()),
    )(ptr, urow, sel, post_doc, post_tf, doc_len, alive_f, l2g,
      avgdl, qn, gmatrix, gadj, gvalidf, n_cand, w_lex, w_vec)


# ---------------------------------------------------------------------------
# the pipeline object
# ---------------------------------------------------------------------------


class FusedHybrid:
    """Device-fused hybrid search over a (BM25Index, BruteForceIndex)
    pair. Stateless beyond the lexical snapshot (owned by
    :class:`DeviceBM25`) and the lexical-row -> vector-slot join map;
    both re-derive from the live host indexes, which remain the
    mutable sources of truth."""

    def __init__(
        self,
        bm25: BM25Index,
        brute: BruteForceIndex,
        n_shards: int = 1,
        min_n: int = 256,
        rebuild_stale_frac: float = 0.1,
        build_inline: bool = True,
        rrf_k: int = DEFAULT_RRF_K,
        walk_min_n: Optional[int] = None,
        cagra: Optional[CagraIndex] = None,
    ):
        self.bm25 = bm25
        self.brute = brute
        self.rrf_k = rrf_k
        self.n_shards = max(1, n_shards)
        self.lex = DeviceBM25(
            bm25, n_shards=self.n_shards, min_n=min_n,
            rebuild_stale_frac=rebuild_stale_frac,
            build_inline=build_inline)
        # walk tier: above `walk_min_n` live vectors the vector half
        # runs the CAGRA greedy walk instead of the exact matmul
        # (None = tier disabled, matmul always). A caller that already
        # owns a graph over the SAME brute index (the service's cagra
        # strategy tier) shares it here — one graph, one rebuild
        # cadence; otherwise the pipeline wraps its own.
        self.walk_min_n = walk_min_n
        if cagra is not None and cagra._brute is not brute:
            # a graph over some OTHER brute index (e.g. captured by a
            # background build that raced an index reload) must never
            # serve: its row ids and freshness counters belong to a
            # discarded corpus
            cagra = None
        if cagra is None and walk_min_n is not None:
            from nornicdb_tpu.search.ann_quality import current_profile

            p = current_profile()
            cagra = CagraIndex(
                brute=brute, degree=p.cagra_degree,
                itopk=p.cagra_itopk, search_width=p.cagra_width,
                min_n=walk_min_n, n_shards=self.n_shards,
                build_inline=build_inline)
        self.cagra = cagra
        self._grow_cache: Optional[Tuple] = None
        # sharded placement cache for the brute device arrays, keyed on
        # the array object identity (BruteForceIndex recreates it on
        # mutation) — a persistent serving index never re-ships the
        # corpus across devices per batch
        self._vec_placed: Optional[Tuple] = None

    def build(self) -> bool:
        return self.lex.build()

    @property
    def ready(self) -> bool:
        return self.lex.snapshot_built

    def ensure(self) -> bool:
        """Have (or start building) a lexical snapshot; False while the
        host path must serve."""
        return self.lex.ensure_snapshot() is not None

    # -- freshness helpers ------------------------------------------------

    def _ensure_map(self, snap: Dict[str, Any], mutations: int):
        """Device lex-row -> vector-slot map consistent with the brute
        matrix at generation ``mutations``, or None when a concurrent
        write/compaction moved the matrix on from the captured view —
        slots_of pins the read to the expected generation under the
        brute lock, so a remap can never mis-join silently."""

        def derive():
            ids = ["" if e is None else e for e in snap["row_ids"]]
            raw = self.brute.slots_of(ids, expect_mutations=mutations)
            return None if raw is None else np.asarray(raw, np.int32)

        return self.lex.row_map(snap, "l2v", mutations, derive)

    def _ensure_walk_map(self, snap: Dict[str, Any], g: Dict[str, Any]):
        """Device lex-row -> graph-row map for the walk tier, keyed on
        the graph's build sequence so a background rebuild (new row
        space) rebinds the join on the very next batch instead of
        serving a stale map."""

        def derive():
            grow = self._graph_rows(g)
            return np.asarray(
                [-1 if e is None else grow.get(e, -1)
                 for e in snap["row_ids"]], dtype=np.int32)

        return self.lex.row_map(snap, "l2g", g["build_seq"], derive)

    def _graph_rows(self, g: Dict[str, Any]) -> Dict[str, int]:
        # keyed on build_seq, NOT the dict: holding g here would pin a
        # replaced graph's device arrays until the next walk dispatch
        cached = self._grow_cache
        if cached is not None and cached[0] == g["build_seq"]:
            return cached[1]
        grow = {e: i for i, e in enumerate(g["row_ids"])
                if e is not None}
        self._grow_cache = (g["build_seq"], grow)
        return grow

    def rebind_cagra(self, cagra: CagraIndex) -> bool:
        """Swap the walk tier's graph index in place (the strategy
        machine built its own over the same brute index). Keeps the
        lexical snapshot serving — the graph-derived state (l2g map,
        row cache) rebinds lazily via the new graph's build_seq.
        False when the graph wraps a DIFFERENT brute index (caller
        must re-wrap the whole pipeline instead)."""
        if cagra is not None and cagra._brute is not self.brute:
            return False
        self.cagra = cagra
        self._grow_cache = None
        return True

    def _vec_arrays(self, m, valid, snap):
        if snap["shards"] == 1 or "mesh" not in snap:
            return m, valid
        if m.shape[0] % snap["shards"] != 0:
            return None, None  # capacity not shardable; caller falls back
        cached = self._vec_placed
        if cached is not None and cached[0] is m:
            return cached[1], cached[2]
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = snap["mesh"]
        mp = jax.device_put(m, NamedSharding(mesh, P("data", None)))
        vp = jax.device_put(valid, NamedSharding(mesh, P("data")))
        self._vec_placed = (m, mp, vp)
        return mp, vp

    def _record_cost(self, kind: str, b: int, snap: Dict[str, Any],
                     vec_flops_bytes: Tuple[float, float]) -> None:
        """Per-query cost accounting for one fused dispatch: the vector
        tier's price (matmul or walk, passed in) plus the lexical CSR
        price from the (nnz, unique-terms) the lexical plan() just
        stashed on this thread. The lexical matmul is priced at the
        snapshot's PADDED doc width (shards * c_local — the shape the
        program executes, same as DeviceBM25's standalone pricing), not
        the live row count. Best-effort — pricing must never fail a
        search, and with telemetry off the arithmetic is skipped
        entirely."""
        if not _cost.pricing_enabled():
            return
        try:
            nnz, u = self.lex._plan_cost.stats
            lex_f, lex_b = _cost.price_bm25(
                pow2_bucket(max(b, 1)), nnz, u,
                int(snap["shards"]) * int(snap["c_local"]))
            vec_f, vec_b = vec_flops_bytes
            _cost.record_query_cost(
                kind, _cost.cost_name(self.lex), b,
                lex_f + vec_f, lex_b + vec_b)
        except Exception:  # noqa: BLE001
            pass

    # -- search -----------------------------------------------------------

    def search_batch(
        self,
        queries_emb: np.ndarray,
        kq: int,
        extras: Sequence[Dict[str, Any]],
    ) -> List[Optional[Dict[str, Any]]]:
        """One fused dispatch for a coalesced hybrid batch.

        ``extras[i]`` carries the non-stackable half of request i:
        ``tokens`` (tokenized query), ``n_cand`` (its overfetch depth)
        and ``w`` ((w_lex, w_vec) fusion weights). Returns one row per
        query: dict with ``lex``/``vec``/``fused`` ranked lists and the
        shared stage ``times``, or None when the device path must not
        serve this batch (caller falls back to the host path)."""
        b = len(queries_emb)
        none_rows: List[Optional[Dict[str, Any]]] = [None] * b
        snap = self.lex.ensure_snapshot()
        if snap is None:
            return none_rows
        delta = self.lex.delta_block(snap)
        if delta is None:
            _HYB_C.labels("host_fallback_changelog").inc()
            self._ledger(TIER_BRUTE_F32, "host", "changelog_overrun",
                         snap)
            self.lex._kick_background_rebuild()
            return none_rows
        if self.brute.view_meta() is None:
            return none_rows  # vector index empty
        t_plan0 = time.time()
        try:
            self.lex.refresh_alive(snap)
            token_rows = [e["tokens"] for e in extras]
            ptr, urow, sel, avgdl = self.lex.plan(snap, token_rows, b)
        except SnapshotStale:
            _HYB_C.labels("host_fallback_compaction").inc()
            self._ledger(TIER_BRUTE_F32, "host", "compaction", snap)
            self.lex._kick_background_rebuild()
            return none_rows
        except PlanOverflow:
            _HYB_C.labels("host_fallback_overflow").inc()
            self._ledger(TIER_BRUTE_F32, "host", "overflow", snap)
            return none_rows
        n_cand = np.asarray(
            [int(e["n_cand"]) for e in extras], dtype=np.int32)
        w_lex = np.asarray([e["w"][0] for e in extras], dtype=np.float32)
        w_vec = np.asarray([e["w"][1] for e in extras], dtype=np.float32)
        qn = l2_normalize(jnp.asarray(queries_emb, dtype=jnp.float32))
        lex_base = (jnp.asarray(ptr), jnp.asarray(urow),
                    jnp.asarray(sel), snap["post_doc"],
                    snap["post_tf"], snap["doc_len"], snap["alive"])
        tail = (jnp.asarray(n_cand), jnp.asarray(w_lex),
                jnp.asarray(w_vec))
        # tier selection: walk above walk_min_n (sub-linear vector
        # half), else the exact matmul; a vetoed walk batch falls
        # through to the matmul tier, never to the host
        wctx = self._walk_context(snap, kq)
        walk_discarded_s = 0.0
        if wctx is not None:
            t_w0 = time.time()
            out = self._dispatch_walk(snap, wctx, lex_base, avgdl, qn,
                                      tail, kq, b, delta, token_rows,
                                      extras, t_plan0)
            if out is not None:
                return out
            # vetoed: account the discarded walk explicitly and reset
            # the plan clock, or the brute tier's plan_s (and the
            # lexical.score trace span) would silently absorb the
            # whole walk dispatch + decode
            walk_discarded_s = time.time() - t_w0
            t_plan0 = time.time()
        # quantized brute tier (device_quant): int8/PQ coarse scoring
        # replaces the float32 matmul inside the same compiled program;
        # the decode exact-reranks and host-refuses. A veto (freshness
        # gap, under-fill) falls through to the float32 exact tier —
        # the ladder is quantized -> float32 -> host
        qctx = self._quant_context(snap)
        if qctx is not None:
            t_q0 = time.time()
            out = self._dispatch_quant(snap, qctx, lex_base, avgdl, qn,
                                       tail, kq, b, delta, token_rows,
                                       extras, t_plan0)
            if out is not None:
                return out
            walk_discarded_s += time.time() - t_q0
            t_plan0 = time.time()
        # the exact tier's view capture happens only here — the walk
        # dispatch above never touches the brute matrix, so a served
        # walk batch skips the post-write device re-ship entirely
        view = self.brute.device_view()
        if view is None:
            return none_rows
        m, valid, vec_ext, mutations, _compactions = view
        l2v = self._ensure_map(snap, mutations)
        if l2v is None:
            # a write/compaction moved the brute matrix between the
            # view capture and the map read — retry next batch
            _HYB_C.labels("host_fallback_vec_race").inc()
            self._ledger(TIER_BRUTE_F32, "host", "vec_race", snap)
            return none_rows
        args = (*lex_base, l2v, jnp.float32(avgdl), qn)
        t0 = time.time()
        if snap["shards"] == 1:
            ls, li, vs, vi, fs, fpos = _fused_single(
                *args, jnp.asarray(m), jnp.asarray(valid), *tail,
                kq=kq, rrf_k=self.rrf_k)
            lgrow = li
        elif "mesh" in snap and len(jax.devices()) >= snap["shards"]:
            mp, vp = self._vec_arrays(m, valid, snap)
            if mp is None:
                _HYB_C.labels("host_fallback_unshardable").inc()
                self._ledger(TIER_BRUTE_F32, "host", "unshardable", snap)
                return none_rows
            ls, lgrow, vs, vi, fs, fpos = _fused_sharded_impl(
                *args, mp, vp, *tail, kq=kq, rrf_k=self.rrf_k,
                mesh_holder=_holder(snap["mesh"]))
        else:
            ls, lgrow, vs, vi, fs, fpos = self._shard_loop(
                snap, args, m, valid, tail, kq)
        # force to host inside the timed window (async dispatch)
        ls, lgrow = np.asarray(ls), np.asarray(lgrow)
        vs, vi = np.asarray(vs), np.asarray(vi)
        fs, fpos = np.asarray(fs), np.asarray(fpos)
        t1 = time.time()
        record_dispatch("hybrid_fused", pow2_bucket(b), kq, t1 - t0)
        _HYB_C.labels("dispatch").inc()
        self._record_cost("hybrid_fused", b, snap,
                          vec_flops_bytes=_cost.price_brute(
                              pow2_bucket(b), int(m.shape[0]),
                              int(m.shape[1])))
        out = self._decode(snap, vec_ext, delta, token_rows, extras,
                           ls, lgrow, vs, vi, fs, fpos, kq,
                           tier=TIER_BRUTE_F32)
        if delta:
            _HYB_C.labels("delta_merge").inc(len(extras))
        times = {"plan_s": t0 - t_plan0, "device_t0": t0,
                 "device_t1": t1, "decode_s": time.time() - t1,
                 "tier": "brute"}
        if walk_discarded_s:
            times["walk_discarded_s"] = round(walk_discarded_s, 6)
        for row in out:
            if row is not None:
                row["times"] = times
                row["tier"] = "brute"
        return out

    def _ledger(self, from_tier: str, to_tier: str, reason: str,
                snap=None, g=None) -> None:
        """Structured degrade record for this pipeline (the legacy
        hybrid_fused_events_total labels stay as aliases)."""
        versions = {}
        if snap is not None:
            versions["lex_built_mutations"] = snap.get("built_mutations")
        if g is not None:
            versions["graph_build_seq"] = g.get("build_seq")
            versions["graph_built_mutations"] = g.get("built_mutations")
        versions["brute_mutations"] = getattr(self.brute, "mutations", 0)
        _audit.record_degrade(
            "hybrid", from_tier, to_tier, reason,
            index=_cost.cost_name(self.lex), versions=versions)

    # -- quantized brute tier ---------------------------------------------

    def _quant_context(self, snap) -> Optional[Dict[str, Any]]:
        """Eligibility + freshness gate for the quantized vector half
        of the brute tier. None means the float32 exact tier serves —
        every gap degrades DOWN (quantized -> float32 -> host), never
        into a wrong answer."""
        from nornicdb_tpu.search.device_quant import quant_mode

        if quant_mode() == "off" or snap["shards"] != 1:
            # the quant programs are single-shard; sharded snapshots
            # keep the float32 mesh path
            return None
        hold = None
        if not _audit.tier_allowed(quant_tier(quant_mode())):
            # shadow-parity quarantine: the quantized rung steps down
            # to the float32 tier of the same ladder
            hold = "quarantine"
        elif not _audit.admission_allows(quant_tier(quant_mode())):
            # admission posture (ISSUE 15): overload forces the quant
            # rung down to float32 to shrink device pressure
            hold = "admission"
        if hold is not None:
            _HYB_C.labels("quant_quarantined").inc()
            self._ledger(quant_tier(quant_mode()), TIER_BRUTE_F32,
                         hold, snap)
            return None
        brute = self.brute
        plane = getattr(brute, "quant_plane", lambda: None)()
        if plane is None:
            return None
        qsnap = plane.ensure()
        if qsnap is None:
            _HYB_C.labels("quant_pending_build").inc()
            return None
        if qsnap["shards"] != 1:
            return None
        if qsnap["built_compactions"] != getattr(brute, "compactions",
                                                 0):
            _HYB_C.labels("quant_fallback_compaction").inc()
            self._ledger(quant_tier(qsnap["mode"]), TIER_BRUTE_F32,
                         "compaction", snap)
            plane._kick_background_rebuild()
            return None
        vdelta = brute.changed_since(qsnap["built_mutations"])
        if vdelta is None:
            _HYB_C.labels("quant_fallback_changelog").inc()
            self._ledger(quant_tier(qsnap["mode"]), TIER_BRUTE_F32,
                         "changelog_overrun", snap)
            plane._kick_background_rebuild()
            return None
        ids_view = brute.ids_meta()
        if ids_view is None:
            return None
        ids, mutations, compactions = ids_view
        if compactions != qsnap["built_compactions"]:
            return None
        return {"plane": plane, "qsnap": qsnap, "vdelta": vdelta,
                "ids": ids, "mutations": mutations}

    def _dispatch_quant(self, snap, qctx, lex_base, avgdl, qn, tail,
                        kq, b, delta, token_rows, extras, t_plan0):
        """One quantized brute-tier dispatch. Returns decoded rows, or
        None when the float32 exact tier must re-serve the batch
        (join-map race, rerank race, under-fill)."""
        qsnap = qctx["qsnap"]
        tier = quant_tier(qsnap["mode"])
        brute = self.brute
        l2v = self._ensure_map(snap, qctx["mutations"])
        if l2v is None:
            _HYB_C.labels("quant_fallback_vec_race").inc()
            self._ledger(tier, TIER_BRUTE_F32, "vec_race", snap)
            return None
        args = (*lex_base, l2v, jnp.float32(avgdl), qn)
        # the vector half overfetches past kq: coarse ordering is
        # noisiest exactly where the rerank matters, so the pool takes
        # the standalone plane's policy (overfetch * kq, floored; PQ
        # adds the capacity-scaled floor)
        plane = qctx["plane"]
        pool = plane.pool_for(kq, qsnap)
        t0 = time.time()
        if qsnap["mode"] == "int8":
            aux = qsnap["scale"]
            vec_price = _cost.price_int8_coarse(
                pow2_bucket(b), qsnap["capacity"], qsnap["dims"])
        else:
            aux = qsnap["codebooks"]
            vec_price = _cost.price_pq_adc(
                pow2_bucket(b), qsnap["capacity"], qsnap["pq_m"],
                qsnap["pq_codes"], qsnap["dims"] // qsnap["pq_m"])
        ls, li, vs, vi = _fused_single_quant(
            *args, qsnap["codes_t"], aux, qsnap["valid"], kq=kq,
            pool=pool, mode=qsnap["mode"])
        lgrow = li
        ls, lgrow = np.asarray(ls), np.asarray(lgrow)
        vs, vi = np.asarray(vs), np.asarray(vi)
        # decode never reads the device fuse on quant tiers (it always
        # re-fuses on host over the exact-reranked lists) — vs/vi stand
        # in for the unused (fs, fpos) slots
        fs = fpos = None
        t1 = time.time()
        record_dispatch("hybrid_fused_quant", pow2_bucket(b), kq,
                        t1 - t0)
        rf, rb = _cost.price_rerank(pow2_bucket(b), vs.shape[1],
                                    qsnap["dims"])
        self._record_cost("hybrid_fused_quant", b, snap,
                          vec_flops_bytes=(vec_price[0] + rf,
                                           vec_price[1] + rb))
        # exact rerank: gather the vector candidates' CURRENT float32
        # rows from the host source of truth (one lock hold) and
        # re-score — compressed scores rank the pool, never an answer
        qh = np.asarray(qn)
        uniq = np.unique(vi)
        got = brute.rows_for_slots(
            uniq, expect_compactions=qsnap["built_compactions"])
        if got is None:
            _HYB_C.labels("quant_fallback_vec_race").inc()
            self._ledger(tier, TIER_BRUTE_F32, "rerank_race", snap)
            return None
        rows_u, alive_u, _ids_u = got
        exact_u = qh @ rows_u.T  # [B, U]
        inv = np.searchsorted(uniq, vi)
        vs_e = np.take_along_axis(exact_u, inv, axis=1)
        ok = (vs > 0.5 * NEG_INF) & alive_u[inv]
        vs_e = np.where(ok, vs_e, np.float32(NEG_INF)).astype(
            np.float32)
        order = np.argsort(-vs_e, axis=1, kind="stable")
        vs_e = np.take_along_axis(vs_e, order, axis=1)
        vi = np.take_along_axis(vi, order, axis=1)
        # vector delta block: exact-float32 side-scan of post-build
        # adds/updates (the changelog discipline — stale plane codes
        # for an updated doc never reach an answer; ids removed since
        # logging are skipped by the shared one-lock gather)
        d_ids, d_mat = brute.delta_vectors(qctx["vdelta"])
        vec_delta = (d_ids, d_mat)
        out = self._decode(snap, qctx["ids"], delta, token_rows,
                           extras, ls, lgrow, vs_e, vi, fs, fpos, kq,
                           vec_delta=vec_delta, qn=qh,
                           force_refuse=True, tier=tier)
        # under-fill veto: live-filtering can leave a row short of
        # candidates the corpus does have — the float32 tier re-serves
        alive_n = len(brute)
        for row, e in zip(out, extras):
            if row is None:
                continue
            if len(row["vec"]) < min(int(e["n_cand"]), kq, alive_n):
                _HYB_C.labels("quant_underfill_f32").inc()
                self._ledger(tier, TIER_BRUTE_F32, "underfill", snap)
                return None
        _HYB_C.labels("quant_dispatch").inc()
        if d_ids:
            _HYB_C.labels("quant_delta_merge").inc()
        if delta:
            _HYB_C.labels("delta_merge").inc(len(extras))
        times = {"plan_s": t0 - t_plan0, "device_t0": t0,
                 "device_t1": t1, "decode_s": time.time() - t1,
                 "tier": "brute", "quant": qsnap["mode"]}
        for row in out:
            if row is not None:
                row["times"] = times
                row["tier"] = "brute"
        return out

    # -- walk tier --------------------------------------------------------

    def _walk_context(self, snap, kq: int) -> Optional[Dict[str, Any]]:
        """Eligibility + freshness gate for the walk tier. None means
        the brute-fused tier serves this batch — every ineligibility
        degrades DOWN the ladder (walk -> brute-fused -> host), never
        sideways into a wrong answer."""
        cagra = self.cagra
        if cagra is None or self.walk_min_n is None:
            return None
        if len(self.brute) < self.walk_min_n:
            return None
        g = cagra.ensure_graph()
        if g is None:
            # first build (or a rebuild after shrinking below min_n)
            # still running in the background: exact tier serves
            _HYB_C.labels("walk_pending_build").inc()
            return None
        tier = (TIER_WALK_QUANT
                if snap["shards"] == 1 and g.get("quant") is not None
                else TIER_WALK_F32)
        hold = None
        if not _audit.tier_allowed(tier):
            # shadow-parity quarantine: walk steps down its ladder to
            # the brute-fused tier until the breach clears
            hold = "quarantine"
        elif not _audit.admission_allows(tier):
            # admission posture (ISSUE 15): overload forces the walk
            # down to the brute-fused tier to shrink device pressure
            hold = "admission"
        if hold is not None:
            _HYB_C.labels("walk_quarantined").inc()
            self._ledger(tier, TIER_BRUTE_F32, hold, snap, g)
            return None
        if kq > cagra.itopk:
            # the walk pool only ever holds itopk candidates; a deeper
            # overfetch must come from the exact matmul tier
            _HYB_C.labels("walk_fallback_itopk").inc()
            self._ledger(tier, TIER_BRUTE_F32, "itopk_exceeded", snap, g)
            return None
        if g["shards"] != snap["shards"]:
            # lexical snapshot and graph must agree on the mesh layout
            # to run inside one shard_map program
            _HYB_C.labels("walk_fallback_shards").inc()
            self._ledger(tier, TIER_BRUTE_F32, "shard_mismatch", snap, g)
            return None
        delta_ids, delta_vecs = cagra.delta_block(g)
        if delta_ids is None:
            # churn outran the brute changelog (rebuild in flight):
            # brute-fused serves exactly until the fresh graph lands
            _HYB_C.labels("walk_fallback_changelog").inc()
            self._ledger(tier, TIER_BRUTE_F32, "changelog_overrun",
                         snap, g)
            return None
        # staleness from the LIVE counter, read only after delta_block
        # drained the changelog (the same order as CagraIndex._resolve):
        # a delete landing after an earlier capture would bump the
        # counter delta_block sees while the old value still compared
        # clean — skipping the live-filter and serving a tombstone
        return {"g": g, "l2g": self._ensure_walk_map(snap, g),
                "delta_ids": delta_ids, "delta_vecs": delta_vecs,
                "stale": self.brute.mutations != g["built_mutations"],
                "iters": g["iters"], "width": cagra.search_width,
                "itopk": cagra.itopk, "hash_bits": cagra.hash_bits,
                "n_seeds": cagra.n_seeds, "tier": tier}

    def _dispatch_walk(self, snap, wctx, lex_base, avgdl, qn, tail,
                       kq, b, delta, token_rows, extras, t_plan0):
        """One walk-tier dispatch. Returns the decoded rows, or None
        when the walk output under-filled a row's candidate list (the
        caller re-dispatches the batch through the exact tier)."""
        g = wctx["g"]
        # the program runs at per-source width itopk, not kq: the fuse
        # masks candidate depth by the traced n_cand anyway, and the
        # extra columns are what give the live-filter slack — a few
        # tombstones in the walk's top-n_cand must not force the exact
        # tier. One compiled width per graph config, so the (B, k)
        # compile universe stays one bucket per batch size.
        kp = wctx["itopk"]
        statics = dict(kq=kp, rrf_k=self.rrf_k, iters=wctx["iters"],
                       width=wctx["width"], itopk=wctx["itopk"],
                       hash_bits=wctx["hash_bits"],
                       n_seeds=wctx["n_seeds"])
        quant = g.get("quant") if snap["shards"] == 1 else None
        t0 = time.time()
        if quant is not None and quant["mode"] == "pq":
            # PQ graph base (ISSUE 17): the codes-only ADC walk runs
            # inside the same compiled program; exact pool rerank and
            # host re-fuse below are shared with the int8 path
            q_statics = dict(statics)
            del q_statics["rrf_k"]
            # 4x pool (matches cagra's PQ widening): ADC reconstruction
            # noise needs a wider pool for the exact rerank to recover
            q_statics["itopk"] = min(4 * q_statics["itopk"], 1024)
            kp = q_statics["itopk"]
            ls, li, vs, vi = _walk_fused_single_pq(
                *lex_base, wctx["l2g"], jnp.float32(avgdl), qn,
                quant["codes"], quant["codebooks"],
                g["adj"], g["validf"], **q_statics)
            lgrow = li
            fs = fpos = None
        elif quant is not None:
            # quantized graph base: the two-stage int8 walk runs inside
            # the same compiled program; the pool is exact-reranked
            # below from the HOST-resident float32 rows, and the host
            # re-fuse replaces the device fuse (fs/fpos never read)
            qp = qn @ quant["rot_dev"]
            q_statics = dict(statics)
            del q_statics["rrf_k"]
            ls, li, vs, vi = _walk_fused_single_q(
                *lex_base, wctx["l2g"], jnp.float32(avgdl), qp,
                quant["codes"], quant["codes_head"], quant["scale"],
                g["adj"], g["validf"], **q_statics,
                keep=quant["keep"])
            lgrow = li
            fs = fpos = None
        elif snap["shards"] == 1:
            ls, li, vs, vi, fs, fpos = _walk_fused_single(
                *lex_base, wctx["l2g"], jnp.float32(avgdl), qn,
                g["matrix"], g["adj"], g["validf"], *tail, **statics)
            lgrow = li
        elif "mesh" in snap and "mesh" in g \
                and len(jax.devices()) >= snap["shards"]:
            args = (*lex_base, wctx["l2g"], jnp.float32(avgdl), qn,
                    g["matrix"], g["adj"], g["validf"], *tail)
            ls, lgrow, vs, vi, fs, fpos = _walk_fused_sharded_impl(
                *args, **statics, mesh_holder=_holder(snap["mesh"]))
        else:
            ls, lgrow, vs, vi, fs, fpos = self._walk_shard_loop(
                snap, g, lex_base, wctx["l2g"], avgdl, qn, tail, kp,
                wctx)
        # force to host inside the timed window (async dispatch)
        ls, lgrow = np.asarray(ls), np.asarray(lgrow)
        vs, vi = np.asarray(vs), np.asarray(vi)
        if fs is not None:
            fs, fpos = np.asarray(fs), np.asarray(fpos)
        if quant is not None:
            # exact rerank of the walk pool against the host float32
            # rows (non-delta rows are immutable between builds, so
            # these ARE current values; delta ids re-score in _decode)
            gathered = g["matrix"][vi]  # host f32 [B, kp, D]
            vs_e = np.einsum("bpd,bd->bp", gathered, np.asarray(qn))
            vs_e = np.where(vs > 0.5 * NEG_INF, vs_e,
                            np.float32(NEG_INF)).astype(np.float32)
            order = np.argsort(-vs_e, axis=1, kind="stable")
            vs = np.take_along_axis(vs_e, order, axis=1)
            vi = np.take_along_axis(vi, order, axis=1)
        t1 = time.time()
        kind = ("hybrid_walk_fused_quant" if quant is not None
                else "hybrid_walk_fused")
        record_dispatch(kind, pow2_bucket(b), kp, t1 - t0)
        _HYB_C.labels("walk_dispatch").inc()
        if quant is not None:
            d_model = int(qn.shape[1])
            if quant["mode"] == "pq":
                vf, vb = _cost.price_walk_pq(
                    pow2_bucket(b), d_model, wctx["iters"],
                    wctx["width"], int(g["adj"].shape[1]),
                    kp, quant["pq_m"], quant["pq_codes"],
                    n_seeds=wctx["n_seeds"])
            else:
                vf, vb = _cost.price_walk_quant(
                    pow2_bucket(b), d_model, wctx["iters"],
                    wctx["width"], int(g["adj"].shape[1]),
                    wctx["itopk"], quant["head_dims"], quant["keep"],
                    n_seeds=wctx["n_seeds"])
            rf, rb = _cost.price_rerank(pow2_bucket(b), kp, d_model)
            self._record_cost(kind, b, snap,
                              vec_flops_bytes=(vf + rf, vb + rb))
        else:
            self._record_cost(kind, b, snap,
                              vec_flops_bytes=_cost.price_walk(
                                  pow2_bucket(b),
                                  int(g["matrix"].shape[1]),
                                  wctx["iters"], wctx["width"],
                                  int(g["adj"].shape[1]), wctx["itopk"],
                                  n_seeds=wctx["n_seeds"]))
        out = self._decode(
            snap, g["row_ids"], delta, token_rows, extras,
            ls, lgrow, vs, vi, fs, fpos, kp,
            vec_delta=(wctx["delta_ids"], wctx["delta_vecs"]),
            vec_stale=wctx["stale"], qn=np.asarray(qn),
            force_refuse=quant is not None, tier=wctx["tier"])
        # under-fill veto: a stale graph's live-filter (or a walk miss)
        # can leave a row short of candidates the corpus does have —
        # those batches re-dispatch through the exact tier, the same
        # never-under-serve contract as CagraIndex.search_batch
        alive_n = len(self.brute)
        for row, e in zip(out, extras):
            if row is None:
                continue
            if len(row["vec"]) < min(int(e["n_cand"]), kp, alive_n):
                _HYB_C.labels("walk_underfill_brute").inc()
                self._ledger(wctx["tier"], TIER_BRUTE_F32, "underfill",
                             snap, g)
                return None
        # freshness/merge accounting only once the batch actually
        # serves from the walk tier — a vetoed batch re-dispatches
        # through the exact tier and must not count twice
        if wctx["delta_ids"]:
            _HYB_C.labels("walk_delta_merge").inc()
        elif wctx["stale"]:
            _HYB_C.labels("walk_live_filter").inc()
        if delta:
            _HYB_C.labels("delta_merge").inc(len(extras))
        times = {"plan_s": t0 - t_plan0, "device_t0": t0,
                 "device_t1": t1, "decode_s": time.time() - t1,
                 "tier": "walk", "walk_iters": wctx["iters"],
                 "walk_itopk": wctx["itopk"],
                 **({"quant": "int8"} if quant is not None else {})}
        for row in out:
            if row is not None:
                row["times"] = times
                row["tier"] = "walk"
        return out

    def _walk_shard_loop(self, snap, g, lex_base, l2g, avgdl, qn,
                         tail, kq, wctx):
        """Single-device reference for the sharded walk tier: each
        shard's lexical parts + local-subgraph walk, merged in shard
        order (the all-gather layout), fused once. The mesh path must
        match this bit-for-bit."""
        ptr, urow, sel, pd, pt, dl, al = lex_base
        n_cand, w_lex, w_vec = tail
        s_n = snap["shards"]
        c_local = snap["c_local"]
        p_b = ptr.shape[0] // s_n
        p_cap = pd.shape[0] // s_n
        r = g["rows_per_shard"]
        kw = min(kq, wctx["itopk"])
        avgdl_j = jnp.float32(avgdl)
        lex_parts, vec_parts = [], []
        for sh in range(s_n):
            ls, lid, lgrow = _lex_parts(
                ptr[sh * p_b:(sh + 1) * p_b],
                urow[sh * p_b:(sh + 1) * p_b],
                sel,
                pd[sh * p_cap:(sh + 1) * p_cap],
                pt[sh * p_cap:(sh + 1) * p_cap],
                dl[sh * c_local:(sh + 1) * c_local],
                al[sh * c_local:(sh + 1) * c_local],
                l2g[sh * c_local:(sh + 1) * c_local],
                avgdl_j, jnp.int32(sh * c_local), kq=kq)
            lex_parts.append((ls, lid, lgrow))
        for sh, (m_sh, a_sh, v_sh) in enumerate(g["shard_slices"]):
            ws, wi = _cagra_walk(
                qn, m_sh, a_sh, v_sh, k=kw, iters=wctx["iters"],
                width=wctx["width"], itopk=wctx["itopk"],
                hash_bits=wctx["hash_bits"], n_seeds=wctx["n_seeds"])
            vec_parts.append((ws, wi + sh * r))
        ls2, lid2, lgrow2 = _merge_parts(lex_parts, kq)
        vs2, vi2 = _merge_parts(vec_parts, kq)
        fs, fpos = _fuse_merged(ls2, lid2, lgrow2, vs2, vi2, n_cand,
                                w_lex, w_vec, kq=kq, rrf_k=self.rrf_k,
                                c_vec_total=int(g["shards"] * r))
        return ls2, lgrow2, vs2, vi2, fs, fpos

    def _shard_loop(self, snap, args, m, valid, tail, kq):
        """Single-device reference for the sharded layout: run every
        shard's local parts, merge in shard order (the all-gather
        layout), fuse once. The mesh path must match this bit-for-bit."""
        ptr, urow, sel, pd, pt, dl, al, l2v, avgdl, qn = args
        n_cand, w_lex, w_vec = tail
        s_n = snap["shards"]
        c_local = snap["c_local"]
        p_b = ptr.shape[0] // s_n
        p_cap = pd.shape[0] // s_n
        mj, vj = jnp.asarray(m), jnp.asarray(valid)
        c_vec_local = mj.shape[0] // s_n
        lex_parts, vec_parts = [], []
        for sh in range(s_n):
            ls, lid, lgrow, vvs, gvi = _local_parts(
                ptr[sh * p_b:(sh + 1) * p_b],
                urow[sh * p_b:(sh + 1) * p_b],
                sel,
                pd[sh * p_cap:(sh + 1) * p_cap],
                pt[sh * p_cap:(sh + 1) * p_cap],
                dl[sh * c_local:(sh + 1) * c_local],
                al[sh * c_local:(sh + 1) * c_local],
                l2v[sh * c_local:(sh + 1) * c_local],
                avgdl, qn,
                mj[sh * c_vec_local:(sh + 1) * c_vec_local],
                vj[sh * c_vec_local:(sh + 1) * c_vec_local],
                jnp.int32(sh * c_local), jnp.int32(sh * c_vec_local),
                kq=kq)
            lex_parts.append((ls, lid, lgrow))
            vec_parts.append((vvs, gvi))
        ls2, lid2, lgrow2 = _merge_parts(lex_parts, kq)
        vs2, vi2 = _merge_parts(vec_parts, kq)
        fs, fpos = _fuse_merged(ls2, lid2, lgrow2, vs2, vi2, n_cand,
                                w_lex, w_vec, kq=kq, rrf_k=self.rrf_k,
                                c_vec_total=int(mj.shape[0]))
        return ls2, lgrow2, vs2, vi2, fs, fpos

    def _decode(self, snap, vec_ids, delta, token_rows, extras,
                ls, lgrow, vs, vi, fs, fpos, kq,
                vec_delta=None, vec_stale=False, qn=None,
                force_refuse=False, tier=TIER_BRUTE_F32):
        """Decode one dispatch's device candidates into per-request
        ranked lists. ``vec_ids`` maps vector candidate ids to ext ids
        (the brute ext-id table for the matmul tier, graph ``row_ids``
        for the walk tier). The walk tier's vector-side freshness rides
        ``vec_delta``/``vec_stale``: tombstoned docs are live-filtered
        out of the walk output, post-build adds/updates are
        exact-scored (``qn @ delta_vecs``) and merged in, and any
        vector-side correction reroutes fusion through the
        bit-compatible host ``rrf_fuse`` — read-your-writes without a
        graph rebuild.

        Every returned row carries ``served_by`` (obs/audit taxonomy):
        ``tier`` when the device fuse answered, ``host`` for rows whose
        freshness correction (live-filter drop, delta merge) forced the
        host re-fuse — PER ROW, so one corrected rider in a coalesced
        batch never relabels its batch-mates. The quant tiers' by-design
        host re-fuse (``force_refuse``) keeps the quant tier label: the
        exact rerank is the tier's contract, not a degrade."""
        row_ids = snap["row_ids"]
        d_ids, d_vecs = vec_delta if vec_delta is not None else ([], None)
        d_set = set(d_ids)
        d_scores = qn @ d_vecs.T if d_ids else None  # exact cosines
        live: Optional[set] = None
        if vec_stale:
            # ONE locked membership pass over every distinct walk
            # candidate — a per-id `in brute` inside the loop would
            # take the index lock up to B*itopk times per batch
            cand = {vec_ids[i] for i in np.unique(vi)}
            cand.discard(None)
            live = self.brute.contains_many(cand)
        out: List[Optional[Dict[str, Any]]] = []
        live_filtered_rows = 0
        for r in range(len(extras)):
            n_cand = int(extras[r]["n_cand"])
            lex_hits: List[Tuple[str, float]] = []
            lex_by_pos: Dict[int, str] = {}
            for c in range(min(kq, ls.shape[1])):
                if ls[r, c] < 0.5 * NEG_INF or len(lex_hits) >= n_cand:
                    break
                eid = row_ids[int(lgrow[r, c])]
                if eid is None:
                    continue
                lex_by_pos[c] = eid
                lex_hits.append((eid, float(ls[r, c])))
            vec_hits: List[Tuple[str, float]] = []
            vec_by_pos: Dict[int, str] = {}
            vec_fixed = force_refuse  # this row's list diverged from
            #   the device-fused one: re-fuse on host. A merely-stale
            #   graph whose top-itopk held no tombstone keeps the
            #   device fuse. Quantized tiers ALWAYS re-fuse: their
            #   device fuse ranked coarse scores, the decode reranked
            #   them exactly.
            # the quant tiers overfetch vs/vi wider than kq (rerank
            # pool); the break on n_cand keeps served depth identical
            for c in range(vs.shape[1]):
                if vs[r, c] < 0.5 * NEG_INF or len(vec_hits) >= n_cand:
                    break
                eid = vec_ids[int(vi[r, c])]
                if eid is None:
                    continue
                if eid in d_set:
                    continue  # walk scored the pre-update vector
                if live is not None and eid not in live:
                    vec_fixed = True
                    continue  # tombstoned since the graph build
                vec_by_pos[c] = eid
                vec_hits.append((eid, float(vs[r, c])))
            if d_ids:
                vec_hits = merge_delta_hits(vec_hits, d_ids,
                                            d_scores[r], n_cand)
                vec_fixed = True
            served_by = tier
            if delta:
                # read-your-writes: exact host scores for post-snapshot
                # docs, then the (bit-compatible) host fuse over the
                # merged lists (the caller counts delta_merge once the
                # batch actually serves — a vetoed walk decode must not
                # double-count against the brute re-dispatch)
                dset = set(delta)
                fresh = self.bm25.score_docs(token_rows[r], delta)
                merged = [(e, s) for e, s in lex_hits if e not in dset]
                merged.extend(sorted(fresh.items()))
                merged.sort(key=lambda kv: -kv[1])
                lex_hits = merged[:n_cand]
                fused = rrf_fuse([lex_hits, vec_hits],
                                 weights=list(extras[r]["w"]),
                                 k=self.rrf_k, limit=n_cand)
                if not force_refuse:
                    served_by = "host"
            elif vec_fixed:
                # the device fuse saw the pre-correction vector list;
                # re-fuse on host (bit-compatible) over the fixed lists
                fused = rrf_fuse([lex_hits, vec_hits],
                                 weights=list(extras[r]["w"]),
                                 k=self.rrf_k, limit=n_cand)
                if not force_refuse:
                    # this rider's live-filter/delta correction routed
                    # its fusion to the host — ITS tier is host, its
                    # batch-mates keep the device tier
                    served_by = "host"
                    live_filtered_rows += 1
            else:
                fused = []
                for c in range(fs.shape[1]):
                    if fs[r, c] < 0.5 * NEG_INF or len(fused) >= n_cand:
                        break
                    pos = int(fpos[r, c])
                    eid = (lex_by_pos.get(pos) if pos < kq
                           else vec_by_pos.get(pos - kq))
                    if eid is None:
                        continue
                    fused.append((eid, float(fs[r, c])))
            out.append({"lex": lex_hits, "vec": vec_hits,
                        "fused": fused, "served_by": served_by})
        if live_filtered_rows:
            # one ledger record per batch for the rider-level host
            # re-fuse (delta merges are routine read-your-writes and
            # ride the delta_merge counter instead)
            self._ledger(tier, "host", "live_filter", snap)
        return out


def _holder(mesh):
    from nornicdb_tpu.parallel.mesh import _MeshHolder

    return _MeshHolder(mesh)
