"""Device-resident graph ANN: CAGRA-style fixed-out-degree index.

The sub-linear indexes so far (HNSW, IVF-HNSW, IVF-PQ) are
pointer-chasing CPU walks; only brute force ran on the accelerator.
CAGRA (arxiv 2308.15136) shows the accelerator-native shape of graph
ANN: a *fixed* out-degree adjacency searched with wide, batched frontier
expansion — every step is a padded gather + one batched dot + one
top-k, which is exactly what the MXU + XLA pipeline wants and what
pointer-chasing is not.

Design:

- **Build** (host + device): a k-NN graph from the device brute-force
  kernel (chunked matmul top-k; the Pallas fused kernel when
  ``NORNICDB_PALLAS_TOPK=1``), then CAGRA-style rank-based reordering:
  keep the top ``degree/2`` forward edges by rank and fill the rest with
  rank-ordered *reverse* edges, which restores reachability that pure
  k-NN graphs lack on clustered data.
- **Search** (device, jitted): a batched greedy walk with a candidate
  pool of ``itopk`` entries per query. Each iteration expands the best
  ``search_width`` unexplored candidates, gathers their ``degree``
  neighbors (``[B, W*deg]``), hash-bitmask-checks the visited set,
  scores the fresh ones with one batched dot against the queries, and
  merges into the pool with one top-k. The iteration count is FIXED so
  one XLA compile serves every query at a given (batch, k) pow2 bucket
  (microbatch.pow2_bucket discipline — same as the brute path).
- **Sharding** (``shard_map``): base vectors and adjacency are
  row-sharded over the ``data`` mesh axis. Each shard runs the walk over
  its *local* subgraph, then one all-gather + top-k merges shard-local
  winners into the exact global pool union — the same collective
  pattern as ``parallel.mesh.sharded_cosine_topk``. A single-device
  reference path (per-shard walk + identical merge) exists for parity
  testing and for meshes smaller than the shard count.
- **Freshness**: the index wraps a ``BruteForceIndex`` (source of truth
  for vectors/ids). Deletes after a build are filtered out of results
  via live-membership checks; once the mutation churn since the build
  exceeds ``rebuild_stale_frac`` of the corpus the graph is rebuilt
  in-line. Below ``min_n`` rows the graph is never built and search
  delegates to the (already device-resident) brute kernel.
"""

from __future__ import annotations

import functools
import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nornicdb_tpu.obs import REGISTRY, declare_kind, record_dispatch
from nornicdb_tpu.obs import audit as _audit
from nornicdb_tpu.ops.similarity import (
    NEG_INF,
    concat_topk,
    cosine_topk_auto,
    l2_normalize,
    pad_dim,
)
from nornicdb_tpu.search.microbatch import pow2_bucket
from nornicdb_tpu.search.vector_index import BruteForceIndex, _use_pallas

_HASH_MULT = np.uint32(2654435761)  # Knuth multiplicative hash
# globally unique graph build sequence (GIL-atomic): consumers cache
# derived state per graph keyed on this, and a PER-INDEX counter would
# collide across indexes (two first builds both numbered 1) when a
# consumer rebinds from one index to another over the same corpus
_BUILD_SEQ = itertools.count(1)

# freshness machinery events: graph (re)builds, delta side-scans merged
# into walk results, and the exact-fallback reasons — the counters that
# make strategy-machine decisions observable (ISSUE 3)
_CAGRA_C = REGISTRY.counter(
    "nornicdb_cagra_events_total",
    "CAGRA index lifecycle and per-search freshness decisions",
    labels=("event",))

declare_kind("cagra_walk")


# ---------------------------------------------------------------------------
# the batched greedy walk (pure function; jitted below and traced inside
# shard_map for the sharded path)
# ---------------------------------------------------------------------------


def _walk_body(
    queries: jnp.ndarray,  # [B, D] L2-normalized
    matrix: jnp.ndarray,  # [C, D] L2-normalized, zero pad rows
    adj: jnp.ndarray,  # [C, deg] int32 row indices (pad rows -> 0)
    validf: jnp.ndarray,  # [C] float32 {0,1}
    k: int,
    iters: int,
    width: int,
    itopk: int,
    hash_bits: int,
    n_seeds: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fixed-iteration batched greedy graph walk.

    Returns (scores [B,k], row ids [B,k]) best-first; slots that never
    filled carry scores <= NEG_INF (callers filter, same contract as
    ops.similarity).
    """
    b = queries.shape[0]
    c, deg = adj.shape
    p = itopk
    m = width * deg
    tbl = 1 << hash_bits

    def hbucket(ids):
        h = ids.astype(jnp.uint32) * _HASH_MULT
        return (h >> np.uint32(32 - hash_bits)).astype(jnp.int32)

    # -- seed round: score `n_seeds` strided rows with one small matmul
    # and keep the best `itopk` as the initial pool. A k-NN graph on
    # clustered data has almost no cross-cluster edges, so the walk can
    # only find what some seed's cluster reaches — the wide seed round
    # is what guarantees every sizable cluster gets an entry point.
    # Exactness of marking ALL scored seeds visited: the pool only ever
    # improves, so a row that lost the seed round (ranked > itopk among
    # seeds) can never belong to the final top-k for k <= itopk.
    # stride = c // s0 guarantees no wraparound dups when c >= s0; when
    # c < s0 the tail repeats and is masked to NEG_INF so a duplicate id
    # can never surface with a finite score.
    s0 = max(n_seeds, p)
    stride = max(1, c // s0)
    seed_ids = (jnp.arange(s0, dtype=jnp.int32) * stride) % c
    seed_unique = jnp.arange(s0) < c
    seed_s = queries @ matrix[seed_ids].T  # [B, S0]
    seed_ok = seed_unique[None, :] & (validf[seed_ids][None, :] > 0.0)
    seed_s = jnp.where(seed_ok, seed_s, NEG_INF)
    pool_s, pos0 = jax.lax.top_k(seed_s, p)
    pool_i = jnp.take_along_axis(
        jnp.broadcast_to(seed_ids[None, :], (b, s0)), pos0, axis=1)
    explored = jnp.zeros((b, p), dtype=bool)

    # visited hash-bitmask: [B, 2^hash_bits] bool. Collisions only ever
    # SKIP a node (slight recall loss), never duplicate one — insertion
    # sets the exact bucket of the inserted id.
    visited0 = jnp.zeros((tbl,), dtype=bool).at[hbucket(seed_ids)].set(True)
    visited = jnp.broadcast_to(visited0[None, :], (b, tbl))

    rows_b = jnp.arange(b, dtype=jnp.int32)[:, None]
    slot = jnp.arange(p, dtype=jnp.int32)
    mcol = jnp.arange(m, dtype=jnp.int32)
    # dup[i] = an equal id appears earlier in the same expansion batch
    earlier = (mcol[None, :] < mcol[:, None])[None, :, :]

    def body(_, carry):
        pool_s, pool_i, explored, visited = carry
        # frontier: best `width` unexplored pool entries
        f_s, f_pos = jax.lax.top_k(
            jnp.where(explored, NEG_INF, pool_s), width
        )  # [B, W]
        f_ids = jnp.take_along_axis(pool_i, f_pos, axis=1)
        explored = explored | jnp.any(
            slot[None, None, :] == f_pos[:, :, None], axis=1
        )
        f_ok = f_s > 0.5 * NEG_INF  # exhausted-pool slots expand nothing

        nbrs = adj[f_ids].reshape(b, m)  # [B, W*deg]
        nb_ok = jnp.repeat(f_ok, deg, axis=1)
        h = hbucket(nbrs)
        seen = jnp.take_along_axis(visited, h, axis=1)
        dup = jnp.any((nbrs[:, :, None] == nbrs[:, None, :]) & earlier, axis=2)
        fresh = nb_ok & ~seen & ~dup & (validf[nbrs] > 0.0)

        scores = jnp.einsum("bmd,bd->bm", matrix[nbrs], queries)
        scores = jnp.where(fresh, scores, NEG_INF)
        # max == OR for bool and is well-defined under duplicate buckets
        # (two neighbors of one query hashing to the same word) — a
        # plain .set would leave the winner undefined and could let a
        # pool member be re-inserted as a finite-score duplicate
        visited = visited.at[rows_b, h].max(fresh)

        all_s = jnp.concatenate([pool_s, scores], axis=1)
        all_i = jnp.concatenate([pool_i, nbrs], axis=1)
        all_e = jnp.concatenate(
            [explored, jnp.zeros((b, m), dtype=bool)], axis=1
        )
        pool_s, pos = jax.lax.top_k(all_s, p)
        pool_i = jnp.take_along_axis(all_i, pos, axis=1)
        explored = jnp.take_along_axis(all_e, pos, axis=1)
        return pool_s, pool_i, explored, visited

    pool_s, pool_i, _, _ = jax.lax.fori_loop(
        0, iters, body, (pool_s, pool_i, explored, visited)
    )
    top_s, pos = jax.lax.top_k(pool_s, k)
    top_i = jnp.take_along_axis(pool_i, pos, axis=1)
    return top_s, top_i


_cagra_walk = functools.partial(
    jax.jit,
    static_argnames=("k", "iters", "width", "itopk", "hash_bits",
                     "n_seeds"),
)(_walk_body)


# ---------------------------------------------------------------------------
# sharded walk: per-shard local walk + one all-gather top-k merge, the
# same collective pattern (and the same _MeshHolder static-arg trick) as
# parallel.mesh.sharded_cosine_topk
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=("k", "iters", "width", "itopk", "hash_bits",
                     "n_seeds", "mesh_holder"),
)
def _sharded_walk_impl(
    queries, matrix, adj, validf, k, iters, width, itopk, hash_bits,
    n_seeds, mesh_holder,
):
    from jax.sharding import PartitionSpec as P

    from nornicdb_tpu.parallel.mesh import compat_shard_map

    mesh = mesh_holder.mesh
    n_shards = mesh.shape["data"]
    shard_rows = matrix.shape[0] // n_shards

    def local_walk(q, m, a, v):
        # q replicated; m/a/v are this shard's local rows + LOCAL adjacency
        s, i = _walk_body(q, m, a, v, k, iters, width, itopk, hash_bits,
                          n_seeds)
        shard = jax.lax.axis_index("data")
        gi = i + shard * shard_rows
        all_s = jax.lax.all_gather(s, "data", axis=1, tiled=True)
        all_i = jax.lax.all_gather(gi, "data", axis=1, tiled=True)
        top_s, pos = jax.lax.top_k(all_s, k)
        return top_s, jnp.take_along_axis(all_i, pos, axis=1)

    return compat_shard_map(
        local_walk,
        mesh=mesh,
        in_specs=(P(), P("data", None), P("data", None), P("data")),
        out_specs=(P(), P()),
    )(queries, matrix, adj, validf)


def sharded_cagra_walk(
    queries: jnp.ndarray,
    matrix: jnp.ndarray,
    adj: jnp.ndarray,
    validf: jnp.ndarray,
    k: int,
    iters: int,
    width: int,
    itopk: int,
    hash_bits: int,
    n_seeds: int = 1024,
    mesh=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Multi-device CAGRA search: row-shard vectors + local adjacency
    over the mesh's ``data`` axis, walk per shard, one all-gather merge.
    ``adj`` must hold SHARD-LOCAL indices and ``matrix.shape[0]`` must
    divide evenly by the shard count."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nornicdb_tpu.parallel.mesh import _MeshHolder, data_mesh

    mesh = mesh or data_mesh()
    n = mesh.shape["data"]
    if matrix.shape[0] % n != 0:
        raise ValueError(
            f"capacity {matrix.shape[0]} not divisible by {n} shards")
    matrix = jax.device_put(matrix, NamedSharding(mesh, P("data", None)))
    adj = jax.device_put(adj, NamedSharding(mesh, P("data", None)))
    validf = jax.device_put(validf, NamedSharding(mesh, P("data")))
    queries = jax.device_put(queries, NamedSharding(mesh, P()))
    return _sharded_walk_impl(
        queries, matrix, adj, validf, k, iters, width, itopk, hash_bits,
        n_seeds, _MeshHolder(mesh),
    )


def merge_delta_hits(
    hits: Sequence[Tuple[str, float]],
    delta_ids: Sequence[str],
    delta_scores,
    k: int,
) -> List[Tuple[str, float]]:
    """One ranked hit list with exact delta scores merged in: an
    updated id's stale entry is REPLACED (its graph/snapshot score came
    from the pre-update vector), the list re-sorts score-desc and
    truncates to ``k``. The single read-your-writes merge semantic
    shared by the walk index and the walk-fused hybrid tier."""
    merged = dict(hits)
    for j, eid in enumerate(delta_ids):
        merged[eid] = float(delta_scores[j])
    return sorted(merged.items(), key=lambda kv: -kv[1])[:k]


# ---------------------------------------------------------------------------
# graph construction: device k-NN + rank-based reorder/reverse fill
# ---------------------------------------------------------------------------


def _knn_forward(matrix_n: np.ndarray, degree: int,
                 chunk: int = 1024) -> np.ndarray:
    """Forward k-NN edges [n, deg] by rank (self excluded), computed with
    the device brute-force kernel in query chunks (the Pallas fused
    kernel when enabled — same routing as BruteForceIndex.search_batch).
    """
    n = matrix_n.shape[0]
    deg = min(degree, max(n - 1, 1))
    k_knn = min(deg + 1, n)
    mj = jnp.asarray(matrix_n)
    vj = jnp.ones((n,), dtype=bool)
    if _use_pallas():
        from nornicdb_tpu.ops.pallas_topk import fused_cosine_topk

        topk = lambda q: fused_cosine_topk(q, mj, vj, k_knn)  # noqa: E731
    else:
        topk = lambda q: cosine_topk_auto(q, mj, vj, k_knn)  # noqa: E731
    fwd = np.empty((n, deg), dtype=np.int32)
    for start in range(0, n, chunk):
        stop = min(start + chunk, n)
        _, idx = topk(mj[start:stop])
        idx = np.asarray(idx)
        # drop self wherever it ranked (duplicate vectors can push the
        # self-match out of the top-k entirely); stable-sort keeps rank
        # order among the survivors
        not_self = idx != np.arange(start, stop, dtype=np.int32)[:, None]
        order = np.argsort(~not_self, axis=1, kind="stable")
        fwd[start:stop] = np.take_along_axis(idx, order, axis=1)[:, :deg]
    return fwd


def _rank_reorder(fwd: np.ndarray, degree: int,
                  chunk: int = 8192) -> np.ndarray:
    """CAGRA-style rank-based reordering: keep the top ``degree//2``
    forward edges, fill the rest with rank-ordered reverse edges (dedup
    against the kept set), then backfill with the remaining forward
    edges. Reverse edges are what make a pure k-NN graph navigable —
    hub nodes gain in-links from every cluster that ranks them."""
    n, deg = fwd.shape
    if n <= 1:
        return np.zeros((n, degree), dtype=np.int32)
    keep_f = min(max(degree // 2, 1), deg)

    # reverse lists grouped by destination, ordered (rank, src)
    dst = fwd.ravel()
    src = np.repeat(np.arange(n, dtype=np.int32), deg)
    rank = np.tile(np.arange(deg, dtype=np.int32), n)
    order = np.lexsort((src, rank, dst))
    dsts, srcs = dst[order], src[order]
    counts = np.bincount(dsts, minlength=n)
    offsets = np.zeros(n, dtype=np.int64)
    np.cumsum(counts[:-1], out=offsets[1:])
    pos = np.arange(len(dsts), dtype=np.int64) - offsets[dsts]
    rev = np.full((n, degree), -1, dtype=np.int32)
    take = pos < degree
    rev[dsts[take], pos[take]] = srcs[take]

    adj = np.full((n, degree), -1, dtype=np.int32)
    adj[:, :keep_f] = fwd[:, :keep_f]
    fill_w = degree - keep_f
    if fill_w == 0:
        return adj
    cand = np.concatenate([rev, fwd[:, keep_f:]], axis=1)
    mc = cand.shape[1]
    earlier = np.arange(mc)[None, :] < np.arange(mc)[:, None]
    for start in range(0, n, chunk):
        stop = min(start + chunk, n)
        c = cand[start:stop]
        bad = (c < 0) | (c == np.arange(start, stop,
                                        dtype=np.int32)[:, None])
        bad |= (c[:, :, None] == adj[start:stop, None, :keep_f]).any(2)
        bad |= ((c[:, :, None] == c[:, None, :]) & earlier[None]).any(2)
        good_first = np.argsort(bad, axis=1, kind="stable")
        picked = np.take_along_axis(c, good_first[:, :fill_w], axis=1)
        n_good = (~bad).sum(axis=1)
        usable = np.arange(fill_w)[None, :] < n_good[:, None]
        # short rows duplicate their best forward edge: a duplicate slot
        # is a no-op at search time (visited mask), never a wrong edge
        adj[start:stop, keep_f:] = np.where(usable, picked,
                                            fwd[start:stop, :1])
    return adj


# ---------------------------------------------------------------------------
# the index
# ---------------------------------------------------------------------------


class CagraIndex:
    """Fixed-out-degree graph ANN over a wrapped ``BruteForceIndex``.

    The brute index remains the mutable source of truth (adds/removes
    delegate to it); the graph is an immutable device-side build over a
    snapshot, rebuilt when churn exceeds ``rebuild_stale_frac``. Below
    ``min_n`` live rows search delegates to the brute kernel — at small
    N one MXU matmul beats any walk's dispatch chain.
    """

    def __init__(
        self,
        dims: Optional[int] = None,
        degree: int = 32,
        itopk: int = 64,
        search_width: int = 1,
        iters: Optional[int] = None,
        hash_bits: int = 16,
        n_seeds: int = 1024,
        min_n: int = 4096,
        n_shards: int = 1,
        rebuild_stale_frac: float = 0.1,
        build_inline: bool = True,
        brute: Optional[BruteForceIndex] = None,
    ):
        if itopk <= 0 or itopk & (itopk - 1):
            raise ValueError(
                f"itopk must be a positive power of two, got {itopk}")
        self.degree = degree
        self.itopk = itopk
        self.search_width = search_width
        self.iters = iters
        self.hash_bits = hash_bits
        self.n_seeds = n_seeds
        self.min_n = min_n
        self.n_shards = max(1, n_shards)
        self.rebuild_stale_frac = rebuild_stale_frac
        # build_inline=False defers even the FIRST build to a background
        # thread (read-path wiring like qdrant: searches serve the exact
        # brute kernel until the graph is ready); True blocks once, the
        # right call when the build runs on a write path (service
        # strategy switch) or in tests/benches that need determinism.
        self.build_inline = build_inline
        self._brute = brute if brute is not None else BruteForceIndex(dims)
        self._graph: Optional[Dict[str, Any]] = None
        self._build_lock = threading.Lock()
        self._rebuilding = False
        self._rebuild_started = 0.0  # backlog age for /readyz + gauges
        self._rebuild_flag_lock = threading.Lock()
        # (brute.mutations, built_mutations, ids, vectors) — the delta
        # block is identical between searches until a mutation lands, so
        # the steady state pays one integer compare instead of O(churn)
        # locked get() calls per request
        self._delta_cache: Optional[Tuple] = None
        self.builds = 0

    # -- delegation: the brute index owns the vectors. Mutations may go
    # through this wrapper OR directly to the shared brute (the service
    # and qdrant layers do the latter) — freshness therefore keys off
    # the brute's own mutation counter + changelog, never wrapper state.

    def __len__(self) -> int:
        return len(self._brute)

    def __contains__(self, ext_id: str) -> bool:
        return ext_id in self._brute

    def add(self, ext_id: str, vector: Sequence[float]) -> None:
        self._brute.add(ext_id, vector)

    def add_batch(self, items) -> None:
        self._brute.add_batch(items)

    def remove(self, ext_id: str) -> bool:
        return self._brute.remove(ext_id)

    def get(self, ext_id: str):
        return self._brute.get(ext_id)

    def ids(self) -> List[str]:
        return self._brute.ids()

    def snapshot(self):
        return self._brute.snapshot()

    def save(self, path: str) -> None:
        """Vectors only — the graph is derived state, rebuilt on demand
        after a load (a 50k x 256d build is seconds on any backend)."""
        self._brute.save(path)

    @classmethod
    def load(cls, path: str, **kwargs) -> "CagraIndex":
        brute = BruteForceIndex.load(path)
        return cls(brute=brute, **kwargs)

    # -- build ------------------------------------------------------------

    def _auto_iters(self, n: int) -> int:
        # the wide seed round lands every query in its basin, so the
        # walk only refines locally: ~0.75 * log2(n) hops, floor 8.
        # Measured at 50k x 256d (clustered): recall@10 plateaus ~2
        # iterations below this; the margin absorbs harder corpora.
        return max(8, int(np.ceil(0.75 * np.log2(max(n, 4)))))

    def build(self) -> bool:
        """(Re)build the graph from the brute snapshot. Returns False
        when below ``min_n`` (search stays on the brute path)."""
        with self._build_lock:
            return self._build_locked()

    def _build_locked(self) -> bool:
        mutations = getattr(self._brute, "mutations", 0)
        g = self._graph
        if g is not None and g["built_mutations"] == mutations:
            # another thread rebuilt while we waited on the lock (or an
            # explicit build() raced the auto-rebuild): the graph is
            # already current — a second multi-second kNN pass over the
            # same snapshot would only stall serving
            return True
        matrix, valid, ext_ids = self._brute.snapshot()
        live = [i for i, e in enumerate(ext_ids)
                if e is not None and valid[i]]
        n = len(live)
        if n < self.min_n:
            self._graph = None
            return False
        rows = np.asarray(matrix[live], dtype=np.float32)
        row_ids = [ext_ids[i] for i in live]

        s = self.n_shards
        base = -(-n // s)  # ceil
        r = pad_dim(base)
        d = rows.shape[1]
        mat = np.zeros((s * r, d), dtype=np.float32)
        adj = np.zeros((s * r, self.degree), dtype=np.int32)
        validf = np.zeros((s * r,), dtype=np.float32)
        all_ids: List[Optional[str]] = [None] * (s * r)
        for sh in range(s):
            lo, hi = sh * base, min((sh + 1) * base, n)
            if lo >= hi:
                continue
            local = rows[lo:hi]
            fwd = _knn_forward(local, self.degree)
            ladj = _rank_reorder(fwd, self.degree)
            mat[sh * r: sh * r + (hi - lo)] = local
            adj[sh * r: sh * r + (hi - lo)] = ladj
            validf[sh * r: sh * r + (hi - lo)] = 1.0
            all_ids[sh * r: sh * r + (hi - lo)] = row_ids[lo:hi]

        # quantized base (NORNICDB_VECTOR_QUANT != off, single-shard):
        # HBM holds int8 PCA-projected codes + the head prefilter
        # column; float32 rows stay HOST-side for the exact pool
        # rerank, so the device footprint drops ~4x. Sharded graphs
        # keep float32 (the mesh walk program is float32-only) — a
        # degrade, never a wrong answer.
        quant = None
        from nornicdb_tpu.search.device_quant import quant_mode

        if s == 1 and quant_mode() != "off" and n >= self.min_n:
            from nornicdb_tpu.config import env_int
            from nornicdb_tpu.search.device_quant import (
                quantize_graph_base,
            )

            # None = a PQ-mode gap (indivisible dims, too few rows to
            # train honest codebooks): the f32 graph serves instead
            quant = quantize_graph_base(mat)
            if quant is not None and quant["mode"] == "int8":
                quant["rot_dev"] = jnp.asarray(quant["rot"])
                # keep 3/4 of each expansion past the head prefilter:
                # measured (8k x 64d clustered, CPU) recall@10 0.93 at
                # 1/2, 0.98 at 3/4, 1.00 unpruned — 3/4 clears the
                # 0.95 sentinel floor with margin while still dropping
                # a quarter of the full-row gathers
                quant["keep"] = max(8, env_int(
                    "QUANT_WALK_KEEP",
                    (3 * self.search_width * self.degree) // 4))
        graph: Dict[str, Any] = {
            "n": n,
            "shards": s,
            "rows_per_shard": r,
            # host float32 under quant (rerank gather source); device
            # array otherwise — every consumer but the walk reads only
            # shapes/rows from it
            "matrix": mat if quant is not None else jnp.asarray(mat),
            "quant": quant,
            "adj": jnp.asarray(adj),
            "validf": jnp.asarray(validf),
            "row_ids": all_ids,
            "iters": (self.iters if self.iters is not None
                      else self._auto_iters(n)),
            "built_mutations": mutations,
            # globally unique build sequence: consumers that cache
            # derived state per graph (the walk-fused join map) key on
            # this instead of object identity, which can alias across
            # a gc'd dict or collide across index instances
            "build_seq": next(_BUILD_SEQ),
        }
        if s > 1:
            # pre-slice once for the single-device reference merge (a
            # per-search slice would re-copy every call) ...
            graph["shard_slices"] = [
                (graph["matrix"][sh * r:(sh + 1) * r],
                 graph["adj"][sh * r:(sh + 1) * r],
                 graph["validf"][sh * r:(sh + 1) * r])
                for sh in range(s)]
            if len(jax.devices()) >= s:
                # ... and place the arrays on the mesh ONCE: device_put
                # with an identical sharding is a no-op at search time,
                # so a persistent serving index never re-ships the
                # corpus across devices per batch
                from jax.sharding import NamedSharding, PartitionSpec
                from nornicdb_tpu.parallel.mesh import data_mesh

                mesh = data_mesh(s)
                graph["mesh"] = mesh
                rows_sh = NamedSharding(mesh, PartitionSpec("data", None))
                graph["matrix"] = jax.device_put(graph["matrix"], rows_sh)
                graph["adj"] = jax.device_put(graph["adj"], rows_sh)
                graph["validf"] = jax.device_put(
                    graph["validf"], NamedSharding(mesh,
                                                   PartitionSpec("data")))
        self._graph = graph
        self.builds += 1
        _CAGRA_C.labels("build").inc()
        return True

    def _ensure_graph(self) -> Optional[Dict[str, Any]]:
        g = self._graph
        mutations = getattr(self._brute, "mutations", 0)
        n_alive = len(self._brute)
        if g is not None:
            churn = mutations - g["built_mutations"]
            if churn > self.rebuild_stale_frac * max(g["n"], 1):
                # serve the CURRENT graph while a fresh one builds off
                # the search path: stale results stay correct (deletes
                # live-filtered, adds/updates delta-merged), and the
                # MicroBatcher leader never stalls a convoy for the
                # multi-second device kNN rebuild
                self._kick_background_rebuild()
            return g
        if n_alive < self.min_n:
            self._graph = None
            return None
        if not self.build_inline:
            # read-path wiring: never stall a search convoy on the first
            # build either — brute serves exactly until the graph lands
            self._kick_background_rebuild()
            return self._graph
        # inline initial build: there is no older graph to serve, and it
        # mirrors the blocking first HNSW build of that tier
        self.build()
        return self._graph

    def _kick_background_rebuild(self) -> None:
        with self._rebuild_flag_lock:
            if self._rebuilding:
                return
            self._rebuilding = True
            self._rebuild_started = time.time()
        _CAGRA_C.labels("background_rebuild").inc()

        def run():
            from nornicdb_tpu import admission as _adm

            try:
                # background maintenance lane (ISSUE 15): any coalescer
                # ride from this thread seals behind interactive work
                with _adm.lane_scope(_adm.LANE_BACKGROUND):
                    self.build()  # _build_locked no-ops if already fresh
            finally:
                # same lock as the set in _kick_background_rebuild: an
                # unguarded clear can interleave with a concurrent
                # kick's read-then-set and double-start a rebuild
                with self._rebuild_flag_lock:
                    self._rebuilding = False
                    self._rebuild_started = 0.0

        t = threading.Thread(target=run, name="cagra-rebuild", daemon=True)
        t.start()

    @property
    def graph_built(self) -> bool:
        return self._graph is not None

    # -- external consumers (the walk-fused hybrid tier) ------------------

    def ensure_graph(self) -> Optional[Dict[str, Any]]:
        """Current graph dict under the index's own rebuild policy
        (churn kicks a background rebuild; the stale graph keeps
        serving), or None while callers must use an exact tier."""
        return self._ensure_graph()

    def delta_block(self, g) -> Tuple[Optional[List[str]],
                                      Optional[np.ndarray]]:
        """Public delta accessor for fused pipelines composing their
        own freshness ladder on this graph: (ids, vectors) added or
        updated since ``g`` was built, or (None, None) on changelog
        overrun (callers degrade to an exact tier)."""
        return self._delta_block(g)

    def stats(self) -> Dict[str, Any]:
        g = self._graph
        return {
            "n_alive": len(self._brute),
            "graph_built": g is not None,
            "graph_n": g["n"] if g else 0,
            "shards": g["shards"] if g else 0,
            "degree": self.degree,
            "itopk": self.itopk,
            "iters": g["iters"] if g else None,
            "builds": self.builds,
        }

    def resource_stats(self) -> Dict[str, Any]:
        """Memory + freshness accounting for obs/resources.py: device
        bytes of the graph arrays (base matrix + fixed-degree adjacency
        + validity — the reorder maps live in ``adj``), the mutation
        gap between the live brute index and the built graph, and the
        background-rebuild backlog state."""
        g = self._graph
        dev_b = 0
        graph_rows = 0
        host_extra = 0
        quant_b = 0
        f32_base = 0
        if g is not None:
            quant = g.get("quant")
            for key in ("adj", "validf"):
                dev_b += int(getattr(g[key], "nbytes", 0) or 0)
            f32_base = int(getattr(g["matrix"], "nbytes", 0) or 0)
            if quant is None:
                dev_b += f32_base
            else:
                # quantized base: float32 rows live HOST-side (rerank
                # gather source); HBM holds codes+head+scale+rotation
                host_extra += f32_base
                keys = (("codes", "codebooks")
                        if quant["mode"] == "pq"
                        else ("codes", "codes_head", "scale", "rot_dev"))
                for key in keys:
                    quant_b += int(
                        getattr(quant[key], "nbytes", 0) or 0)
                dev_b += quant_b
            graph_rows = g["n"]
        mutations = getattr(self._brute, "mutations", 0)
        gap = (mutations - g["built_mutations"]) if g is not None else 0
        started = self._rebuild_started
        stats_extra = {}
        if quant_b:
            stats_extra = {
                "quant_device_bytes": quant_b,
                "compression_ratio": round(f32_base / max(quant_b, 1),
                                           3),
            }
        return {
            **stats_extra,
            "rows": graph_rows,
            "capacity": (g["shards"] * g["rows_per_shard"]) if g else 0,
            "device_bytes": dev_b,
            # row_ids table (pointer-sized slots) + the host-resident
            # float32 base under quantization
            "host_bytes": (8 * len(g["row_ids"]) + host_extra)
            if g else 0,
            "mutation_gap": gap,
            "rebuild_in_flight": 1.0 if self._rebuilding else 0.0,
            "rebuild_backlog_s": (
                round(time.time() - started, 3)
                if self._rebuilding and started else 0.0),
            "builds": self.builds,
        }

    # -- search -----------------------------------------------------------

    def search(self, query: Sequence[float], k: int = 10,
               **kw) -> List[Tuple[str, float]]:
        return self.search_batch(
            np.asarray([query], dtype=np.float32), k, **kw)[0]

    def search_batch(
        self,
        queries: np.ndarray,
        k: int = 10,
        itopk: Optional[int] = None,
        iters: Optional[int] = None,
        width: Optional[int] = None,
    ) -> List[List[Tuple[str, float]]]:
        """Batched ANN search; per-query [(ext_id, cosine)] best-first.

        Batch and k are padded to pow2 buckets so every arrival-rate
        batch from the MicroBatcher reuses one of log2(max_batch)
        compiled programs. ``itopk``/``iters``/``width`` overrides exist
        for recall/qps sweeps (bench.py); production callers leave them
        to the index config."""
        queries = np.asarray(queries, dtype=np.float32)
        if queries.ndim != 2:
            raise ValueError(f"queries must be [B, D], got {queries.shape}")
        if len(queries) == 0:
            return []
        g = self._ensure_graph()
        if g is None:
            return self._brute.search_batch(queries, k)
        tier = ("vector_walk_quant" if g.get("quant") is not None
                else "vector_walk_f32")
        hold = None
        if not _audit.tier_allowed(tier):
            # shadow-parity quarantine: the walk steps down its ladder
            # to the exact tier until the breach clears
            hold = "quarantine"
        elif not _audit.admission_allows(tier):
            # admission posture (ISSUE 15): overload forces the walk
            # down to the exact tier to shrink device pressure
            hold = "admission"
        if hold is not None:
            _CAGRA_C.labels("exact_fallback_quarantine").inc()
            self._degrade(tier, hold, g)
            return self._brute.search_batch(queries, k)
        p = itopk or self.itopk
        quant0 = g.get("quant")
        if quant0 is not None and quant0["mode"] == "pq" and itopk is None:
            # PQ ADC carries reconstruction noise the int8 rung doesn't:
            # widen the beam 4x (still pow2) so the exact host rerank of
            # the pool recovers the true top-k despite noisy navigation
            p = min(4 * p, 1024)
        if min(k, g["n"]) > p:
            # the pool can only ever hold itopk candidates — a deeper
            # request silently truncated would differ from the brute and
            # hnsw strategies, so serve it exactly instead
            _CAGRA_C.labels("exact_fallback_itopk").inc()
            self._degrade(tier, "itopk_exceeded", g)
            return self._brute.search_batch(queries, k)
        delta_ids, delta_vecs = self._delta_block(g)
        if delta_ids is None:
            # churn outran the brute changelog (only possible while a
            # background rebuild is in flight): serve exactly until the
            # fresh graph swaps in
            _CAGRA_C.labels("exact_fallback_changelog").inc()
            self._degrade(tier, "changelog_overrun", g)
            return self._brute.search_batch(queries, k)
        n_iters = iters if iters is not None else g["iters"]
        w = width or self.search_width
        k_eff = min(k, g["n"], p)
        if k_eff < 1:
            return [[] for _ in range(len(queries))]
        b = len(queries)
        bb = pow2_bucket(max(b, 1))
        kb = min(pow2_bucket(k_eff), p)
        if bb != b:
            queries = np.concatenate(
                [queries,
                 np.broadcast_to(queries[:1], (bb - b,) + queries.shape[1:])],
                axis=0)
        qn = l2_normalize(jnp.asarray(queries))
        t0 = time.time()
        s, i = self._walk(g, qn, kb, n_iters, w, p)
        # force to host INSIDE the timed window: jax dispatch is async,
        # so timing the call alone would record enqueue, not the walk
        s_host, i_host = np.asarray(s), np.asarray(i)
        record_dispatch("cagra_walk", bb, kb, time.time() - t0)
        # per-query cost: seed round + iters x width x degree distance
        # evals at the padded batch; real (pre-pad) queries counted
        from nornicdb_tpu.obs import cost as _cost

        if _cost.pricing_enabled():
            quant = g.get("quant")
            if quant is not None:
                if quant["mode"] == "pq":
                    flops, byts = _cost.price_walk_pq(
                        bb, int(queries.shape[1]), n_iters, w,
                        self.degree, p, quant["pq_m"],
                        quant["pq_codes"], n_seeds=self.n_seeds)
                else:
                    flops, byts = _cost.price_walk_quant(
                        bb, int(queries.shape[1]), n_iters, w,
                        self.degree, p, quant["head_dims"],
                        quant["keep"], n_seeds=self.n_seeds)
                rf, rb = _cost.price_rerank(bb, p,
                                            int(queries.shape[1]))
                flops, byts = flops + rf, byts + rb
            else:
                flops, byts = _cost.price_walk(
                    bb, int(queries.shape[1]), n_iters, w, self.degree,
                    p, n_seeds=self.n_seeds)
            _cost.record_query_cost("cagra_walk", _cost.cost_name(self),
                                    b, flops, byts)
        out = self._resolve(g, s_host[:b], i_host[:b], k_eff)
        if delta_ids:
            _CAGRA_C.labels("delta_merge").inc()
            out = self._merge_delta(out, delta_ids, delta_vecs,
                                    np.asarray(qn)[:b], k_eff)
        # a stale graph's live-filter can under-fill a row even though
        # plenty of live rows remain (deletes clustered in the query's
        # neighborhood). Serve those batches exactly — rare by
        # construction (churn is capped by the rebuild threshold), and
        # callers like hybrid RRF assume k hits when the corpus has them
        want = min(k_eff, len(self._brute))
        if any(len(hits) < want for hits in out):
            _CAGRA_C.labels("exact_fallback_underfill").inc()
            self._degrade(tier, "underfill", g)
            return self._brute.search_batch(queries[:b], k)
        _audit.note_batch_tier(tier)
        return out

    def _degrade(self, tier: str, reason: str, g) -> None:
        """Structured ledger record for a walk -> exact-tier step (the
        legacy cagra_events_total label stays as the alias)."""
        from nornicdb_tpu.obs import cost as _cost

        _audit.record_degrade(
            "vector", tier, "vector_brute_f32", reason,
            index=_cost.cost_name(self._brute),
            versions={"build_seq": g.get("build_seq"),
                      "built_mutations": g.get("built_mutations"),
                      "mutations": getattr(self._brute, "mutations", 0)})

    def _delta_block(self, g):
        """(ids, vectors[m,D]) of rows added/updated since the graph
        build, straight from the brute changelog — mutations that
        bypassed this wrapper (service index_node, qdrant upserts write
        straight to the shared brute) are covered too. (None, None) =
        changelog trimmed past the marker. Memoized on the mutation
        counter: until a write lands, repeat searches pay one integer
        compare instead of O(churn) locked row fetches."""
        m = getattr(self._brute, "mutations", 0)
        cached = self._delta_cache
        if cached is not None and cached[0] == m \
                and cached[1] == g["built_mutations"]:
            return cached[2], cached[3]
        fn = getattr(self._brute, "changed_since", None)
        ids = fn(g["built_mutations"]) if fn is not None else []
        if ids is None:
            block = (None, None)
        else:
            pairs = []
            for eid in ids:
                v = self._brute.get(eid)  # None if removed since logging
                if v is not None:
                    pairs.append((eid, v))
            block = ([eid for eid, _ in pairs],
                     np.stack([v for _, v in pairs]) if pairs else None)
        self._delta_cache = (m, g["built_mutations"], block[0], block[1])
        return block

    def _merge_delta(self, hits_rows, ids, dvecs, qn, k_eff):
        """Exact-score rows added/updated since the build and merge them
        into the walk results (read-your-writes without a rebuild). The
        walk's entry for an updated id is replaced — its graph score was
        computed from the pre-update vector."""
        ds = qn @ dvecs.T  # rows are stored normalized; exact cosine
        return [merge_delta_hits(hits, ids, ds[r], k_eff)
                for r, hits in enumerate(hits_rows)]

    def _walk(self, g, qn, kb, n_iters, w, p):
        if g.get("quant") is not None:
            return self._walk_quant(g, qn, kb, n_iters, w, p)
        if g["shards"] == 1:
            return _cagra_walk(
                qn, g["matrix"], g["adj"], g["validf"],
                k=kb, iters=n_iters, width=w, itopk=p,
                hash_bits=self.hash_bits, n_seeds=self.n_seeds)
        if "mesh" in g and len(jax.devices()) >= g["shards"]:
            return sharded_cagra_walk(
                qn, g["matrix"], g["adj"], g["validf"],
                kb, n_iters, w, p, self.hash_bits, self.n_seeds,
                mesh=g["mesh"])
        return self._walk_shards_single_device(g, qn, kb, n_iters, w, p)

    def _walk_quant(self, g, qn, kb, n_iters, w, p):
        """Quantized walk (device_quant): the greedy walk runs over the
        int8 PCA-projected base with the two-stage frontier scorer
        (head prefilter -> full int8 dot), then the ENTIRE itopk pool
        is exactly re-scored against the host float32 rows before the
        final top-k — approximate scores rank the pool, never an
        answer. Shapes match the float32 walk's (scores, row ids)."""
        from nornicdb_tpu.search.device_quant import (
            _pq_walk,
            _quant_walk,
        )

        q = g["quant"]
        if q["mode"] == "pq":
            # PQ rung (ISSUE 17 satellite): codes-only ADC walk in the
            # original basis — M bytes per row in HBM, exact rerank of
            # the whole pool below is identical to the int8 path
            s, i = _pq_walk(
                qn, q["codes"], q["codebooks"], g["adj"], g["validf"],
                k=p, iters=n_iters, width=w, itopk=p,
                hash_bits=self.hash_bits, n_seeds=self.n_seeds)
        else:
            qp = qn @ q["rot_dev"]  # orthogonal: norms/dots preserved
            s, i = _quant_walk(
                qp, q["codes"], q["codes_head"], q["scale"], g["adj"],
                g["validf"], k=p, iters=n_iters, width=w, itopk=p,
                hash_bits=self.hash_bits, n_seeds=self.n_seeds,
                keep=q["keep"])
        s_h, i_h = np.asarray(s), np.asarray(i)
        qh = np.asarray(qn)
        gathered = g["matrix"][i_h]  # host f32 [B, itopk, D]
        exact = np.einsum("bpd,bd->bp", gathered, qh)
        exact = np.where(s_h > 0.5 * NEG_INF, exact,
                         np.float32(NEG_INF))
        order = np.argsort(-exact, axis=1, kind="stable")[:, :kb]
        return (np.take_along_axis(exact, order, axis=1),
                np.take_along_axis(i_h, order, axis=1))

    def _walk_shards_single_device(self, g, qn, kb, n_iters, w, p):
        """Reference merge for the sharded layout on one device: walk
        each shard's local subgraph, concatenate shard-local winners in
        shard order (exactly the all-gather layout) and take one global
        top-k. The sharded path must be bit-identical to this."""
        r = g["rows_per_shard"]
        parts_s, parts_i = [], []
        for sh, (m_sh, a_sh, v_sh) in enumerate(g["shard_slices"]):
            s, i = _cagra_walk(
                qn, m_sh, a_sh, v_sh,
                k=kb, iters=n_iters, width=w, itopk=p,
                hash_bits=self.hash_bits, n_seeds=self.n_seeds)
            parts_s.append(s)
            parts_i.append(i + sh * r)
        return concat_topk(parts_s, parts_i, kb)

    def _resolve(self, g, s, i, k_eff):
        """Map walk row ids to ext ids, dropping never-filled slots and
        rows deleted since the build (live-membership filter keeps stale
        graphs honest between rebuilds)."""
        row_ids = g["row_ids"]
        stale = getattr(self._brute, "mutations", 0) != g["built_mutations"]
        out: List[List[Tuple[str, float]]] = []
        for row in range(s.shape[0]):
            hits: List[Tuple[str, float]] = []
            for col in range(s.shape[1]):
                if s[row, col] < 0.5 * NEG_INF:
                    break
                eid = row_ids[int(i[row, col])]
                if eid is None:
                    continue
                if stale and eid not in self._brute:
                    continue
                hits.append((eid, float(s[row, col])))
                if len(hits) >= k_eff:
                    break
            out.append(hits)
        return out
