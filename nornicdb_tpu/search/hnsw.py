"""HNSW approximate-nearest-neighbor index.

Re-expresses the reference's custom HNSW (pkg/search/hnsw_index.go:74
``HNSWIndex``, Add :174, SearchWithEf :342, heap-pooled layer search :973,
tombstones + ShouldRebuild :456, Save/Load :490,568) for the TPU design:

- the graph walk is inherently serial/pointer-chasing and stays on CPU
  (SURVEY.md §7 "hard parts");
- distance evaluations are *batched*: a node's whole neighbor list is
  scored with one NumPy matrix-vector product (the CPU analog of the
  reference's GPU distance batches), and build candidate sets can be
  scored on-device for large indexes;
- **BM25-seeded insertion order**: lexically discriminative docs are
  inserted first to form a high-quality backbone (reference
  search.go:3785-3871; 2.7x faster 1M-vector builds).

Tombstoned entries are traversed but never returned; when the tombstone
ratio passes ``rebuild_threshold`` the owner should rebuild.
"""

from __future__ import annotations

import heapq
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class HNSWIndex:
    def __init__(
        self,
        dims: Optional[int] = None,
        m: int = 16,
        ef_construction: int = 200,
        ef_search: int = 64,
        seed: int = 42,
        rebuild_threshold: float = 0.2,
    ):
        self.dims = dims
        self.m = m
        self.m0 = 2 * m  # level-0 degree cap
        self.ef_construction = ef_construction
        self.ef_search = ef_search
        self.rebuild_threshold = rebuild_threshold
        self._ml = 1.0 / math.log(m)
        self._rng = np.random.default_rng(seed)
        self._lock = threading.RLock()

        self._vectors: Optional[np.ndarray] = None  # [cap, D] normalized
        self._capacity = 0
        self._count = 0
        self._ext_ids: List[Optional[str]] = []
        self._slot_of: Dict[str, int] = {}
        self._alive: List[bool] = []
        self._levels: List[int] = []
        # _neighbors[slot][level] -> list of neighbor slots
        self._neighbors: List[List[List[int]]] = []
        self._entry: int = -1
        self._max_level: int = -1
        self._tombstones = 0

    def __len__(self) -> int:
        return len(self._slot_of)

    def __contains__(self, ext_id: str) -> bool:
        with self._lock:
            return ext_id in self._slot_of

    def ids(self):
        """Alive external ids (IVF-HNSW reload rebuilds its routing map
        from these)."""
        with self._lock:
            return list(self._slot_of.keys())

    @property
    def tombstone_ratio(self) -> float:
        total = self._count
        return self._tombstones / total if total else 0.0

    def should_rebuild(self) -> bool:
        """Reference: ShouldRebuild (hnsw_index.go:456)."""
        return self.tombstone_ratio > self.rebuild_threshold

    # -- storage ----------------------------------------------------------

    @staticmethod
    def _normalize(v: np.ndarray) -> np.ndarray:
        n = np.linalg.norm(v)
        return v / n if n > 1e-12 else v

    def _grow(self, needed: int, dims: int) -> None:
        if self.dims is None:
            self.dims = dims
        if dims != self.dims:
            raise ValueError(f"dims mismatch: index={self.dims}, vector={dims}")
        if needed <= self._capacity:
            return
        new_cap = max(256, self._capacity * 2, needed)
        new_m = np.zeros((new_cap, self.dims), dtype=np.float32)
        if self._vectors is not None:
            new_m[: self._capacity] = self._vectors
        self._vectors = new_m
        self._capacity = new_cap

    def _dist_many(self, q: np.ndarray, slots: Sequence[int]) -> np.ndarray:
        """Batched cosine distances (1 - dot) — one mat-vec per call."""
        idx = np.asarray(slots, dtype=np.int64)
        return 1.0 - self._vectors[idx] @ q

    # -- layer search (reference: searchLayerHeapPooled :973) --------------

    def _search_layer(
        self, q: np.ndarray, entries: List[Tuple[float, int]], ef: int, level: int
    ) -> List[Tuple[float, int]]:
        """Beam search one layer. entries/result: (dist, slot) min-heaps."""
        visited = {s for _, s in entries}
        candidates = list(entries)  # min-heap by dist
        heapq.heapify(candidates)
        result = [(-d, s) for d, s in entries]  # max-heap (neg dist)
        heapq.heapify(result)
        while candidates:
            d, slot = heapq.heappop(candidates)
            if result and d > -result[0][0]:
                break
            neigh = [
                n for n in self._neighbors[slot][level] if n not in visited
            ]
            if not neigh:
                continue
            visited.update(neigh)
            dists = self._dist_many(q, neigh)
            worst = -result[0][0] if result else float("inf")
            for nd, ns in zip(dists, neigh):
                nd = float(nd)
                if len(result) < ef or nd < worst:
                    heapq.heappush(candidates, (nd, ns))
                    heapq.heappush(result, (-nd, ns))
                    if len(result) > ef:
                        heapq.heappop(result)
                    worst = -result[0][0]
        return sorted((-nd, s) for nd, s in result)

    def _select_neighbors(
        self, cands: List[Tuple[float, int]], m: int
    ) -> List[int]:
        """Heuristic neighbor selection with diversity pruning: a candidate
        is kept only if it is closer to the query than to any already-kept
        neighbor (standard HNSW heuristic)."""
        kept: List[int] = []
        for d, slot in cands:
            if len(kept) >= m:
                break
            if not kept:
                kept.append(slot)
                continue
            d_to_kept = 1.0 - self._vectors[kept] @ self._vectors[slot]
            if np.all(d < d_to_kept):
                kept.append(slot)
        # backfill with closest if the heuristic was too aggressive
        if len(kept) < m:
            for d, slot in cands:
                if slot not in kept:
                    kept.append(slot)
                    if len(kept) >= m:
                        break
        return kept

    # -- insert (reference: Add :174) --------------------------------------

    def add(self, ext_id: str, vector: Sequence[float]) -> None:
        v = self._normalize(np.asarray(vector, dtype=np.float32))
        with self._lock:
            if ext_id in self._slot_of:
                # an in-place vector overwrite would leave the node's graph
                # edges anchored in the old region (silent recall loss);
                # tombstone the old slot and insert fresh so links re-form
                self.remove(ext_id)
            self._grow(self._count + 1, v.shape[0])
            slot = self._count
            self._count += 1
            self._vectors[slot] = v
            self._ext_ids.append(ext_id)
            self._slot_of[ext_id] = slot
            self._alive.append(True)
            level = int(-math.log(max(self._rng.random(), 1e-12)) * self._ml)
            self._levels.append(level)
            self._neighbors.append([[] for _ in range(level + 1)])

            if self._entry < 0:
                self._entry = slot
                self._max_level = level
                return

            # greedy descend from the top to level+1
            ep = [(float(1.0 - self._vectors[self._entry] @ v), self._entry)]
            for lv in range(self._max_level, level, -1):
                ep = self._search_layer(v, ep, 1, lv)

            # connect on each level from min(max_level, level) down to 0
            for lv in range(min(self._max_level, level), -1, -1):
                cands = self._search_layer(v, ep, self.ef_construction, lv)
                m_max = self.m0 if lv == 0 else self.m
                chosen = self._select_neighbors(cands, self.m)
                self._neighbors[slot][lv] = list(chosen)
                for c in chosen:
                    nb = self._neighbors[c][lv]
                    nb.append(slot)
                    if len(nb) > m_max:
                        # re-prune the overfull neighbor's list
                        d = 1.0 - self._vectors[nb] @ self._vectors[c]
                        order = sorted(zip(d.tolist(), nb))
                        self._neighbors[c][lv] = self._select_neighbors(
                            order, m_max
                        )
                ep = cands
            if level > self._max_level:
                self._max_level = level
                self._entry = slot

    def build(
        self,
        items: Sequence[Tuple[str, Sequence[float]]],
        seed_ids: Optional[Sequence[str]] = None,
    ) -> None:
        """Bulk build; if ``seed_ids`` given (BM25 seeds), those docs are
        inserted first to form the backbone (reference: seed-first build,
        search.go:3785-3871)."""
        if seed_ids:
            seed_set = set(seed_ids)
            by_id = {i: v for i, v in items}
            ordered = [(i, by_id[i]) for i in seed_ids if i in by_id]
            ordered += [(i, v) for i, v in items if i not in seed_set]
        else:
            ordered = list(items)
        for ext_id, vec in ordered:
            self.add(ext_id, vec)

    # -- delete (tombstones) ----------------------------------------------

    def remove(self, ext_id: str) -> bool:
        with self._lock:
            slot = self._slot_of.pop(ext_id, None)
            if slot is None:
                return False
            self._alive[slot] = False
            self._tombstones += 1
            return True

    # -- query (reference: SearchWithEf :342) -------------------------------

    def search(
        self, query: Sequence[float], k: int = 10, ef: Optional[int] = None
    ) -> List[Tuple[str, float]]:
        q = self._normalize(np.asarray(query, dtype=np.float32))
        with self._lock:
            if self._entry < 0 or not self._slot_of:
                return []
            ef = max(ef or self.ef_search, k)
            # tombstones are filtered from results after the beam, so widen
            # the beam proportionally or k alive survivors may not remain
            if self._tombstones:
                ef = int(ef * (1.0 + 2.0 * self.tombstone_ratio)) + 1
            ep = [(float(1.0 - self._vectors[self._entry] @ q), self._entry)]
            for lv in range(self._max_level, 0, -1):
                ep = self._search_layer(q, ep, 1, lv)
            found = self._search_layer(q, ep, ef, 0)
            out = []
            for d, slot in found:
                if not self._alive[slot]:
                    continue
                out.append((self._ext_ids[slot], 1.0 - d))
                if len(out) >= k:
                    break
            return out

    # -- persistence -------------------------------------------------------

    def save(self, path: str) -> None:
        with self._lock:
            np.savez_compressed(
                path,
                vectors=self._vectors[: self._count]
                if self._vectors is not None
                else np.zeros((0, 0), np.float32),
                levels=np.asarray(self._levels, dtype=np.int32),
                alive=np.asarray(self._alive, dtype=bool),
                ext_ids=np.asarray(
                    [e if e is not None else "" for e in self._ext_ids],
                    dtype=object,
                ),
                neighbors=np.asarray(
                    [
                        [list(map(int, lv)) for lv in per_node]
                        for per_node in self._neighbors
                    ],
                    dtype=object,
                ),
                meta=np.asarray(
                    [self._entry, self._max_level, self.m, self.dims or 0,
                     self.ef_construction, self.ef_search],
                    dtype=np.int64,
                ),
            )

    @classmethod
    def load(cls, path: str) -> "HNSWIndex":
        data = np.load(path if path.endswith(".npz") else path + ".npz",
                       allow_pickle=True)
        meta = [int(x) for x in data["meta"]]
        entry, max_level, m, dims = meta[:4]
        # older snapshots (4-field meta) predate ef persistence
        ef_c = meta[4] if len(meta) > 4 else 200
        ef_s = meta[5] if len(meta) > 5 else 64
        idx = cls(dims=dims or None, m=m, ef_construction=ef_c,
                  ef_search=ef_s)
        vecs = data["vectors"]
        idx._count = vecs.shape[0]
        idx._capacity = vecs.shape[0]
        idx._vectors = np.ascontiguousarray(vecs, dtype=np.float32)
        idx._levels = [int(x) for x in data["levels"]]
        idx._alive = [bool(x) for x in data["alive"]]
        idx._ext_ids = [str(e) if e else None for e in data["ext_ids"]]
        idx._neighbors = [
            [list(lv) for lv in per_node] for per_node in data["neighbors"]
        ]
        idx._slot_of = {
            e: i
            for i, e in enumerate(idx._ext_ids)
            if e is not None and idx._alive[i]
        }
        idx._tombstones = sum(1 for a in idx._alive if not a)
        idx._entry = entry
        idx._max_level = max_level
        return idx
