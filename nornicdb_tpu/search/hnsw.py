"""HNSW approximate-nearest-neighbor index with batched wave builds.

Re-expresses the reference's custom HNSW (pkg/search/hnsw_index.go:74
``HNSWIndex``, Add :174, SearchWithEf :342, heap-pooled layer search :973,
tombstones + ShouldRebuild :456, Save/Load :490,568) for the TPU design:

- the graph walk is inherently serial/pointer-chasing per query and
  stays on the host (SURVEY.md §7 "hard parts") — but it vectorizes
  *across queries*: adjacency is stored as padded int32 matrices (one
  [n, width] matrix per level), so a whole batch of beam searches runs
  as gathers + one ``einsum`` per expansion step instead of per-node
  Python heap churn. This is the layout GPU/TPU bulk builders use
  (batch-parallel construction), and the arrays feed the device
  data plane unchanged.
- ``build()`` inserts in *waves*: each wave's beam searches run batched
  against the pre-wave graph, then links are connected host-side. Wave
  sizes are capped relative to the current graph so intra-wave
  blindness (wave members not seeing each other) cannot degrade the
  backbone — the same trade bulk GPU HNSW builders make.
- **BM25-seeded insertion order**: lexically discriminative docs are
  inserted first to form a high-quality backbone (reference
  search.go:3785-3871; 2.7x faster 1M-vector builds).

Tombstoned entries are traversed but never returned; when the tombstone
ratio passes ``rebuild_threshold`` the owner should rebuild.
"""

from __future__ import annotations

import heapq
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class HNSWIndex:
    def __init__(
        self,
        dims: Optional[int] = None,
        m: int = 16,
        ef_construction: int = 200,
        ef_search: int = 64,
        seed: int = 42,
        rebuild_threshold: float = 0.2,
    ):
        self.dims = dims
        self.m = m
        self.m0 = 2 * m  # level-0 degree cap
        self.ef_construction = ef_construction
        self.ef_search = ef_search
        self.rebuild_threshold = rebuild_threshold
        self._ml = 1.0 / math.log(m)
        self._rng = np.random.default_rng(seed)
        self._lock = threading.RLock()

        self._vectors: Optional[np.ndarray] = None  # [cap, D] normalized
        self._capacity = 0
        self._count = 0
        self._ext_ids: List[Optional[str]] = []
        self._slot_of: Dict[str, int] = {}
        self._alive: List[bool] = []
        self._levels: List[int] = []
        # per-level padded adjacency: _nbrL[lv] int32 [cap, width] (-1
        # pad), _cntL[lv] int32 [cap]; width = m0 at level 0 else m
        self._nbrL: List[np.ndarray] = []
        self._cntL: List[np.ndarray] = []
        self._entry: int = -1
        self._max_level: int = -1
        self._tombstones = 0
        # reusable visited-stamp scratch for batched searches (guarded by
        # self._lock); zeroed only when the uint8 generation space wraps
        self._visit_buf: Optional[np.ndarray] = None
        self._visit_base = 0

    def __len__(self) -> int:
        return len(self._slot_of)

    def __contains__(self, ext_id: str) -> bool:
        with self._lock:
            return ext_id in self._slot_of

    def ids(self):
        """Alive external ids (IVF-HNSW reload rebuilds its routing map
        from these)."""
        with self._lock:
            return list(self._slot_of.keys())

    @property
    def tombstone_ratio(self) -> float:
        total = self._count
        return self._tombstones / total if total else 0.0

    def should_rebuild(self) -> bool:
        """Reference: ShouldRebuild (hnsw_index.go:456)."""
        return self.tombstone_ratio > self.rebuild_threshold

    # -- storage ----------------------------------------------------------

    @staticmethod
    def _normalize(v: np.ndarray) -> np.ndarray:
        n = np.linalg.norm(v)
        return v / n if n > 1e-12 else v

    # adjacency rows carry this much slack past the degree cap; a row is
    # pruned back to the cap only when the slack fills, amortizing the
    # (vectorized but still per-node) diversity prune across ~SLACK
    # back-link insertions
    SLACK = 8

    def _level_width(self, lv: int) -> int:
        return (self.m0 if lv == 0 else self.m) + self.SLACK

    def _level_cap(self, lv: int) -> int:
        return self.m0 if lv == 0 else self.m

    def _ensure_level(self, lv: int) -> None:
        while len(self._nbrL) <= lv:
            w = self._level_width(len(self._nbrL))
            self._nbrL.append(np.full((self._capacity, w), -1, np.int32))
            self._cntL.append(np.zeros(self._capacity, np.int32))

    def _grow(self, needed: int, dims: int) -> None:
        if self.dims is None:
            self.dims = dims
        if dims != self.dims:
            raise ValueError(f"dims mismatch: index={self.dims}, vector={dims}")
        if needed <= self._capacity:
            return
        new_cap = max(256, self._capacity * 2, needed)
        new_m = np.zeros((new_cap, self.dims), dtype=np.float32)
        if self._vectors is not None:
            new_m[: self._capacity] = self._vectors
        self._vectors = new_m
        for lv in range(len(self._nbrL)):
            w = self._nbrL[lv].shape[1]
            grown = np.full((new_cap, w), -1, np.int32)
            grown[: self._capacity] = self._nbrL[lv]
            self._nbrL[lv] = grown
            gcnt = np.zeros(new_cap, np.int32)
            gcnt[: self._capacity] = self._cntL[lv]
            self._cntL[lv] = gcnt
        self._capacity = new_cap

    def _neighbors_of(self, slot: int, lv: int) -> np.ndarray:
        return self._nbrL[lv][slot, : self._cntL[lv][slot]]

    def _set_neighbors(self, slot: int, lv: int, nbrs: Sequence[int]) -> None:
        w = self._nbrL[lv].shape[1]
        nbrs = list(nbrs)[:w]
        self._nbrL[lv][slot, : len(nbrs)] = nbrs
        self._nbrL[lv][slot, len(nbrs):] = -1
        self._cntL[lv][slot] = len(nbrs)

    def _dist_many(self, q: np.ndarray, slots: np.ndarray) -> np.ndarray:
        """Batched cosine distances (1 - dot) — one mat-vec per call."""
        return 1.0 - self._vectors[slots] @ q

    # -- layer search (reference: searchLayerHeapPooled :973) --------------

    def _search_layer(
        self, q: np.ndarray, entries: List[Tuple[float, int]], ef: int, level: int
    ) -> List[Tuple[float, int]]:
        """Beam search one layer, single query (latency path).
        entries/result: (dist, slot) min-heaps."""
        visited = {s for _, s in entries}
        candidates = list(entries)  # min-heap by dist
        heapq.heapify(candidates)
        result = [(-d, s) for d, s in entries]  # max-heap (neg dist)
        heapq.heapify(result)
        while candidates:
            d, slot = heapq.heappop(candidates)
            if result and d > -result[0][0]:
                break
            row = self._neighbors_of(slot, level)
            neigh = [n for n in row.tolist() if n not in visited]
            if not neigh:
                continue
            visited.update(neigh)
            dists = self._dist_many(q, np.asarray(neigh, np.int64))
            worst = -result[0][0] if result else float("inf")
            for nd, ns in zip(dists, neigh):
                nd = float(nd)
                if len(result) < ef or nd < worst:
                    heapq.heappush(candidates, (nd, ns))
                    heapq.heappush(result, (-nd, ns))
                    if len(result) > ef:
                        heapq.heappop(result)
                    worst = -result[0][0]
        return sorted((-nd, s) for nd, s in result)

    def _select_neighbors(
        self, cands: List[Tuple[float, int]], m: int
    ) -> List[int]:
        """Heuristic neighbor selection with diversity pruning: a candidate
        is kept only if it is closer to the query than to any already-kept
        neighbor (standard HNSW heuristic). Vectorized: one pairwise
        distance matrix over the (4m-capped) candidate list, then a
        greedy mask update per kept neighbor — no per-candidate matvec."""
        cands = cands[: 4 * m]
        C = len(cands)
        if C <= m:
            return [s for _, s in cands]
        slots = np.fromiter((s for _, s in cands), dtype=np.int64, count=C)
        dq = np.fromiter((d for d, _ in cands), dtype=np.float32, count=C)
        V = self._vectors[slots]
        M = 1.0 - V @ V.T  # [C, C] candidate-candidate distances
        ok = np.ones(C, dtype=bool)
        taken = np.zeros(C, dtype=bool)
        kept: List[int] = []
        for i in range(C):
            if not ok[i]:
                continue
            kept.append(int(slots[i]))
            taken[i] = True
            if len(kept) >= m:
                break
            # survivors must be closer to the query than to neighbor i
            ok &= dq < M[:, i]
            ok[i] = False
        # backfill with closest if the heuristic was too aggressive
        if len(kept) < m:
            for i in range(C):
                if not taken[i]:
                    kept.append(int(slots[i]))
                    taken[i] = True
                    if len(kept) >= m:
                        break
        return kept

    def _visit_scratch(self, rows: int) -> Tuple[np.ndarray, np.ndarray]:
        """[rows, capacity] stamp buffer + per-row generation starts
        (caller holds the lock and consumes at most 16 generations —
        one per (level, phase), far above any real level count). The
        buffer is reallocated only on growth and zeroed only when the
        uint8 generation space wraps, instead of ~100MB of fresh zeroed
        pages per wave."""
        buf = self._visit_buf
        if (buf is None or buf.shape[0] < rows
                or buf.shape[1] < self._capacity):
            rows_cap = max(rows, self.WAVE_MAX)
            self._visit_buf = buf = np.zeros(
                (rows_cap, self._capacity), np.uint8)
            self._visit_base = 0
        if self._visit_base > 239:
            buf[:] = 0
            self._visit_base = 0
        base = self._visit_base
        self._visit_base = base + 16
        return buf, np.full(rows, base, np.uint8)

    def _add_link(self, c: int, lv: int, slot: int) -> None:
        """Append back-link c -> slot; when the slack fills, prune the
        row back to the level's degree cap."""
        cnt = int(self._cntL[lv][c])
        w = self._nbrL[lv].shape[1]
        if cnt < w:
            self._nbrL[lv][c, cnt] = slot
            self._cntL[lv][c] = cnt + 1
            return
        nb = self._nbrL[lv][c].tolist() + [slot]
        d = 1.0 - self._vectors[nb] @ self._vectors[c]
        order = sorted(zip(d.tolist(), nb))
        self._set_neighbors(
            c, lv, self._select_neighbors(order, self._level_cap(lv))
        )

    # -- insert (reference: Add :174) --------------------------------------

    MAX_LEVEL = 12  # clamp the geometric tail: _visit_scratch reserves 16
    # generations per call (one per level + slack); an unbounded draw
    # could overlap the next call's range and stamp false "visited"

    def _sample_level(self) -> int:
        return min(
            int(-math.log(max(self._rng.random(), 1e-12)) * self._ml),
            self.MAX_LEVEL,
        )

    def add(self, ext_id: str, vector: Sequence[float]) -> None:
        v = self._normalize(np.asarray(vector, dtype=np.float32))
        with self._lock:
            if ext_id in self._slot_of:
                # an in-place vector overwrite would leave the node's graph
                # edges anchored in the old region (silent recall loss);
                # tombstone the old slot and insert fresh so links re-form
                self.remove(ext_id)
            level = self._sample_level()
            slot = self._alloc_slot(ext_id, v, level)
            if self._entry < 0:
                self._entry = slot
                self._max_level = level
                return
            self._connect(slot, v, level)
            if level > self._max_level:
                self._max_level = level
                self._entry = slot

    def _alloc_slot(self, ext_id: str, v: np.ndarray, level: int) -> int:
        self._grow(self._count + 1, v.shape[0])
        slot = self._count
        self._count += 1
        self._vectors[slot] = v
        self._ext_ids.append(ext_id)
        self._slot_of[ext_id] = slot
        self._alive.append(True)
        self._levels.append(level)
        self._ensure_level(max(level, 0))
        return slot

    def _connect(self, slot: int, v: np.ndarray, level: int) -> None:
        """Descend + link one node (single-query latency path)."""
        ep = [(float(1.0 - self._vectors[self._entry] @ v), self._entry)]
        for lv in range(self._max_level, level, -1):
            ep = self._search_layer(v, ep, 1, lv)
        for lv in range(min(self._max_level, level), -1, -1):
            cands = self._search_layer(v, ep, self.ef_construction, lv)
            self._link_from_cands(slot, lv, cands)
            ep = cands

    def _link_from_cands(
        self, slot: int, lv: int, cands: List[Tuple[float, int]]
    ) -> None:
        chosen = self._select_neighbors(cands, self.m)
        self._set_neighbors(slot, lv, chosen)
        for c in chosen:
            self._add_link(c, lv, slot)

    # -- bulk build (batched waves) -----------------------------------------

    # Wave members search the pre-wave graph only; capping the wave at
    # this fraction of the current graph keeps the backbone intact. The
    # absolute cap bounds the [wave, capacity] visited buffer and keeps
    # per-step gathers cache-sized.
    WAVE_FRACTION = 8
    WAVE_MAX = 1024
    BOOTSTRAP = 256

    def build(
        self,
        items: Sequence[Tuple[str, Sequence[float]]],
        seed_ids: Optional[Sequence[str]] = None,
        bulk_ef_scale: float = 0.5,
    ) -> None:
        """Bulk build; if ``seed_ids`` given (BM25 seeds), those docs are
        inserted first to form the backbone (reference: seed-first build,
        search.go:3785-3871). Inserts run in batched waves: every wave's
        beam searches are vectorized across the wave (one einsum per
        expansion step), then links connect host-side.

        The seeded build converts backbone quality into WALL-CLOCK the
        way the reference's does: the backbone (seeds, full
        ef_construction) is topically representative, so the bulk phase
        descends through it straight to the right neighborhood and a
        smaller construction beam (``bulk_ef_scale`` x ef_construction)
        finds the same links — beam work is the build's cost, so halving
        the bulk beam is ~2x fewer distance evaluations per insert.
        Recall parity between the two modes is pinned in
        tests/test_ann_stack.py::TestSeededBuild."""
        if seed_ids:
            seed_set = set(seed_ids)
            by_id = {i: v for i, v in items}
            ordered = [(i, by_id[i]) for i in seed_ids if i in by_id]
            n_seed = len(ordered)
            ordered += [(i, v) for i, v in items if i not in seed_set]
        else:
            ordered = list(items)
            n_seed = 0
        bulk_ef = max(32, int(self.ef_construction * bulk_ef_scale))
        with self._lock:
            i = 0
            n = len(ordered)
            while i < n and self._count < self.BOOTSTRAP:
                self.add(*ordered[i])
                i += 1
            while i < n:
                wave = min(
                    max(64, self._count // self.WAVE_FRACTION),
                    self.WAVE_MAX,
                )
                batch = ordered[i: i + wave]
                efc = (self.ef_construction
                       if (n_seed == 0 or i < n_seed)
                       else bulk_ef)
                i += len(batch)
                self._build_wave_locked(batch, efc=efc)

    def _build_wave_locked(self, batch: Sequence[Tuple[str, Sequence[float]]],
                    efc: Optional[int] = None) -> None:
        # intra-wave duplicate ids: keep the last occurrence (add()'s
        # overwrite order); without this, two alive slots share one id
        # and remove() can only ever reach the tracked one
        last = {ext_id: i for i, (ext_id, _) in enumerate(batch)}
        if len(last) != len(batch):
            batch = [bv for i, bv in enumerate(batch)
                     if last[bv[0]] == i]
        B = len(batch)
        Q = np.stack([
            self._normalize(np.asarray(v, dtype=np.float32))
            for _, v in batch
        ])
        # duplicate ids: tombstone + reinsert (same semantics as add())
        for ext_id, _ in batch:
            if ext_id in self._slot_of:
                self.remove(ext_id)
        levels = [self._sample_level() for _ in range(B)]
        pre_entry, pre_max = self._entry, self._max_level
        slots = [
            self._alloc_slot(batch[j][0], Q[j], levels[j]) for j in range(B)
        ]
        if pre_entry < 0:
            # empty index: seed sequentially (rare — build() bootstraps)
            self._entry = slots[0]
            self._max_level = levels[0]
            for j in range(1, B):
                self._connect(slots[j], Q[j], levels[j])
                if levels[j] > self._max_level:
                    self._max_level = levels[j]
                    self._entry = slots[j]
            return

        efc = efc or self.ef_construction
        lvq = np.asarray(levels)
        from nornicdb_tpu.search.hnsw_native import (
            connect_wave, get_lib, wave_search,
        )

        lib = get_lib()
        if lib is not None and hasattr(lib, "hnsw_wave_search"):
            # fully native search + connect: the numpy wave search's
            # per-step glue (argpartition/where/concatenate over
            # [B, ef+E*W] arrays) was ~70% of build wall-clock — the
            # classic per-query heap search in C++ does the same
            # distance evaluations with none of it
            n_levels = min(len(self._nbrL), pre_max + 1)
            wd, ws = wave_search(
                lib, self._vectors, self._nbrL[:n_levels],
                self._cntL[:n_levels], Q, lvq, pre_entry, efc,
                self._capacity)
            for lv in range(min(int(lvq.max()), n_levels - 1), -1, -1):
                collect = np.nonzero(lvq >= lv)[0]
                if len(collect) == 0:
                    continue
                counts = []
                for j in collect:
                    counts.append(int((ws[j, lv] >= 0).sum()))
                off = np.zeros(len(collect) + 1, np.int64)
                np.cumsum(counts, out=off[1:])
                cs = np.empty(int(off[-1]), np.int64)
                cd = np.empty(int(off[-1]), np.float32)
                for i, j in enumerate(collect):
                    k = counts[i]
                    lo = int(off[i])
                    cs[lo:lo + k] = ws[j, lv, :k]
                    cd[lo:lo + k] = wd[j, lv, :k]
                wave_slots = np.asarray([slots[j] for j in collect],
                                        np.int64)
                connect_wave(lib, self._vectors, self._nbrL[lv],
                             self._cntL[lv], self.m,
                             self._level_cap(lv),
                             wave_slots, off, cs, cd)
            top = int(np.argmax(lvq))
            if levels[top] > self._max_level:
                self._max_level = levels[top]
                self._entry = slots[top]
            return

        visited, gen = self._visit_scratch(B)

        d0 = 1.0 - Q @ self._vectors[pre_entry]
        bd = np.full((B, efc), np.inf, dtype=np.float32)
        bs = np.full((B, efc), -1, dtype=np.int64)
        bd[:, 0] = d0
        bs[:, 0] = pre_entry
        cands_at: Dict[int, List[Tuple[int, List[Tuple[float, int]]]]] = {}
        for lv in range(pre_max, -1, -1):
            collect = np.nonzero(lvq >= lv)[0]
            greedy = np.nonzero(lvq < lv)[0]
            for sub, ef in ((greedy, 1), (collect, efc)):
                if len(sub) == 0:
                    continue
                gen[sub] += 1
                rd, rs = self._batched_search_layer(
                    Q, bd, bs, sub, ef, lv, visited, gen
                )
                bd[sub] = np.inf
                bs[sub] = -1
                bd[sub, : rd.shape[1]] = rd
                bs[sub, : rs.shape[1]] = rs
            if len(collect):
                per = []
                for row, j in enumerate(collect):
                    dd = bd[j]
                    ss = bs[j]
                    ok = ss >= 0
                    order = np.argsort(dd[ok], kind="stable")
                    per.append((
                        int(j),
                        list(zip(dd[ok][order].tolist(),
                                 ss[ok][order].tolist())),
                    ))
                cands_at[lv] = per

        # connect phase: wave nodes link against the pre-wave graph.
        # Native kernel when available (diversity-select + back-link
        # prune are the remaining per-node sequential hot loop,
        # native/nornichnsw.cpp); Python fallback is semantics-identical.
        for lv in sorted(cands_at.keys(), reverse=True):
            per = cands_at[lv]
            if lib is not None and per:
                wave_slots = np.asarray([slots[j] for j, _ in per],
                                        np.int64)
                counts = [len(c) for _, c in per]
                off = np.zeros(len(per) + 1, np.int64)
                np.cumsum(counts, out=off[1:])
                cs = np.empty(int(off[-1]), np.int64)
                cd = np.empty(int(off[-1]), np.float32)
                for i, (_, cands) in enumerate(per):
                    lo = int(off[i])
                    for k, (d, s) in enumerate(cands):
                        cd[lo + k] = d
                        cs[lo + k] = s
                connect_wave(lib, self._vectors, self._nbrL[lv],
                             self._cntL[lv], self.m,
                             self._level_cap(lv),
                             wave_slots, off, cs, cd)
            else:
                for j, cands in per:
                    self._link_from_cands(slots[j], lv, cands)
        top = int(np.argmax(lvq))
        if levels[top] > self._max_level:
            self._max_level = levels[top]
            self._entry = slots[top]

    def _batched_search_layer(
        self,
        Q: np.ndarray,
        bd: np.ndarray,
        bs: np.ndarray,
        sub: np.ndarray,
        ef: int,
        lv: int,
        visited: np.ndarray,
        gen: np.ndarray,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Beam search one layer for the query subset ``sub``, batched.

        The beam doubles as the candidate pool (the bulk-builder variant
        of HNSW's search: every beam entry is expanded exactly once; an
        entry that leaves the beam is abandoned). Each expansion step is
        a gather + one einsum over [A, width, D] — no per-node Python.
        Entry beams arrive in bd/bs[sub]; returns (dist, slot) arrays
        [A, ef], +inf/-1 padded.
        """
        A = len(sub)
        qd = np.where(bs[sub] >= 0, bd[sub], np.inf)[:, :ef]
        qs = bs[sub][:, :ef]
        if qd.shape[1] < ef:
            pad = ef - qd.shape[1]
            qd = np.pad(qd, ((0, 0), (0, pad)), constant_values=np.inf)
            qs = np.pad(qs, ((0, 0), (0, pad)), constant_values=-1)
        exp = qs < 0  # padding counts as already-expanded
        # stamp entries visited (vectorized over the whole subset)
        er, ec = np.nonzero(qs >= 0)
        visited[sub[er], qs[er, ec]] = gen[sub[er]]
        nbr = self._nbrL[lv]
        Qs = Q[sub]
        # expand the E best unexpanded beam entries per step: total
        # expansions are unchanged (every beam slot expands at most
        # once), but the per-step Python/alloc overhead is amortized E
        # ways — this is what makes the wave build fast
        E = max(1, min(16, ef))
        while True:
            dmask = np.where(exp, np.inf, qd)
            if E == 1:
                j = np.argmin(dmask, axis=1)[:, None]
            else:
                j = np.argpartition(dmask, E - 1, axis=1)[:, :E]
            jd = np.take_along_axis(dmask, j, axis=1)  # [A, E]
            act = np.nonzero(np.isfinite(jd).any(axis=1))[0]
            if len(act) == 0:
                return qd, qs
            ja = j[act]
            fin = np.isfinite(jd[act])
            rows = np.where(fin, np.take_along_axis(qs[act], ja, axis=1), -1)
            ea = exp[act]
            np.put_along_axis(ea, ja, True, axis=1)
            exp[act] = ea
            w = nbr.shape[1]
            nb = np.where(rows[:, :, None] >= 0, nbr[np.maximum(rows, 0)],
                          -1).reshape(len(act), -1)  # [A', E*W]
            valid = nb >= 0
            nb0 = np.where(valid, nb, 0)
            suba = sub[act]
            seen = visited[suba[:, None], nb0] == gen[suba][:, None]
            valid &= ~seen
            # compact to the unvisited entries before touching vectors:
            # typically most neighbor slots were already visited, and the
            # [A, E*W, D] gather would dwarf every other cost
            vr, vc = np.nonzero(valid)
            dd = np.full(nb.shape, np.inf, dtype=np.float32)
            if len(vr):
                flat_slots = nb0[vr, vc]
                # E>1 concatenates several nodes' neighbor lists into one
                # row, so a slot can repeat within this step — the seen
                # stamp can't catch that; keep first occurrences only
                key = vr.astype(np.int64) * self._capacity + flat_slots
                _, first = np.unique(key, return_index=True)
                if len(first) != len(vr):
                    vr, vc = vr[first], vc[first]
                    flat_slots = flat_slots[first]
                visited[suba[vr], flat_slots] = gen[suba[vr]]
                dd[vr, vc] = 1.0 - np.einsum(
                    "nd,nd->n", self._vectors[flat_slots], Qs[act][vr],
                    optimize=True,
                )
            # convergence: a query whose step produced nothing better
            # than its current worst beam entry is done — the classic
            # search's best-candidate > worst-result stop, batched. (A
            # filling beam has +inf padding, so its worst is +inf and it
            # always continues.)
            worst = qd[act].max(axis=1)
            stalled = dd.min(axis=1) >= worst
            md = np.concatenate([qd[act], dd], axis=1)
            ms = np.concatenate([qs[act], nb0], axis=1)
            me = np.concatenate(
                [ea, np.zeros((len(act), nb.shape[1]), dtype=bool)], axis=1
            )
            me |= ~np.isfinite(md)
            sel = np.argpartition(md, ef - 1, axis=1)[:, :ef]
            qd[act] = np.take_along_axis(md, sel, axis=1)
            qs[act] = np.where(
                np.isfinite(qd[act]),
                np.take_along_axis(ms, sel, axis=1), -1,
            )
            newexp = np.take_along_axis(me, sel, axis=1)
            newexp |= stalled[:, None]
            exp[act] = newexp

    # -- delete (tombstones) ----------------------------------------------

    def remove(self, ext_id: str) -> bool:
        with self._lock:
            slot = self._slot_of.pop(ext_id, None)
            if slot is None:
                return False
            self._alive[slot] = False
            self._tombstones += 1
            return True

    # -- query (reference: SearchWithEf :342) -------------------------------

    def search(
        self, query: Sequence[float], k: int = 10, ef: Optional[int] = None
    ) -> List[Tuple[str, float]]:
        q = self._normalize(np.asarray(query, dtype=np.float32))
        with self._lock:
            if self._entry < 0 or not self._slot_of:
                return []
            ef = max(ef or self.ef_search, k)
            # tombstones are filtered from results after the beam, so widen
            # the beam proportionally or k alive survivors may not remain
            if self._tombstones:
                ef = int(ef * (1.0 + 2.0 * self.tombstone_ratio)) + 1
            native = self._native_query(q[None, :], ef)
            if native is not None:
                wd, ws = native
                return self._collect_alive(wd[0, 0], ws[0, 0], k)
            ep = [(float(1.0 - self._vectors[self._entry] @ q), self._entry)]
            for lv in range(self._max_level, 0, -1):
                ep = self._search_layer(q, ep, 1, lv)
            found = self._search_layer(q, ep, ef, 0)
            out = []
            for d, slot in found:
                if not self._alive[slot]:
                    continue
                out.append((self._ext_ids[slot], 1.0 - d))
                if len(out) >= k:
                    break
            return out

    def _native_query(self, Q: np.ndarray, ef: int):
        """Query-time use of the native wave kernel: query_levels=0, so
        the beam is collected at level 0 only after a greedy descent —
        classic HNSW search, same distance evaluations as the Python
        heap path without its interpreter overhead. Caller holds the
        lock. Returns (dists, slots) or None when the kernel is absent."""
        from nornicdb_tpu.search.hnsw_native import get_lib, wave_search

        lib = get_lib()
        if lib is None or not hasattr(lib, "hnsw_wave_search"):
            return None
        n_levels = min(len(self._nbrL), self._max_level + 1)
        if n_levels <= 0:
            return None
        return wave_search(
            lib, self._vectors, self._nbrL[:n_levels],
            self._cntL[:n_levels],
            np.ascontiguousarray(Q, np.float32),
            np.zeros(len(Q), np.int64), self._entry, ef,
            self._capacity)

    def _collect_alive(self, dists, slots, k: int):
        out = []
        for d, slot in zip(dists.tolist(), slots.tolist()):
            if slot < 0:
                break
            if not self._alive[slot]:
                continue
            out.append((self._ext_ids[slot], 1.0 - d))
            if len(out) >= k:
                break
        return out

    def search_batch(
        self,
        queries: np.ndarray,
        k: int = 10,
        ef: Optional[int] = None,
    ) -> List[List[Tuple[str, float]]]:
        """Batched queries over the same matrices the builder uses —
        amortizes the per-step Python across the whole batch (the
        throughput path; ``search`` is the latency path)."""
        Q = np.asarray(queries, dtype=np.float32)
        norms = np.linalg.norm(Q, axis=1, keepdims=True)
        Q = Q / np.maximum(norms, 1e-12)
        if len(Q) > self.WAVE_MAX:
            out: List[List[Tuple[str, float]]] = []
            for i in range(0, len(Q), self.WAVE_MAX):
                out.extend(self.search_batch(Q[i: i + self.WAVE_MAX], k, ef))
            return out
        with self._lock:
            if self._entry < 0 or not self._slot_of:
                return [[] for _ in range(len(Q))]
            B = len(Q)
            ef = max(ef or self.ef_search, k)
            if self._tombstones:
                ef = int(ef * (1.0 + 2.0 * self.tombstone_ratio)) + 1
            native = self._native_query(Q, ef)
            if native is not None:
                wd, ws = native
                return [self._collect_alive(wd[j, 0], ws[j, 0], k)
                        for j in range(B)]
            visited, gen = self._visit_scratch(B)
            d0 = 1.0 - Q @ self._vectors[self._entry]
            bd = np.full((B, ef), np.inf, dtype=np.float32)
            bs = np.full((B, ef), -1, dtype=np.int64)
            bd[:, 0] = d0
            bs[:, 0] = self._entry
            allq = np.arange(B)
            for lv in range(self._max_level, -1, -1):
                width = 1 if lv > 0 else ef
                gen += 1
                rd, rs = self._batched_search_layer(
                    Q, bd, bs, allq, width, lv, visited, gen
                )
                bd[:] = np.inf
                bs[:] = -1
                bd[:, : rd.shape[1]] = rd
                bs[:, : rs.shape[1]] = rs
            out: List[List[Tuple[str, float]]] = []
            for r in range(B):
                ok = bs[r] >= 0
                dd, ss = bd[r][ok], bs[r][ok]
                order = np.argsort(dd, kind="stable")
                hits: List[Tuple[str, float]] = []
                for d, slot in zip(dd[order].tolist(), ss[order].tolist()):
                    if not self._alive[slot]:
                        continue
                    hits.append((self._ext_ids[slot], 1.0 - d))
                    if len(hits) >= k:
                        break
                out.append(hits)
            return out

    # -- persistence -------------------------------------------------------

    def save(self, path: str) -> None:
        with self._lock:
            neighbors = np.empty(self._count, dtype=object)
            for slot in range(self._count):
                neighbors[slot] = [
                    self._neighbors_of(slot, lv).tolist()
                    for lv in range(self._levels[slot] + 1)
                ]
            np.savez_compressed(
                path,
                vectors=self._vectors[: self._count]
                if self._vectors is not None
                else np.zeros((0, 0), np.float32),
                levels=np.asarray(self._levels, dtype=np.int32),
                alive=np.asarray(self._alive, dtype=bool),
                ext_ids=np.asarray(
                    [e if e is not None else "" for e in self._ext_ids],
                    dtype=object,
                ),
                neighbors=neighbors,
                meta=np.asarray(
                    [self._entry, self._max_level, self.m, self.dims or 0,
                     self.ef_construction, self.ef_search],
                    dtype=np.int64,
                ),
            )

    @classmethod
    def load(cls, path: str) -> "HNSWIndex":
        data = np.load(path if path.endswith(".npz") else path + ".npz",
                       allow_pickle=True)
        meta = [int(x) for x in data["meta"]]
        entry, max_level, m, dims = meta[:4]
        # older snapshots (4-field meta) predate ef persistence
        ef_c = meta[4] if len(meta) > 4 else 200
        ef_s = meta[5] if len(meta) > 5 else 64
        idx = cls(dims=dims or None, m=m, ef_construction=ef_c,
                  ef_search=ef_s)
        vecs = data["vectors"]
        idx._count = vecs.shape[0]
        idx._capacity = vecs.shape[0]
        idx._vectors = np.ascontiguousarray(vecs, dtype=np.float32)
        idx._levels = [int(x) for x in data["levels"]]
        idx._alive = [bool(x) for x in data["alive"]]
        idx._ext_ids = [str(e) if e else None for e in data["ext_ids"]]
        idx._ensure_level(max(idx._levels, default=0))
        for slot, per_node in enumerate(data["neighbors"]):
            for lv, lst in enumerate(per_node):
                idx._set_neighbors(slot, lv, [int(x) for x in lst])
        idx._slot_of = {
            e: i
            for i, e in enumerate(idx._ext_ids)
            if e is not None and idx._alive[i]
        }
        idx._tombstones = sum(1 for a in idx._alive if not a)
        idx._entry = entry
        idx._max_level = max_level
        return idx
