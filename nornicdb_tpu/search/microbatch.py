"""Micro-batching aggregation for concurrent single-query kNN.

SURVEY §7 names this the hard part of the TPU design: a single b=1
query cannot feed the MXU, so the device path only wins at batch — and
a serving workload is exactly many concurrent b=1 queries. This
coalescer turns them into device-sized batches (reference analog: the
strategy machine's batch thresholds, search.go:528-535; the reference
never needed the window because its per-query CPU/GPU dispatch is
cheap, while a device dispatch here costs ~100us+).

Design: adaptive leader election instead of a timed window. The first
idle request becomes the leader of the next batch and runs immediately
(ZERO added latency when the service is idle); requests arriving while
a batch is in flight queue up and are drained as ONE batched call by
the next leader. Under load the batch size self-tunes to the arrival
rate; there is no artificial sleep to tune.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Sequence, Tuple

import numpy as np

from nornicdb_tpu.obs import (
    REGISTRY,
    SIZE_BUCKETS,
    attach_span,
    record_dispatch,
    record_stage,
)
from nornicdb_tpu.obs import audit as _audit
from nornicdb_tpu.obs import device as _device
from nornicdb_tpu.obs import tenant as _tenant
from nornicdb_tpu import admission as _adm

# one metric family set shared by every batcher instance (per-collection
# MicroBatchers, the search service's, the upsert coalescer): the
# registry is process-global and get-or-create is idempotent
_BATCH_H = REGISTRY.histogram(
    "nornicdb_microbatch_batch_size",
    "Coalesced queries per device dispatch", buckets=SIZE_BUCKETS)
_QUEUE_H = REGISTRY.histogram(
    "nornicdb_microbatch_queue_depth",
    "Requests still pending when a batch sealed", buckets=SIZE_BUCKETS)
_CONVOY_H = REGISTRY.histogram(
    "nornicdb_convoy_batch_size",
    "Coalesced items per merged apply (write convoys)",
    buckets=SIZE_BUCKETS)
# deadline-aware dispatch (ISSUE 15): batches sealed EARLY — the gather
# window skipped because a rider's remaining budget would expire inside
# it — dispatch smaller now instead of convoying toward a miss (pow2
# buckets absorb the size change: no new compile universe)
_EARLY_C = REGISTRY.counter(
    "nornicdb_deadline_early_dispatch_total",
    "Batches sealed early because a rider's deadline budget was tight",
    labels=("surface",))


def _expire_in_queue(owner, item, msg: str) -> bool:
    """Caller holds ``owner._cond``: fail one budget-expired item fast
    if it is still pending (not yet claimed by a leader). Shared by the
    MicroBatcher/BatchCoalescer wait loops (ISSUE 15)."""
    try:
        owner._pending.remove(item)
    except ValueError:
        return False  # claimed: it rides out the in-flight batch
    item.error = _adm.DeadlineExceeded(msg)
    item.done = True
    return True


def _seal_pending(owner, now: float, msg: str):
    """Caller holds ``owner._cond``: drop budget-expired items (failed
    fast, never dispatched) then select the next batch via the shared
    lane-priority/weighted-share policy (admission.select_batch). The
    ONE seal implementation both coalescers share (ISSUE 15)."""
    pending = owner._pending
    expired = [r for r in pending
               if r.deadline is not None and now >= r.deadline]
    if expired:
        dead = set(map(id, expired))
        pending = [r for r in pending if id(r) not in dead]
        owner._pending = pending
        for r in expired:
            r.error = _adm.DeadlineExceeded(msg)
            r.done = True
        owner._cond.notify_all()
    batch, rest = _adm.select_batch(pending, owner._max_batch, now)
    owner._pending = rest
    return batch


def pow2_bucket(n: int) -> int:
    """Smallest power of two >= n. Shape bucketing for device dispatch:
    every distinct (B, k) is its own XLA compile, so batch and k are
    padded to buckets to cap the compile universe at log2 shapes."""
    b = 1
    while b < n:
        b <<= 1
    return b


class BatchCoalescer:
    """Leader-elected coalescer for arbitrary batchable operations.

    The generalization of MicroBatcher's search-specific protocol to any
    op where N concurrent requests are cheaper served as one merged
    apply (gRPC point upserts: one merged ``upsert_points`` per
    collection means one lock acquisition, one index touch and ONE cache
    generation bump for the whole convoy instead of one per RPC).

    ``apply_batch(items) -> results`` must return one result per item;
    raising fails every waiter in the batch unless ``apply_single`` is
    given, in which case the coalescer falls back to per-item
    application so one poisoned item cannot fail its convoy-mates.
    """

    def __init__(self, apply_batch, apply_single=None, max_batch: int = 64,
                 surface: str = "convoy"):
        self._apply_batch = apply_batch
        self._apply_single = apply_single
        self._max_batch = max_batch
        # bounded stage-attribution label for
        # nornicdb_request_stage_seconds{surface,...} — code-chosen, one
        # value per coalescer role (never client-derived)
        self._surface = surface
        self._cond = threading.Condition()
        self._pending: List["_Item"] = []
        self._busy = False
        self.batches = 0
        self.batched_items = 0

    def queue_depth(self) -> int:
        """Live pending items (not yet claimed by a convoy leader) —
        same contract as MicroBatcher.queue_depth, so write convoys get
        the nornicdb_queue_depth gauge and the /readyz saturation check
        when registered with obs/resources."""
        with self._cond:
            return len(self._pending)

    def submit(self, value: Any) -> Any:
        t_enq = time.time()
        # admission context (ISSUE 15): convoy items carry the caller's
        # lane + deadline budget like MicroBatcher riders — an expired
        # item fails fast instead of riding a merged apply
        dl = _adm.deadline()
        lane = _adm.lane()
        if dl is not None and t_enq >= dl:
            _adm.record_deadline_miss(self._surface, "ingress", lane)
            raise _adm.DeadlineExceeded(
                f"deadline budget expired before enqueue "
                f"({self._surface})")
        item = _Item(value)
        item.deadline, item.lane, item.t_enq = dl, lane, t_enq
        item.tenant = _tenant.current_tenant()
        with self._cond:
            self._pending.append(item)
        while True:
            batch: List[_Item] = []
            with self._cond:
                while not item.done and self._busy:
                    timeout = 30.0
                    if item.deadline is not None:
                        timeout = min(
                            timeout,
                            max(item.deadline - time.time(), 0.0) + 1e-3)
                    self._cond.wait(timeout=timeout)
                    if (not item.done and item.deadline is not None
                            and time.time() >= item.deadline):
                        if _expire_in_queue(
                                self, item,
                                f"deadline budget expired in convoy "
                                f"queue ({self._surface})"):
                            break
                        continue  # claimed: ride out the convoy
                if item.done:
                    break
                batch = _seal_pending(
                    self, time.time(),
                    f"deadline budget expired in convoy queue "
                    f"({self._surface})")
                if not batch:
                    continue  # taken by another leader but not done yet
                self._busy = True
            try:
                self._run(batch)
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()
            if item.done:
                break
        if item.apply_t1:
            # queue-delay attribution + trace spans: the wait from
            # enqueue to the leader sealing our convoy, and the shared
            # merged apply every convoy-mate experienced
            record_stage(self._surface, "coalesce_wait",
                         item.apply_t0 - t_enq)
            record_stage(self._surface, "apply",
                         item.apply_t1 - item.apply_t0)
            record_stage("lane:" + item.lane, "coalesce_wait",
                         item.apply_t0 - t_enq)
            _adm.CONTROLLER.note_wait(item.lane, item.apply_t0 - t_enq)
            attach_span("coalesce.wait", t_enq, item.apply_t0,
                        surface=self._surface, batch=item.batch_size,
                        lane=item.lane)
            attach_span("apply", item.apply_t0, item.apply_t1,
                        surface=self._surface, batch=item.batch_size)
        if isinstance(item.error, _adm.DeadlineExceeded) \
                and not item.apply_t1:
            _adm.record_deadline_miss(self._surface, "queued",
                                      item.lane)
            raise item.error
        if item.error is not None:
            raise item.error
        return item.result

    def _run(self, batch: List["_Item"]) -> None:
        self.batches += 1
        self.batched_items += len(batch)
        _CONVOY_H.observe(len(batch))
        t0 = time.time()
        for item in batch:
            item.apply_t0 = t0
            item.batch_size = len(batch)
        try:
            # the riders' tenant mix binds around the merged apply so
            # any cost/serve recorded inside splits per tenant (18)
            with _tenant.batch_scope([i.tenant for i in batch]):
                results = self._apply_batch([i.value for i in batch])
            for item, res in zip(batch, results):
                item.result = res
        except Exception as exc:  # noqa: BLE001 — delivered per-request
            if self._apply_single is None or len(batch) == 1:
                for item in batch:
                    item.error = exc
            else:
                # isolate the poison: apply per item so only the bad
                # request(s) observe the error
                for item in batch:
                    try:
                        with _tenant.batch_scope([item.tenant]):
                            item.result = self._apply_single(item.value)
                    except Exception as single_exc:  # noqa: BLE001
                        item.error = single_exc
        t1 = time.time()
        for item in batch:
            item.apply_t1 = t1
            item.done = True


class _Item:
    __slots__ = ("value", "done", "result", "error", "apply_t0",
                 "apply_t1", "batch_size", "lane", "deadline", "t_enq",
                 "tenant")

    def __init__(self, value: Any):
        self.value = value
        self.done = False
        self.result: Any = None
        self.error: Any = None
        # stamped by the convoy leader: the shared merged-apply interval
        self.apply_t0 = 0.0
        self.apply_t1 = 0.0
        self.batch_size = 0
        # admission context captured at enqueue (ISSUE 15)
        self.lane = _adm.LANE_INTERACTIVE
        self.deadline: "float | None" = None
        self.t_enq = 0.0
        # tenant captured at enqueue (ISSUE 18): the convoy leader
        # binds the batch's tenant mix so merged-apply cost splits
        self.tenant: "str | None" = None


class _Req:
    __slots__ = ("vec", "k", "extra", "done", "result", "error",
                 "dispatch_t0", "dispatch_t1", "batch_size", "tier",
                 "lane", "deadline", "t_enq", "early", "tenant")

    def __init__(self, vec: np.ndarray, k: int, extra: Any = None):
        self.vec = vec
        self.k = k
        self.extra = extra
        self.done = False
        self.result: Any = None
        self.error: Any = None
        # stamped by the batch LEADER so every rider can graft the one
        # shared device-dispatch interval into its own trace
        self.dispatch_t0 = 0.0
        self.dispatch_t1 = 0.0
        self.batch_size = 0
        # serving-tier verdict of the batch that answered this request
        # (leader consumes the dispatch path's audit.note_batch_tier)
        self.tier: Any = None
        # admission context captured at enqueue (ISSUE 15): priority
        # lane + absolute deadline budget — leaders seal batches in
        # lane order and fail budget-expired riders fast
        self.lane = _adm.LANE_INTERACTIVE
        self.deadline: "float | None" = None
        self.t_enq = 0.0
        # the leader skipped the gather window because this rider's (or
        # a batch-mate's) budget was tight — annotated on the trace
        self.early = False
        # tenant captured at enqueue (ISSUE 18): the batch leader binds
        # the riders' mix so the padded-dispatch cost splits per tenant
        self.tenant: "str | None" = None


class MicroBatcher:
    """Coalesces concurrent ``search(vec, k)`` calls into
    ``search_batch(queries[B,D], k_max)`` calls.

    ``search_batch`` must return one result list per query row. Results
    for a request asking k smaller than the batch max are truncated."""

    def __init__(
        self,
        search_batch: Callable[[np.ndarray, int], List[List[Tuple[str, float]]]],
        max_batch: int = 64,
        gather_window_s: float = 0.0005,
        pass_extras: bool = False,
        truncate: bool = True,
        surface: str = "search",
        tier_surface: "str | None" = None,
    ):
        self._search_batch = search_batch
        self._max_batch = max_batch
        # bounded stage-attribution label (code-chosen per batcher role:
        # "service:vector", "service:hybrid", "qdrant", ...) for the
        # nornicdb_request_stage_seconds{surface,stage} histograms
        self._surface = surface
        # tier-attribution surface ("vector", ...): when set, each rider
        # records nornicdb_served_tier_total/_seconds for the tier the
        # dispatch path noted (audit.note_batch_tier) — rider-accurate
        # counting without the batcher knowing the ladder. None = the
        # caller above this batcher does its own (per-row) attribution.
        self._tier_surface = tier_surface
        # pass_extras: dispatch as search_batch(queries, k, extras) with
        # one opaque per-request item (the hybrid path rides tokenized
        # query terms and per-request fusion options alongside the
        # stackable embedding rows). truncate=False leaves per-request
        # result shaping to the dispatch fn (hybrid rows are structured
        # triples, not plain hit lists).
        self._pass_extras = pass_extras
        self._truncate = truncate
        # when the PREVIOUS batch was concurrent, the next leader waits
        # up to this long for stragglers that are mid-return from that
        # batch — without it, mean batch size collapses to ~half the
        # client count. An idle service (last batch = 1) never waits.
        self._gather_window_s = gather_window_s
        self._last_batch = 1
        self._cond = threading.Condition()
        self._pending: List[_Req] = []
        self._busy = False
        # observability: how well the window is aggregating
        self.batches = 0
        self.batched_queries = 0

    def queue_depth(self) -> int:
        """Live pending requests (not yet claimed by a batch leader) —
        the saturation signal /readyz and the resource gauges read
        (the threshold itself lives with its env knob in
        http_server._readyz: depth >= READY_QUEUE_FACTOR x max_batch)."""
        with self._cond:
            return len(self._pending)

    def search(self, vec: Sequence[float], k: int,
               extra: Any = None) -> List[Tuple[str, float]]:
        t_enq = time.time()
        # admission context (ISSUE 15): the deadline budget minted at
        # ingress and the caller's priority lane ride the request —
        # a rider ALREADY past budget fails fast before it can occupy
        # a queue slot, let alone a device one
        dl = _adm.deadline()
        lane = _adm.lane()
        if dl is not None and t_enq >= dl:
            _adm.record_deadline_miss(self._surface, "ingress", lane)
            raise _adm.DeadlineExceeded(
                f"deadline budget expired before enqueue "
                f"({self._surface})")
        # cost-aware admission (ISSUE 20): at posture >= degrade, a
        # rider whose CALIBRATED predicted dispatch cost exceeds its
        # remaining budget sheds here (reason ``admission_cost``) —
        # before taking a queue slot it cannot convert into an answer.
        # Predicts at the bucket the next batch will likely compile to;
        # an unconfident model abstains and admission stays
        # queue-wait-only.
        if dl is not None:
            _adm.CONTROLLER.cost_check(
                self._surface, "microbatch",
                pow2_bucket(max(min(self._last_batch, self._max_batch),
                                1)),
                lane, now=t_enq)
        req = _Req(np.asarray(vec, np.float32), k, extra)
        req.deadline, req.lane, req.t_enq = dl, lane, t_enq
        req.tenant = _tenant.current_tenant()
        with self._cond:
            self._pending.append(req)
        while True:
            batch: List[_Req] = []
            with self._cond:
                while not req.done and self._busy:
                    timeout = 30.0
                    if req.deadline is not None:
                        timeout = min(
                            timeout,
                            max(req.deadline - time.time(), 0.0) + 1e-3)
                    self._cond.wait(timeout=timeout)
                    if (not req.done and req.deadline is not None
                            and time.time() >= req.deadline):
                        # budget expired while queued: leave the queue
                        # instead of riding (and padding) a dispatch
                        # whose answer nobody will read. A rider a
                        # leader already claimed is no longer in
                        # _pending — it rides out the in-flight batch.
                        if _expire_in_queue(
                                self, req,
                                f"deadline budget expired in queue "
                                f"({self._surface})"):
                            break
                        continue
                if req.done:
                    break
                if req.deadline is not None \
                        and time.time() >= req.deadline:
                    # would-be leader past budget: same fail-fast
                    if _expire_in_queue(
                            self, req,
                            f"deadline budget expired in queue "
                            f"({self._surface})"):
                        break
                    continue
                # leader candidate: if the service just served a
                # concurrent batch, give its returning clients one short
                # window to re-enqueue before sealing this batch —
                # UNLESS a pending rider's remaining budget would expire
                # inside the window: dispatch smaller NOW (the pow2
                # buckets absorb the size change)
                early = False
                if (self._gather_window_s > 0.0
                        and self._last_batch >= 2
                        and len(self._pending)
                        < min(self._last_batch, self._max_batch)):
                    if self._deadline_tight_locked():
                        early = True
                    else:
                        self._cond.wait(timeout=self._gather_window_s)
                        if req.done:
                            break
                        if self._busy:
                            continue  # another thread led while we waited
                # idle and our request unserved: lead the next batch
                batch = _seal_pending(
                    self, time.time(),
                    f"deadline budget expired in queue "
                    f"({self._surface})")
                if not batch:
                    # taken by another leader but not done yet — loop
                    continue
                if early:
                    _EARLY_C.labels(self._surface).inc()
                    for r in batch:
                        r.early = True
                _QUEUE_H.observe(len(self._pending))
                self._busy = True
            try:
                self._run(batch)
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()
            if req.done:
                break
            # our request was queued behind this batch — go again
        if isinstance(req.error, _adm.DeadlineExceeded) \
                and not req.dispatch_t1:
            # failed fast without a dispatch: count the miss + one
            # ledger/journal shed record in THIS rider's own trace
            _adm.record_deadline_miss(self._surface, "queued", req.lane)
            raise req.error
        if req.error is not None:
            self._trace_req(req, t_enq)
            raise req.error
        self._trace_req(req, t_enq)
        return req.result

    def _deadline_tight_locked(self) -> bool:
        """Any pending rider whose remaining budget would not survive
        the gather window (with dispatch margin)? Caller holds _cond."""
        horizon = time.time() + 4.0 * self._gather_window_s
        return any(r.deadline is not None and r.deadline <= horizon
                   for r in self._pending)

    def _trace_req(self, req: "_Req", t_enq: float) -> None:
        """Graft this request's coalescing story into the active trace
        AND the per-stage latency histograms: the wait from enqueue to
        the (leader-stamped) device dispatch, the shared dispatch
        interval, and the post-dispatch merge. The histogram half runs
        even without an active trace — fleet-wide queue-delay
        attribution must not depend on tracing. No-op when the request
        errored before dispatch."""
        if not req.dispatch_t1:
            return
        t_done = time.time()
        record_stage(self._surface, "coalesce_wait",
                     req.dispatch_t0 - t_enq)
        record_stage(self._surface, "device_dispatch",
                     req.dispatch_t1 - req.dispatch_t0)
        record_stage(self._surface, "merge", t_done - req.dispatch_t1)
        # lane-keyed queue-wait mirror (ISSUE 15): the same coalesce
        # wait re-recorded under surface "lane:<lane>" so per-lane
        # queueing is one /admin/telemetry query (bounded: 3 lanes),
        # and fed to the admission controller as a MEASURED wait
        # observation — the signal the shedding verdict gates on
        record_stage("lane:" + req.lane, "coalesce_wait",
                     req.dispatch_t0 - t_enq)
        _adm.CONTROLLER.note_wait(req.lane, req.dispatch_t0 - t_enq)
        wait_attrs: dict = {"surface": self._surface,
                            "batch": req.batch_size, "lane": req.lane}
        disp_attrs: dict = {"surface": self._surface,
                            "batch": req.batch_size, "k": req.k}
        if req.deadline is not None:
            # the budget at the dispatch decision (ISSUE 15 acceptance:
            # a trace shows the deadline at ingress, ring crossing and
            # dispatch) — remaining ms when the leader sealed us in
            disp_attrs["deadline_ms"] = round(
                (req.deadline - req.dispatch_t0) * 1e3, 1)
        if req.early:
            disp_attrs["early_dispatch"] = True
        attach_span("coalesce.wait", t_enq, req.dispatch_t0,
                    **wait_attrs)
        attach_span("device.dispatch", req.dispatch_t0, req.dispatch_t1,
                    **disp_attrs)
        attach_span("merge", req.dispatch_t1, t_done)
        # rider-accurate serving-tier attribution (ISSUE 10): the tier
        # the leader consumed from the dispatch path stamps THIS
        # rider's count/latency/span, and the stage split re-records
        # keyed by tier — which rung was slow, not just which surface
        if self._tier_surface is not None and req.tier is not None:
            _audit.record_served(self._tier_surface, req.tier,
                                 seconds=t_done - t_enq)
            _audit.record_tier_stages(
                req.tier, req.dispatch_t0 - t_enq,
                req.dispatch_t1 - req.dispatch_t0,
                t_done - req.dispatch_t1)
        # sampling call sites above the batcher read the verdict here
        _audit.set_last_served(req.tier)

    def _run(self, batch: List[_Req]) -> None:
        try:
            self.batches += 1
            self.batched_queries += len(batch)
            self._last_batch = len(batch)
            _BATCH_H.observe(len(batch))
            # k is usually a static jit arg too: bucket it alongside B
            k_max = pow2_bucket(max(r.k for r in batch))
            queries = np.stack([r.vec for r in batch])
            # pad the batch dim to a power-of-two bucket: every distinct
            # B is a fresh XLA compile on an accelerator backend (~secs
            # each over a tunnel), and arrival-rate batches take nearly
            # every size — observed on silicon as 24 q/s instead of
            # 100k+. Buckets cap the compile universe at log2(max_batch)
            # shapes; the pad rows repeat row 0 (no NaN paths) and their
            # results are dropped.
            b = len(batch)
            bucket = pow2_bucket(b)
            if bucket != b:
                pad = np.broadcast_to(
                    queries[0], (bucket - b,) + queries.shape[1:])
                queries = np.concatenate([queries, pad], axis=0)
            t0 = time.time()
            _audit.consume_batch_tier()  # clear any stale leader note
            # bind the riders' tenant mix around the dispatch (18): the
            # padded program's cost splits across riders by tenant, and
            # (ISSUE 20) the dispatch scope credits inner-plane pricing
            # to this serving kind while the sampled bracket pins t1 to
            # device completion — the measured wall seconds then split
            # across the same rider mix
            with _tenant.batch_scope([r.tenant for r in batch]):
                with _device.dispatch_scope("microbatch"):
                    # the inner plane prices the PADDED array; the
                    # padding-efficiency join needs the rider count
                    _device.note_real_rows(float(b))
                    if self._pass_extras:
                        # pad extras like the query rows: repeat
                        # request 0's
                        extras = [r.extra for r in batch]
                        extras += [batch[0].extra] * (bucket - b)
                        results = self._search_batch(queries, k_max,
                                                     extras)
                    else:
                        results = self._search_batch(queries, k_max)
                    _device.maybe_sync(results)
                    t1 = time.time()
                tier = _audit.consume_batch_tier()
                record_dispatch("microbatch", bucket, k_max, t1 - t0)
            for r, res in zip(batch, results):
                r.dispatch_t0, r.dispatch_t1 = t0, t1
                r.batch_size = b
                r.tier = tier
                if self._truncate:
                    r.result = res[: r.k] if r.k < k_max else res
                else:
                    r.result = res
        except Exception:  # noqa: BLE001
            # isolate the poison: one malformed request (wrong dims in
            # np.stack, bad k) must not fail its convoy-mates — replay
            # each request as its own single-row batch and deliver
            # errors only to the requests that actually own them
            for r in batch:
                if r.deadline is not None and time.time() >= r.deadline:
                    # the failed batch consumed this rider's budget:
                    # don't burn a b=1 device dispatch on an answer
                    # nobody will read
                    r.error = _adm.DeadlineExceeded(
                        f"deadline budget expired during replay "
                        f"({self._surface})")
                    continue
                try:
                    kb = pow2_bucket(max(r.k, 1))
                    r.dispatch_t0 = time.time()
                    q1 = np.asarray(r.vec, np.float32)[None, :]
                    _audit.consume_batch_tier()
                    with _tenant.batch_scope([r.tenant]):
                        with _device.dispatch_scope("microbatch"):
                            if self._pass_extras:
                                res = self._search_batch(q1, kb,
                                                         [r.extra])[0]
                            else:
                                res = self._search_batch(q1, kb)[0]
                            _device.maybe_sync(res)
                            r.dispatch_t1 = time.time()
                        r.tier = _audit.consume_batch_tier()
                        r.batch_size = 1
                        record_dispatch("microbatch", 1, kb,
                                        r.dispatch_t1 - r.dispatch_t0)
                    if self._truncate:
                        r.result = res[: r.k] if r.k < kb else res
                    else:
                        r.result = res
                except Exception as exc:  # noqa: BLE001 — per-request
                    r.error = exc
        for r in batch:
            r.done = True
