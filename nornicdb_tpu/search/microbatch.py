"""Micro-batching aggregation for concurrent single-query kNN.

SURVEY §7 names this the hard part of the TPU design: a single b=1
query cannot feed the MXU, so the device path only wins at batch — and
a serving workload is exactly many concurrent b=1 queries. This
coalescer turns them into device-sized batches (reference analog: the
strategy machine's batch thresholds, search.go:528-535; the reference
never needed the window because its per-query CPU/GPU dispatch is
cheap, while a device dispatch here costs ~100us+).

Design: adaptive leader election instead of a timed window. The first
idle request becomes the leader of the next batch and runs immediately
(ZERO added latency when the service is idle); requests arriving while
a batch is in flight queue up and are drained as ONE batched call by
the next leader. Under load the batch size self-tunes to the arrival
rate; there is no artificial sleep to tune.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Sequence, Tuple

import numpy as np


class _Req:
    __slots__ = ("vec", "k", "done", "result", "error")

    def __init__(self, vec: np.ndarray, k: int):
        self.vec = vec
        self.k = k
        self.done = False
        self.result: Any = None
        self.error: Any = None


class MicroBatcher:
    """Coalesces concurrent ``search(vec, k)`` calls into
    ``search_batch(queries[B,D], k_max)`` calls.

    ``search_batch`` must return one result list per query row. Results
    for a request asking k smaller than the batch max are truncated."""

    def __init__(
        self,
        search_batch: Callable[[np.ndarray, int], List[List[Tuple[str, float]]]],
        max_batch: int = 64,
        gather_window_s: float = 0.0005,
    ):
        self._search_batch = search_batch
        self._max_batch = max_batch
        # when the PREVIOUS batch was concurrent, the next leader waits
        # up to this long for stragglers that are mid-return from that
        # batch — without it, mean batch size collapses to ~half the
        # client count. An idle service (last batch = 1) never waits.
        self._gather_window_s = gather_window_s
        self._last_batch = 1
        self._cond = threading.Condition()
        self._pending: List[_Req] = []
        self._busy = False
        # observability: how well the window is aggregating
        self.batches = 0
        self.batched_queries = 0

    def search(self, vec: Sequence[float], k: int) -> List[Tuple[str, float]]:
        req = _Req(np.asarray(vec, np.float32), k)
        with self._cond:
            self._pending.append(req)
        while True:
            batch: List[_Req] = []
            with self._cond:
                while not req.done and self._busy:
                    self._cond.wait(timeout=30.0)
                if req.done:
                    break
                # leader candidate: if the service just served a
                # concurrent batch, give its returning clients one short
                # window to re-enqueue before sealing this batch
                if (self._gather_window_s > 0.0
                        and self._last_batch >= 2
                        and len(self._pending)
                        < min(self._last_batch, self._max_batch)):
                    self._cond.wait(timeout=self._gather_window_s)
                    if req.done:
                        break
                    if self._busy:
                        continue  # another thread led while we waited
                # idle and our request unserved: lead the next batch
                batch = self._pending[: self._max_batch]
                del self._pending[: len(batch)]
                if not batch:
                    # taken by another leader but not done yet — loop
                    continue
                self._busy = True
            try:
                self._run(batch)
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()
            if req.done:
                break
            # our request was queued behind this batch — go again
        if req.error is not None:
            raise req.error
        return req.result

    def _run(self, batch: List[_Req]) -> None:
        try:
            self.batches += 1
            self.batched_queries += len(batch)
            self._last_batch = len(batch)
            # k is usually a static jit arg too: bucket it alongside B
            k_req = max(r.k for r in batch)
            k_max = 1
            while k_max < k_req:
                k_max <<= 1
            queries = np.stack([r.vec for r in batch])
            # pad the batch dim to a power-of-two bucket: every distinct
            # B is a fresh XLA compile on an accelerator backend (~secs
            # each over a tunnel), and arrival-rate batches take nearly
            # every size — observed on silicon as 24 q/s instead of
            # 100k+. Buckets cap the compile universe at log2(max_batch)
            # shapes; the pad rows repeat row 0 (no NaN paths) and their
            # results are dropped.
            b = len(batch)
            bucket = 1
            while bucket < b:
                bucket <<= 1
            if bucket != b:
                pad = np.broadcast_to(
                    queries[0], (bucket - b,) + queries.shape[1:])
                queries = np.concatenate([queries, pad], axis=0)
            results = self._search_batch(queries, k_max)
            for r, res in zip(batch, results):
                r.result = res[: r.k] if r.k < k_max else res
        except Exception as exc:  # noqa: BLE001 — delivered per-request
            for r in batch:
                r.error = exc
        for r in batch:
            r.done = True
