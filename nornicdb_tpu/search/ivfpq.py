"""IVF-PQ: compressed ANN via coarse quantization + product quantization.

Reference: pkg/search ivfpq_index.go, BuildIVFPQFromVectorStore
(ivfpq_build.go:16 — BM25 seeds pick the training sample),
ivfpq_persist.go:169. Selected by NORNICDB_VECTOR_ANN_QUALITY=compressed
(ann_quality.py).

TPU design: training is two levels of k-means on device (ops/kmeans
lloyd iterations are jitted einsum + segment-sum); query-time scanning
is asymmetric distance computation (ADC) — one [M, 256] lookup table
per query built with a single matmul, then a gather+sum over candidate
codes. Codes live in RAM as uint8 [N, M]; HBM holds only centroids and
codebooks, giving a 4*D/M compression of the vector set (e.g. 1024-d
float32 → 32 bytes/vector at M=32).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from nornicdb_tpu.ops.kmeans import (
    euclid_kmeans as _euclid_kmeans,
    optimal_k,
    train_subspace_codebooks,
)
from nornicdb_tpu.search.util import normalize_rows as _normalize

# _euclid_kmeans moved to ops/kmeans.py (euclid_kmeans): the device PQ
# plane (search/device_quant.py) trains through the SAME implementation,
# so host IVF-PQ and device PQ codebooks stay bit-identical given the
# same sample/seed. The alias keeps this module's call sites intact.


class IVFPQIndex:
    def __init__(
        self,
        n_subspaces: int = 16,
        n_codes: int = 256,
        n_clusters: Optional[int] = None,
        nprobe: int = 8,
        keep_vectors: bool = False,
        refine_factor: int = 4,
        min_refine_pool: int = 128,
    ):
        """``keep_vectors`` retains an fp16 copy of every vector for an
        exact-rerank refinement stage: ADC ranks a candidate pool of
        ``max(refine_factor * k, min_refine_pool)``, then true cosine
        re-scores it. PQ codes alone cap recall hard (8-32 bytes cannot
        separate near neighbors); rerank buys back exactness for
        2 bytes/dim — the standard IVFPQ+refine design (the reference
        keeps a vector cache alongside its IVFPQ tier,
        pkg/search/vector_index_cache.go). Default OFF: the compressed
        tier exists for the memory budget, and a silent fp16 copy would
        multiply it ~30x; quality-critical callers opt in."""
        self.m = n_subspaces
        self.n_codes = n_codes
        self.n_clusters = n_clusters
        self.nprobe = nprobe
        self.keep_vectors = keep_vectors
        self.refine_factor = max(1, refine_factor)
        self.min_refine_pool = max(1, min_refine_pool)

        self.dims: Optional[int] = None
        self.coarse: Optional[np.ndarray] = None  # [K, D]
        self.codebooks: Optional[np.ndarray] = None  # [M, 256, D/M]
        self._ids: List[str] = []
        self._codes: Optional[np.ndarray] = None  # [N, M] uint8
        self._assign: Optional[np.ndarray] = None  # [N] coarse cluster
        self._vecs: Optional[np.ndarray] = None  # [N, D] fp16 (refine)
        self._id_pos: Dict[str, int] = {}
        self._alive: Optional[np.ndarray] = None  # [N] bool
        self._lock = threading.Lock()
        # search-snapshot cache: (mut_gen, codes, assign, alive); any
        # mutation bumps _mut_gen, invalidating it
        self._mut_gen = 0
        self._snap = None

    # -- training --------------------------------------------------------

    @property
    def trained(self) -> bool:
        return self.codebooks is not None

    def train(
        self,
        sample: np.ndarray,
        seed_ids: Optional[Sequence[int]] = None,
    ) -> None:
        """Train coarse centroids + per-subspace codebooks. ``seed_ids``
        (row indices, e.g. BM25-picked) steer k-means++ initialization
        the way the reference seeds its training sample
        (ivfpq_build.go:16)."""
        sample = _normalize(np.asarray(sample, dtype=np.float32))
        n, d = sample.shape
        if d % self.m != 0:
            raise ValueError(f"dims {d} not divisible by M={self.m}")
        self.dims = d
        k = self.n_clusters or max(1, optimal_k(n))
        self.coarse, assign = _euclid_kmeans(sample, k, seed_ids=seed_ids)
        residuals = sample - self.coarse[assign]
        self.codebooks = train_subspace_codebooks(
            residuals, self.m, self.n_codes)  # [M, 256, D/M]

    # -- encode / add ----------------------------------------------------

    def _encode(self, vecs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """vecs [B, D] → (coarse assignment [B], codes [B, M] uint8)."""
        d = self.dims
        dist = (
            np.sum(vecs**2, axis=1, keepdims=True)
            - 2.0 * vecs @ self.coarse.T
            + np.sum(self.coarse**2, axis=1)[None, :]
        )
        assign = np.argmin(dist, axis=1)
        residual = vecs - self.coarse[assign]
        sub = residual.reshape(len(vecs), self.m, d // self.m)
        codes = np.empty((len(vecs), self.m), dtype=np.uint8)
        for j in range(self.m):
            cb = self.codebooks[j]  # [256, D/M]
            dj = (
                np.sum(sub[:, j, :] ** 2, axis=1, keepdims=True)
                - 2.0 * sub[:, j, :] @ cb.T
                + np.sum(cb**2, axis=1)[None, :]
            )
            codes[:, j] = np.argmin(dj, axis=1).astype(np.uint8)
        return assign, codes

    def add_batch(
        self, items: Sequence[Tuple[str, Sequence[float]]]
    ) -> None:
        if not self.trained:
            raise RuntimeError("IVFPQIndex.train() first")
        if not items:
            return
        vecs = _normalize(np.asarray([v for _, v in items],
                                     dtype=np.float32))
        assign, codes = self._encode(vecs)
        with self._lock:
            self._mut_gen += 1
            existing = 0 if self._codes is None else len(self._codes)
            new_rows: List[int] = []
            staged: Dict[str, int] = {}  # ext_id -> index into new_rows
            for row, (ext_id, _) in enumerate(items):
                pos = self._id_pos.get(ext_id)
                if pos is not None and pos < existing:
                    self._assign[pos] = assign[row]
                    self._codes[pos] = codes[row]
                    self._alive[pos] = True
                elif ext_id in staged:
                    # duplicate id within this batch whose first occurrence
                    # is only staged — overwrite the staged row instead of
                    # indexing arrays it hasn't been appended to yet
                    new_rows[staged[ext_id]] = row
                else:
                    self._id_pos[ext_id] = len(self._ids)
                    self._ids.append(ext_id)
                    staged[ext_id] = len(new_rows)
                    new_rows.append(row)
            for row, (ext_id, _) in enumerate(items):
                pos = self._id_pos.get(ext_id)
                if (self.keep_vectors and pos is not None
                        and pos < existing):
                    self._vecs[pos] = vecs[row].astype(np.float16)
            if new_rows:
                # one concatenate per batch, not per item (O(N*B) -> O(B))
                nc = codes[new_rows]
                na = assign[new_rows]
                nv = np.ones(len(new_rows), dtype=bool)
                if self._codes is None:
                    self._codes, self._assign, self._alive = (
                        nc.copy(), na.copy(), nv)
                    if self.keep_vectors:
                        self._vecs = vecs[new_rows].astype(np.float16)
                else:
                    self._codes = np.vstack([self._codes, nc])
                    self._assign = np.concatenate([self._assign, na])
                    self._alive = np.concatenate([self._alive, nv])
                    if self.keep_vectors:
                        self._vecs = np.vstack([
                            self._vecs,
                            vecs[new_rows].astype(np.float16)])

    def remove(self, ext_id: str) -> bool:
        with self._lock:
            pos = self._id_pos.get(ext_id)
            if pos is None or not self._alive[pos]:
                return False
            self._mut_gen += 1
            self._alive[pos] = False
            return True

    def __len__(self) -> int:
        with self._lock:
            return 0 if self._alive is None else int(self._alive.sum())

    # -- search (ADC) ----------------------------------------------------

    def search(
        self, query: Sequence[float], k: int = 10,
        nprobe: Optional[int] = None,
    ) -> List[Tuple[str, float]]:
        """Approximate top-k: ADC over the nprobe nearest clusters ranks
        a refine_factor*k candidate pool; when vectors are kept, exact
        cosine reranks the pool (scores = cosine). Without the refine
        store, scores are negated squared residual-ADC distances."""
        if not self.trained or self._codes is None:
            return []
        q = _normalize(np.asarray(query, dtype=np.float32))
        nprobe = min(nprobe or self.nprobe, self.coarse.shape[0])
        cd = np.sum((self.coarse - q[None, :]) ** 2, axis=1)
        probe = np.argpartition(cd, nprobe - 1)[:nprobe]
        d_sub = self.dims // self.m
        out_scores: List[np.ndarray] = []
        out_pos: List[np.ndarray] = []
        with self._lock:
            # snapshot by value: add_batch/remove mutate rows in place,
            # so reference-only snapshots could read torn code rows.
            # The copy is generation-cached — copying 50k x 32 codes per
            # QUERY was the ADC path's single biggest cost
            if self._snap is None or self._snap[0] != self._mut_gen:
                self._snap = (self._mut_gen, self._codes.copy(),
                              self._assign.copy(), self._alive.copy())
            _g, codes, assign, alive = self._snap
            has_refine = self._vecs is not None
        for c in probe:
            mask = (assign == c) & alive
            pos = np.nonzero(mask)[0]
            if pos.size == 0:
                continue
            residual_q = (q - self.coarse[c]).reshape(self.m, d_sub)
            # ADC table [M, 256]: one einsum per probe
            table = (
                np.sum(residual_q**2, axis=1)[:, None]
                - 2.0 * np.einsum("ms,mcs->mc", residual_q, self.codebooks)
                + np.sum(self.codebooks**2, axis=2)
            )
            cand = codes[pos]  # [n_c, M]
            dist = table[np.arange(self.m)[None, :], cand].sum(axis=1)
            out_scores.append(-dist)
            out_pos.append(pos)
        if not out_pos:
            return []
        scores = np.concatenate(out_scores)
        pos = np.concatenate(out_pos)
        if has_refine:
            # refinement: exact cosine over the ADC top pool. The pool
            # floor matters — ADC ordering is noisy exactly when refine
            # is needed, so k*refine_factor alone under-collects
            pool = min(max(k * self.refine_factor, self.min_refine_pool),
                       len(pos))
            keep = np.argpartition(-scores, pool - 1)[:pool]
            cand_pos = pos[keep]
            with self._lock:
                # copy the candidate rows under the lock: add_batch
                # overwrites re-added ids' rows in place, and a torn
                # fp16 row would mis-rank that candidate
                exact = self._vecs[cand_pos].astype(np.float32) @ q
            k_eff = min(k, pool)
            top = np.argpartition(-exact, k_eff - 1)[:k_eff]
            top = top[np.argsort(-exact[top])]
            return [(self._ids[int(cand_pos[i])], float(exact[i]))
                    for i in top]
        k_eff = min(k, len(pos))
        top = np.argpartition(-scores, k_eff - 1)[:k_eff]
        top = top[np.argsort(-scores[top])]
        return [(self._ids[int(pos[i])], float(scores[i])) for i in top]

    # -- diagnostics ------------------------------------------------------

    def coarse_hit_rate(
        self, queries: np.ndarray, true_ids: Sequence[Sequence[str]],
        nprobe: Optional[int] = None,
    ) -> float:
        """Fraction of ground-truth neighbors whose assigned cluster is
        among the probed clusters — separates 'coarse index misses the
        right cluster' (fix: more nprobe / better k-means) from 'PQ
        codes cannot rank inside the cluster' (fix: more subspaces /
        rerank). The r3 flat-recall bug class becomes diagnosable."""
        if not self.trained or self._assign is None:
            return 0.0
        qn = _normalize(np.asarray(queries, dtype=np.float32))
        nprobe = min(nprobe or self.nprobe, self.coarse.shape[0])
        hits = total = 0
        for qi in range(len(qn)):
            cd = np.sum((self.coarse - qn[qi][None, :]) ** 2, axis=1)
            probed = set(np.argpartition(cd, nprobe - 1)[:nprobe].tolist())
            for tid in true_ids[qi]:
                pos = self._id_pos.get(tid)
                if pos is None:
                    continue
                total += 1
                if int(self._assign[pos]) in probed:
                    hits += 1
        return hits / max(total, 1)

    # -- persistence (reference: ivfpq_persist.go:169) -------------------

    def save(self, path: str) -> None:
        if not self.trained:
            raise RuntimeError("cannot save an untrained IVFPQIndex")
        with self._lock:
            # trained-but-empty saves use (0, M) arrays — np.savez would
            # pickle None as a 0-d object array that poisons load()
            codes = (self._codes if self._codes is not None
                     else np.zeros((0, self.m), np.uint8))
            assign = (self._assign if self._assign is not None
                      else np.zeros(0, np.int64))
            alive = (self._alive if self._alive is not None
                     else np.zeros(0, bool))
            extra = {}
            if self.keep_vectors and self._vecs is not None:
                extra["vecs"] = self._vecs
            np.savez_compressed(
                path,
                m=self.m, n_codes=self.n_codes, nprobe=self.nprobe,
                refine_factor=self.refine_factor,
                min_refine_pool=self.min_refine_pool,
                dims=self.dims, coarse=self.coarse,
                codebooks=self.codebooks,
                ids=np.asarray(self._ids, dtype=object),
                codes=codes, assign=assign, alive=alive, **extra,
            )

    @classmethod
    def load(cls, path: str) -> "IVFPQIndex":
        z = np.load(path if path.endswith(".npz") else path + ".npz",
                    allow_pickle=True)
        idx = cls(n_subspaces=int(z["m"]), n_codes=int(z["n_codes"]),
                  nprobe=int(z["nprobe"]),
                  keep_vectors="vecs" in z.files,
                  refine_factor=int(z["refine_factor"])
                  if "refine_factor" in z.files else 4,
                  min_refine_pool=int(z["min_refine_pool"])
                  if "min_refine_pool" in z.files else 128)
        idx.dims = int(z["dims"])
        idx.coarse = z["coarse"]
        idx.codebooks = z["codebooks"]
        idx._ids = list(z["ids"])
        idx._codes = z["codes"]
        idx._assign = z["assign"]
        idx._alive = z["alive"]
        idx._vecs = z["vecs"] if "vecs" in z.files else None
        idx._id_pos = {e: i for i, e in enumerate(idx._ids)}
        return idx
