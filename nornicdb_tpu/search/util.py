"""Shared numpy helpers for the search package."""

from __future__ import annotations

import numpy as np


def normalize_rows(x: np.ndarray, eps: float = 1e-12) -> np.ndarray:
    """Row-L2-normalize; zero rows stay (near-)zero instead of NaN."""
    n = np.linalg.norm(x, axis=-1, keepdims=True)
    return x / np.maximum(n, eps)
