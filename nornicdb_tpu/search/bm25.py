"""BM25 fulltext index (Okapi BM25, compact postings).

Re-expresses the reference's BM25 v2 engine (pkg/search/fulltext_index_v2.go:51
``FulltextIndexV2``: compact postings, top-k pruning, batch indexing) and its
tokenizer (pkg/indexing/config.go ``TokenizeForBM25``). Pointer-chasing
stays on CPU; scoring is vectorized with NumPy over postings arrays.

Also provides the BM25 seed-selection used to order HNSW builds and to
sample k-means training sets (reference: bm25_seed_provider.go:12
``bm25SeedDocIDs``, docs/release-notes-since-v1.0.11.md:75-151 — lexically
discriminative docs first → 2.7x faster 1M-vector HNSW build).
"""

from __future__ import annotations

import math
import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_TOKEN_RE = re.compile(r"[a-z0-9]+")

# minimal english stopword set (reference keeps indexing light-weight)
STOPWORDS = frozenset(
    """a an and are as at be by for from has he in is it its of on that the
    to was were will with this these those i you your not or but if then
    than so we they them there here what which who whom when where how"""
    .split()
)

K1 = 1.2
B = 0.75


def tokenize(text: str, min_len: int = 2, max_len: int = 40) -> List[str]:
    """Lowercase alphanumeric tokens, stopword- and length-filtered."""
    out = []
    for tok in _TOKEN_RE.findall(text.lower()):
        if len(tok) < min_len or len(tok) > max_len:
            continue
        if tok in STOPWORDS:
            continue
        out.append(tok)
    return out


class _Posting:
    __slots__ = ("doc_ids", "tfs", "_np_ids", "_np_tfs")

    def __init__(self):
        self.doc_ids: List[int] = []
        self.tfs: List[int] = []
        self._np_ids: Optional[np.ndarray] = None
        self._np_tfs: Optional[np.ndarray] = None

    def arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """Cached numpy views of the posting — rebuilding them from the
        Python lists on every query dominated search wall-clock. The
        cache key is the list length (postings only ever append; compaction
        swaps in fresh _Posting objects)."""
        if self._np_ids is None or self._np_ids.size != len(self.doc_ids):
            self._np_ids = np.asarray(self.doc_ids, dtype=np.int64)
            self._np_tfs = np.asarray(self.tfs, dtype=np.float32)
        return self._np_ids, self._np_tfs


class BM25Index:
    """Incremental BM25 index over (doc_id -> text). Thread-safe."""

    def __init__(self):
        self._lock = threading.RLock()
        self._postings: Dict[str, _Posting] = {}
        self._doc_len: List[int] = []  # internal idx -> token count
        self._ext_ids: List[str] = []  # internal idx -> external id
        self._int_of: Dict[str, int] = {}
        self._alive: List[bool] = []
        self._total_len = 0
        self._n_alive = 0
        # per-term LIVE document frequency, maintained incrementally on
        # add/remove/tombstone — scoring and seed selection read it in
        # O(1) instead of re-counting live postings per query (the old
        # seed_doc_ids did an O(terms * postings) Python sum)
        self._df: Dict[str, int] = {}
        # slot -> unique terms of that doc, so a tombstone can decrement
        # the live df counters without re-tokenizing
        self._doc_terms: List[Optional[Tuple[str, ...]]] = []
        # cached numpy doc_len/alive, invalidated by generation counter
        self._mut_gen = 0
        self._np_gen = -1
        self._np_doc_len: Optional[np.ndarray] = None
        self._np_alive: Optional[np.ndarray] = None
        # changelog of (mutation gen, ext_id) for adds/updates — the
        # device snapshot (device_bm25.py) exact-scores these between
        # rebuilds (read-your-writes), mirroring BruteForceIndex's
        # changelog discipline. Length-capped; _changelog_floor marks
        # how far back it reaches. Compaction remaps slots, so it
        # advances the floor past every outstanding marker.
        self._changelog: List[Tuple[int, str]] = []
        self._changelog_floor = 0
        # compaction counter: slot ids are only meaningful between
        # compactions, so snapshot consumers pin reads on it
        self.compactions = 0
        # total posting entries across all terms, maintained
        # incrementally so the resource-accounting scrape never walks
        # the vocabulary (tombstones keep their postings until
        # compaction, which recounts)
        self._n_postings = 0

    def _np_state(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._np_gen != self._mut_gen:
            self._np_doc_len = np.asarray(self._doc_len, dtype=np.float32)
            self._np_alive = np.asarray(self._alive, dtype=bool)
            self._np_gen = self._mut_gen
        return self._np_doc_len, self._np_alive

    # -- indexing --------------------------------------------------------

    def index(self, doc_id: str, text: str) -> None:
        with self._lock:
            if doc_id in self._int_of:
                self._remove_locked(doc_id)
            self._maybe_compact_locked()
            self._mut_gen += 1
            toks = tokenize(text)
            idx = len(self._ext_ids)
            self._ext_ids.append(doc_id)
            self._int_of[doc_id] = idx
            self._doc_len.append(len(toks))
            self._alive.append(True)
            self._total_len += len(toks)
            self._n_alive += 1
            counts: Dict[str, int] = {}
            for t in toks:
                counts[t] = counts.get(t, 0) + 1
            for t, c in counts.items():
                p = self._postings.get(t)
                if p is None:
                    p = self._postings[t] = _Posting()
                p.doc_ids.append(idx)
                p.tfs.append(c)
                self._df[t] = self._df.get(t, 0) + 1
            self._n_postings += len(counts)
            self._doc_terms.append(tuple(counts))
            self._log_change_locked(doc_id)

    def changelog_cap(self) -> int:
        """Current changelog length cap (mirrors _log_change_locked's
        trim) — reported next to depth by the accounting layer."""
        return max(4096, len(self._ext_ids) // 4)

    def resource_stats(self) -> Dict[str, float]:
        """Memory + freshness accounting for obs/resources.py: postings
        footprint (incremental entry count — never an O(vocab) walk),
        tombstone pressure, and changelog depth vs cap."""
        with self._lock:
            n_slots = len(self._ext_ids)
            # per posting entry: one int in doc_ids + one in tfs (list
            # slots + boxed ints ~= 16B each conservatively as arrays)
            postings_b = self._n_postings * 16
            return {
                "rows": self._n_alive,
                "capacity": n_slots,
                "device_bytes": 0,  # host index; the CSR snapshot owns HBM
                "host_bytes": postings_b + n_slots * 24,
                "dead_fraction": round(
                    (n_slots - self._n_alive) / max(n_slots, 1), 6),
                "changelog_depth": len(self._changelog),
                "changelog_cap": self.changelog_cap(),
                "mutations": self._mut_gen,
                "postings": self._n_postings,
                "terms": len(self._postings),
            }

    def _log_change_locked(self, doc_id: str) -> None:
        self._changelog.append((self._mut_gen, doc_id))
        limit = self.changelog_cap()
        if len(self._changelog) > limit:
            cut = len(self._changelog) - limit
            self._changelog_floor = self._changelog[cut - 1][0]
            del self._changelog[:cut]

    def changed_since(self, seq: int) -> Optional[List[str]]:
        """ext_ids added or UPDATED after mutation ``seq`` (latest first,
        deduped). Deletes are not reported — consumers live-filter those.
        Returns None when the changelog was trimmed (or slots remapped
        by compaction) past ``seq``: the consumer must rebuild or take
        the host-exact path instead."""
        with self._lock:
            if seq < self._changelog_floor:
                return None
            out: List[str] = []
            for s, eid in reversed(self._changelog):
                if s <= seq:
                    break
                out.append(eid)
        return list(dict.fromkeys(out))

    def index_batch(self, docs: Sequence[Tuple[str, str]]) -> None:
        """Reference: IndexBatch (fulltext_index_v2.go:114)."""
        for doc_id, text in docs:
            self.index(doc_id, text)

    def _remove_locked(self, doc_id: str) -> None:
        idx = self._int_of.pop(doc_id, None)
        if idx is None or not self._alive[idx]:
            return
        self._mut_gen += 1
        self._alive[idx] = False
        self._total_len -= self._doc_len[idx]
        self._n_alive -= 1
        for t in self._doc_terms[idx] or ():
            left = self._df.get(t, 0) - 1
            if left > 0:
                self._df[t] = left
            else:
                self._df.pop(t, None)
        self._doc_terms[idx] = None  # release the tombstone's term list

    def remove(self, doc_id: str) -> None:
        with self._lock:
            self._remove_locked(doc_id)

    def _maybe_compact_locked(self) -> None:
        """Re-indexing tombstones the old slot; without compaction a
        hot-update workload grows slots and postings without bound. Rebuild
        in place once dead slots dominate."""
        n_slots = len(self._ext_ids)
        if n_slots < 1024 or self._n_alive * 2 > n_slots:
            return
        remap: Dict[int, int] = {}
        new_ext: List[str] = []
        new_len: List[int] = []
        new_terms: List[Optional[Tuple[str, ...]]] = []
        for old_idx, ext in enumerate(self._ext_ids):
            if self._alive[old_idx]:
                remap[old_idx] = len(new_ext)
                new_ext.append(ext)
                new_len.append(self._doc_len[old_idx])
                new_terms.append(self._doc_terms[old_idx])
        new_postings: Dict[str, _Posting] = {}
        new_df: Dict[str, int] = {}
        for t, p in self._postings.items():
            np_post = _Posting()
            for did, tf in zip(p.doc_ids, p.tfs):
                new_idx = remap.get(did)
                if new_idx is not None:
                    np_post.doc_ids.append(new_idx)
                    np_post.tfs.append(tf)
            if np_post.doc_ids:
                new_postings[t] = np_post
                new_df[t] = len(np_post.doc_ids)
        self._ext_ids = new_ext
        self._doc_len = new_len
        self._alive = [True] * len(new_ext)
        self._int_of = {e: i for i, e in enumerate(new_ext)}
        self._postings = new_postings
        self._df = new_df
        self._doc_terms = new_terms
        self._n_postings = sum(
            len(p.doc_ids) for p in new_postings.values())
        self._mut_gen += 1
        self.compactions += 1
        # slots were remapped: every outstanding snapshot marker is now
        # meaningless, so invalidate the whole changelog window
        self._changelog.clear()
        self._changelog_floor = self._mut_gen

    def __contains__(self, doc_id: str) -> bool:
        with self._lock:
            idx = self._int_of.get(doc_id)
            return idx is not None and self._alive[idx]

    def __len__(self) -> int:
        return self._n_alive

    def ids(self) -> list:
        """Live (non-tombstoned) document ids."""
        with self._lock:
            return [e for e, i in self._int_of.items() if self._alive[i]]

    # -- scoring ---------------------------------------------------------

    def _idf(self, df: int) -> float:
        n = max(self._n_alive, 1)
        return math.log(1.0 + (n - df + 0.5) / (df + 0.5))

    @property
    def mut_gen(self) -> int:
        """Mutation generation — bumped on every add/update/remove/
        compaction. Derived device snapshots key freshness off it."""
        return self._mut_gen

    def term_stats(self, terms: Sequence[str]) -> Tuple[Dict[str, int], int, float]:
        """(live df per term, n_alive, avgdl) in one lock acquisition —
        the host-side idf inputs the device scorer shares with this
        index, read from the incremental counters."""
        with self._lock:
            avgdl = max(self._total_len / max(self._n_alive, 1), 1.0)
            return ({t: self._df.get(t, 0) for t in terms},
                    self._n_alive, avgdl)

    def search(self, query: str, k: int = 10) -> List[Tuple[str, float]]:
        """Top-k (doc_id, bm25_score). Accumulates scores over the query
        terms' postings with NumPy (vectorized tf normalization)."""
        with self._lock:
            return self._search_locked(tokenize(query), k)

    def search_batch(
        self, queries: Sequence[str], k: int = 10
    ) -> List[List[Tuple[str, float]]]:
        """Batched host search: one lock acquisition for the whole batch,
        one result list per query. The host fallback of the device path
        (device_bm25.DeviceBM25.search_batch) shares this contract, so
        callers swap between them without reshaping results."""
        with self._lock:
            return [self._search_locked(tokenize(q), k) for q in queries]

    def _search_locked(self, toks_seq: Sequence[str],
                       k: int) -> List[Tuple[str, float]]:
        # terms iterate in SORTED order and idf is cast to float32:
        # per-doc accumulation then happens in the same order and
        # precision as the device scorer's flattened-entry segment sum,
        # keeping host and device rankings aligned
        toks = sorted(set(toks_seq))
        if not toks or self._n_alive == 0:
            return []
        n_docs = len(self._ext_ids)
        avgdl = max(self._total_len / max(self._n_alive, 1), 1.0)
        scores = np.zeros(n_docs, dtype=np.float32)
        doc_len, alive = self._np_state()
        touched = np.zeros(n_docs, dtype=bool)
        for t in toks:
            p = self._postings.get(t)
            if p is None:
                continue
            ids, tfs = p.arrays()
            # scoring runs over LIVE postings only: a tombstoned slot
            # (re-index leaves one) must not surface — and the df the
            # idf sees is the incremental live counter, which equals
            # the live-posting count by construction
            live = alive[ids]
            ids, tfs = ids[live], tfs[live]
            df = self._df.get(t, 0)
            if df == 0 or ids.size == 0:
                continue
            idf = np.float32(self._idf(df))
            dl = doc_len[ids]
            tf_norm = tfs * (K1 + 1.0) / (tfs + K1 * (1.0 - B + B * dl / avgdl))
            scores[ids] += idf * tf_norm
            touched[ids] = True
        mask = touched & alive
        cand = np.nonzero(mask)[0]
        if cand.size == 0:
            return []
        order = cand[np.argsort(-scores[cand], kind="stable")][:k]
        return [(self._ext_ids[i], float(scores[i])) for i in order]

    def score_docs(
        self, tokens: Sequence[str], doc_ids: Sequence[str]
    ) -> Dict[str, float]:
        """Exact BM25 scores of specific live docs for a tokenized query
        (only docs matching >= 1 term appear). The device snapshot's
        read-your-writes delta side-scan: docs indexed after the
        snapshot are scored here, host-exact, and merged into the
        device top-k."""
        with self._lock:
            toks = sorted(set(tokens))
            want: Dict[int, str] = {}
            for eid in doc_ids:
                idx = self._int_of.get(eid)
                if idx is not None and self._alive[idx]:
                    want[idx] = eid
            if not toks or not want:
                return {}
            avgdl = max(self._total_len / max(self._n_alive, 1), 1.0)
            out: Dict[str, float] = {}
            for t in toks:
                p = self._postings.get(t)
                df = self._df.get(t, 0)
                if p is None or df == 0:
                    continue
                idf = np.float32(self._idf(df))
                ids, tfs = p.arrays()
                # postings append in strictly increasing slot order, so
                # membership is a binary search, not a scan
                want_idx = sorted(want)
                pos = np.searchsorted(ids, want_idx)
                for idx, j in zip(want_idx, pos):
                    if j >= ids.size or int(ids[j]) != idx:
                        continue
                    eid = want[idx]
                    tf = np.float32(tfs[j])
                    dl = np.float32(self._doc_len[idx])
                    tf_norm = tf * np.float32(K1 + 1.0) / (
                        tf + np.float32(K1) * np.float32(1.0 - B + B * dl / avgdl))
                    out[eid] = float(np.float32(out.get(eid, 0.0))
                                     + idf * tf_norm)
            return out

    def csr_snapshot(self) -> Dict[str, object]:
        """Flatten the live postings into CSR arrays for the device
        scorer (device_bm25.py): sorted terms, per-term offset ranges
        over (doc_row, tf) columns in live-row space, plus doc lengths
        and row ext ids. Tombstoned slots are dropped and slot ids are
        remapped to a dense 0..n_live row space."""
        with self._lock:
            doc_len, alive = self._np_state()
            rows = np.nonzero(alive)[0] if len(self._ext_ids) else \
                np.zeros((0,), dtype=np.int64)
            n_slots = len(self._ext_ids)
            remap = np.full(n_slots, -1, dtype=np.int32)
            remap[rows] = np.arange(len(rows), dtype=np.int32)
            terms = sorted(self._postings)
            doc_parts: List[np.ndarray] = []
            tf_parts: List[np.ndarray] = []
            offsets = np.zeros(len(terms) + 1, dtype=np.int64)
            total = 0
            for ti, t in enumerate(terms):
                ids, tfs = self._postings[t].arrays()
                live = alive[ids]
                doc_parts.append(remap[ids[live]])
                tf_parts.append(tfs[live])
                total += int(live.sum())
                offsets[ti + 1] = total
            return {
                "gen": self._mut_gen,
                "compactions": self.compactions,
                "terms": terms,
                "vocab": {t: i for i, t in enumerate(terms)},
                "offsets": offsets,
                "post_doc": (np.concatenate(doc_parts)
                             if doc_parts else np.zeros(0, np.int32)),
                "post_tf": (np.concatenate(tf_parts).astype(np.float32)
                            if tf_parts else np.zeros(0, np.float32)),
                "doc_len": doc_len[rows].astype(np.float32),
                "row_ids": [self._ext_ids[int(s)] for s in rows],
                # original slot per row: consumers live-filter by SLOT
                # (an update tombstones the old slot while the ext id
                # stays live at a new one)
                "slots": rows.astype(np.int64),
            }

    def alive_slots(
        self, slots: Sequence[int],
        expect_compactions: Optional[int] = None,
    ) -> Optional[np.ndarray]:
        """Bool per slot id: still live? Slot ids are only meaningful in
        the slot space they were snapshotted from, so the read and the
        compaction check happen under ONE lock hold: when
        ``expect_compactions`` no longer matches (a compaction remapped
        slots since the snapshot), returns None and the caller must
        fall back rather than trust resurrected slot ids."""
        with self._lock:
            if expect_compactions is not None \
                    and self.compactions != expect_compactions:
                return None
            n = len(self._alive)
            return np.asarray(
                [0 <= s < n and self._alive[int(s)] for s in slots],
                dtype=bool)

    # -- seed selection (BM25-seeded builds) ------------------------------

    def seed_doc_ids(
        self, max_seeds: int = 2048, n_terms: int = 256, per_term: Optional[int] = None
    ) -> List[str]:
        """Lexically discriminative docs: take the `n_terms` highest-IDF
        terms (ignoring hapax noise) and collect their top-tf docs, up to
        `max_seeds`, highest-signal first. These anchor HNSW insertion
        order and k-means init (reference: search.go:3785-3871)."""
        with self._lock:
            if self._n_alive == 0:
                return []
            ranked_terms = []
            for t in self._postings:
                # incremental live-df counter: O(1) per term instead of
                # the old O(postings) alive-scan per term per call
                df = self._df.get(t, 0)
                if df < 2:  # hapax terms don't discriminate clusters
                    continue
                ranked_terms.append((self._idf(df), t))
            ranked_terms.sort(reverse=True)
            per_term = per_term or max(1, max_seeds // max(n_terms, 1))
            seen: Dict[int, None] = {}
            for _, t in ranked_terms[:n_terms]:
                p = self._postings[t]
                order = np.argsort(-np.asarray(p.tfs))[:per_term]
                for j in order:
                    idx = p.doc_ids[int(j)]
                    if self._alive[idx]:
                        seen.setdefault(idx, None)
                if len(seen) >= max_seeds:
                    break
            return [self._ext_ids[i] for i in list(seen)[:max_seeds]]

    # -- persistence ------------------------------------------------------

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "ext_ids": list(self._ext_ids),
                "doc_len": list(self._doc_len),
                "alive": [bool(a) for a in self._alive],
                "postings": {
                    t: {"ids": list(p.doc_ids), "tfs": list(p.tfs)}
                    for t, p in self._postings.items()
                },
            }

    @classmethod
    def from_dict(cls, d: dict) -> "BM25Index":
        idx = cls()
        idx._ext_ids = list(d["ext_ids"])
        idx._doc_len = list(d["doc_len"])
        idx._alive = list(d["alive"])
        idx._int_of = {
            e: i for i, e in enumerate(idx._ext_ids) if idx._alive[i]
        }
        terms_per_doc: List[List[str]] = [[] for _ in idx._ext_ids]
        for t, p in d["postings"].items():
            post = _Posting()
            post.doc_ids = list(p["ids"])
            post.tfs = list(p["tfs"])
            idx._postings[t] = post
            df = 0
            for did in post.doc_ids:
                if idx._alive[did]:
                    df += 1
                    terms_per_doc[did].append(t)
            if df:
                idx._df[t] = df
        idx._doc_terms = [
            tuple(ts) if idx._alive[i] else None
            for i, ts in enumerate(terms_per_doc)
        ]
        idx._total_len = sum(
            l for l, a in zip(idx._doc_len, idx._alive) if a
        )
        idx._n_alive = sum(1 for a in idx._alive if a)
        idx._n_postings = sum(
            len(p.doc_ids) for p in idx._postings.values())
        return idx
