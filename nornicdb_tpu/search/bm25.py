"""BM25 fulltext index (Okapi BM25, compact postings).

Re-expresses the reference's BM25 v2 engine (pkg/search/fulltext_index_v2.go:51
``FulltextIndexV2``: compact postings, top-k pruning, batch indexing) and its
tokenizer (pkg/indexing/config.go ``TokenizeForBM25``). Pointer-chasing
stays on CPU; scoring is vectorized with NumPy over postings arrays.

Also provides the BM25 seed-selection used to order HNSW builds and to
sample k-means training sets (reference: bm25_seed_provider.go:12
``bm25SeedDocIDs``, docs/release-notes-since-v1.0.11.md:75-151 — lexically
discriminative docs first → 2.7x faster 1M-vector HNSW build).
"""

from __future__ import annotations

import math
import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_TOKEN_RE = re.compile(r"[a-z0-9]+")

# minimal english stopword set (reference keeps indexing light-weight)
STOPWORDS = frozenset(
    """a an and are as at be by for from has he in is it its of on that the
    to was were will with this these those i you your not or but if then
    than so we they them there here what which who whom when where how"""
    .split()
)

K1 = 1.2
B = 0.75


def tokenize(text: str, min_len: int = 2, max_len: int = 40) -> List[str]:
    """Lowercase alphanumeric tokens, stopword- and length-filtered."""
    out = []
    for tok in _TOKEN_RE.findall(text.lower()):
        if len(tok) < min_len or len(tok) > max_len:
            continue
        if tok in STOPWORDS:
            continue
        out.append(tok)
    return out


class _Posting:
    __slots__ = ("doc_ids", "tfs", "_np_ids", "_np_tfs")

    def __init__(self):
        self.doc_ids: List[int] = []
        self.tfs: List[int] = []
        self._np_ids: Optional[np.ndarray] = None
        self._np_tfs: Optional[np.ndarray] = None

    def arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """Cached numpy views of the posting — rebuilding them from the
        Python lists on every query dominated search wall-clock. The
        cache key is the list length (postings only ever append; compaction
        swaps in fresh _Posting objects)."""
        if self._np_ids is None or self._np_ids.size != len(self.doc_ids):
            self._np_ids = np.asarray(self.doc_ids, dtype=np.int64)
            self._np_tfs = np.asarray(self.tfs, dtype=np.float32)
        return self._np_ids, self._np_tfs


class BM25Index:
    """Incremental BM25 index over (doc_id -> text). Thread-safe."""

    def __init__(self):
        self._lock = threading.RLock()
        self._postings: Dict[str, _Posting] = {}
        self._doc_len: List[int] = []  # internal idx -> token count
        self._ext_ids: List[str] = []  # internal idx -> external id
        self._int_of: Dict[str, int] = {}
        self._alive: List[bool] = []
        self._total_len = 0
        self._n_alive = 0
        # cached numpy doc_len/alive, invalidated by generation counter
        self._mut_gen = 0
        self._np_gen = -1
        self._np_doc_len: Optional[np.ndarray] = None
        self._np_alive: Optional[np.ndarray] = None

    def _np_state(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._np_gen != self._mut_gen:
            self._np_doc_len = np.asarray(self._doc_len, dtype=np.float32)
            self._np_alive = np.asarray(self._alive, dtype=bool)
            self._np_gen = self._mut_gen
        return self._np_doc_len, self._np_alive

    # -- indexing --------------------------------------------------------

    def index(self, doc_id: str, text: str) -> None:
        with self._lock:
            if doc_id in self._int_of:
                self._remove_locked(doc_id)
            self._maybe_compact_locked()
            self._mut_gen += 1
            toks = tokenize(text)
            idx = len(self._ext_ids)
            self._ext_ids.append(doc_id)
            self._int_of[doc_id] = idx
            self._doc_len.append(len(toks))
            self._alive.append(True)
            self._total_len += len(toks)
            self._n_alive += 1
            counts: Dict[str, int] = {}
            for t in toks:
                counts[t] = counts.get(t, 0) + 1
            for t, c in counts.items():
                p = self._postings.get(t)
                if p is None:
                    p = self._postings[t] = _Posting()
                p.doc_ids.append(idx)
                p.tfs.append(c)

    def index_batch(self, docs: Sequence[Tuple[str, str]]) -> None:
        """Reference: IndexBatch (fulltext_index_v2.go:114)."""
        for doc_id, text in docs:
            self.index(doc_id, text)

    def _remove_locked(self, doc_id: str) -> None:
        idx = self._int_of.pop(doc_id, None)
        if idx is None or not self._alive[idx]:
            return
        self._mut_gen += 1
        self._alive[idx] = False
        self._total_len -= self._doc_len[idx]
        self._n_alive -= 1

    def remove(self, doc_id: str) -> None:
        with self._lock:
            self._remove_locked(doc_id)

    def _maybe_compact_locked(self) -> None:
        """Re-indexing tombstones the old slot; without compaction a
        hot-update workload grows slots and postings without bound. Rebuild
        in place once dead slots dominate."""
        n_slots = len(self._ext_ids)
        if n_slots < 1024 or self._n_alive * 2 > n_slots:
            return
        remap: Dict[int, int] = {}
        new_ext: List[str] = []
        new_len: List[int] = []
        for old_idx, ext in enumerate(self._ext_ids):
            if self._alive[old_idx]:
                remap[old_idx] = len(new_ext)
                new_ext.append(ext)
                new_len.append(self._doc_len[old_idx])
        new_postings: Dict[str, _Posting] = {}
        for t, p in self._postings.items():
            np_post = _Posting()
            for did, tf in zip(p.doc_ids, p.tfs):
                new_idx = remap.get(did)
                if new_idx is not None:
                    np_post.doc_ids.append(new_idx)
                    np_post.tfs.append(tf)
            if np_post.doc_ids:
                new_postings[t] = np_post
        self._ext_ids = new_ext
        self._doc_len = new_len
        self._alive = [True] * len(new_ext)
        self._int_of = {e: i for i, e in enumerate(new_ext)}
        self._postings = new_postings
        self._mut_gen += 1

    def __contains__(self, doc_id: str) -> bool:
        with self._lock:
            idx = self._int_of.get(doc_id)
            return idx is not None and self._alive[idx]

    def __len__(self) -> int:
        return self._n_alive

    def ids(self) -> list:
        """Live (non-tombstoned) document ids."""
        with self._lock:
            return [e for e, i in self._int_of.items() if self._alive[i]]

    # -- scoring ---------------------------------------------------------

    def _idf(self, df: int) -> float:
        n = max(self._n_alive, 1)
        return math.log(1.0 + (n - df + 0.5) / (df + 0.5))

    def search(self, query: str, k: int = 10) -> List[Tuple[str, float]]:
        """Top-k (doc_id, bm25_score). Accumulates scores over the query
        terms' postings with NumPy (vectorized tf normalization)."""
        with self._lock:
            toks = set(tokenize(query))
            if not toks or self._n_alive == 0:
                return []
            n_docs = len(self._ext_ids)
            avgdl = max(self._total_len / max(self._n_alive, 1), 1.0)
            scores = np.zeros(n_docs, dtype=np.float32)
            doc_len, alive = self._np_state()
            touched = np.zeros(n_docs, dtype=bool)
            for t in toks:
                p = self._postings.get(t)
                if p is None:
                    continue
                ids, tfs = p.arrays()
                # df over LIVE postings only: a tombstoned slot (re-index
                # leaves one) must not inflate df — with few docs that
                # flips idf negative and hits get min_score-filtered
                live = alive[ids]
                ids, tfs = ids[live], tfs[live]
                df = int(ids.size)
                if df == 0:
                    continue
                idf = self._idf(df)
                dl = doc_len[ids]
                tf_norm = tfs * (K1 + 1.0) / (tfs + K1 * (1.0 - B + B * dl / avgdl))
                scores[ids] += idf * tf_norm
                touched[ids] = True
            mask = touched & alive
            cand = np.nonzero(mask)[0]
            if cand.size == 0:
                return []
            order = cand[np.argsort(-scores[cand], kind="stable")][:k]
            return [(self._ext_ids[i], float(scores[i])) for i in order]

    # -- seed selection (BM25-seeded builds) ------------------------------

    def seed_doc_ids(
        self, max_seeds: int = 2048, n_terms: int = 256, per_term: Optional[int] = None
    ) -> List[str]:
        """Lexically discriminative docs: take the `n_terms` highest-IDF
        terms (ignoring hapax noise) and collect their top-tf docs, up to
        `max_seeds`, highest-signal first. These anchor HNSW insertion
        order and k-means init (reference: search.go:3785-3871)."""
        with self._lock:
            if self._n_alive == 0:
                return []
            ranked_terms = []
            for t, p in self._postings.items():
                df = sum(1 for i in p.doc_ids if self._alive[i])
                if df < 2:  # hapax terms don't discriminate clusters
                    continue
                ranked_terms.append((self._idf(df), t))
            ranked_terms.sort(reverse=True)
            per_term = per_term or max(1, max_seeds // max(n_terms, 1))
            seen: Dict[int, None] = {}
            for _, t in ranked_terms[:n_terms]:
                p = self._postings[t]
                order = np.argsort(-np.asarray(p.tfs))[:per_term]
                for j in order:
                    idx = p.doc_ids[int(j)]
                    if self._alive[idx]:
                        seen.setdefault(idx, None)
                if len(seen) >= max_seeds:
                    break
            return [self._ext_ids[i] for i in list(seen)[:max_seeds]]

    # -- persistence ------------------------------------------------------

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "ext_ids": list(self._ext_ids),
                "doc_len": list(self._doc_len),
                "alive": [bool(a) for a in self._alive],
                "postings": {
                    t: {"ids": list(p.doc_ids), "tfs": list(p.tfs)}
                    for t, p in self._postings.items()
                },
            }

    @classmethod
    def from_dict(cls, d: dict) -> "BM25Index":
        idx = cls()
        idx._ext_ids = list(d["ext_ids"])
        idx._doc_len = list(d["doc_len"])
        idx._alive = list(d["alive"])
        idx._int_of = {
            e: i for i, e in enumerate(idx._ext_ids) if idx._alive[i]
        }
        for t, p in d["postings"].items():
            post = _Posting()
            post.doc_ids = list(p["ids"])
            post.tfs = list(p["tfs"])
            idx._postings[t] = post
        idx._total_len = sum(
            l for l, a in zip(idx._doc_len, idx._alive) if a
        )
        idx._n_alive = sum(1 for a in idx._alive if a)
        return idx
