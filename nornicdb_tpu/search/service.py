"""Hybrid search service: BM25 + vector + RRF, with strategy state machine.

Reference: pkg/search/search.go ``Service`` (:417-524), ``Search`` (:2841),
``BuildIndexes`` (:2246), ``IndexNode`` (:1785), strategy state machine
bruteCPU <-> bruteGPU <-> HNSW (:528-535). TPU design: the "GPU" strategy
is simply the device-backed BruteForceIndex (ops dispatch to whatever
backend JAX has); HNSW kicks in above ``hnsw_threshold`` with a
BM25-seeded build.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from nornicdb_tpu.obs import REGISTRY, attach_span
from nornicdb_tpu.obs import audit as _audit
from nornicdb_tpu.obs import tenant as _tenant
from nornicdb_tpu.search.bm25 import BM25Index, tokenize
from nornicdb_tpu.search.hnsw import HNSWIndex
from nornicdb_tpu.search.rrf import rrf_fuse
from nornicdb_tpu.search.vector_index import BruteForceIndex
from nornicdb_tpu.storage.types import Engine, Node

TEXT_PROPERTIES = ("content", "title", "name", "description", "text", "summary")

# which index the strategy machine actually routed each vector search
# to — the brute/cagra/hnsw split the ROADMAP tuning loop reads
_STRATEGY_C = REGISTRY.counter(
    "nornicdb_search_strategy_total",
    "Vector search dispatches by chosen strategy", labels=("strategy",))

# tier-mix truth for result-cache hits (ISSUE 10): cached child — the
# hit path must not pay a labels() probe per request
_HYBRID_CACHED_SERVED = _audit.served_counter("hybrid", "cached")


def _copy_tree(v):
    """Manual deep copy of plain JSON-shaped data. copy.deepcopy's
    protocol machinery (memo dict, reduce dispatch) costs ~8x more per
    hit and sat at the top of the REST-search request profile."""
    if isinstance(v, dict):
        return {k: _copy_tree(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_copy_tree(x) for x in v]
    return v


def _copy_hit(r: Dict[str, Any]) -> Dict[str, Any]:
    """Cache-safe copy of one search hit: the nested properties/labels
    come from the node BY REFERENCE (to_dict), so a shallow dict() would
    let a caller's mutation poison the cached entry for the whole TTL."""
    c = dict(r)
    if "properties" in c:
        c["properties"] = _copy_tree(c["properties"])
    if "labels" in c:
        c["labels"] = list(c["labels"])
    return c


def extract_text(node: Node) -> str:
    """Searchable text from a node (reference: pkg/indexing
    ExtractSearchableText — title/content-ish properties + labels)."""
    parts: List[str] = []
    for key in TEXT_PROPERTIES:
        v = node.properties.get(key)
        if isinstance(v, str) and v:
            parts.append(v)
    parts.extend(node.labels)
    return " ".join(parts)


@dataclass
class SearchResult:
    node_id: str
    score: float
    node: Optional[Node] = None
    bm25_score: Optional[float] = None
    vector_score: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"id": self.node_id, "score": self.score}
        if self.bm25_score is not None:
            d["bm25_score"] = self.bm25_score
        if self.vector_score is not None:
            d["vector_score"] = self.vector_score
        if self.node is not None:
            d["labels"] = self.node.labels
            d["properties"] = self.node.properties
        return d


@dataclass
class SearchStats:
    indexed_docs: int = 0
    indexed_vectors: int = 0
    strategy: str = "brute"
    searches: int = 0
    cache_hits: int = 0
    hnsw_builds: int = 0
    cagra_builds: int = 0
    # per-stage timings of the most recent search, populated when
    # NORNICDB_TPU_SEARCH_DIAG is set (reference:
    # NORNICDB_SEARCH_DIAG_TIMINGS)
    last_timings: Dict[str, float] = field(default_factory=dict)


class SearchService:
    """One search service per logical database
    (reference: per-DB instances, pkg/nornicdb/search_services.go:68)."""

    def __init__(
        self,
        storage: Optional[Engine] = None,
        embedder: Optional[Any] = None,
        hnsw_threshold: int = 10_000,
        hnsw_m: int = 16,
        hnsw_ef_search: int = 64,
        reranker: Optional[Any] = None,
        database: str = "neo4j",
        vector_registry: Optional[Any] = None,
        persist_dir: Optional[str] = None,
        save_debounce_s: float = 5.0,
        resource_name: Optional[str] = None,
    ):
        self.storage = storage
        self.embedder = embedder
        self.reranker = reranker  # stage-2 rerank (rerank.py), optional
        self.hnsw_threshold = hnsw_threshold
        self._lock = threading.RLock()
        self.bm25 = BM25Index()
        # the document vector index lives in a registered vector space
        # (reference: pkg/vectorspace/registry.go keyed spaces; the
        # service's default doc space is (db, "node", "embedding"))
        from nornicdb_tpu.vectorspace import VectorSpaceRegistry

        self.database = database
        # per-service registry unless the caller shares one (multidb
        # passes a shared registry so spaces are keyed per database)
        self.vector_registry = vector_registry or VectorSpaceRegistry()
        self._doc_space = self.vector_registry.get_or_create(
            database=database, entity_type="node", backend="brute"
        )
        self.vectors = self._doc_space.ensure_index()
        self.hnsw: Optional[HNSWIndex] = None
        # device-resident graph ANN (profile cagra): wraps self.vectors
        # as its vector store, so index mutations propagate and the
        # graph rebuilds itself from the shared brute snapshot
        self.cagra = None
        self._hnsw_m = hnsw_m
        self._hnsw_ef = hnsw_ef_search
        self.stats = SearchStats()
        # Search() result cache, query+options keyed — same semantics
        # as the Cypher query cache and the reference's
        # searchResultCache; generation-guarded puts + copy-on-return
        # (cache.py ResultCache)
        from nornicdb_tpu.cache import ResultCache

        self._result_cache: ResultCache = ResultCache(_copy_hit)
        # index persistence: debounced saves + load-on-open so a restart
        # skips the rebuild (reference: search.go:496-507, versioned
        # persisted indexes + resumeVectorBuild search.go:432)
        self.persist_dir = persist_dir
        self._save_debounce_s = save_debounce_s
        self._save_timer: Optional[threading.Timer] = None
        self._save_lock = threading.Lock()  # serializes snapshot writers
        self._saved_at_ms = 0
        self._closed = False

        # concurrent b=1 vector queries coalesce into one batched device
        # call (SURVEY §7: "batched query aggregation, or the TPU path
        # only wins at batch/scale")
        from nornicdb_tpu.search.microbatch import MicroBatcher

        # dispatch resolves the ACTIVE ANN index per batch (cagra once
        # built, else brute), so the coalescing window feeds whichever
        # device index the strategy machine currently owns;
        # tier_surface="vector" makes every rider record the serving
        # tier the dispatch path noted (walk/quant/brute — ISSUE 10)
        self._microbatch = MicroBatcher(self._ann_search_batch,
                                        surface="service:vector",
                                        tier_surface="vector")
        # fused hybrid pipeline (hybrid_fused.py): concurrent hybrid
        # searches coalesce here into ONE device dispatch that scores
        # BM25 + cosine + RRF end-to-end, instead of convoying on the
        # BM25 lock. Tokens/fusion options ride as extras; rows come
        # back pre-shaped, so the batcher neither stacks nor truncates
        # them (pass_extras/truncate flags).
        self._fused = None
        self._hybrid_batch = MicroBatcher(
            self._fused_hybrid_dispatch, pass_extras=True, truncate=False,
            surface="service:hybrid")
        # resource & freshness accounting (obs/resources.py): register
        # the index structures and coalescing queues so /metrics carries
        # their device-memory/staleness gauges and /readyz can gate on
        # rebuild/backlog/queue state. Weak registration — a dropped
        # service's series disappear with it.
        from nornicdb_tpu.obs import register_resource

        # resource identity: "service:<db>" unless the caller tags this
        # service (read replicas pass "service:<db>@<node>" so an
        # in-process fleet's per-replica gauges never collide)
        self.resource_name = resource_name or f"service:{database}"
        register_resource("bm25", self.resource_name, self.bm25)
        register_resource("brute", self.resource_name, self.vectors)
        register_resource("queue", f"{self.resource_name}:vector",
                          self._microbatch)
        register_resource("queue", f"{self.resource_name}:hybrid",
                          self._hybrid_batch)

    def _ann_search_batch(self, queries, k):
        """Batched device dispatch for the micro-batcher: the CAGRA
        graph walk when built, else the brute matmul+top-k."""
        cagra = self.cagra
        if cagra is not None:
            return cagra.search_batch(queries, k)
        return self.vectors.search_batch(queries, k)

    def _fused_hybrid_dispatch(self, queries, k_max, extras):
        """Batched device dispatch of the hybrid batcher: one compiled
        BM25+vector+RRF program per pow2 (B, k) bucket. None rows tell
        riders to fall back to the host hybrid path."""
        fused = self._fused
        if fused is None:
            return [None] * len(queries)
        return fused.search_batch(queries, k_max, extras)

    def _ensure_fused(self):
        """Resolve (building if needed) the fused hybrid pipeline, or
        None while the host path must serve. Env-gated like the ANN
        profiles: NORNICDB_HYBRID_FUSED (default on),
        NORNICDB_HYBRID_MIN_N corpus floor, NORNICDB_HYBRID_SHARDS mesh
        row-sharding, NORNICDB_HYBRID_INLINE_BUILD for deterministic
        (blocking) first builds in tests/benches. The walk tier
        (NORNICDB_HYBRID_WALK, default on) replaces the pipeline's
        exact vector matmul with the CAGRA greedy walk above
        NORNICDB_HYBRID_WALK_MIN_N live vectors (default 100k — below
        it the O(N) matmul is cheap enough that exact rank parity
        wins), sharing the strategy machine's graph when one exists.

        Lifecycle: the wrapper is evicted and re-wrapped when the
        underlying index OBJECTS move — an index reload
        (:meth:`load_indexes` clears ``_fused``) — and rebound IN PLACE
        (:meth:`FusedHybrid.rebind_cagra`, below) when the strategy
        machine builds a new CAGRA graph over the same brute index, so
        a stale pipeline can never keep serving a discarded corpus or
        keep walking a replaced graph while its row->slot maps silently
        mis-age. Anything snapshot-coupled to the graph must live on
        the per-graph snapshot (keyed by ``build_seq``), not on the
        wrapper: a graph swap does NOT rebuild the wrapper."""
        from nornicdb_tpu.config import env_bool, env_int

        # the whole resolve runs under the service RLock: the eviction
        # checks and the re-wrap race load_indexes (which swaps the
        # index objects and clears _fused under the same lock) — an
        # unguarded re-wrap here could briefly resurrect a wrapper over
        # a discarded corpus and double-build under concurrent searches
        with self._lock:
            f = self._ensure_fused_locked(env_bool, env_int)
        if f is None or not f.ensure():
            return None  # first build runs in background; host serves
        return f

    def _ensure_fused_locked(self, env_bool, env_int):
        if not env_bool("HYBRID_FUSED", True):
            self._fused = None
            return None
        min_n = env_int("HYBRID_MIN_N", 4096)
        if len(self.bm25) < min_n or len(self.vectors) == 0:
            self._fused = None
            return None
        f = self._fused
        if f is not None and f.bm25 is self.bm25 \
                and f.brute is self.vectors \
                and self.cagra is not None \
                and f.cagra is not self.cagra:
            # the strategy machine built its own graph over the same
            # brute index: rebind it in place — one graph, one rebuild
            # cadence, and the lexical snapshot keeps serving (a full
            # re-wrap would drop hybrid to the host path until the CSR
            # snapshot rebuilt)
            if not f.rebind_cagra(self.cagra):
                # the candidate graph wraps a brute other than the live
                # one (a racy background build finished after an index
                # reload): the wrapper itself is sound, so keep serving
                # it — rewrapping here would rebuild the pipeline on
                # EVERY search while the stale graph lingered — and
                # drop the graph, which would serve the discarded
                # corpus from any path that walked it
                self.cagra = None
        if f is None or f.bm25 is not self.bm25 \
                or f.brute is not self.vectors:
            # index reload swapped the underlying objects: re-wrap so
            # the pipeline can never serve a discarded corpus
            from nornicdb_tpu.search.hybrid_fused import FusedHybrid

            walk_min_n = None
            if env_bool("HYBRID_WALK", True):
                walk_min_n = env_int("HYBRID_WALK_MIN_N", 100_000)
            cagra = self.cagra
            if cagra is not None and cagra._brute is not self.vectors:
                # a racy background build captured a pre-reload brute:
                # its graph indexes a discarded corpus (FusedHybrid
                # re-checks this too; None = wrap a fresh one)
                cagra = None
            f = FusedHybrid(
                self.bm25, self.vectors,
                n_shards=max(1, env_int("HYBRID_SHARDS", 1)),
                min_n=min_n,
                build_inline=env_bool("HYBRID_INLINE_BUILD", False),
                walk_min_n=walk_min_n,
                cagra=cagra)
            self._fused = f
            from nornicdb_tpu.obs import register_resource

            register_resource("device_bm25",
                              self.resource_name, f.lex)
            if f.cagra is not None and f.cagra is not self.cagra:
                # pipeline-owned graph (walk tier without the cagra
                # strategy profile): account for its device arrays too
                register_resource(
                    "cagra", f"{self.resource_name}:hybrid_walk",
                    f.cagra)
        return f

    def _fused_hybrid_trio(self, query, qv, overfetch, weights):
        """One coalesced fused-hybrid ride: (lex, vec, fused) candidate
        lists for this query, or None when the host path must serve.
        Fail-open — any device-path error degrades to host, never to a
        failed search."""
        f = self._ensure_fused()
        if f is None:
            return None
        w = tuple(weights) if weights else (1.0, 1.0)
        if len(w) != 2:
            return None  # host rrf_fuse handles exotic weight shapes
        t_ride = time.time()
        try:
            trio = self._hybrid_batch.search(
                qv, overfetch,
                extra={"tokens": tuple(tokenize(query)),
                       "n_cand": overfetch, "w": w})
        except Exception:
            return None
        if trio is None:
            return None
        tier = trio.get("tier", "brute")
        _STRATEGY_C.labels("hybrid_walk_fused" if tier == "walk"
                           else "hybrid_fused").inc()
        # rider-accurate tier attribution: this ROW's served_by (a
        # live-filter correction makes one rider "host" while its
        # batch-mates keep the device tier), counted + latency-observed
        # + stamped on the trace span (ISSUE 10)
        served = trio.get("served_by", "hybrid_brute_f32")
        _audit.record_served("hybrid", served,
                             seconds=time.time() - t_ride)
        if served != "host" and _audit.sampling_active():
            self._maybe_shadow_hybrid(served, trio, query, qv,
                                      overfetch, w)
        t = trio.get("times")
        if t:
            # the whole lexical+vector scoring ran inside one device
            # dispatch; split the trace at the decode boundary so
            # /admin/traces shows the hybrid ladder per request
            attach_span("lexical.score", t["device_t0"] - t["plan_s"],
                        t["device_t1"])
            if tier == "walk":
                # the vector half was the graph walk: surface its
                # fixed-iteration/pool config on the request's trace
                attach_span("vector.walk", t["device_t0"],
                            t["device_t1"], iters=t.get("walk_iters"),
                            itopk=t.get("walk_itopk"))
            attach_span("fuse", t["device_t1"],
                        t["device_t1"] + t["decode_s"])
        return trio

    def _maybe_shadow_hybrid(self, tier, trio, query, qv, overfetch, w):
        """Offer one device-served hybrid answer to the shadow-parity
        auditor. The reference closure re-runs the HOST hybrid path —
        live BM25 scoring, exact brute vector scan, bit-compatible
        rrf_fuse — on the audit worker thread, never on the hot path.
        Best-effort: sampling must never fail a search."""
        try:
            device_ids = [i for i, _ in trio["fused"]]
            bm25, vectors = self.bm25, self.vectors
            weights = list(w)

            def ref():
                bm_hits = bm25.search(query, overfetch)
                vec_hits = vectors.search_batch(
                    qv[None, :], overfetch, exact=True)[0]
                fused = rrf_fuse([bm_hits, vec_hits], weights=weights,
                                 limit=overfetch)
                return [i for i, _ in fused]

            # the result-cache generation bumps on EVERY index mutation
            # (text or vector), so it is the one version the replay-time
            # staleness check needs: a write between sampling and the
            # host reference run drops the sample instead of scoring a
            # correct device answer as a mismatch
            def versions_now():
                return {"generation": self._result_cache.generation}

            _audit.maybe_sample(
                "hybrid", tier, device_ids, k=min(10, overfetch),
                ref=ref, versions=versions_now(),
                versions_now=versions_now,
                query={"query": query, "overfetch": overfetch,
                       "weights": weights})
        except Exception:  # noqa: BLE001
            pass

    def _clear_result_cache(self) -> None:
        self._result_cache.bump_generation()

    @property
    def generation(self) -> int:
        """Write generation of the result cache — bumped on every index
        mutation. The gRPC wire cache (api/grpc_server.py) validates its
        cached response BYTES against this, so native-search responses
        served from raw bytes stay exactly as fresh as the result cache
        itself."""
        return self._result_cache.generation

    def microbatch_stats(self) -> Dict[str, float]:
        """Coalescing effectiveness of the vector micro-batcher (how
        many concurrent b=1 queries rode one device dispatch)."""
        mb = self._microbatch
        return {
            "batches": mb.batches,
            "batched_queries": mb.batched_queries,
            "mean_batch": mb.batched_queries / max(mb.batches, 1),
        }

    # -- indexing ---------------------------------------------------------

    def index_node(self, node: Node) -> None:
        """Index one node's text + embedding
        (reference: Service.IndexNode search.go:1785)."""
        if any(lbl.startswith("_") for lbl in node.labels):
            # system-owned nodes (Qdrant collections/points, meta) stay
            # out of the native hybrid index — they have their own
            # per-collection indexes (api/qdrant.py)
            return
        text = extract_text(node)
        with self._lock:
            if text:
                self.bm25.index(node.id, text)
            else:
                self.bm25.remove(node.id)  # update cleared the text
            vec = node.embedding
            if vec is None and node.chunk_embeddings:
                # whole-doc vector = mean of chunks (best-of-chunks is used
                # at query time by inference; mean anchors doc search)
                vec = list(np.mean(np.asarray(node.chunk_embeddings), axis=0))
            if vec is not None:
                self.vectors.add(node.id, vec)
                if self.hnsw is not None:
                    self.hnsw.add(node.id, vec)
            else:
                # update removed the embedding: drop stale vectors
                self.vectors.remove(node.id)
                if self.hnsw is not None:
                    self.hnsw.remove(node.id)
            self.stats.indexed_docs = len(self.bm25)
            self.stats.indexed_vectors = len(self.vectors)
            self._maybe_switch_strategy()
        self._clear_result_cache()
        self._schedule_save()

    def remove_node(self, node_id: str) -> None:
        with self._lock:
            self.bm25.remove(node_id)
            self.vectors.remove(node_id)
            if self.hnsw is not None:
                self.hnsw.remove(node_id)
                if self.hnsw.should_rebuild():
                    self._rebuild_hnsw_locked()
            self.stats.indexed_docs = len(self.bm25)
            self.stats.indexed_vectors = len(self.vectors)
        self._clear_result_cache()
        self._schedule_save()

    def prune_missing(self) -> int:
        """Drop every indexed id whose storage node no longer exists.
        Bulk deletions that bypass per-node mutation events — a
        ``delete_by_prefix`` WAL record replayed on a read replica, a
        database drop under a shared store — leave the indexes holding
        tombstone-less ghosts; this reconciles them through the same
        ``remove_node`` path a live delete takes (changelogs, rebuild
        triggers and freshness ladders all see ordinary removals).
        Returns the number of ids pruned."""
        if self.storage is None:
            return 0
        with self._lock:
            indexed = set(self.bm25.ids()) | set(self.vectors.ids())
        pruned = 0
        for nid in indexed:
            try:
                missing = not self.storage.has_node(nid)
            except Exception:  # noqa: BLE001 — storage races resolve next sweep
                continue
            if missing:
                self.remove_node(nid)
                pruned += 1
        return pruned

    def build_indexes(self) -> int:
        """Index every node in storage (reference: BuildIndexes :2246).
        Returns count indexed. With a persist_dir, a valid on-disk
        snapshot is loaded first and only nodes created/updated since the
        snapshot are (re)indexed — the resume-aware build of
        search.go:432 resumeVectorBuild."""
        if self.storage is None:
            return 0
        resumed = self.load_indexes()
        n = 0
        for node in self.storage.all_nodes():
            if resumed and not self._needs_reindex(node):
                continue
            self.index_node(node)
            n += 1
        if resumed:
            # drop index entries whose node vanished while we were down —
            # both vector AND bm25 entries (a text-only node never enters
            # the vector index)
            live = {nd.id for nd in self.storage.all_nodes()}
            stale = set(self.vectors.ids()) | set(self.bm25.ids())
            for ext_id in stale - live:
                self.remove_node(ext_id)
        return n

    def _needs_reindex(self, node: Node) -> bool:
        if any(lbl.startswith("_") for lbl in node.labels):
            return False  # system nodes never enter this index (index_node)
        if (node.updated_at or 0) > self._saved_at_ms:
            return True
        has_vec = node.embedding is not None or node.chunk_embeddings
        if has_vec and node.id not in self.vectors:
            return True
        return node.id not in self.bm25 and bool(extract_text(node))

    # -- persistence ------------------------------------------------------

    _FORMAT_VERSION = 1

    def save_indexes(self) -> bool:
        """Write BM25 + vector (+ HNSW) snapshots atomically. Serialized:
        a timer-thread save racing a close() save over the same .tmp
        paths would publish a torn or mixed-generation snapshot."""
        if not self.persist_dir:
            return False
        with self._save_lock:
            return self._save_indexes_locked()

    def _save_indexes_locked(self) -> bool:
        import json
        import os

        os.makedirs(self.persist_dir, exist_ok=True)
        # capture under the service lock, but do the (slow) compression
        # and disk writes OUTSIDE it — the index objects snapshot under
        # their own locks, so searches/indexing keep flowing during the
        # multi-second write of a large matrix
        with self._lock:
            saved_at = int(time.time() * 1000)
            bm25_doc = self.bm25.to_dict()
            vectors = self.vectors
            hnsw = self.hnsw
        vectors.save(os.path.join(self.persist_dir, "vectors.npz.tmp"))
        if hnsw is not None:
            # HNSWIndex.save appends .npz itself
            hnsw.save(os.path.join(self.persist_dir, "hnsw.tmp"))
        with open(os.path.join(self.persist_dir, "bm25.json.tmp"), "w") as f:
            json.dump(bm25_doc, f)
        meta = {
            "format": self._FORMAT_VERSION,
            "saved_at_ms": saved_at,
            "has_hnsw": hnsw is not None,
            "strategy": self.stats.strategy,
        }
        with open(os.path.join(self.persist_dir, "meta.json.tmp"), "w") as f:
            json.dump(meta, f)
        # publish: meta last, so a torn save is simply ignored on load
        renames = [("vectors.npz.tmp", "vectors.npz"),
                   ("hnsw.tmp.npz", "hnsw.npz"),
                   ("bm25.json.tmp", "bm25.json"),
                   ("meta.json.tmp", "meta.json")]
        for tmp_name, name in renames:
            tmp = os.path.join(self.persist_dir, tmp_name)
            if os.path.exists(tmp):
                os.replace(tmp, os.path.join(self.persist_dir, name))
        self._saved_at_ms = saved_at
        return True

    def load_indexes(self) -> bool:
        """Load a persisted snapshot; False if absent/invalid/other
        format version (caller falls back to full rebuild)."""
        if not self.persist_dir:
            return False
        import json
        import os

        meta_path = os.path.join(self.persist_dir, "meta.json")
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            if meta.get("format") != self._FORMAT_VERSION:
                return False
            with open(os.path.join(self.persist_dir, "bm25.json")) as f:
                bm25 = BM25Index.from_dict(json.load(f))
            vectors = BruteForceIndex.load(
                os.path.join(self.persist_dir, "vectors.npz"))
            hnsw = None
            if meta.get("has_hnsw"):
                hnsw = HNSWIndex.load(
                    os.path.join(self.persist_dir, "hnsw.npz"))
        except (OSError, ValueError, KeyError):
            return False
        with self._lock:
            self.bm25 = bm25
            # swap contents into the registered vector space so the
            # space's index IS still the live service index
            self._doc_space.index = vectors
            self.vectors = vectors
            # re-point the resource gauges at the restored structures
            from nornicdb_tpu.obs import register_resource

            register_resource("bm25", self.resource_name, bm25)
            register_resource("brute", self.resource_name,
                              vectors)
            self.hnsw = hnsw
            # any prior graph wraps the REPLACED brute index — drop it
            # or searches would keep serving the discarded corpus
            self.cagra = None
            self._fused = None  # same: the fused pipeline re-wraps lazily
            self._saved_at_ms = int(meta.get("saved_at_ms", 0))
            self.stats.indexed_docs = len(self.bm25)
            self.stats.indexed_vectors = len(self.vectors)
            self.stats.strategy = "brute"
            if hnsw is not None:
                self.stats.strategy = "hnsw"
            elif meta.get("strategy") == "cagra":
                # the graph is derived state (not persisted): rebuild it
                # from the restored vectors now so a read-only workload
                # after restart doesn't silently serve brute-force
                self._maybe_switch_strategy()
        return True

    def _schedule_save(self) -> None:
        """Throttled persistence: at most one pending timer — a steady
        write stream persists every debounce interval instead of pushing
        the save out forever (and no Timer churn per indexed node)."""
        if not self.persist_dir or self._closed:
            return
        with self._save_lock:
            if self._save_timer is not None:
                return
            t = threading.Timer(self._save_debounce_s, self._save_quietly)
            t.daemon = True
            self._save_timer = t
            t.start()

    def _save_quietly(self) -> None:
        with self._save_lock:
            self._save_timer = None
        try:
            self.save_indexes()
        except Exception:
            pass  # a failed background save must not take down the app

    def close(self) -> None:
        """Final save; cancels any pending save timer."""
        self._closed = True
        with self._save_lock:
            if self._save_timer is not None:
                self._save_timer.cancel()
                self._save_timer = None
        if self.persist_dir:
            try:
                self.save_indexes()
            except Exception:
                pass

    # -- strategy state machine -------------------------------------------

    def _maybe_switch_strategy(self) -> None:
        if len(self.vectors) < self.hnsw_threshold:
            return
        from nornicdb_tpu.search.ann_quality import current_profile

        if current_profile().index_kind == "cagra":
            # device-graph tier: the CagraIndex manages its own rebuild
            # cadence after the first build (mutation-churn threshold)
            if self.cagra is None:
                self._rebuild_cagra_locked()
            return
        if self.hnsw is None:
            self._rebuild_hnsw_locked()

    def _rebuild_cagra_locked(self) -> None:
        """Build the device-resident graph over the live brute index.
        Config-gated (NORNICDB_VECTOR_ANN_QUALITY=cagra); the service
        threshold is the build gate, so min_n only keeps the index
        honest if the corpus later shrinks."""
        from nornicdb_tpu.search.ann_quality import (
            cagra_shards_from_env,
            current_profile,
        )
        from nornicdb_tpu.search.cagra import CagraIndex

        p = current_profile()
        # build_inline=False: the first build happens right here (the
        # explicit build() below, on the write path); any LATER
        # graph-from-scratch transition (corpus shrank below min_n and
        # regrew) must not stall a search convoy — brute serves while
        # the background build runs
        idx = CagraIndex(
            brute=self.vectors,
            degree=p.cagra_degree, itopk=p.cagra_itopk,
            search_width=p.cagra_width,
            min_n=min(p.cagra_min_n, self.hnsw_threshold),
            n_shards=cagra_shards_from_env(p.cagra_shards),
            build_inline=False,
        )
        if not idx.build():
            return
        if idx._brute is not self.vectors:
            return  # an index reload swapped the corpus mid-build
        self.cagra = idx
        # any fused wrapper built before this graph existed rebinds to
        # it on the next search (_ensure_fused's in-place rebind) —
        # one graph, one rebuild cadence, no second copy in HBM
        from nornicdb_tpu.obs import register_resource

        register_resource("cagra", self.resource_name, idx)
        # surface the graph index as its own vector space, mirroring the
        # hnsw tier (reference: backend kinds, registry.go:1-60)
        cagra_space = self.vector_registry.get_or_create(
            database=self.database, entity_type="node",
            vector_name="embedding_cagra", backend="cagra",
        )
        cagra_space.index = idx
        self.stats.cagra_builds += 1
        self.stats.strategy = "cagra"

    def _rebuild_hnsw_locked(self) -> None:
        """(Re)build HNSW from the brute index, BM25 seeds first."""
        items = []
        matrix, valid, ext_ids = self.vectors.snapshot()
        for slot, eid in enumerate(ext_ids):
            if eid is not None and valid[slot]:
                items.append((eid, matrix[slot]))
        seeds = self.bm25.seed_doc_ids()
        idx = HNSWIndex(m=self._hnsw_m, ef_search=self._hnsw_ef)
        idx.build(items, seed_ids=seeds)
        self.hnsw = idx
        # surface the graph index as its own vector space (reference:
        # backend kinds auto/brute-force/hnsw, registry.go:1-60)
        hnsw_space = self.vector_registry.get_or_create(
            database=self.database, entity_type="node",
            vector_name="embedding_hnsw", backend="hnsw",
        )
        hnsw_space.index = idx
        self.stats.hnsw_builds += 1
        self.stats.strategy = "hnsw"

    # -- search -----------------------------------------------------------

    def _query_embedding(self, query: str) -> Optional[np.ndarray]:
        if self.embedder is None:
            return None
        try:
            return np.asarray(self.embedder.embed(query), dtype=np.float32)
        except Exception:
            return None  # fail-open: hybrid degrades to text-only

    def similar(self, node_id: str, limit: int = 10) -> List[Dict[str, Any]]:
        """Nodes nearest to a stored node's embedding (reference: the REST
        /similar endpoint, server_nornicdb.go). Empty when the node has no
        vector yet."""
        try:
            node = self.storage.get_node(node_id)
        except KeyError:
            return []
        emb = node.embedding or (
            node.chunk_embeddings[0] if node.chunk_embeddings else None)
        if emb is None:
            return []
        hits = self.vector_search_candidates(emb, limit + 1)
        out: List[Dict[str, Any]] = []
        for nid, score in hits:
            if nid == node_id:
                continue
            res = SearchResult(node_id=nid, score=score, vector_score=score)
            try:
                res.node = self.storage.get_node(nid)
            except KeyError:
                continue
            out.append(res.to_dict())
            if len(out) >= limit:
                break
        return out

    def vector_search_candidates(
        self, query_vec: Sequence[float], k: int = 10, exact: bool = False,
        lexical_doc_ids: Optional[Sequence[str]] = None,
    ) -> List[Tuple[str, float]]:
        """Raw vector candidates (reference: VectorSearchCandidates
        search.go:3045). Strategy: HNSW if built (unless exact), else the
        doc space's index. Cluster-routed indexes (IVF-HNSW) additionally
        take the BM25 top hits for hybrid probe selection
        (reference: hybrid_cluster_routing.go:248-256)."""
        with self._lock:
            hnsw = self.hnsw
            cagra = self.cagra
        if not exact:
            if lexical_doc_ids \
                    and hasattr(self.vectors, "_tiered_search_batch"):
                # beyond-HBM tier (ISSUE 17): hybrid lexical+semantic
                # cluster routing — the BM25 top docs bias the probe
                # set toward partitions the lexical half already ranked.
                # Direct (un-coalesced) call: probe hints are per-query
                # and cannot ride a shared micro-batch. None = plane
                # off/cold/degraded; fall through to the ladder below.
                out = self.vectors._tiered_search_batch(
                    np.asarray([query_vec], dtype=np.float32), k,
                    lex_hints=[list(lexical_doc_ids)])
                if out is not None:
                    _STRATEGY_C.labels("tiered_route").inc()
                    tier = _audit.consume_batch_tier()
                    _audit.record_served("vector",
                                         tier or "vector_tiered")
                    return out[0]
            if cagra is not None:
                # device graph walk, micro-batched: concurrent b=1
                # queries coalesce into one pow2-bucketed walk dispatch
                _STRATEGY_C.labels("cagra").inc()
                return self._vector_ride(query_vec, k)
            if hnsw is not None:
                _STRATEGY_C.labels("hnsw").inc()
                # host-resident graph index: the host tier by taxonomy
                _audit.record_served("vector", "host")
                return hnsw.search(query_vec, k)
        if lexical_doc_ids and hasattr(self.vectors, "route"):
            _STRATEGY_C.labels("ivf_route").inc()
            _audit.record_served("vector", "host")
            return self.vectors.search(query_vec, k,
                                       lexical_doc_ids=lexical_doc_ids)
        if hasattr(self.vectors, "search_batch"):
            if exact:
                # exact requests never ride the micro-batcher: its
                # dispatch re-reads self.cagra, so a concurrent graph
                # build could answer an exact request approximately.
                # Direct brute call (rare path: eval + exact=True).
                _STRATEGY_C.labels("exact").inc()
                _audit.record_served("vector", "vector_brute_f32")
                return self.vectors.search_batch(
                    np.asarray([query_vec], dtype=np.float32), k,
                    exact=True)[0]
            # micro-batched: concurrent singles ride one device call
            _STRATEGY_C.labels("brute").inc()
            return self._vector_ride(query_vec, k)
        _STRATEGY_C.labels("backend").inc()
        _audit.record_served("vector", "host")
        return self.vectors.search(query_vec, k)  # IVF backends

    def _vector_ride(self, query_vec, k: int):
        """One coalesced vector ride. The MicroBatcher stamps the
        serving tier (leader-consumed from the dispatch path) onto this
        rider's count/span; on the way out the answer is offered to the
        shadow-parity auditor with an exact-brute reference closure."""
        hits = self._microbatch.search(query_vec, k)
        if _audit.sampling_active():
            tier = _audit.last_served()
            if tier is not None and tier != "host":
                try:
                    qv = np.asarray(query_vec, dtype=np.float32)
                    vectors = self.vectors

                    def versions_now():
                        return {"brute_mutations":
                                getattr(vectors, "mutations", 0)}

                    # (id, score) pairs: exact tiers score TIE-AWARE
                    # rank parity (a padded-batch dispatch may permute
                    # rows within an exact score tie vs the b=1 replay)
                    _audit.maybe_sample(
                        "vector", tier,
                        [(i, float(s)) for i, s in hits],
                        k=min(10, k),
                        ref=lambda: [
                            (i, float(s)) for i, s in
                            vectors.search_batch(
                                qv[None, :], k, exact=True)[0]],
                        versions=versions_now(),
                        versions_now=versions_now,
                        query={"k": k})
                except Exception:  # noqa: BLE001
                    pass
        return hits

    def search(
        self,
        query: str = "",
        limit: int = 10,
        query_embedding: Optional[Sequence[float]] = None,
        mode: str = "hybrid",
        min_score: float = 0.0,
        enrich: bool = True,
        labels: Optional[Sequence[str]] = None,
        weights: Optional[Sequence[float]] = None,
    ) -> List[Dict[str, Any]]:
        """Hybrid search (reference: Service.Search search.go:2841):
        BM25 + vector candidate lists fused with (optionally weighted)
        RRF, enriched from storage. On large corpora the whole hybrid
        candidate stage — lexical scoring, vector scoring and the RRF
        fuse — runs as ONE compiled device program per coalesced batch
        (hybrid_fused.py); the host path below is the exact fallback
        and the small-corpus fast path. Results are cached by
        query+options (reference: search.go:2853-2856 cacheKey Get/Put)
        and invalidated on any index mutation. ``weights`` is the
        per-source (lexical, vector) RRF weighting of the reference's
        weighted fusion; None means (1.0, 1.0)."""
        self.stats.searches += 1
        # opt-in per-stage timing diagnostics (reference:
        # NORNICDB_SEARCH_DIAG_TIMINGS, server_nornicdb.go:282-286);
        # recorded on stats.last_timings for /status and log inspection.
        # Stale-timing clearing runs BEFORE the cache probe so a cache
        # hit can't serve timings from a prior diag run forever.
        from nornicdb_tpu.config import env_bool

        # deliberate per-query env read: the toggle must take effect on
        # the NEXT search (pinned by test_aux_cmds diag tests), and the
        # ~1 us read is noise against the ms-scale hybrid search it
        # gates — unlike the 50 us chain path the hot-path rule guards
        diag = env_bool("TPU_SEARCH_DIAG")  # lint: env-ok
        if not diag and self.stats.last_timings:
            self.stats.last_timings = {}  # never serve stale timings
        # explicit query embeddings are unhashable request-local state;
        # those requests bypass the cache (the reference keys only on
        # query text + options too)
        cache_key = None
        if query_embedding is None and self.reranker is None:
            cache_key = (query, limit, mode, min_score, enrich,
                         tuple(labels) if labels else None,
                         tuple(weights) if weights else None)
            cached = self._result_cache.get_hits(cache_key)
            if cached is not None:
                self.stats.cache_hits += 1
                _HYBRID_CACHED_SERVED.inc()
                # pre-bound child skips record_served; the per-tenant
                # request still counts the hit (ISSUE 18)
                _tenant.record_served("hybrid", "cached")
                return cached
            gen_at_miss = self._result_cache.generation
        timings: Dict[str, float] = {}
        t0 = time.perf_counter() if diag else 0.0
        overfetch = max(limit * 3, 30)
        bm25_hits: List[Tuple[str, float]] = []
        vec_hits: List[Tuple[str, float]] = []
        qv = None
        if mode in ("hybrid", "vector"):
            qv = (
                np.asarray(query_embedding, dtype=np.float32)
                if query_embedding is not None
                else (self._query_embedding(query) if query.strip() else None)
            )
            if diag:
                timings["embed_ms"] = (time.perf_counter() - t0) * 1e3
                t0 = time.perf_counter()
        trio = None
        trio_eligible = (mode == "hybrid" and bool(query)
                         and qv is not None and len(self.vectors) > 0)
        if trio_eligible:
            # fused device path: concurrent hybrid searches coalesce
            # into one compiled BM25+vector+RRF dispatch. None = the
            # pipeline isn't (yet/any longer) eligible — host serves.
            trio = self._fused_hybrid_trio(query, qv, overfetch, weights)
            if trio is None:
                # a fused-eligible query served by the host hybrid
                # path: count the host tier so the mix stays truthful
                _audit.record_served("hybrid", "host")
        if trio is not None:
            bm25_hits, vec_hits = trio["lex"], trio["vec"]
            if diag:
                timings["fused_ms"] = (time.perf_counter() - t0) * 1e3
                t0 = time.perf_counter()
        else:
            if mode in ("hybrid", "text") and query:
                t_lex = time.time()
                bm25_hits = self.bm25.search(query, overfetch)
                attach_span("lexical.score", t_lex, time.time())
            if diag:
                timings["bm25_ms"] = (time.perf_counter() - t0) * 1e3
                t0 = time.perf_counter()
            if qv is not None and len(self.vectors) > 0:
                if trio_eligible:
                    # this query is already counted (hybrid host tier):
                    # the nested vector ride is a sub-dispatch, not a
                    # second served query — one query, one increment
                    with _audit.suppress_attribution():
                        vec_hits = self.vector_search_candidates(
                            qv, overfetch,
                            lexical_doc_ids=[d for d, _ in
                                             bm25_hits[:32]])
                else:
                    vec_hits = self.vector_search_candidates(
                        qv, overfetch,
                        lexical_doc_ids=[d for d, _ in bm25_hits[:32]],
                    )
            if diag:
                timings["vector_ms"] = (time.perf_counter() - t0) * 1e3
                t0 = time.perf_counter()

        if bm25_hits and vec_hits:
            # the fused trio already carries the device-fused ranking;
            # the host fuse is bit-compatible with it (rrf.py)
            if trio is not None:
                fused = trio["fused"]
            else:
                t_fuse = time.time()
                fused = rrf_fuse([bm25_hits, vec_hits],
                                 weights=list(weights) if weights else (),
                                 limit=overfetch)
                attach_span("fuse", t_fuse, time.time())
        elif bm25_hits:
            fused = bm25_hits[:overfetch]
        else:
            fused = vec_hits[:overfetch]
        if diag:
            timings["fuse_ms"] = (time.perf_counter() - t0) * 1e3
            t0 = time.perf_counter()

        t_rerank = time.time()
        bm = dict(bm25_hits)
        vs = dict(vec_hits)
        out: List[Dict[str, Any]] = []
        for node_id, score in fused:
            # min_score filters on the raw similarity scores (cosine and/or
            # BM25), NOT the fused RRF value — fused magnitudes depend on
            # which lists fired and are not comparable across modes. A hit
            # survives if ANY of its raw scores clears the threshold (a
            # strong text match must not be vetoed by a negative cosine).
            v_sc, b_sc = vs.get(node_id), bm.get(node_id)
            gates = [g for g in (v_sc, b_sc) if g is not None]
            if gates and max(gates) < min_score:
                continue
            res = SearchResult(
                node_id=node_id,
                score=score,
                bm25_score=b_sc,
                vector_score=v_sc,
            )
            if (enrich or labels) and self.storage is not None:
                try:
                    node = self.storage.get_node(node_id)
                except KeyError:
                    continue  # deleted since indexing; drop stale hit
                if labels and not set(labels) & set(node.labels):
                    continue
                if enrich:
                    res.node = node
            out.append(res.to_dict())
            if len(out) >= limit and self.reranker is None:
                break
        if self.reranker is not None and out:
            # stage-2 rerank over the full fused overfetch, then cut
            # (reference: rerank.go after RRF). Pass the query embedding
            # already computed — the reranker must not re-embed.
            try:
                out = self.reranker.rerank(query, out, limit=limit,
                                           query_embedding=qv)
            except Exception:
                out = out[:limit]  # fail-open (reference: llm_rerank.go)
        attach_span("rerank", t_rerank, time.time(),
                    reranker=self.reranker is not None)
        if diag:
            timings["enrich_rerank_ms"] = (time.perf_counter() - t0) * 1e3
            self.stats.last_timings = timings
        out = out[:limit]
        if cache_key is not None:
            return self._result_cache.put_guarded(cache_key, out,
                                                  gen_at_miss)
        return out
