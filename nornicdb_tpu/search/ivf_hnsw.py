"""IVF-HNSW: k-means centroid routing over per-cluster HNSW graphs.

Reference: pkg/search ivf_hnsw_candidate_gen.go + SaveIVFHNSW/
LoadIVFHNSWCluster (hnsw_index.go:636,660) — for large CPU datasets the
vector set is partitioned by k-means and each cluster gets its own HNSW
graph; queries probe the nprobe nearest clusters' graphs. Centroid
routing is a single device matmul (ops/kmeans); graph walks stay on the
host (HNSW is pointer-chasing — SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from nornicdb_tpu.search.hnsw import HNSWIndex
from nornicdb_tpu.search.util import normalize_rows as _normalize


class IVFHNSWIndex:
    def __init__(self, n_clusters: int = 16, nprobe: int = 3,
                 m: int = 16, ef_construction: int = 100,
                 ef_search: int = 64):
        self.n_clusters = n_clusters
        self.nprobe = nprobe
        self.m = m
        self.ef_construction = ef_construction
        self.ef_search = ef_search
        self.centroids: Optional[np.ndarray] = None  # [K, D] normalized
        self.clusters: Dict[int, HNSWIndex] = {}
        self._where: Dict[str, int] = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._where)

    # -- build -----------------------------------------------------------

    def build(
        self,
        items: Sequence[Tuple[str, Sequence[float]]],
        seed_ids: Optional[Sequence[str]] = None,
    ) -> None:
        """Partition by cosine k-means, build one HNSW per cluster
        (seed-first insertion within each, reference BM25-seeded
        order)."""
        from nornicdb_tpu.ops.kmeans import kmeans_fit

        if not items:
            return
        vecs = _normalize(np.asarray([v for _, v in items],
                                     dtype=np.float32))
        k = min(self.n_clusters, len(items))
        res = kmeans_fit(vecs, k=k)
        self.centroids = _normalize(
            np.asarray(res.centroids, dtype=np.float32))
        assign = np.asarray(res.assignments)
        seeds = set(seed_ids or [])
        with self._lock:
            self.clusters = {}
            self._where = {}
            for c in range(self.centroids.shape[0]):
                members = [
                    (items[i][0], vecs[i])
                    for i in np.nonzero(assign == c)[0]
                ]
                if not members:
                    continue
                idx = HNSWIndex(m=self.m,
                                ef_construction=self.ef_construction,
                                ef_search=self.ef_search)
                idx.build(members,
                          seed_ids=[e for e, _ in members if e in seeds])
                self.clusters[int(c)] = idx
                for ext_id, _ in members:
                    self._where[ext_id] = int(c)

    # -- incremental -----------------------------------------------------

    def add(self, ext_id: str, vector: Sequence[float]) -> None:
        if self.centroids is None:
            raise RuntimeError("IVFHNSWIndex.build() first")
        v = _normalize(np.asarray(vector, dtype=np.float32))
        c = int(np.argmax(self.centroids @ v))
        with self._lock:
            old = self._where.get(ext_id)
            if old is not None and old != c:
                self.clusters[old].remove(ext_id)
            idx = self.clusters.get(c)
            if idx is None:
                idx = HNSWIndex(m=self.m,
                                ef_construction=self.ef_construction,
                                ef_search=self.ef_search)
                self.clusters[c] = idx
            self._where[ext_id] = c
            # insert under the lock: a concurrent remove() between the
            # map write and the graph insert would leave a ghost entry
            idx.add(ext_id, v)

    def remove(self, ext_id: str) -> bool:
        with self._lock:
            c = self._where.pop(ext_id, None)
            if c is None:
                return False
            idx = self.clusters.get(c)
            # tombstone under the same lock as add(): an interleaved
            # add() would otherwise get its fresh insert tombstoned
            return idx.remove(ext_id) if idx is not None else False

    # -- search ----------------------------------------------------------

    def route(
        self, query: Sequence[float], nprobe: Optional[int] = None,
        lexical_doc_ids: Optional[Sequence[str]] = None,
        lexical_weight: float = 0.3,
    ) -> np.ndarray:
        """Pick the clusters to probe. With ``lexical_doc_ids`` (e.g.
        BM25 top hits) the semantic centroid similarity is blended with
        each cluster's share of the lexical hits — hybrid cluster routing
        (reference: hybrid_cluster_routing.go:248-256): a cluster full of
        keyword-matching docs gets probed even when its centroid is not
        among the cosine-nearest."""
        assert self.centroids is not None
        nprobe = min(nprobe or self.nprobe, self.centroids.shape[0])
        q = _normalize(np.asarray(query, dtype=np.float32))
        sims = self.centroids @ q  # [-1, 1]
        if lexical_doc_ids:
            lex = np.zeros(self.centroids.shape[0], np.float32)
            with self._lock:
                for ext_id in lexical_doc_ids:
                    c = self._where.get(ext_id)
                    if c is not None:
                        lex[c] += 1.0
            if lex.sum() > 0:
                lex /= lex.sum()
                # lexical share scaled to [0, 2] so a keyword-dominant
                # cluster can outrank a max-similarity centroid (1.0)
                sims = (1.0 - lexical_weight) * sims + lexical_weight * 2.0 * lex
        return np.argpartition(-sims, nprobe - 1)[:nprobe]

    def search(
        self, query: Sequence[float], k: int = 10,
        nprobe: Optional[int] = None, ef: Optional[int] = None,
        lexical_doc_ids: Optional[Sequence[str]] = None,
    ) -> List[Tuple[str, float]]:
        if self.centroids is None:
            return []
        q = _normalize(np.asarray(query, dtype=np.float32))
        probe = self.route(q, nprobe, lexical_doc_ids=lexical_doc_ids)
        hits: List[Tuple[str, float]] = []
        for c in probe:
            idx = self.clusters.get(int(c))
            if idx is not None:
                hits.extend(idx.search(q, k=k, ef=ef or self.ef_search))
        hits.sort(key=lambda t: -t[1])
        return hits[:k]

    # -- persistence (reference: SaveIVFHNSW hnsw_index.go:636) ----------

    def save(self, directory: str) -> None:
        os.makedirs(directory, exist_ok=True)
        # clear stale cluster files first — load() globs cluster-*.npz,
        # so leftovers from a previous save would resurrect old vectors
        for name in os.listdir(directory):
            if name.startswith("cluster-") and name.endswith(".npz"):
                os.unlink(os.path.join(directory, name))
        with self._lock:
            np.savez_compressed(
                os.path.join(directory, "routing"),
                centroids=self.centroids,
                nprobe=self.nprobe, m=self.m,
                ef_construction=self.ef_construction,
                ef_search=self.ef_search,
            )
            for c, idx in self.clusters.items():
                idx.save(os.path.join(directory, f"cluster-{c}.npz"))

    @classmethod
    def load(cls, directory: str) -> "IVFHNSWIndex":
        z = np.load(os.path.join(directory, "routing.npz"))
        idx = cls(nprobe=int(z["nprobe"]), m=int(z["m"]),
                  ef_construction=int(z["ef_construction"]),
                  ef_search=int(z["ef_search"]) if "ef_search" in z else 64)
        idx.centroids = z["centroids"]
        idx.n_clusters = idx.centroids.shape[0]
        for name in os.listdir(directory):
            if name.startswith("cluster-") and name.endswith(".npz"):
                c = int(name[len("cluster-"):-len(".npz")])
                sub = HNSWIndex.load(os.path.join(directory, name))
                idx.clusters[c] = sub
                for ext_id in sub.ids():
                    idx._where[ext_id] = c
        return idx
