"""Hybrid search stack: BM25 + vector (brute/HNSW) + RRF fusion.

Reference: pkg/search (search.go Service), fulltext_index_v2.go (BM25 v2),
hnsw_index.go, bm25_seed_provider.go (seeded builds), vector_index.go.
Design split: BM25 and the HNSW graph walk are pointer-chasing and stay on
CPU; all bulk distance math runs on device via nornicdb_tpu.ops.
"""

from nornicdb_tpu.search.bm25 import BM25Index, tokenize  # noqa: F401
from nornicdb_tpu.search.vector_index import BruteForceIndex  # noqa: F401
from nornicdb_tpu.search.cagra import CagraIndex  # noqa: F401
from nornicdb_tpu.search.device_bm25 import DeviceBM25  # noqa: F401
from nornicdb_tpu.search.hybrid_fused import FusedHybrid  # noqa: F401
from nornicdb_tpu.search.hnsw import HNSWIndex  # noqa: F401
from nornicdb_tpu.search.ivf_hnsw import IVFHNSWIndex  # noqa: F401
from nornicdb_tpu.search.ivfpq import IVFPQIndex  # noqa: F401
from nornicdb_tpu.search.ann_quality import (  # noqa: F401
    ANNProfile,
    PROFILES,
    current_profile,
)
from nornicdb_tpu.search.rerank import LLMReranker, LocalReranker  # noqa: F401
from nornicdb_tpu.search.rrf import rrf_fuse  # noqa: F401
from nornicdb_tpu.search.service import SearchService, SearchResult  # noqa: F401
