"""Quantized device planes: int8/PQ coarse scoring + exact rerank.

Every device-resident vector structure so far (brute matrix, CAGRA base
vectors, fused-hybrid vector half) holds float32 rows, which makes HBM
the binding constraint on corpus size — PR 5's per-index device-bytes
gauges made the ceiling visible, PR 7's cost accounting priced it. This
module is the quantization ladder that moves it:

- **int8 plane** (4x): per-row-scale symmetric quantization. Coarse
  scoring is an int8 x int8 matmul with int32 accumulation (the MXU's
  native narrow-dtype path; on CPU XLA lowers it to a widened dot) —
  scores are de-scaled by ``q_scale * row_scale`` and exact only up to
  quantization noise, which the rerank stage removes.
- **PQ plane** (typically 16-64x): uint8 codes + per-subspace codebooks
  trained **density-aware** in the AQR-HNSW style (arXiv:2602.21600):
  the existing jitted device k-means (``ops.kmeans.kmeans_fit``)
  clusters the corpus coarsely and the training sample draws a
  sqrt-size quota from every cluster, so dense regions cannot drown
  sparse ones out of the codebooks; the per-subspace Lloyd then runs
  through the SAME seeded-Euclidean implementation as host IVF-PQ
  (``ops.kmeans.train_subspace_codebooks`` — codebooks bit-identical
  given the same sample). Scoring is ADC: one small ``[B, K]`` matmul
  per subspace builds the lookup tables, a ``lax.scan`` gather+sum
  accumulates ``[B, C]`` scores without ever materializing a
  ``[B, M, C]`` intermediate.
- **Coarse-then-exact serving**: the compressed plane ranks an
  overfetched candidate pool on device; the top candidates' float32
  rows are gathered from the host source-of-truth matrix (HBM never
  holds them) and exactly re-scored — for int8 with a pool that covers
  the corpus tail this makes the final top-k *rank-identical* to the
  float32 path; for PQ it is what buys the recall floor back.
- **PCA prefilter for the walk** (pHNSW, arXiv:2602.19242): graph base
  vectors are rotated into their PCA basis before int8 encoding, so a
  partial dot over the first P projected dims is an energy-ranked
  estimate of the full dot. ``_walk_body_quant`` scores every frontier
  expansion on a separate ``codes_head [C, P]`` gather first and only
  the best ``keep`` survivors pay the full-row int8 dot — fewer bytes
  AND fewer flops per iteration.

Freshness follows the established discipline (PR 2/4/6): the plane is
a **mutation-generation snapshot** of its ``BruteForceIndex``; the
changelog delta side-scan stays exact-float32 (adds/updates since the
build are host-scored and merged), deletes are live-filtered at the
rerank gather, and any gap — compaction remap, changelog overrun,
rerank race, under-fill — degrades quantized -> float32 -> host, never
to a wrong answer. Selected via ``NORNICDB_VECTOR_QUANT={off,int8,pq}``.
"""

from __future__ import annotations

import functools
import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nornicdb_tpu.obs import REGISTRY, declare_kind, record_dispatch
from nornicdb_tpu.obs import audit as _audit
from nornicdb_tpu.obs import cost as _cost
from nornicdb_tpu.ops.kmeans import kmeans_fit, train_subspace_codebooks
from nornicdb_tpu.ops.similarity import NEG_INF, concat_topk, l2_normalize
from nornicdb_tpu.search.microbatch import pow2_bucket

# quantized-plane lifecycle + per-search freshness decisions — the same
# observability contract as the cagra/device-bm25 tiers
_QUANT_C = REGISTRY.counter(
    "nornicdb_quant_events_total",
    "Quantized device plane lifecycle and freshness decisions",
    labels=("event",))

declare_kind("int8_coarse")
declare_kind("pq_adc")
declare_kind("quant_rerank")

MODES = ("off", "int8", "pq")

# globally unique plane build sequence (GIL-atomic), mirroring
# cagra._BUILD_SEQ: consumers cache derived state keyed on it
_BUILD_SEQ = itertools.count(1)


def quant_mode() -> str:
    """NORNICDB_VECTOR_QUANT={off,int8,pq}; unknown values read as off
    (fail-open to the exact float32 tier, never to a crash)."""
    from nornicdb_tpu.config import env_str

    mode = env_str("VECTOR_QUANT", "off").strip().lower()
    return mode if mode in MODES else "off"


def quant_min_n() -> int:
    """Corpus floor below which the quantized plane never engages —
    at small N the float32 matmul is already cheap and rank-exact."""
    from nornicdb_tpu.config import env_int

    return max(1, env_int("QUANT_MIN_N", 8192))


# ---------------------------------------------------------------------------
# int8 plane: per-row-scale symmetric quantization + int8 matmul top-k
# ---------------------------------------------------------------------------


@jax.jit
def _int8_encode_impl(rows: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """rows [N, D] f32 -> (codes int8 [N, D], scale f32 [N]).
    Symmetric per-row scale = max|x| / 127; zero rows get scale eps so
    dequantization stays finite."""
    amax = jnp.max(jnp.abs(rows), axis=1)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    codes = jnp.clip(jnp.round(rows / scale[:, None]), -127, 127)
    return codes.astype(jnp.int8), scale


def int8_encode(rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    codes, scale = _int8_encode_impl(jnp.asarray(rows, jnp.float32))
    return np.asarray(codes), np.asarray(scale)


def _int8_scores(qn, codes_t, scale):
    """De-scaled coarse scores [B, C] over int8 column-major codes.

    HBM holds ONE byte per matrix element (``codes_t [D, C]`` int8 +
    the per-row f32 scales); the arithmetic runs float32 — each scan
    chunk is cast on the fly, so the converted block lives only in
    cache/VMEM, never in HBM. On the MXU the convert fuses into the
    matmul's operand load; on CPU the chunked scan keeps the cast block
    cache-resident (measured 3.4x over the widened int8 dot_general at
    131k x 64). Queries stay float32 — with f32 accumulation there is
    nothing to win by quantizing the query side, and its noise would
    cost pool recall."""
    d, c = codes_t.shape
    nchunk = next((n for n in (4, 2) if c % n == 0), 1)
    if nchunk == 1:
        acc = qn @ codes_t.astype(jnp.float32)
    else:
        ct = codes_t.reshape(d, nchunk, c // nchunk).transpose(1, 0, 2)

        def step(_, ct_m):
            return None, qn @ ct_m.astype(jnp.float32)

        _, parts = jax.lax.scan(step, None, ct)  # [nchunk, B, c/n]
        acc = parts.transpose(1, 0, 2).reshape(qn.shape[0], c)
    return acc * scale[None, :]


@functools.partial(jax.jit, static_argnames=("k",))
def _int8_topk_impl(qn, codes_t, scale, valid, k):
    scores = _int8_scores(qn, codes_t, scale)
    scores = jnp.where(valid[None, :], scores, NEG_INF)
    return jax.lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnames=("k",))
def _int8_local_topk(qn, codes_t, scale, valid, row_offset, k):
    """One shard's local int8 top-k with globalized row ids — the
    building block of the single-device reference merge."""
    scores = _int8_scores(qn, codes_t, scale)
    scores = jnp.where(valid[None, :], scores, NEG_INF)
    s, i = jax.lax.top_k(scores, k)
    return s, i + row_offset


@functools.partial(jax.jit, static_argnames=("k", "mesh_holder"))
def _int8_sharded_impl(qn, codes_t, scale, valid, k, mesh_holder):
    """Mesh int8 coarse top-k: code COLUMNS (= corpus rows) sharded
    over ``data``, one all-gather + top-k merge — the same collective
    pattern (and the same bit-identity contract vs
    :func:`int8_topk_shard_reference`) as cagra / device-BM25 / the
    fused pipeline."""
    from jax.sharding import PartitionSpec as P

    from nornicdb_tpu.parallel.mesh import compat_shard_map

    mesh = mesh_holder.mesh
    n_shards = mesh.shape["data"]
    c_local = codes_t.shape[1] // n_shards
    k_local = min(k, c_local)

    def local_fn(qn_r, codes_s, scale_s, valid_s):
        scores = _int8_scores(qn_r, codes_s, scale_s)
        scores = jnp.where(valid_s[None, :], scores, NEG_INF)
        s, i = jax.lax.top_k(scores, k_local)
        gi = i + jax.lax.axis_index("data") * c_local
        all_s = jax.lax.all_gather(s, "data", axis=1, tiled=True)
        all_i = jax.lax.all_gather(gi, "data", axis=1, tiled=True)
        top_s, pos = jax.lax.top_k(all_s, k)
        return top_s, jnp.take_along_axis(all_i, pos, axis=1)

    return compat_shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(), P(None, "data"), P("data"), P("data")),
        out_specs=(P(), P()),
    )(qn, codes_t, scale, valid)


def int8_topk_shard_reference(qn, codes_t, scale, valid, k, n_shards):
    """Single-device reference for the sharded int8 score+merge: score
    each shard's local rows, concatenate shard winners in shard order
    (exactly the all-gather layout) and take one global top-k via the
    shared :func:`ops.similarity.concat_topk`. The mesh path must be
    bit-identical to this."""
    c = codes_t.shape[1]
    c_local = c // n_shards
    k_local = min(k, c_local)
    parts_s, parts_i = [], []
    for sh in range(n_shards):
        lo = sh * c_local
        s, i = _int8_local_topk(
            qn, codes_t[:, lo:lo + c_local],
            scale[lo:lo + c_local], valid[lo:lo + c_local],
            jnp.int32(lo), k=k_local)
        parts_s.append(s)
        parts_i.append(i)
    return concat_topk(parts_s, parts_i, k)


# ---------------------------------------------------------------------------
# PQ plane: density-aware codebooks + ADC-matmul scoring
# ---------------------------------------------------------------------------


def train_pq(matrix: np.ndarray, m: int, n_codes: int = 256,
             sample_n: int = 16384, seed: int = 0) -> np.ndarray:
    """Density-aware PQ codebooks [M, n_codes, D/M] (AQR-HNSW style).

    The jitted device k-means clusters the corpus coarsely; the
    training sample then draws a sqrt(cluster-size) quota per cluster
    — dense regions contribute proportionally fewer rows, so sparse
    clusters keep codebook representation and their quantization error
    (where re-ranking has the least slack) stays bounded. The
    per-subspace Lloyd runs through the shared seeded-Euclidean
    implementation (``ops.kmeans``), the same code path host IVF-PQ
    trains through."""
    matrix = np.asarray(matrix, dtype=np.float32)
    n = len(matrix)
    if n > sample_n:
        k = min(64, max(8, n // 2048))
        res = kmeans_fit(matrix, k=k, seed=seed)
        assign = res.assignments
        rng = np.random.default_rng(seed)
        counts = np.bincount(assign[assign >= 0], minlength=k)
        quota = np.sqrt(np.maximum(counts, 0))
        quota = (quota / max(quota.sum(), 1e-12) * sample_n).astype(int)
        picks: List[np.ndarray] = []
        for c in range(k):
            members = np.nonzero(assign == c)[0]
            if members.size == 0 or quota[c] == 0:
                continue
            take = min(members.size, max(int(quota[c]), 1))
            picks.append(rng.choice(members, size=take, replace=False))
        sample = matrix[np.concatenate(picks)] if picks else matrix
    else:
        sample = matrix
    return train_subspace_codebooks(sample, m, n_codes)


@jax.jit
def _pq_encode_chunk(rows: jnp.ndarray, codebooks: jnp.ndarray):
    """rows [n, D] -> codes uint8 [n, M] (nearest codebook entry per
    subspace, squared-L2)."""
    n, d = rows.shape
    m, k, ds = codebooks.shape
    sub = rows.reshape(n, m, ds).transpose(1, 0, 2)  # [M, n, ds]
    d2 = (jnp.sum(sub * sub, axis=2)[:, :, None]
          - 2.0 * jnp.einsum("mns,mks->mnk", sub, codebooks)
          + jnp.sum(codebooks * codebooks, axis=2)[:, None, :])
    return jnp.argmin(d2, axis=2).astype(jnp.uint8).T  # [n, M]


def encode_pq(rows: np.ndarray, codebooks: np.ndarray,
              chunk: int = 4096) -> np.ndarray:
    """Chunked device PQ encoding (the [M, n, K] distance intermediate
    bounds at chunk size; the padded last chunk reuses one compile)."""
    rows = np.asarray(rows, dtype=np.float32)
    cb = jnp.asarray(codebooks)
    n = len(rows)
    m = codebooks.shape[0]
    out = np.empty((n, m), dtype=np.uint8)
    for start in range(0, n, chunk):
        stop = min(start + chunk, n)
        block = rows[start:stop]
        if stop - start < chunk and n > chunk:
            block = np.concatenate(
                [block, np.zeros((chunk - (stop - start), rows.shape[1]),
                                 np.float32)])
        codes = np.asarray(_pq_encode_chunk(jnp.asarray(block), cb))
        out[start:stop] = codes[: stop - start]
    return out


def _pq_adc_scores(qn, codes_t, codebooks):
    """ADC scores [B, C]: per subspace, one [B, K] table matmul then a
    gather+sum over the code column — accumulated by lax.scan so the
    peak intermediate is [B, C], never [B, M, C]."""
    b = qn.shape[0]
    m, c = codes_t.shape
    ds = codebooks.shape[2]
    qsub = qn.reshape(b, m, ds).transpose(1, 0, 2)  # [M, B, ds]

    def step(acc, xs):
        q_m, cb_m, code_m = xs
        table = q_m @ cb_m.T  # [B, K] — the ADC matmul
        return acc + table[:, code_m.astype(jnp.int32)], None

    acc, _ = jax.lax.scan(
        step, jnp.zeros((b, c), jnp.float32), (qsub, codebooks, codes_t))
    return acc


@functools.partial(jax.jit, static_argnames=("k",))
def _pq_topk_impl(qn, codes_t, codebooks, valid, k):
    scores = _pq_adc_scores(qn, codes_t, codebooks)
    scores = jnp.where(valid[None, :], scores, NEG_INF)
    return jax.lax.top_k(scores, k)


# ---------------------------------------------------------------------------
# PCA rotation + the quantized walk body (pHNSW-style prefilter)
# ---------------------------------------------------------------------------


def fit_rotation(rows: np.ndarray, sample_n: int = 8192,
                 seed: int = 0) -> np.ndarray:
    """Orthogonal energy-compacting rotation [D, D]: the PCA basis of a
    sample covariance, eigenvalue-descending. Because the rotation is
    orthogonal the full projected dot equals the original dot; the
    LEADING dims carry most of the energy, which is what makes the
    walk's first-P-dims prefilter an honest estimate (pHNSW)."""
    rows = np.asarray(rows, dtype=np.float32)
    if len(rows) > sample_n:
        rng = np.random.default_rng(seed)
        rows = rows[rng.choice(len(rows), sample_n, replace=False)]
    cov = rows.T @ rows / max(len(rows), 1)
    _, vecs = np.linalg.eigh(cov)  # ascending eigenvalues
    return np.ascontiguousarray(vecs[:, ::-1], dtype=np.float32)


def _walk_body_quant(
    queries_p: jnp.ndarray,  # [B, D] PCA-projected, L2-normalized
    codes: jnp.ndarray,  # [C, D] int8 projected rows
    codes_head: jnp.ndarray,  # [C, P] leading projected dims (int8)
    scale: jnp.ndarray,  # [C] f32 per-row dequant scale
    adj: jnp.ndarray,  # [C, deg] int32
    validf: jnp.ndarray,  # [C] f32 {0,1}
    k: int,
    iters: int,
    width: int,
    itopk: int,
    hash_bits: int,
    n_seeds: int,
    keep: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The CAGRA greedy walk over an int8 base with a two-stage
    frontier scorer: every expansion candidate is first scored on the
    leading ``P`` projected dims (a ``codes_head`` gather — P bytes per
    row instead of D), and only the best ``keep`` survivors pay the
    full-row int8 dot. Returned scores are approximate (callers rerank
    the pool exactly); structure mirrors ``cagra._walk_body``."""
    from nornicdb_tpu.search.cagra import _HASH_MULT

    b = queries_p.shape[0]
    c, deg = adj.shape
    p = itopk
    m = width * deg
    keep = min(keep, m)
    p_dims = codes_head.shape[1]
    tbl = 1 << hash_bits

    def hbucket(ids):
        h = ids.astype(jnp.uint32) * _HASH_MULT
        return (h >> np.uint32(32 - hash_bits)).astype(jnp.int32)

    # seed round: full int8 dot over the strided seed rows (one small
    # gathered matmul — same coverage contract as the float32 walk)
    s0 = max(n_seeds, p)
    stride = max(1, c // s0)
    seed_ids = (jnp.arange(s0, dtype=jnp.int32) * stride) % c
    seed_unique = jnp.arange(s0) < c
    seed_rows = codes[seed_ids].astype(jnp.float32)  # [S0, D]
    seed_s = (queries_p @ seed_rows.T) * scale[seed_ids][None, :]
    seed_ok = seed_unique[None, :] & (validf[seed_ids][None, :] > 0.0)
    seed_s = jnp.where(seed_ok, seed_s, NEG_INF)
    pool_s, pos0 = jax.lax.top_k(seed_s, p)
    pool_i = jnp.take_along_axis(
        jnp.broadcast_to(seed_ids[None, :], (b, s0)), pos0, axis=1)
    explored = jnp.zeros((b, p), dtype=bool)

    visited0 = jnp.zeros((tbl,), dtype=bool).at[hbucket(seed_ids)].set(True)
    visited = jnp.broadcast_to(visited0[None, :], (b, tbl))

    rows_b = jnp.arange(b, dtype=jnp.int32)[:, None]
    slot = jnp.arange(p, dtype=jnp.int32)
    mcol = jnp.arange(m, dtype=jnp.int32)
    earlier = (mcol[None, :] < mcol[:, None])[None, :, :]
    q_head = queries_p[:, :p_dims]

    def body(_, carry):
        pool_s, pool_i, explored, visited = carry
        f_s, f_pos = jax.lax.top_k(
            jnp.where(explored, NEG_INF, pool_s), width)
        f_ids = jnp.take_along_axis(pool_i, f_pos, axis=1)
        explored = explored | jnp.any(
            slot[None, None, :] == f_pos[:, :, None], axis=1)
        f_ok = f_s > 0.5 * NEG_INF

        nbrs = adj[f_ids].reshape(b, m)
        nb_ok = jnp.repeat(f_ok, deg, axis=1)
        h = hbucket(nbrs)
        seen = jnp.take_along_axis(visited, h, axis=1)
        dup = jnp.any((nbrs[:, :, None] == nbrs[:, None, :]) & earlier,
                      axis=2)
        fresh = nb_ok & ~seen & ~dup & (validf[nbrs] > 0.0)
        # every FRESH candidate counts as visited (same one-look
        # discipline as the float32 walk): a prefilter reject is a
        # prune, not a deferral — that is the pHNSW semantic
        visited = visited.at[rows_b, h].max(fresh)

        # stage 1: partial dot on the leading P projected dims — the
        # cheap gather that rejects most candidates
        head = codes_head[nbrs].astype(jnp.float32)  # [B, m, P]
        part = jnp.einsum("bmp,bp->bm", head, q_head) * scale[nbrs]
        part = jnp.where(fresh, part, NEG_INF)
        keep_s, keep_pos = jax.lax.top_k(part, keep)
        keep_ids = jnp.take_along_axis(nbrs, keep_pos, axis=1)
        keep_ok = jnp.take_along_axis(fresh, keep_pos, axis=1) \
            & (keep_s > 0.5 * NEG_INF)

        # stage 2: full int8 dot, survivors only
        full = codes[keep_ids].astype(jnp.float32)  # [B, keep, D]
        scores = jnp.einsum("bkd,bd->bk", full, queries_p) \
            * scale[keep_ids]
        scores = jnp.where(keep_ok, scores, NEG_INF)

        all_s = jnp.concatenate([pool_s, scores], axis=1)
        all_i = jnp.concatenate([pool_i, keep_ids], axis=1)
        all_e = jnp.concatenate(
            [explored, jnp.zeros((b, keep), dtype=bool)], axis=1)
        pool_s, pos = jax.lax.top_k(all_s, p)
        pool_i = jnp.take_along_axis(all_i, pos, axis=1)
        explored = jnp.take_along_axis(all_e, pos, axis=1)
        return pool_s, pool_i, explored, visited

    pool_s, pool_i, _, _ = jax.lax.fori_loop(
        0, iters, body, (pool_s, pool_i, explored, visited))
    top_s, pos = jax.lax.top_k(pool_s, k)
    top_i = jnp.take_along_axis(pool_i, pos, axis=1)
    return top_s, top_i


_quant_walk = functools.partial(
    jax.jit,
    static_argnames=("k", "iters", "width", "itopk", "hash_bits",
                     "n_seeds", "keep"),
)(_walk_body_quant)


def _walk_body_pq(
    qn: jnp.ndarray,  # [B, D] L2-normalized queries (original basis)
    codes: jnp.ndarray,  # [C, M] uint8 PQ codes of the base rows
    codebooks: jnp.ndarray,  # [M, K, D/M] f32
    adj: jnp.ndarray,  # [C, deg] int32
    validf: jnp.ndarray,  # [C] f32 {0,1}
    k: int,
    iters: int,
    width: int,
    itopk: int,
    hash_bits: int,
    n_seeds: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The CAGRA greedy walk over a PQ base: codes-only frontier
    scoring via per-query ADC tables (ISSUE 17 satellite — the deepest
    compression rung of the graph ladder). The tables are one
    [B, M, K] einsum per dispatch; after that every candidate costs M
    uint8 gathers + M table adds instead of a D-dim float dot, and HBM
    holds M bytes per row. Returned scores are ADC approximations —
    callers exactly rerank the itopk pool against the host float32
    rows, same contract as the int8 walk."""
    from nornicdb_tpu.search.cagra import _HASH_MULT

    b = qn.shape[0]
    c, deg = adj.shape
    m_sub, n_codes, ds = codebooks.shape
    p = itopk
    m = width * deg
    tbl = 1 << hash_bits

    # per-query ADC tables, flattened so a candidate's score is one
    # gather of M entries: entry index = subspace * K + code
    qsub = qn.reshape(b, m_sub, ds)
    tflat = jnp.einsum("bms,mks->bmk", qsub,
                       codebooks).reshape(b, m_sub * n_codes)
    offs = jnp.arange(m_sub, dtype=jnp.int32) * n_codes

    def adc_shared(ids):  # [X] ids shared across the batch -> [B, X]
        idx = codes[ids].astype(jnp.int32) + offs[None, :]
        return tflat[:, idx].sum(axis=-1)

    def adc_rows(ids):  # [B, X] per-query ids -> [B, X]
        idx = codes[ids].astype(jnp.int32) + offs[None, None, :]
        return jax.vmap(lambda t, i: t[i])(tflat, idx).sum(axis=-1)

    def hbucket(ids):
        h = ids.astype(jnp.uint32) * _HASH_MULT
        return (h >> np.uint32(32 - hash_bits)).astype(jnp.int32)

    # seed round: ADC over the strided seed rows — same coverage
    # contract as the float32/int8 walks
    s0 = max(n_seeds, p)
    stride = max(1, c // s0)
    seed_ids = (jnp.arange(s0, dtype=jnp.int32) * stride) % c
    seed_unique = jnp.arange(s0) < c
    seed_s = adc_shared(seed_ids)
    seed_ok = seed_unique[None, :] & (validf[seed_ids][None, :] > 0.0)
    seed_s = jnp.where(seed_ok, seed_s, NEG_INF)
    pool_s, pos0 = jax.lax.top_k(seed_s, p)
    pool_i = jnp.take_along_axis(
        jnp.broadcast_to(seed_ids[None, :], (b, s0)), pos0, axis=1)
    explored = jnp.zeros((b, p), dtype=bool)

    visited0 = jnp.zeros((tbl,), dtype=bool).at[hbucket(seed_ids)].set(True)
    visited = jnp.broadcast_to(visited0[None, :], (b, tbl))

    rows_b = jnp.arange(b, dtype=jnp.int32)[:, None]
    slot = jnp.arange(p, dtype=jnp.int32)
    mcol = jnp.arange(m, dtype=jnp.int32)
    earlier = (mcol[None, :] < mcol[:, None])[None, :, :]

    def body(_, carry):
        pool_s, pool_i, explored, visited = carry
        f_s, f_pos = jax.lax.top_k(
            jnp.where(explored, NEG_INF, pool_s), width)
        f_ids = jnp.take_along_axis(pool_i, f_pos, axis=1)
        explored = explored | jnp.any(
            slot[None, None, :] == f_pos[:, :, None], axis=1)
        f_ok = f_s > 0.5 * NEG_INF

        nbrs = adj[f_ids].reshape(b, m)
        nb_ok = jnp.repeat(f_ok, deg, axis=1)
        h = hbucket(nbrs)
        seen = jnp.take_along_axis(visited, h, axis=1)
        dup = jnp.any((nbrs[:, :, None] == nbrs[:, None, :]) & earlier,
                      axis=2)
        fresh = nb_ok & ~seen & ~dup & (validf[nbrs] > 0.0)
        visited = visited.at[rows_b, h].max(fresh)

        # single-stage ADC: M lookups per candidate is already cheaper
        # than the int8 walk's head prefilter, so no keep stage
        scores = jnp.where(fresh, adc_rows(nbrs), NEG_INF)

        all_s = jnp.concatenate([pool_s, scores], axis=1)
        all_i = jnp.concatenate([pool_i, nbrs], axis=1)
        all_e = jnp.concatenate(
            [explored, jnp.zeros((b, m), dtype=bool)], axis=1)
        pool_s, pos = jax.lax.top_k(all_s, p)
        pool_i = jnp.take_along_axis(all_i, pos, axis=1)
        explored = jnp.take_along_axis(all_e, pos, axis=1)
        return pool_s, pool_i, explored, visited

    pool_s, pool_i, _, _ = jax.lax.fori_loop(
        0, iters, body, (pool_s, pool_i, explored, visited))
    top_s, pos = jax.lax.top_k(pool_s, k)
    top_i = jnp.take_along_axis(pool_i, pos, axis=1)
    return top_s, top_i


_pq_walk = functools.partial(
    jax.jit,
    static_argnames=("k", "iters", "width", "itopk", "hash_bits",
                     "n_seeds"),
)(_walk_body_pq)


def quantize_graph_base(rows: np.ndarray,
                        mode: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Compressed representation of a graph's base vectors — the
    device arrays the quantized walk reads. ``mode`` defaults to the
    configured :func:`quant_mode`.

    - ``int8``: PCA-projected int8 codes + head prefilter column +
      per-row scale; the host rotation projects queries per batch.
    - ``pq``: PQ codes + codebooks only — the deepest rung (M bytes
      per row). Returns None on any gap (subspace split impossible,
      too few rows to train honest codebooks, training failure) and
      the caller serves the existing float32 graph instead — a
      degrade, never a wrong answer.
    """
    d = rows.shape[1]
    if mode is None:
        mode = quant_mode()
    if mode == "pq":
        # denser split than the tiered plane (2 dims/subspace vs 4):
        # ADC scores STEER the graph walk here, so reconstruction noise
        # compounds across iterations instead of just ranking a pool
        m = max(4, min(64, d // 2))
        while m > 1 and d % m != 0:
            m -= 1
        # train on the non-zero rows: graph layouts pad dead slots
        # with zero vectors that would otherwise soak up codebook mass
        norms = np.abs(rows).sum(axis=1)
        live = rows[norms > 0.0]
        if m < 2 or len(live) < 1024:
            return None
        try:
            codebooks = train_pq(live, m, 256)
            codes = encode_pq(rows, codebooks)
        except Exception:  # noqa: BLE001 — degrade, never fail a build
            return None
        return {
            "mode": "pq",
            "pq_m": m,
            "pq_codes": 256,
            "codes": jnp.asarray(codes),
            "codebooks": jnp.asarray(codebooks),
        }
    rot = fit_rotation(rows)
    proj = rows @ rot
    codes, scale = int8_encode(proj)
    head_dims = min(d, max(8, d // 4))
    return {
        "mode": "int8",
        "rot": rot,  # host [D, D] — queries project on host per batch
        "codes": jnp.asarray(codes),
        "codes_head": jnp.asarray(
            np.ascontiguousarray(codes[:, :head_dims])),
        "scale": jnp.asarray(scale),
        "head_dims": head_dims,
    }


# ---------------------------------------------------------------------------
# the serving plane over a BruteForceIndex
# ---------------------------------------------------------------------------


class QuantizedBrutePlane:
    """Compressed device snapshot of a ``BruteForceIndex`` matrix with
    coarse-then-exact serving.

    The brute index stays the mutable float32 source of truth (host
    RAM); HBM holds only the compressed representation. The plane is a
    mutation-generation snapshot: adds/updates since the build ride the
    brute changelog into an exact-float32 side-scan, deletes are
    live-filtered at the rerank gather, and every freshness gap —
    compaction remap, changelog overrun, mid-rerank race, under-fill —
    returns None so the caller degrades to the float32 tier (never to a
    wrong answer). Rebuilds run in the background off the search path.
    """

    def __init__(
        self,
        brute,
        mode: Optional[str] = None,
        n_shards: int = 1,
        rebuild_stale_frac: float = 0.1,
        build_inline: bool = False,
        pq_m: Optional[int] = None,
        pq_codes: int = 256,
        overfetch: int = 8,
        min_pool: int = 128,
    ):
        self.brute = brute
        self._mode = mode
        self.n_shards = max(1, n_shards)
        self.rebuild_stale_frac = rebuild_stale_frac
        self.build_inline = build_inline
        self.pq_m = pq_m
        self.pq_codes = pq_codes
        # rerank pool: max(overfetch * k, min_pool) compressed winners
        # re-scored exactly — ADC/int8 ordering is noisiest exactly
        # where rerank matters, so k * overfetch alone under-collects
        # (same floor logic as IVFPQIndex.min_refine_pool)
        self.overfetch = max(1, overfetch)
        self.min_pool = max(1, min_pool)
        self._snap: Optional[Dict[str, Any]] = None
        self._build_lock = threading.Lock()
        self._rebuilding = False
        self._rebuild_started = 0.0
        self._rebuild_flag_lock = threading.Lock()
        self.builds = 0

    @property
    def mode(self) -> str:
        return self._mode or quant_mode()

    def pool_for(self, k: int, snap: Dict[str, Any]) -> int:
        """Rerank pool width for a request depth ``k``:
        max(overfetch * k, min_pool), pow2-bucketed, clamped to
        capacity. PQ adds a capacity-scaled floor (capacity / n_codes —
        measured at N=100k x 64d, 256 codes: recall@10 0.81 at pool
        128, 1.00 at 512): ADC rank noise grows with corpus size AND
        with codebook coarseness, so the floor widens when the plane
        was built with fewer codes — a fixed pool that clears the 0.95
        recall floor at 100k x 256 codes would silently sink below it
        at 1M or at 64 codes."""
        floor = max(k * self.overfetch, self.min_pool)
        if snap["mode"] == "pq":
            floor = max(floor,
                        snap["capacity"] // min(snap["pq_codes"], 256))
        return min(pow2_bucket(floor), snap["capacity"])

    # -- build ------------------------------------------------------------

    def _pq_m_for(self, d: int) -> int:
        """Subspace count: requested, else d/4 clamped to [4, 64] and
        rounded down to a divisor of d."""
        m = self.pq_m or max(4, min(64, d // 4))
        while m > 1 and d % m != 0:
            m -= 1
        return max(1, m)

    def build(self) -> bool:
        with self._build_lock:
            return self._build_locked()

    def _build_locked(self) -> bool:
        mode = self.mode
        if mode == "off":
            self._snap = None
            return False
        brute = self.brute
        mutations = getattr(brute, "mutations", 0)
        snap = self._snap
        if snap is not None and snap["built_mutations"] == mutations \
                and snap["mode"] == mode:
            return True  # raced another builder; already fresh
        matrix, valid, ext_ids = brute.snapshot()
        n_alive = int(valid.sum())
        if n_alive < 1:
            self._snap = None
            return False
        cap, d = matrix.shape
        s_n = self.n_shards if cap % self.n_shards == 0 else 1
        snap = {
            "mode": mode,
            "capacity": cap,
            "dims": d,
            "rows": n_alive,
            "shards": s_n,
            "built_mutations": mutations,
            "built_compactions": getattr(brute, "compactions", 0),
            "build_seq": next(_BUILD_SEQ),
        }
        valid_j = jnp.asarray(valid)
        if mode == "int8":
            codes, scale = int8_encode(matrix)
            # column-major on device: the coarse matmul streams code
            # COLUMNS (corpus rows) and casts chunk-by-chunk in cache
            snap["codes_t"] = jnp.asarray(np.ascontiguousarray(codes.T))
            snap["scale"] = jnp.asarray(scale)
            snap["device_bytes"] = cap * d + cap * 4 + cap
        else:  # pq
            m = self._pq_m_for(d)
            live_rows = matrix[valid] if n_alive < cap else matrix
            codebooks = train_pq(live_rows, m, self.pq_codes)
            codes = encode_pq(matrix, codebooks)
            snap["pq_m"] = m
            snap["pq_codes"] = self.pq_codes
            snap["codebooks"] = jnp.asarray(codebooks)
            # codes transposed once at build: the ADC scan gathers one
            # [C] code column per subspace step
            snap["codes_t"] = jnp.asarray(
                np.ascontiguousarray(codes.T))
            snap["device_bytes"] = (
                m * cap + codebooks.nbytes + cap)
        if s_n > 1 and len(jax.devices()) >= s_n and mode == "int8":
            # place the plane on the mesh ONCE (cagra discipline);
            # codes_t shards along its COLUMN axis = corpus rows
            from jax.sharding import NamedSharding, PartitionSpec

            from nornicdb_tpu.parallel.mesh import data_mesh

            mesh = data_mesh(s_n)
            snap["mesh"] = mesh
            cols_sh = NamedSharding(mesh, PartitionSpec(None, "data"))
            vec_sh = NamedSharding(mesh, PartitionSpec("data"))
            snap["codes_t"] = jax.device_put(snap["codes_t"], cols_sh)
            snap["scale"] = jax.device_put(snap["scale"], vec_sh)
            valid_j = jax.device_put(valid_j, vec_sh)
        snap["valid"] = valid_j
        self._snap = snap
        self.builds += 1
        _QUANT_C.labels("build").inc()
        return True

    def _kick_background_rebuild(self) -> None:
        with self._rebuild_flag_lock:
            if self._rebuilding:
                return
            self._rebuilding = True
            self._rebuild_started = time.time()
        _QUANT_C.labels("background_rebuild").inc()

        def run():
            from nornicdb_tpu import admission as _adm

            try:
                # background maintenance lane (ISSUE 15): any coalescer
                # ride from this thread seals behind interactive work
                with _adm.lane_scope(_adm.LANE_BACKGROUND):
                    self.build()
            finally:
                # same lock as the set above: an unguarded clear can
                # interleave with a concurrent kick's read-then-set
                with self._rebuild_flag_lock:
                    self._rebuilding = False
                    self._rebuild_started = 0.0

        t = threading.Thread(target=run, name="quant-rebuild", daemon=True)
        t.start()

    def ensure(self) -> Optional[Dict[str, Any]]:
        """Current plane snapshot under the background-rebuild policy,
        or None while the float32 tier must serve."""
        if self.mode == "off":
            return None
        snap = self._snap
        mutations = getattr(self.brute, "mutations", 0)
        if snap is not None and snap["mode"] == self.mode:
            churn = mutations - snap["built_mutations"]
            if churn > self.rebuild_stale_frac * max(snap["rows"], 1):
                self._kick_background_rebuild()
            return snap
        if not self.build_inline:
            self._kick_background_rebuild()
            return self._snap
        self.build()
        return self._snap

    @property
    def plane_built(self) -> bool:
        return self._snap is not None

    def resource_stats_extra(self) -> Dict[str, Any]:
        """The compression keys BruteForceIndex.resource_stats merges:
        quantized device bytes and the ratio vs the float32 bytes the
        plane replaces (capacity-padded matrix), plus the plane's own
        rebuild state."""
        snap = self._snap
        if snap is None:
            return {"quant_device_bytes": 0}
        f32_b = snap["capacity"] * snap["dims"] * 4
        qb = snap["device_bytes"]
        return {
            "quant_device_bytes": qb,
            "compression_ratio": round(f32_b / max(qb, 1), 3),
            "quant_mode_" + snap["mode"]: 1,
        }

    # -- serving ----------------------------------------------------------

    def _coarse(self, snap, qn_np, pool, bb, b):
        """One compressed coarse dispatch -> (scores, slots) host
        arrays [bb, pool]. ``bb`` is the padded compile bucket,
        ``b`` the REAL query count (cost is per real query)."""
        t0 = time.time()
        if snap["mode"] == "int8":
            qn = jnp.asarray(qn_np)
            if snap["shards"] > 1 and "mesh" in snap \
                    and len(jax.devices()) >= snap["shards"]:
                from nornicdb_tpu.parallel.mesh import _MeshHolder

                s, i = _int8_sharded_impl(
                    qn, snap["codes_t"], snap["scale"],
                    snap["valid"], k=pool,
                    mesh_holder=_MeshHolder(snap["mesh"]))
            elif snap["shards"] > 1:
                s, i = int8_topk_shard_reference(
                    qn, snap["codes_t"], snap["scale"],
                    snap["valid"], pool, snap["shards"])
            else:
                s, i = _int8_topk_impl(
                    qn, snap["codes_t"], snap["scale"],
                    snap["valid"], k=pool)
            kind = "int8_coarse"
            flops, byts = _cost.price_int8_coarse(
                bb, snap["capacity"], snap["dims"])
        else:
            s, i = _pq_topk_impl(
                jnp.asarray(qn_np), snap["codes_t"], snap["codebooks"],
                snap["valid"], k=pool)
            kind = "pq_adc"
            flops, byts = _cost.price_pq_adc(
                bb, snap["capacity"], snap["pq_m"], snap["pq_codes"],
                snap["dims"] // snap["pq_m"])
        s, i = np.asarray(s), np.asarray(i)  # force inside timed window
        record_dispatch(kind, bb, pool, time.time() - t0)
        if _cost.pricing_enabled():
            _cost.record_query_cost(kind, _cost.cost_name(self.brute),
                                    b, flops, byts)
        return s, i

    def search_batch(
        self, queries: np.ndarray, k: int = 10
    ) -> Optional[List[List[Tuple[str, float]]]]:
        """Coarse-then-exact batched search, or None when the float32
        tier must serve this batch (every return path that answers is
        exact-rescored and live-filtered — approximate is allowed in
        the POOL, never in an answer)."""
        brute = self.brute
        snap = self.ensure()
        if snap is None:
            return None
        tier = f"vector_{snap['mode']}"
        hold = None
        if not _audit.tier_allowed(tier):
            # shadow-parity quarantine: step down to the float32 tier
            # until the breach clears (audit.tier_allowed probation)
            hold = "quarantine"
        elif not _audit.admission_allows(tier):
            # admission posture (ISSUE 15): overload forces the quant
            # rung down to float32 to shrink device pressure
            hold = "admission"
        if hold is not None:
            _QUANT_C.labels("degrade_quarantine").inc()
            self._degrade(tier, hold, snap)
            return None
        if snap["built_compactions"] != getattr(brute, "compactions", 0):
            # a compaction remapped the slot space: plane slot ids no
            # longer address the live matrix
            _QUANT_C.labels("degrade_compaction").inc()
            self._degrade(tier, "compaction", snap)
            self._kick_background_rebuild()
            return None
        delta = brute.changed_since(snap["built_mutations"])
        if delta is None:
            _QUANT_C.labels("degrade_changelog").inc()
            self._degrade(tier, "changelog_overrun", snap)
            self._kick_background_rebuild()
            return None
        n_alive = len(brute)
        if n_alive == 0:
            return [[] for _ in range(len(queries))]
        k_eff = min(k, n_alive)
        b = len(queries)
        bb = pow2_bucket(max(b, 1))
        pool = self.pool_for(k, snap)
        queries = np.asarray(queries, dtype=np.float32)
        if bb != b:
            queries = np.concatenate(
                [queries,
                 np.broadcast_to(queries[:1],
                                 (bb - b,) + queries.shape[1:])], axis=0)
        qn = np.asarray(l2_normalize(jnp.asarray(queries)))
        s, slots = self._coarse(snap, qn, pool, bb, b)
        s, slots = s[:b], slots[:b]

        # exact rerank: gather the pool's CURRENT float32 rows from the
        # host source of truth under one lock hold (current rows mean
        # in-place updates rerank fresh automatically); None = a
        # compaction landed mid-flight — degrade, never mis-join
        uniq = np.unique(slots)
        got = brute.rows_for_slots(
            uniq, expect_compactions=snap["built_compactions"])
        if got is None:
            _QUANT_C.labels("degrade_rerank_race").inc()
            self._degrade(tier, "rerank_race", snap)
            return None
        rows_u, alive_u, ids_u = got
        t0 = time.time()
        if _cost.pricing_enabled():
            flops, byts = _cost.price_rerank(bb, pool, snap["dims"])
            _cost.record_query_cost("quant_rerank",
                                    _cost.cost_name(brute), b, flops,
                                    byts)
        # ONE exact [B, U] matmul over the gathered unique rows (a
        # per-candidate dot loop costs more than the coarse dispatch)
        exact_u = qn[:b] @ rows_u.T
        inv = np.searchsorted(uniq, slots)  # [b, pool] -> row in uniq
        d_scores = None
        d_ids: List[str] = []
        if delta:
            # ids removed since logging are skipped by the gather
            d_ids, d_mat = brute.delta_vectors(delta)
            if d_ids:
                d_scores = qn[:b] @ d_mat.T  # exact cosine
        d_set = set(d_ids)
        out: List[List[Tuple[str, float]]] = []
        for r in range(b):
            # cand: eid -> (exact score, slot for the float32 path's
            # lower-slot-first tie order)
            cand: Dict[str, Tuple[float, int]] = {}
            for c in range(pool):
                if s[r, c] < 0.5 * NEG_INF:
                    break
                j = int(inv[r, c])
                eid = ids_u[j]
                if eid is None or not alive_u[j] or eid in d_set:
                    continue  # tombstoned / delta supersedes
                cand[eid] = (float(exact_u[r, j]), int(uniq[j]))
            for jd, eid in enumerate(d_ids):
                cand[eid] = (float(d_scores[r, jd]),
                             snap["capacity"] + jd)
            ranked = sorted(cand.items(),
                            key=lambda kv: (-kv[1][0], kv[1][1]))
            out.append([(eid, sc) for eid, (sc, _) in ranked[:k_eff]])
        if any(len(hits) < min(k_eff, n_alive) for hits in out):
            # clustered deletes can empty a query's pool even though
            # live rows remain — serve those batches exactly
            _QUANT_C.labels("degrade_underfill").inc()
            self._degrade(tier, "underfill", snap)
            return None
        _QUANT_C.labels("dispatch").inc()
        if d_ids:
            _QUANT_C.labels("delta_merge").inc()
        record_dispatch("quant_rerank", bb, pool, time.time() - t0)
        _audit.note_batch_tier(tier)
        return out

    def _degrade(self, tier: str, reason: str, snap) -> None:
        """One structured ledger record for a quantized->float32 step
        (the legacy quant_events_total label stays as the alias)."""
        _audit.record_degrade(
            "vector", tier, "vector_brute_f32", reason,
            index=_cost.cost_name(self.brute),
            versions={"built_mutations": snap.get("built_mutations"),
                      "built_compactions": snap.get("built_compactions"),
                      "build_seq": snap.get("build_seq")})
