"""ctypes loader for the native HNSW connect-phase kernel.

See native/nornichnsw.cpp. Loading is lazy and failure-tolerant: when
the toolchain or .so is unavailable the wave build silently uses its
Python connect path (same semantics, pinned by
tests/test_ann_stack.py::TestNativeConnect)."""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_lib: Optional[ctypes.CDLL] = None
_tried = False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    try:
        from nornicdb_tpu._native import load_build_module

        so = load_build_module("build_hnsw.py").build()
        lib = ctypes.CDLL(so)
        lib.hnsw_connect.argtypes = [
            ctypes.POINTER(ctypes.c_float),   # vectors
            ctypes.c_int64,                   # dims
            ctypes.POINTER(ctypes.c_int32),   # nbr
            ctypes.POINTER(ctypes.c_int32),   # cnt
            ctypes.c_int64,                   # width
            ctypes.c_int64,                   # m_forward
            ctypes.c_int64,                   # level_cap
            ctypes.POINTER(ctypes.c_int64),   # wave_slots
            ctypes.POINTER(ctypes.c_int64),   # cand_off
            ctypes.POINTER(ctypes.c_int64),   # cand_slots
            ctypes.POINTER(ctypes.c_float),   # cand_dists
            ctypes.c_int64,                   # n_wave
        ]
        lib.hnsw_connect.restype = None
        # a stale fallback .so (rebuild impossible) may predate the wave
        # kernel — keep the connect kernel usable without it
        if not hasattr(lib, "hnsw_wave_search"):
            _lib = lib
            return _lib
        lib.hnsw_wave_search.argtypes = [
            ctypes.POINTER(ctypes.c_float),   # vectors
            ctypes.c_int64,                   # dims
            ctypes.POINTER(ctypes.c_void_p),  # nbr level pointers
            ctypes.POINTER(ctypes.c_void_p),  # cnt level pointers
            ctypes.POINTER(ctypes.c_int64),   # widths
            ctypes.c_int64,                   # n_levels
            ctypes.POINTER(ctypes.c_float),   # queries
            ctypes.c_int64,                   # B
            ctypes.POINTER(ctypes.c_int64),   # query_levels
            ctypes.c_int64,                   # entry_slot
            ctypes.c_int64,                   # ef
            ctypes.c_int64,                   # capacity
            ctypes.POINTER(ctypes.c_int64),   # out_slots
            ctypes.POINTER(ctypes.c_float),   # out_dists
        ]
        lib.hnsw_wave_search.restype = None
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def wave_search(lib, vectors: np.ndarray, nbr_levels, cnt_levels,
                queries: np.ndarray, query_levels: np.ndarray,
                entry_slot: int, ef: int,
                capacity: int) -> "tuple[np.ndarray, np.ndarray]":
    """Run the native wave layer-search. Returns (dists, slots) shaped
    [B, n_levels, ef] (+inf / -1 padded), ascending per (query, level).
    All adjacency arrays must be C-contiguous int32."""
    p = ctypes.POINTER
    n_levels = len(nbr_levels)
    B = queries.shape[0]
    nbr_ptrs = (ctypes.c_void_p * n_levels)(
        *[a.ctypes.data for a in nbr_levels])
    cnt_ptrs = (ctypes.c_void_p * n_levels)(
        *[a.ctypes.data for a in cnt_levels])
    widths = np.asarray([a.shape[1] for a in nbr_levels], np.int64)
    out_slots = np.empty((B, n_levels, ef), np.int64)
    out_dists = np.empty((B, n_levels, ef), np.float32)
    lib.hnsw_wave_search(
        vectors.ctypes.data_as(p(ctypes.c_float)),
        vectors.shape[1],
        nbr_ptrs,
        cnt_ptrs,
        widths.ctypes.data_as(p(ctypes.c_int64)),
        n_levels,
        queries.ctypes.data_as(p(ctypes.c_float)),
        B,
        np.ascontiguousarray(query_levels, np.int64).ctypes.data_as(
            p(ctypes.c_int64)),
        entry_slot,
        ef,
        capacity,
        out_slots.ctypes.data_as(p(ctypes.c_int64)),
        out_dists.ctypes.data_as(p(ctypes.c_float)),
    )
    return out_dists, out_slots


def connect_wave(lib, vectors: np.ndarray, nbr: np.ndarray,
                 cnt: np.ndarray, m_forward: int, level_cap: int,
                 wave_slots: np.ndarray, cand_off: np.ndarray,
                 cand_slots: np.ndarray, cand_dists: np.ndarray) -> None:
    """All arrays must be C-contiguous with the dtypes the kernel
    expects; adjacency (nbr/cnt) is mutated in place."""
    p = ctypes.POINTER
    lib.hnsw_connect(
        vectors.ctypes.data_as(p(ctypes.c_float)),
        vectors.shape[1],
        nbr.ctypes.data_as(p(ctypes.c_int32)),
        cnt.ctypes.data_as(p(ctypes.c_int32)),
        nbr.shape[1],
        m_forward,
        level_cap,
        wave_slots.ctypes.data_as(p(ctypes.c_int64)),
        cand_off.ctypes.data_as(p(ctypes.c_int64)),
        cand_slots.ctypes.data_as(p(ctypes.c_int64)),
        cand_dists.ctypes.data_as(p(ctypes.c_float)),
        len(wave_slots),
    )
