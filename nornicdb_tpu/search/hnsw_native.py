"""ctypes loader for the native HNSW connect-phase kernel.

See native/nornichnsw.cpp. Loading is lazy and failure-tolerant: when
the toolchain or .so is unavailable the wave build silently uses its
Python connect path (same semantics, pinned by
tests/test_ann_stack.py::TestNativeConnect)."""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_lib: Optional[ctypes.CDLL] = None
_tried = False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    try:
        from nornicdb_tpu._native import load_build_module

        so = load_build_module("build_hnsw.py").build()
        lib = ctypes.CDLL(so)
        lib.hnsw_connect.argtypes = [
            ctypes.POINTER(ctypes.c_float),   # vectors
            ctypes.c_int64,                   # dims
            ctypes.POINTER(ctypes.c_int32),   # nbr
            ctypes.POINTER(ctypes.c_int32),   # cnt
            ctypes.c_int64,                   # width
            ctypes.c_int64,                   # m_forward
            ctypes.c_int64,                   # level_cap
            ctypes.POINTER(ctypes.c_int64),   # wave_slots
            ctypes.POINTER(ctypes.c_int64),   # cand_off
            ctypes.POINTER(ctypes.c_int64),   # cand_slots
            ctypes.POINTER(ctypes.c_float),   # cand_dists
            ctypes.c_int64,                   # n_wave
        ]
        lib.hnsw_connect.restype = None
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def connect_wave(lib, vectors: np.ndarray, nbr: np.ndarray,
                 cnt: np.ndarray, m_forward: int, level_cap: int,
                 wave_slots: np.ndarray, cand_off: np.ndarray,
                 cand_slots: np.ndarray, cand_dists: np.ndarray) -> None:
    """All arrays must be C-contiguous with the dtypes the kernel
    expects; adjacency (nbr/cnt) is mutated in place."""
    p = ctypes.POINTER
    lib.hnsw_connect(
        vectors.ctypes.data_as(p(ctypes.c_float)),
        vectors.shape[1],
        nbr.ctypes.data_as(p(ctypes.c_int32)),
        cnt.ctypes.data_as(p(ctypes.c_int32)),
        nbr.shape[1],
        m_forward,
        level_cap,
        wave_slots.ctypes.data_as(p(ctypes.c_int64)),
        cand_off.ctypes.data_as(p(ctypes.c_int64)),
        cand_slots.ctypes.data_as(p(ctypes.c_int64)),
        cand_dists.ctypes.data_as(p(ctypes.c_float)),
        len(wave_slots),
    )
