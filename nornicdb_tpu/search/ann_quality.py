"""ANN quality profiles: the global strategy selector.

Reference: pkg/search ann_quality.go:10-35 (ANNQuality fast/balanced/
accurate/compressed), ann_profile.go, build_settings.go — one env knob
(NORNICDB_VECTOR_ANN_QUALITY) that maps to index choice + parameters.
"""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass(frozen=True)
class ANNProfile:
    name: str
    index_kind: str  # brute | hnsw | ivf_hnsw | ivfpq | cagra
    hnsw_m: int = 16
    hnsw_ef_construction: int = 100
    hnsw_ef_search: int = 64
    nprobe: int = 8
    pq_subspaces: int = 16
    # exact-rerank refine store for the ivfpq tier: fp16 vector copy
    # (2 bytes/dim) + ADC-pool reranking. On by default for the
    # compressed profile — it is what makes ivfpq recall usable — with
    # NORNICDB_VECTOR_PQ_REFINE=0 to opt out when the memory budget
    # really is codes-only.
    pq_refine: bool = True
    # cagra tier: fixed out-degree device graph (search/cagra.py).
    # Below cagra_min_n live vectors the index serves from the brute
    # device kernel — at small N one matmul beats any walk.
    cagra_degree: int = 32
    cagra_itopk: int = 64
    cagra_width: int = 1
    cagra_min_n: int = 4096
    cagra_shards: int = 1


PROFILES = {
    "fast": ANNProfile(
        name="fast", index_kind="hnsw",
        hnsw_m=8, hnsw_ef_construction=60, hnsw_ef_search=32, nprobe=2),
    "balanced": ANNProfile(
        name="balanced", index_kind="hnsw",
        hnsw_m=16, hnsw_ef_construction=100, hnsw_ef_search=64, nprobe=4),
    "accurate": ANNProfile(
        name="accurate", index_kind="hnsw",
        hnsw_m=32, hnsw_ef_construction=200, hnsw_ef_search=128, nprobe=8),
    "compressed": ANNProfile(
        name="compressed", index_kind="ivfpq",
        nprobe=8, pq_subspaces=16),
    # device-resident graph ANN: the accelerator-native sub-linear tier
    # (CAGRA-style batched walk; docs/ann_architecture.md). Shard count
    # defaults to the env knob so multi-chip deployments row-shard the
    # corpus without a code change.
    "cagra": ANNProfile(
        name="cagra", index_kind="cagra",
        cagra_degree=32, cagra_itopk=64, cagra_width=1,
        cagra_min_n=4096),
}


def cagra_shards_from_env(default: int = 1) -> int:
    """NORNICDB_CAGRA_SHARDS: row-shard count for the cagra tier. When
    fewer devices than shards are live, CagraIndex serves the sharded
    layout through its single-device reference merge instead."""
    from nornicdb_tpu.config import env_int

    return max(1, env_int("CAGRA_SHARDS", default))

ENV_VAR = "NORNICDB_VECTOR_ANN_QUALITY"


def current_profile(name: str | None = None) -> ANNProfile:
    """Resolve a profile by explicit name or the env knob; unknown names
    fall back to balanced (reference behavior)."""
    key = (name or os.environ.get(ENV_VAR, "balanced")).strip().lower()
    return PROFILES.get(key, PROFILES["balanced"])
