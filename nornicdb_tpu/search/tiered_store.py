"""Tiered vector storage: cluster-routed demand paging beyond HBM.

PR 8's quantization ladder shrank the device bytes per vector; this
module shrinks the *fraction of the corpus* that has to be device-
resident at all — the reference's VectorFileStore + IVF-HNSW cluster
routing discipline (SURVEY §2.3), the standard capacity escape hatch of
the GPU graph-vector-search taxonomy:

- **Partitioning**: the corpus is clustered by the shared seeded
  device k-means (``ops.kmeans.kmeans_fit`` — the same implementation
  the IVF backends and PQ sampling train through), one partition per
  centroid. Every partition spills to the disk partition store
  (``storage/partition_store.py``) at build time: slots, ext ids,
  float32 rows and PQ codes.
- **Residency ladder**: HBM holds PQ codes for at most
  ``resident_max`` partitions, laid out in FIXED device slabs (one
  pow2-padded slab per resident partition) so residency churn never
  changes a compiled shape. Float32 exact-rerank rows stay in host RAM
  (the ``BruteForceIndex`` matrix is the pinned source of truth,
  served through ``rows_for_slots`` gathers). Cold partitions live on
  disk until the background pager promotes them.
- **Routing**: each query scores the partition centroids (one small
  host matmul) plus an optional lexical bonus for partitions holding
  the query's BM25 top docs — the reference's hybrid lexical+semantic
  cluster probing — and touches its best ``nprobe`` partitions.
  Resident probes run as ONE masked ADC dispatch over the slab array;
  non-resident probes are answered by an exact host side-scan of those
  partitions' current rows and recorded as a ``tiered_cold`` degrade
  (the ladder is tiered -> quant -> f32 -> host: a cold partition
  costs latency, never a wrong answer) while the pager promotes them
  under the background admission lane with per-job cost accounting.
- **Freshness** (the PR 2/4/6/8 discipline): the plane is a
  mutation-generation snapshot of its brute index. Compaction remaps,
  changelog overruns, mid-rerank races and mid-dispatch residency
  churn (a promotion/eviction landing while a batch is in flight —
  the ``residency_gen`` re-check) all degrade to the next rung; adds
  and updates ride the changelog into an exact side-scan; deletes are
  live-filtered at the rerank gather.
"""

from __future__ import annotations

import functools
import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nornicdb_tpu.obs import REGISTRY, declare_kind, record_dispatch
from nornicdb_tpu.obs import audit as _audit
from nornicdb_tpu.obs import cost as _cost
from nornicdb_tpu.ops.kmeans import kmeans_fit
from nornicdb_tpu.ops.similarity import NEG_INF, l2_normalize, pad_dim
from nornicdb_tpu.search.device_quant import (
    _pq_adc_scores,
    encode_pq,
    train_pq,
)
from nornicdb_tpu.search.microbatch import pow2_bucket
from nornicdb_tpu.storage.partition_store import PartitionStore

# tiered-plane lifecycle, residency churn and per-search freshness
# decisions — same observability contract as the quant/cagra tiers
_TIERED_C = REGISTRY.counter(
    "nornicdb_tiered_events_total",
    "Tiered plane lifecycle, partition paging and freshness decisions",
    labels=("event",))

declare_kind("tiered_adc")
declare_kind("tiered_rerank")

# globally unique plane build sequence (GIL-atomic), mirroring
# device_quant._BUILD_SEQ
_BUILD_SEQ = itertools.count(1)


def tiered_enabled() -> bool:
    """NORNICDB_VECTOR_TIERED=1 turns the tiered plane on; default off
    (the quant/f32 rungs serve)."""
    from nornicdb_tpu.config import env_bool

    return env_bool("VECTOR_TIERED", False)


def tiered_min_n() -> int:
    """Corpus floor below which the tiered plane never engages — small
    corpora fit device-resident through the quant/f32 rungs already."""
    from nornicdb_tpu.config import env_int

    return max(1, env_int("TIERED_MIN_N", 4096))


# ---------------------------------------------------------------------------
# the masked slab ADC dispatch
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("k",))
def _tiered_topk_impl(qn, codes_t, codebooks, valid, sel, k):
    """Partition-masked ADC top-k over the resident slab array.

    ``codes_t`` is ``[M, R*S]`` uint8 (R fixed slabs of S padded slots
    each), ``sel`` is the per-query ``[B, R]`` probe mask. Scores are
    computed over the WHOLE slab (one compiled shape regardless of
    which partitions are probed or resident) and masked to each query's
    selected slabs — routing changes data, never the program."""
    scores = _pq_adc_scores(qn, codes_t, codebooks)  # [B, R*S]
    s = valid.shape[0] // sel.shape[1]
    mask = jnp.repeat(sel, s, axis=1) & valid[None, :]
    scores = jnp.where(mask, scores, NEG_INF)
    return jax.lax.top_k(scores, k)


# ---------------------------------------------------------------------------
# the serving plane
# ---------------------------------------------------------------------------


class TieredStore:
    """Cluster-partitioned tiered serving plane over a
    ``BruteForceIndex``.

    Device: PQ codes of the resident partitions (fixed slab layout).
    Host: the brute index's float32 matrix (exact rerank + cold scan
    source). Disk: every partition's payload, read back by the
    background pager. All knobs are captured at construction — the
    per-request path never reads the environment (PR 14 contract).
    """

    def __init__(
        self,
        brute,
        nprobe: int = 8,
        parts: int = 0,
        resident_max: int = 0,
        part_rows: int = 4096,
        lex_bonus: float = 0.15,
        min_n: Optional[int] = None,
        rebuild_stale_frac: float = 0.1,
        build_inline: bool = False,
        pq_m: Optional[int] = None,
        pq_codes: int = 256,
        overfetch: int = 8,
        min_pool: int = 128,
        root_dir: Optional[str] = None,
    ):
        self.brute = brute
        self.nprobe = max(1, nprobe)
        self.parts = max(0, parts)  # 0 = auto from part_rows
        self.resident_max = max(0, resident_max)  # 0 = all resident
        self.part_rows = max(256, part_rows)
        self.lex_bonus = float(lex_bonus)
        self.min_n = tiered_min_n() if min_n is None else max(1, min_n)
        self.rebuild_stale_frac = rebuild_stale_frac
        self.build_inline = build_inline
        self.pq_m = pq_m
        self.pq_codes = pq_codes
        self.overfetch = max(1, overfetch)
        self.min_pool = max(1, min_pool)
        self.store = PartitionStore(root_dir)
        self._snap: Optional[Dict[str, Any]] = None
        self._build_lock = threading.Lock()
        self._rebuilding = False
        self._rebuild_started = 0.0
        self._rebuild_flag_lock = threading.Lock()
        # residency state lock: resident map, slab tables and the
        # residency generation move together under it
        self._res_lock = threading.Lock()
        self._page_pending: Set[int] = set()
        self._paging = False
        self._page_lock = threading.Lock()
        self.builds = 0
        self.promotions = 0
        self.evictions = 0
        self.cold_scans = 0

    # -- build ------------------------------------------------------------

    def _pq_m_for(self, d: int) -> int:
        m = self.pq_m or max(4, min(64, d // 4))
        while m > 1 and d % m != 0:
            m -= 1
        return max(1, m)

    def _n_parts_for(self, n_alive: int) -> int:
        if self.parts:
            return max(2, self.parts)
        return max(2, min(128, n_alive // self.part_rows))

    def build(self) -> bool:
        with self._build_lock:
            return self._build_locked()

    def _build_locked(self) -> bool:
        brute = self.brute
        mutations = getattr(brute, "mutations", 0)
        snap = self._snap
        if snap is not None and snap["built_mutations"] == mutations:
            return True  # raced another builder; already fresh
        matrix, valid, ext_ids = brute.snapshot()
        n_alive = int(valid.sum())
        if n_alive < self.min_n:
            self._snap = None
            return False
        cap, d = matrix.shape
        k_parts = self._n_parts_for(n_alive)
        # the shared seeded k-means partitioner (cosine; rows are
        # stored normalized) — same implementation as the IVF backends
        res = kmeans_fit(matrix, k=k_parts, valid=valid, seed=0)
        assign = res.assignments  # [cap] int32, -1 for dead/pad slots
        k_parts = res.centroids.shape[0]
        part_slots: List[np.ndarray] = []
        for pid in range(k_parts):
            part_slots.append(
                np.nonzero(assign == pid)[0].astype(np.int64))
        max_rows = max((len(s) for s in part_slots), default=1)
        slab_rows = pad_dim(max(max_rows, 1))
        r_slabs = (min(k_parts, self.resident_max)
                   if self.resident_max else k_parts)
        m = self._pq_m_for(d)
        live_rows = matrix[valid] if n_alive < cap else matrix
        codebooks = train_pq(live_rows, m, self.pq_codes)
        codes_all = encode_pq(matrix, codebooks)  # [cap, M]
        # lexical routing table: ext id -> owning partition
        pid_of_ext: Dict[str, int] = {}
        for pid, slots in enumerate(part_slots):
            for s in slots:
                eid = ext_ids[int(s)]
                if eid is not None:
                    pid_of_ext[eid] = pid
        # spill EVERY partition to disk (the cold tier; promotion and
        # crash recovery both read from here)
        for pid, slots in enumerate(part_slots):
            self.store.save_partition(
                pid, slots,
                [ext_ids[int(s)] or "" for s in slots],
                matrix[slots], codes_all[slots])
        snap = {
            "capacity": cap,
            "dims": d,
            "rows": n_alive,
            "parts": k_parts,
            "slab_rows": slab_rows,
            "r_slabs": r_slabs,
            "pq_m": m,
            "pq_codes": self.pq_codes,
            "codebooks": jnp.asarray(codebooks),
            "centroids": np.asarray(res.centroids, dtype=np.float32),
            "part_slots": part_slots,
            "pid_of_ext": pid_of_ext,
            "built_mutations": mutations,
            "built_compactions": getattr(brute, "compactions", 0),
            "build_seq": next(_BUILD_SEQ),
            # residency state (guarded by _res_lock after publish)
            "resident": {},
            "slab_pid": [-1] * r_slabs,
            "slab_slots": np.full((r_slabs, slab_rows), -1,
                                  dtype=np.int64),
            "lru": [],
            "residency_gen": 0,
        }
        codes_slab = np.zeros((r_slabs * slab_rows, m), dtype=np.uint8)
        slab_valid = np.zeros((r_slabs * slab_rows,), dtype=bool)
        # initial residency: largest partitions first — they carry the
        # most probe mass until real traffic reorders the LRU
        order = sorted(range(k_parts),
                       key=lambda p: -len(part_slots[p]))[:r_slabs]
        for slab_idx, pid in enumerate(order):
            slots = part_slots[pid]
            n_p = len(slots)
            lo = slab_idx * slab_rows
            codes_slab[lo: lo + n_p] = codes_all[slots]
            slab_valid[lo: lo + n_p] = True
            snap["slab_slots"][slab_idx, :n_p] = slots
            snap["resident"][pid] = slab_idx
            snap["slab_pid"][slab_idx] = pid
            snap["lru"].append(pid)
        snap["codes_t"] = jnp.asarray(
            np.ascontiguousarray(codes_slab.T))  # [M, R*S]
        snap["slab_valid"] = jnp.asarray(slab_valid)
        snap["device_bytes"] = (
            r_slabs * slab_rows * m  # uint8 slab codes
            + r_slabs * slab_rows  # slab validity
            + int(snap["codebooks"].nbytes))
        self._snap = snap
        self.builds += 1
        _TIERED_C.labels("build").inc()
        return True

    def _kick_background_rebuild(self) -> None:
        with self._rebuild_flag_lock:
            if self._rebuilding:
                return
            self._rebuilding = True
            self._rebuild_started = time.time()
        _TIERED_C.labels("background_rebuild").inc()

        def run():
            from nornicdb_tpu import admission as _adm

            try:
                # background maintenance lane (ISSUE 15): any coalescer
                # ride from this thread seals behind interactive work
                with _adm.lane_scope(_adm.LANE_BACKGROUND):
                    self.build()
            finally:
                # same lock as the set above: an unguarded clear can
                # interleave with a concurrent kick's read-then-set
                with self._rebuild_flag_lock:
                    self._rebuilding = False
                    self._rebuild_started = 0.0

        t = threading.Thread(target=run, name="tiered-rebuild",
                             daemon=True)
        t.start()

    def ensure(self) -> Optional[Dict[str, Any]]:
        """Current plane snapshot under the background-rebuild policy,
        or None while a lower rung must serve."""
        snap = self._snap
        mutations = getattr(self.brute, "mutations", 0)
        if snap is not None:
            churn = mutations - snap["built_mutations"]
            if churn > self.rebuild_stale_frac * max(snap["rows"], 1):
                self._kick_background_rebuild()
            return snap
        if not self.build_inline:
            self._kick_background_rebuild()
            return self._snap
        self.build()
        return self._snap

    @property
    def plane_built(self) -> bool:
        return self._snap is not None

    # -- residency / paging -----------------------------------------------

    def _install_partition_locked(self, snap: Dict[str, Any],
                                  pid: int) -> bool:
        """Promote one partition into a device slab (res_lock held).
        Picks a free slab or evicts the LRU partition. Returns False
        when the payload cannot be read back (the partition simply
        stays cold — host scan keeps answering)."""
        if pid in snap["resident"]:
            return True
        payload = self.store.load_partition(pid)
        if payload is None:
            _TIERED_C.labels("promote_miss").inc()
            return False
        slab_rows = snap["slab_rows"]
        slab_idx = None
        for i, owner in enumerate(snap["slab_pid"]):
            if owner < 0:
                slab_idx = i
                break
        if slab_idx is None:
            victim = snap["lru"].pop(0)
            slab_idx = snap["resident"].pop(victim)
            self.evictions += 1
            _TIERED_C.labels("evict").inc()
        lo = slab_idx * slab_rows
        n_p = len(payload["slots"])
        codes = np.zeros((slab_rows, snap["pq_m"]), dtype=np.uint8)
        codes[:n_p] = payload["codes"]
        vmask = np.zeros((slab_rows,), dtype=bool)
        vmask[:n_p] = True
        # functional device update: the old arrays stay immutable under
        # any in-flight dispatch; the swap below is what the
        # residency_gen re-check observes
        snap["codes_t"] = snap["codes_t"].at[:, lo: lo + slab_rows].set(
            jnp.asarray(np.ascontiguousarray(codes.T)))
        snap["slab_valid"] = snap["slab_valid"] \
            .at[lo: lo + slab_rows].set(jnp.asarray(vmask))
        snap["slab_slots"][slab_idx] = -1
        snap["slab_slots"][slab_idx, :n_p] = payload["slots"]
        snap["resident"][pid] = slab_idx
        snap["slab_pid"][slab_idx] = pid
        snap["lru"].append(pid)
        snap["residency_gen"] += 1
        self.promotions += 1
        _TIERED_C.labels("promote").inc()
        # per-job paging cost (PR 7 accounting): bytes = the slab codes
        # written to device + the payload read from disk; one "query"
        # per page job so bytes-per-job aggregates cleanly
        _cost.record_query_cost(
            "tiered_page", _cost.cost_name(self.brute), 1, 0.0,
            float(slab_rows * snap["pq_m"]
                  + payload["rows"].nbytes + payload["codes"].nbytes))
        return True

    def promote_inline(self, pids: Sequence[int]) -> int:
        """Synchronous promotion (tests / warmup): returns how many
        partitions were installed."""
        snap = self._snap
        if snap is None:
            return 0
        done = 0
        with self._res_lock:
            for pid in pids:
                if 0 <= pid < snap["parts"] \
                        and self._install_partition_locked(snap, pid):
                    done += 1
        return done

    def _kick_promote(self, pids: Sequence[int]) -> None:
        """Queue cold partitions for background promotion; one pager
        thread drains the pending set under the background lane."""
        with self._page_lock:
            self._page_pending.update(int(p) for p in pids)
            if self._paging or not self._page_pending:
                return
            self._paging = True

        def run():
            from nornicdb_tpu import admission as _adm

            try:
                with _adm.lane_scope(_adm.LANE_BACKGROUND):
                    while True:
                        with self._page_lock:
                            if not self._page_pending:
                                self._paging = False
                                return
                            pid = self._page_pending.pop()
                        self.promote_inline([pid])
            except BaseException:
                with self._page_lock:
                    self._paging = False
                raise

        t = threading.Thread(target=run, name="tiered-pager",
                             daemon=True)
        t.start()

    # -- routing ----------------------------------------------------------

    def route(
        self,
        qn: np.ndarray,
        snap: Dict[str, Any],
        lex_hints: Optional[Sequence[Optional[Sequence[str]]]] = None,
    ) -> np.ndarray:
        """Per-query probe set [B, nprobe]: hybrid lexical+semantic
        cluster scoring. Semantic = query-centroid cosine; lexical =
        a flat bonus for partitions owning the query's BM25 top docs
        (the reference's IVF-HNSW hybrid probe selection). Host-side
        and environment-free — this runs once per request."""
        scores = qn @ snap["centroids"].T  # [B, K]
        if lex_hints is not None:
            pid_of_ext = snap["pid_of_ext"]
            for i, hints in enumerate(lex_hints):
                if not hints or i >= scores.shape[0]:
                    continue
                for eid in hints:
                    pid = pid_of_ext.get(eid)
                    if pid is not None:
                        scores[i, pid] += self.lex_bonus
        nprobe = min(self.nprobe, snap["parts"])
        probe = np.argpartition(-scores, nprobe - 1,
                                axis=1)[:, :nprobe]
        # deterministic probe order (score desc, pid asc) so tests and
        # the cold-scan accounting are stable
        row_scores = np.take_along_axis(scores, probe, axis=1)
        order = np.lexsort((probe, -row_scores), axis=1)
        return np.take_along_axis(probe, order, axis=1)

    def pool_for(self, k: int, snap: Dict[str, Any]) -> int:
        """ADC rerank pool width: max(overfetch*k, min_pool) with the
        PQ capacity floor (same rationale as QuantizedBrutePlane —
        ADC rank noise grows with slab capacity and codebook
        coarseness), clamped to the slab capacity."""
        slab_cap = snap["r_slabs"] * snap["slab_rows"]
        floor = max(k * self.overfetch, self.min_pool,
                    slab_cap // min(snap["pq_codes"], 256))
        return min(pow2_bucket(floor), slab_cap)

    # -- accounting -------------------------------------------------------

    def resource_stats_extra(self) -> Dict[str, Any]:
        """The tiered keys BruteForceIndex.resource_stats merges:
        partition/residency census, the device slab footprint, the
        disk spill footprint and the effective-capacity ratio vs the
        all-device float32 baseline."""
        snap = self._snap
        if snap is None:
            return {"partitions": 0, "resident_partitions": 0,
                    "tiered_device_bytes": 0,
                    "disk_bytes": self.store.disk_bytes()}
        with self._res_lock:
            resident = len(snap["resident"])
        f32_b = snap["capacity"] * snap["dims"] * 4
        dev_b = snap["device_bytes"]
        return {
            "partitions": snap["parts"],
            "resident_partitions": resident,
            "tiered_device_bytes": dev_b,
            "disk_bytes": self.store.disk_bytes(),
            "tiered_capacity_ratio": round(f32_b / max(dev_b, 1), 3),
            "promotions": self.promotions,
            "evictions": self.evictions,
            "cold_scans": self.cold_scans,
        }

    # -- serving ----------------------------------------------------------

    def search_batch(
        self,
        queries: np.ndarray,
        k: int = 10,
        lex_hints: Optional[Sequence[Optional[Sequence[str]]]] = None,
    ) -> Optional[List[List[Tuple[str, float]]]]:
        """Cluster-routed coarse-then-exact batched search, or None
        when a lower rung must serve this batch. Resident probes run
        one masked ADC dispatch + exact host rerank; cold probes are
        host-scanned exactly (one ``tiered_cold`` ledger record per
        batch) and queued for background promotion. Every answered
        path is exact-rescored and live-filtered."""
        brute = self.brute
        snap = self.ensure()
        if snap is None:
            return None
        tier = "vector_tiered"
        hold = None
        if not _audit.tier_allowed(tier):
            # shadow-parity quarantine: step down to the quant/f32
            # rungs until the breach clears
            hold = "quarantine"
        elif not _audit.admission_allows(tier):
            # admission posture (ISSUE 15): overload forces the
            # capacity rung down to shrink paging + device pressure
            hold = "admission"
        if hold is not None:
            _TIERED_C.labels("degrade_quarantine").inc()
            self._degrade(tier, hold, snap)
            return None
        if snap["built_compactions"] != getattr(brute, "compactions", 0):
            # a compaction remapped the slot space: slab slot ids no
            # longer address the live matrix
            _TIERED_C.labels("degrade_compaction").inc()
            self._degrade(tier, "compaction", snap)
            self._kick_background_rebuild()
            return None
        delta = brute.changed_since(snap["built_mutations"])
        if delta is None:
            _TIERED_C.labels("degrade_changelog").inc()
            self._degrade(tier, "changelog_overrun", snap)
            self._kick_background_rebuild()
            return None
        n_alive = len(brute)
        if n_alive == 0:
            return [[] for _ in range(len(queries))]
        k_eff = min(k, n_alive)
        b = len(queries)
        bb = pow2_bucket(max(b, 1))
        pool = self.pool_for(k, snap)
        queries = np.asarray(queries, dtype=np.float32)
        if bb != b:
            queries = np.concatenate(
                [queries,
                 np.broadcast_to(queries[:1],
                                 (bb - b,) + queries.shape[1:])],
                axis=0)
        qn = np.asarray(l2_normalize(jnp.asarray(queries)))
        probe = self.route(qn[:b], snap, lex_hints)
        if _cost.pricing_enabled():
            flops, byts = _cost.price_tiered_route(
                bb, snap["parts"], snap["dims"])
            _cost.record_query_cost(
                "tiered_route", _cost.cost_name(brute), b, flops, byts)

        # capture a CONSISTENT residency view under one lock hold: the
        # probe mask, the slab->slot table copy and the generation all
        # describe the same residency state
        r_slabs = snap["r_slabs"]
        cold_need: List[Set[int]] = [set() for _ in range(b)]
        sel = np.zeros((bb, r_slabs), dtype=bool)
        with self._res_lock:
            gen0 = snap["residency_gen"]
            codes_t = snap["codes_t"]
            slab_valid = snap["slab_valid"]
            slab_slots = snap["slab_slots"].copy()
            resident = dict(snap["resident"])
            for i in range(b):
                for pid in probe[i]:
                    slab = resident.get(int(pid))
                    if slab is None:
                        cold_need[i].add(int(pid))
                    else:
                        sel[i, slab] = True
            # LRU touch for probed resident partitions
            touched = {int(p) for row in probe for p in row
                       if int(p) in resident}
            if touched:
                snap["lru"] = ([p for p in snap["lru"]
                                if p not in touched]
                               + [p for p in snap["lru"]
                                  if p in touched])

        s = slots = None
        if sel.any():
            t0 = time.time()
            s, cells = _tiered_topk_impl(
                jnp.asarray(qn), codes_t, snap["codebooks"],
                slab_valid, jnp.asarray(sel), k=pool)
            # force inside the timed window (async dispatch)
            s, cells = np.asarray(s), np.asarray(cells)
            record_dispatch("tiered_adc", bb, pool, time.time() - t0)
            if _cost.pricing_enabled():
                flops, byts = _cost.price_pq_adc(
                    bb, r_slabs * snap["slab_rows"], snap["pq_m"],
                    snap["pq_codes"], snap["dims"] // snap["pq_m"])
                _cost.record_query_cost(
                    "tiered_adc", _cost.cost_name(brute), b, flops,
                    byts)
            # mid-page eviction race: a promotion/eviction that landed
            # while the dispatch was in flight invalidates the
            # captured residency view — degrade, never mis-join
            with self._res_lock:
                raced = snap["residency_gen"] != gen0
            if raced:
                _TIERED_C.labels("degrade_paging_race").inc()
                self._degrade(tier, "paging_race", snap)
                return None
            s = s[:b]
            flat = slab_slots.reshape(-1)
            slots = flat[np.asarray(cells)[:b]]
            slots[s < 0.5 * NEG_INF] = -1

        # exact rerank of the resident pool against the CURRENT host
        # float32 rows (one lock hold; compaction-checked)
        exact_u = inv = None
        uniq = np.asarray([], dtype=np.int64)
        alive_u: np.ndarray = np.asarray([], dtype=bool)
        ids_u: List[Optional[str]] = []
        if slots is not None:
            uniq = np.unique(slots[slots >= 0])
            if uniq.size:
                got = brute.rows_for_slots(
                    uniq, expect_compactions=snap["built_compactions"])
                if got is None:
                    _TIERED_C.labels("degrade_rerank_race").inc()
                    self._degrade(tier, "rerank_race", snap)
                    return None
                rows_u, alive_u, ids_u = got
                if _cost.pricing_enabled():
                    flops, byts = _cost.price_rerank(
                        bb, pool, snap["dims"])
                    _cost.record_query_cost(
                        "tiered_rerank", _cost.cost_name(brute), b,
                        flops, byts)
                t0 = time.time()
                exact_u = qn[:b] @ rows_u.T
                inv = np.searchsorted(uniq, np.clip(slots, 0, None))
                record_dispatch("tiered_rerank", bb, pool,
                                time.time() - t0)

        # cold partitions: exact host side-scan of their CURRENT rows,
        # one ledger record per batch, promotion queued in background
        cold_pids = sorted({p for need in cold_need for p in need})
        cold_scores = cold_pid_of = cold_ids = cold_alive = None
        cold_slots = np.asarray([], dtype=np.int64)
        if cold_pids:
            self.cold_scans += 1
            _TIERED_C.labels("cold_scan").inc()
            # the ONE structured record for this batch's cold probes:
            # those partitions served through the host-scan rung
            _audit.record_degrade(
                "vector", tier, _audit.TIER_HOST, "tiered_cold",
                index=_cost.cost_name(brute),
                versions={"built_mutations": snap["built_mutations"],
                          "built_compactions":
                              snap["built_compactions"],
                          "build_seq": snap["build_seq"],
                          "residency_gen": gen0})
            cold_slots = np.concatenate(
                [snap["part_slots"][p] for p in cold_pids])
            cold_pid_of = np.concatenate(
                [np.full(len(snap["part_slots"][p]), p,
                         dtype=np.int64) for p in cold_pids])
            got = brute.rows_for_slots(
                cold_slots,
                expect_compactions=snap["built_compactions"])
            if got is None:
                _TIERED_C.labels("degrade_rerank_race").inc()
                self._degrade(tier, "rerank_race", snap)
                return None
            cold_rows, cold_alive, cold_ids = got
            cold_scores = qn[:b] @ cold_rows.T
            if _cost.pricing_enabled():
                flops, byts = _cost.price_rerank(
                    bb, len(cold_slots), snap["dims"])
                _cost.record_query_cost(
                    "tiered_cold_scan", _cost.cost_name(brute), b,
                    flops, byts)
            self._kick_promote(cold_pids)

        # exact delta side-scan (read-your-writes: adds/updates since
        # the build; deletes are live-filtered below)
        d_scores = None
        d_ids: List[str] = []
        if delta:
            d_ids, d_mat = brute.delta_vectors(delta)
            if d_ids:
                d_scores = qn[:b] @ d_mat.T
        d_set = set(d_ids)

        out: List[List[Tuple[str, float]]] = []
        for r in range(b):
            # eid -> (exact score, slot for lower-slot-first tie order
            # matching the float32 path)
            cand: Dict[str, Tuple[float, int]] = {}
            if exact_u is not None and s is not None:
                for c in range(s.shape[1]):
                    if s[r, c] < 0.5 * NEG_INF or slots[r, c] < 0:
                        continue
                    j = int(inv[r, c])
                    eid = ids_u[j]
                    if eid is None or not alive_u[j] or eid in d_set:
                        continue  # tombstoned / delta supersedes
                    cand[eid] = (float(exact_u[r, j]), int(uniq[j]))
            if cold_scores is not None:
                need = cold_need[r]
                for j in range(len(cold_slots)):
                    if int(cold_pid_of[j]) not in need:
                        continue
                    eid = cold_ids[j]
                    if eid is None or eid == "" or not cold_alive[j] \
                            or eid in d_set:
                        continue
                    cand[eid] = (float(cold_scores[r, j]),
                                 int(cold_slots[j]))
            for jd, eid in enumerate(d_ids):
                cand[eid] = (float(d_scores[r, jd]),
                             snap["capacity"] + jd)
            ranked = sorted(cand.items(),
                            key=lambda kv: (-kv[1][0], kv[1][1]))
            out.append([(eid, sc) for eid, (sc, _) in ranked[:k_eff]])
        if any(len(hits) < min(k_eff, n_alive) for hits in out):
            # clustered deletes (or a probe set that ran dry) can leave
            # a query short — serve those batches on a lower rung
            _TIERED_C.labels("degrade_underfill").inc()
            self._degrade(tier, "underfill", snap)
            return None
        _TIERED_C.labels("dispatch").inc()
        if d_ids:
            _TIERED_C.labels("delta_merge").inc()
        _audit.note_batch_tier(tier)
        return out

    def _degrade(self, tier: str, reason: str, snap) -> None:
        """One structured ledger record for a tiered -> lower-rung step
        (the per-module event label stays as the alias)."""
        _audit.record_degrade(
            "vector", tier, "vector_brute_f32", reason,
            index=_cost.cost_name(self.brute),
            versions={"built_mutations": snap.get("built_mutations"),
                      "built_compactions": snap.get("built_compactions"),
                      "build_seq": snap.get("build_seq"),
                      "residency_gen": snap.get("residency_gen")})
