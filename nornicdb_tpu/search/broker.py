"""Cross-worker dispatch broker: the MicroBatcher generalized over a
shared-memory ring (ISSUE 11).

One Python event loop cannot parse and serialize wire traffic fast
enough to feed the device plane (BENCH_r07: the qdrant gRPC surface
knees at 724 qps open-loop while the Go reference does ~29k ops/s on
the same contract, and PR 1's framework-floor calibration says we sit
at the ceiling of one loop). The architectural fix is N frontend
workers — separate processes parsing/serializing in parallel — funneled
into ONE shared device plane, because device throughput is won by
wider batches and more frontends posting concurrently produce exactly
that.

This module is the funnel. Layout:

- a ``multiprocessing.shared_memory`` segment holding a control block
  (shared write-generation mirrors for the wire caches) plus a ring of
  fixed-size request slots, partitioned per worker so every slot has
  ONE writer per protocol state: the owning worker writes
  ``FREE -> POSTED`` and ``DONE -> FREE``, the broker writes
  ``POSTED -> CLAIMED -> DONE`` — single-producer/single-consumer
  transitions, no cross-process lock anywhere on the request path;
- two op kinds: ``OP_VEC`` carries a RAW float32 embedding (no pickle
  on the hot payload) and is coalesced across workers into one batched
  ``search_batch`` device dispatch per group — the MicroBatcher's
  leader/rider protocol with the broker as the standing leader, so
  coalescing gets *better* with more frontends; ``OP_CALL`` carries a
  pickled generic operation executed on a parent-side target object
  (full-fidelity qdrant ``search_points``, upsert convoys, scroll
  pages, admin reads) on a pool whose concurrent execution coalesces
  in the existing MicroBatcher/BatchCoalescer machinery;
- doorbells are unix datagram sockets (worker -> broker on post,
  broker -> worker on completion) so neither side spins; both sides
  also poll slot state on a short timeout, so a lost datagram degrades
  to a few hundred microseconds of latency, never to a hang;
- per-rider serving-tier attribution and stage timing cross the
  process boundary in the response header/meta (the dispatch path's
  ``audit.note_batch_tier`` / ``audit.last_served`` verdicts and the
  leader-stamped t_claim/t0/t1), and OP_CALL responses carry the
  degrade-ledger records the op produced so the worker's
  ``/admin/degrades`` stays truthful;
- a rider whose broker died mid-dispatch times out
  (``NORNICDB_WIRE_TIMEOUT_S``) and surfaces an error — never a hang:
  the abandoned slot is tombstoned until the broker's DONE (if any)
  is observed, then reclaimed.

Responses larger than a slot's payload spill to a temp file next to
the doorbell sockets (marker in the header; reader unlinks) so a 10k-
point scroll page cannot wedge the ring.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import tempfile
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from nornicdb_tpu.obs import (
    REGISTRY,
    SIZE_BUCKETS,
    declare_kind,
    record_dispatch,
)
from nornicdb_tpu.obs import audit as _audit
from nornicdb_tpu.obs import device as _device
from nornicdb_tpu.obs import tenant as _tenant
from nornicdb_tpu.obs import tracing as _tracing
from nornicdb_tpu import admission as _adm
from nornicdb_tpu.search.microbatch import pow2_bucket

# pre-register the ring's dispatch kind so the compile-universe
# accounting reports 0 before first traffic (PR 6 discipline)
declare_kind("broker_vec")

# -- slot protocol ----------------------------------------------------------

ST_FREE, ST_POSTED, ST_CLAIMED, ST_DONE = 0, 1, 2, 3
OP_VEC, OP_CALL = 1, 2
# response delivery: inline payload bytes, or spilled to a file whose
# utf-8 path is the payload (responses bigger than one slot)
RESP_INLINE, RESP_SPILL = 0, 1

# slot header: state, op, ok, resp_kind, seq, req_len, resp_len, k,
# t_post, t_claim, t0, t1, batch, deadline  (packed little-endian).
# On a POSTED slot the resp_kind byte carries the rider's priority-LANE
# code (admission.LANE_CODES) — the response pack overwrites it — and
# ``deadline`` is the rider's absolute budget (0.0 = none), so both
# survive the worker -> plane hop without touching the payload
# (ISSUE 15).
_HDR = struct.Struct("<BBBBIIIIddddId")
_HDR_SIZE = 64  # header struct is exactly 64 bytes; slots align to 64
assert _HDR.size <= _HDR_SIZE

# control block: magic, n_workers, slots_per_worker, slot_bytes (u32 x4)
# then qdrant_gen (u64 @16), search_gen (u64 @24), broker_alive (u8 @32),
# admission posture level (u8 @40) + its write timestamp (f64 @48) —
# the fleet-wide posture word (ISSUE 16)
_CTRL = struct.Struct("<IIII")
_CTRL_SIZE = 64
_MAGIC = 0x4E57_4252  # "NWBR"
_OFF_QDRANT_GEN = 16
_OFF_SEARCH_GEN = 24
_OFF_ALIVE = 32
_OFF_POSTURE = 40
_OFF_POSTURE_TS = 48


def _read_posture_word(buf) -> Tuple[int, float]:
    """(posture level, write timestamp) from a ring control block. A
    torn read across the two fields is harmless — the posture word is
    advisory and self-heals within one publish cadence."""
    (ts,) = struct.unpack_from("<d", buf, _OFF_POSTURE_TS)
    return int(buf[_OFF_POSTURE]), float(ts)


def _write_posture_word(buf, level: int, ttl_s: float) -> bool:
    """Publish one process's LOCAL admission posture into the shared
    control block: write-if-more-severe-or-stale. A severe posture any
    ring member published sticks until it ages past ``ttl_s`` — a
    healthy worker cannot clear a peer's overload signal early, and a
    dead worker's stale signal cannot pin the fleet shed forever."""
    now = time.time()
    cur, ts = _read_posture_word(buf)
    if level >= cur or (now - ts) > ttl_s:
        struct.pack_into("<d", buf, _OFF_POSTURE_TS, now)
        buf[_OFF_POSTURE] = max(0, min(255, int(level)))
        return True
    return False

_BATCH_H = REGISTRY.histogram(
    "nornicdb_broker_batch_size",
    "Cross-worker riders coalesced per broker dispatch group",
    buckets=SIZE_BUCKETS)
_REQS_C = REGISTRY.counter(
    "nornicdb_broker_requests_total",
    "Requests brokered from wire workers to the shared device plane",
    labels=("op",))
_ERRS_C = REGISTRY.counter(
    "nornicdb_broker_errors_total",
    "Broker-path failures by kind (dispatch errors, spills, timeouts)",
    labels=("kind",))
_WORKERS_G = REGISTRY.gauge(
    "nornicdb_wire_workers",
    "Frontend workers configured on this node's wire plane")


def default_timeout_s() -> float:
    try:
        return float(os.environ.get("NORNICDB_WIRE_TIMEOUT_S", "15"))
    except ValueError:
        return 15.0


class BrokerTimeout(RuntimeError):
    """The shared device plane did not answer within the rider timeout
    (broker crashed, wedged, or saturated past the deadline). The wire
    layer maps this to an error response — never a hang."""


class BrokerRemoteError(RuntimeError):
    """A generic op raised in the device-plane process; carries the
    remote type name for error mapping at the wire layer."""

    def __init__(self, type_name: str, message: str, status: int = 400):
        super().__init__(message)
        self.type_name = type_name
        self.status = status


class _Layout:
    """Offset math shared by both sides of the ring."""

    def __init__(self, n_workers: int, slots: int, slot_bytes: int):
        self.n_workers = n_workers
        self.slots = slots
        self.slot_bytes = slot_bytes
        self.payload_bytes = slot_bytes - _HDR_SIZE
        self.total = _CTRL_SIZE + n_workers * slots * slot_bytes

    def slot_off(self, worker: int, slot: int) -> int:
        return _CTRL_SIZE + (worker * self.slots + slot) * self.slot_bytes


def _read_hdr(buf, off: int):
    return _HDR.unpack_from(buf, off)


def _mk_socket(path: str) -> socket.socket:
    s = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
    s.bind(path)
    return s


def _ring_doorbell(sock: socket.socket, path: str) -> None:
    try:
        sock.sendto(b"!", path)
    except OSError:
        # receiver gone or its buffer full — the poll timeout covers it
        pass


def _untrack_shm(shm) -> None:
    """Drop a SharedMemory segment from this process's resource
    tracker: the BROKER owns unlinking (its stop()), while attaching
    clients must never let their tracker reap the live ring when they
    exit (CPython registers attachments too — bpo-39959)."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:  # noqa: BLE001 — best-effort hygiene
        pass


# -- client (frontend worker side) ------------------------------------------


class BrokerClient:
    """Worker-side endpoint of the ring. Thread-safe within the worker:
    slot allocation is an in-process lock; the cross-process protocol
    itself is lock-free (single-writer state transitions)."""

    def __init__(self, spec: Dict[str, Any]):
        from multiprocessing import shared_memory

        self.worker_id = int(spec["worker_id"])
        self._shm = shared_memory.SharedMemory(name=spec["shm_name"])
        if spec.get("untrack_shm", spec.get("cross_process", True)):
            # attaching registers with THIS process's resource tracker
            # (CPython registers attachments too); a worker exiting
            # must not reap the live ring out from under its peers.
            # Thread mode keeps the single registration the creating
            # broker owns.
            _untrack_shm(self._shm)
        self._buf = self._shm.buf
        magic, n_workers, slots, slot_bytes = _CTRL.unpack_from(self._buf, 0)
        if magic != _MAGIC:
            raise RuntimeError("broker shm magic mismatch")
        self._layout = _Layout(n_workers, slots, slot_bytes)
        self.sock_dir = spec["sock_dir"]
        self._broker_path = os.path.join(self.sock_dir, "broker.sock")
        self._sock_path = os.path.join(
            self.sock_dir, f"worker{self.worker_id}.sock")
        if os.path.exists(self._sock_path):
            os.unlink(self._sock_path)
        self._sock = _mk_socket(self._sock_path)
        self._sock.settimeout(0.02)
        # whether the device plane lives in ANOTHER process: governs
        # degrade-record relay (in thread mode the ledger is already
        # shared, replaying would double-record)
        self.cross_process = bool(spec.get("cross_process", True))
        self.timeout_s = float(spec.get("timeout_s") or default_timeout_s())
        self._lock = threading.Lock()
        self._free = list(range(self._layout.slots))
        self._cond = threading.Condition(self._lock)
        # slots abandoned by a timed-out rider: unusable until the
        # broker's DONE is observed (it may still write into them)
        self._tombstoned: set = set()
        self._seq = 0

    # -- shared generation mirrors (wire-cache validation) ------------

    def qdrant_gen(self) -> int:
        return int.from_bytes(
            bytes(self._buf[_OFF_QDRANT_GEN:_OFF_QDRANT_GEN + 8]), "little")

    def search_gen(self) -> int:
        return int.from_bytes(
            bytes(self._buf[_OFF_SEARCH_GEN:_OFF_SEARCH_GEN + 8]), "little")

    def broker_alive(self) -> bool:
        return self._buf[_OFF_ALIVE] == 1

    # -- fleet posture word (ISSUE 16) ---------------------------------

    def ring_posture(self) -> Tuple[int, float]:
        """(posture level, age in seconds) of the shared posture word —
        the AdmissionController posture-source shape."""
        level, ts = _read_posture_word(self._buf)
        return level, max(0.0, time.time() - ts)

    def publish_posture(self, level: int,
                        ttl_s: Optional[float] = None) -> bool:
        if ttl_s is None:
            ttl_s = _adm.cfg()["fleet_posture_ttl_s"]
        return _write_posture_word(self._buf, level, ttl_s)

    def bind_admission(self) -> None:
        """Wire this process's AdmissionController to the ring posture
        word: every local posture evaluation publishes into the control
        block (write-if-more-severe-or-stale), and every refresh reads
        the word back as a fleet posture source — one overloaded wire
        worker tightens EVERY worker's admission verdict within a
        publish cadence."""
        _adm.CONTROLLER.set_posture_publisher(self.publish_posture)
        _adm.CONTROLLER.add_posture_source(self.ring_posture)

    def unbind_admission(self) -> None:
        _adm.CONTROLLER.clear_posture_publisher(self.publish_posture)
        _adm.CONTROLLER.remove_posture_source(self.ring_posture)

    # -- slot lifecycle ------------------------------------------------

    def _acquire_slot(self, deadline: float) -> int:
        with self._cond:
            while True:
                # lazily reclaim tombstones whose DONE has landed
                if self._tombstoned:
                    reclaimed = []
                    for s in self._tombstoned:
                        off = self._layout.slot_off(self.worker_id, s)
                        if self._buf[off] == ST_DONE:
                            self._buf[off] = ST_FREE
                            reclaimed.append(s)
                    for s in reclaimed:
                        self._tombstoned.discard(s)
                        self._free.append(s)
                if self._free:
                    return self._free.pop()
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise BrokerTimeout(
                        "no free broker slots within timeout "
                        f"(worker {self.worker_id})")
                self._cond.wait(timeout=min(remaining, 0.05))

    def _release_slot(self, slot: int) -> None:
        with self._cond:
            self._free.append(slot)
            self._cond.notify()

    def _post(self, slot: int, op: int, payload: bytes, k: int = 0,
              deadline: float = 0.0, lane_code: int = 0) -> int:
        lay = self._layout
        if len(payload) > lay.payload_bytes:
            raise ValueError(
                f"request payload {len(payload)}B exceeds slot capacity "
                f"{lay.payload_bytes}B (raise NORNICDB_WIRE_SLOT_BYTES)")
        off = lay.slot_off(self.worker_id, slot)
        self._seq += 1
        seq = self._seq & 0xFFFFFFFF
        self._buf[off + _HDR_SIZE:off + _HDR_SIZE + len(payload)] = payload
        # resp_kind byte carries the LANE code on a posted slot; the
        # trailing double carries the rider's absolute deadline budget
        # (0.0 = none) — the plane sheds expired riders at claim and
        # binds the budget around the dispatch (ISSUE 15)
        _HDR.pack_into(self._buf, off, ST_FREE, op, 0, lane_code, seq,
                       len(payload), 0, k, time.time(), 0.0, 0.0, 0.0,
                       0, deadline)
        # publish LAST: the state byte flips ownership to the broker
        self._buf[off] = ST_POSTED
        _ring_doorbell(self._sock, self._broker_path)
        return seq

    def _await(self, slot: int, seq: int, deadline: float) -> Tuple:
        off = self._layout.slot_off(self.worker_id, slot)
        while True:
            if self._buf[off] == ST_DONE:
                hdr = _read_hdr(self._buf, off)
                if hdr[4] == seq:
                    return hdr
                # stale DONE from an abandoned predecessor: reclaim the
                # race by treating it as still-pending
            if time.time() >= deadline:
                with self._cond:
                    self._tombstoned.add(slot)
                _ERRS_C.labels("rider_timeout").inc()
                raise BrokerTimeout(
                    "device plane did not answer within the rider "
                    "deadline (op abandoned, slot tombstoned)")
            try:
                self._sock.recv(64)
            except socket.timeout:
                pass
            except OSError:
                time.sleep(0.001)

    def _response(self, slot: int, hdr) -> Any:
        lay = self._layout
        off = lay.slot_off(self.worker_id, slot)
        _state, _op, ok, resp_kind, _seq, _rl, resp_len, _k = hdr[:8]
        raw = bytes(self._buf[off + _HDR_SIZE:off + _HDR_SIZE + resp_len])
        if resp_kind == RESP_SPILL:
            path = raw.decode("utf-8")
            try:
                with open(path, "rb") as f:
                    raw = f.read()
            finally:
                try:
                    os.unlink(path)
                except OSError:
                    pass
        doc = pickle.loads(raw)
        self._buf[off] = ST_FREE
        if not ok:
            type_name, msg, status = doc
            raise BrokerRemoteError(type_name, msg, status)
        return doc

    # -- public ops ----------------------------------------------------

    def vec_search(self, key: str, vec: np.ndarray, k: int,
                   timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Raw-embedding coalesced search: one rider of a cross-worker
        batched device dispatch. Returns ``{"hits", "tier", "t_claim",
        "t0", "t1", "batch", "t_post"}`` plus plane-side ``spans`` when
        the rider posted under an active trace (ISSUE 13): the slot
        carries a compact trace context behind the key, the plane's
        child spans ride the response back, and the worker grafts them
        so the ingress trace shows the full chain."""
        vec = np.ascontiguousarray(vec, dtype=np.float32)
        kb = key.encode("utf-8")
        tb = _tracing.pack_context(_tracing.trace_context()) \
            .encode("utf-8")
        payload = (struct.pack("<HHI", len(kb), len(tb), vec.shape[0])
                   + kb + tb + vec.tobytes())
        return self._roundtrip(OP_VEC, payload, k, timeout_s)

    def _await_deadline(self, timeout_s: Optional[float],
                        now: float) -> Tuple[float, Optional[float]]:
        """(rider await deadline, request deadline or None). The rider
        timeout consults the REQUEST deadline when one is in context —
        a generous CLIENT budget is not truncated to the flat
        ``NORNICDB_WIRE_TIMEOUT_S`` and a tight one is not held open
        past its own expiry (ISSUE 15; closes the PR 11 headroom
        note). Only an EXPLICIT budget (gRPC deadline, the deadline
        header, a programmatic scope) may extend the flat timeout: a
        server-minted surface default (30s http) must not double the
        dead-plane detection latency, so defaults clamp to the flat
        knob while still failing the rider fast if they are tighter.
        An explicit ``timeout_s`` argument still wins (internal
        callers: readiness probes, admin ops)."""
        req_dl = _adm.deadline()
        if timeout_s is not None:
            return now + timeout_s, req_dl
        if req_dl is not None:
            if _adm.deadline_explicit():
                return req_dl, req_dl
            return min(req_dl, now + self.timeout_s), req_dl
        return now + self.timeout_s, req_dl

    def call(self, target: str, method: str, *args,
             timeout_s: Optional[float] = None, **kwargs) -> Dict[str, Any]:
        """Generic op on a device-plane target. Returns ``{"result",
        "meta", timing...}``; remote exceptions re-raise as
        :class:`BrokerRemoteError`. The active trace context rides the
        pickled tuple, so the plane executes the op under a PROPAGATED
        trace — degrade records minted over there carry this rider's
        trace id, and the plane-side span tree comes back in
        ``meta["spans"]``."""
        ctx = _tracing.trace_context()
        if ctx is None:
            # no active trace (worker HTTP frontends don't root one)
            # — the tenant identity still crosses the ring so the
            # plane-side serve attributes to the rider, not
            # __unattributed__ (ISSUE 18)
            t = _tenant.current_tenant()
            if t:
                ctx = {"tenant": t}
        payload = pickle.dumps(
            (target, method, args, kwargs, ctx),
            protocol=5)
        return self._roundtrip(OP_CALL, payload, 0, timeout_s)

    def _roundtrip(self, op: int, payload: bytes, k: int,
                   timeout_s: Optional[float]) -> Dict[str, Any]:
        now = time.time()
        deadline, req_dl = self._await_deadline(timeout_s, now)
        if req_dl is not None and now >= req_dl:
            # budget already spent: never post a slot the plane would
            # claim, dispatch and answer into the void
            lane_name = _adm.lane()
            _adm.record_deadline_miss("broker", "ring", lane_name)
            raise _adm.DeadlineExceeded(
                "deadline budget expired before ring post")
        slot = self._acquire_slot(deadline)
        try:
            seq = self._post(slot, op, payload, k=k,
                             deadline=req_dl or 0.0,
                             lane_code=_adm.LANE_CODES.get(
                                 _adm.lane(), 0))
            hdr = self._await(slot, seq, deadline)
            doc = self._response(slot, hdr)
        except BrokerTimeout:
            raise  # slot tombstoned by _await; never reused raw
        except BaseException:
            # remote error or local parse failure AFTER the broker
            # finished with the slot: safe to recycle
            self._release_slot(slot)
            raise
        self._release_slot(slot)
        doc.update({"t_post": hdr[8], "t_claim": hdr[9],
                    "t0": hdr[10], "t1": hdr[11], "batch": hdr[12]})
        return doc

    def close(self) -> None:
        self.unbind_admission()
        try:
            self._sock.close()
        finally:
            try:
                os.unlink(self._sock_path)
            except OSError:
                pass
            try:
                self._shm.close()
            except Exception:  # noqa: BLE001
                pass


# -- broker (device-plane side) ---------------------------------------------


class DispatchBroker:
    """Parent-side scan/claim/dispatch engine over the ring.

    ``vec_dispatch(key, queries[B, D], k) -> per-row hit lists`` is the
    batched device entry (the same contract as MicroBatcher's
    ``search_batch``); ``targets`` maps OP_CALL target names to live
    objects whose (dotted) methods generic ops invoke. Dispatches run
    on a thread pool, so concurrent OP_CALLs coalesce in the existing
    MicroBatcher/BatchCoalescer machinery below, while OP_VEC groups
    are batched HERE — one ``search_batch`` per group per round, with
    a per-key busy gate so riders arriving mid-dispatch queue for the
    next round exactly like MicroBatcher riders."""

    def __init__(self, vec_dispatch: Callable[[str, np.ndarray, int], List],
                 targets: Dict[str, Any], n_workers: int,
                 slots: Optional[int] = None,
                 slot_bytes: Optional[int] = None,
                 pool_workers: int = 8, max_batch: int = 64,
                 gather_window_s: float = 0.0005):
        from concurrent import futures
        from multiprocessing import shared_memory

        def _env_int(name: str, default: int) -> int:
            try:
                return int(os.environ.get(name, str(default)))
            except ValueError:
                return default

        slots = slots or _env_int("NORNICDB_WIRE_SLOTS", 64)
        slot_bytes = slot_bytes or _env_int("NORNICDB_WIRE_SLOT_BYTES",
                                            256 * 1024)
        self._vec_dispatch = vec_dispatch
        self._targets = dict(targets)
        self._layout = _Layout(n_workers, slots, slot_bytes)
        self._max_batch = max_batch
        self._gather_window_s = gather_window_s
        self._shm = shared_memory.SharedMemory(
            create=True, size=self._layout.total,
            name=f"nornic_wire_{uuid.uuid4().hex[:12]}")
        self._buf = self._shm.buf
        self._buf[:self._layout.total] = b"\x00" * self._layout.total
        _CTRL.pack_into(self._buf, 0, _MAGIC, n_workers, slots, slot_bytes)
        self.sock_dir = tempfile.mkdtemp(prefix="nornic-wire-")
        self._sock_path = os.path.join(self.sock_dir, "broker.sock")
        self._sock = _mk_socket(self._sock_path)
        self._sock.settimeout(0.002)
        self._wake = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        self._pool = futures.ThreadPoolExecutor(
            max_workers=pool_workers, thread_name_prefix="broker-dispatch")
        self._run = False
        self._thread: Optional[threading.Thread] = None
        self._vec_busy: Dict[str, bool] = {}
        self._busy_lock = threading.Lock()
        self._last_round = 1
        _WORKERS_G.set(float(n_workers))

    # -- shared generation mirrors -------------------------------------

    def set_qdrant_gen(self, gen: int) -> None:
        self._buf[_OFF_QDRANT_GEN:_OFF_QDRANT_GEN + 8] = \
            int(gen).to_bytes(8, "little")

    def set_search_gen(self, gen: int) -> None:
        self._buf[_OFF_SEARCH_GEN:_OFF_SEARCH_GEN + 8] = \
            int(gen).to_bytes(8, "little")

    # -- fleet posture word (ISSUE 16) ---------------------------------

    def ring_posture(self) -> Tuple[int, float]:
        """(posture level, age seconds) — see BrokerClient.ring_posture."""
        level, ts = _read_posture_word(self._buf)
        return level, max(0.0, time.time() - ts)

    def publish_posture(self, level: int,
                        ttl_s: Optional[float] = None) -> bool:
        if ttl_s is None:
            ttl_s = _adm.cfg()["fleet_posture_ttl_s"]
        return _write_posture_word(self._buf, level, ttl_s)

    def bind_admission(self) -> None:
        """Parent-side mirror of BrokerClient.bind_admission: the device
        plane's controller publishes/consumes the same posture word as
        the wire workers."""
        _adm.CONTROLLER.set_posture_publisher(self.publish_posture)
        _adm.CONTROLLER.add_posture_source(self.ring_posture)

    def unbind_admission(self) -> None:
        _adm.CONTROLLER.clear_posture_publisher(self.publish_posture)
        _adm.CONTROLLER.remove_posture_source(self.ring_posture)

    # -- lifecycle -----------------------------------------------------

    def client_spec(self, worker_id: int,
                    cross_process: bool = True) -> Dict[str, Any]:
        """Picklable attach spec handed to one frontend worker."""
        return {"shm_name": self._shm.name, "sock_dir": self.sock_dir,
                "worker_id": int(worker_id),
                "cross_process": bool(cross_process)}

    def start(self) -> "DispatchBroker":
        self._run = True
        self._buf[_OFF_ALIVE] = 1
        self._thread = threading.Thread(
            target=self._loop, name="wire-broker", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._run = False
        self.unbind_admission()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        try:
            self._buf[_OFF_ALIVE] = 0
        except (ValueError, TypeError):
            pass  # shm already unlinked under us
        self._pool.shutdown(wait=False)
        try:
            self._sock.close()
            os.unlink(self._sock_path)
        except OSError:
            pass
        try:
            self._wake.close()
        except OSError:
            pass
        try:
            self._shm.close()
            self._shm.unlink()  # unlink also unregisters from the tracker
        except Exception:  # noqa: BLE001
            pass

    def queue_depth(self) -> int:
        """POSTED-but-unclaimed riders across every worker — registered
        with obs/resources as queue "broker" so the shared
        nornicdb_queue_depth gauge and the /readyz saturation check
        cover the cross-worker ring like any MicroBatcher."""
        lay = self._layout
        n = 0
        for w in range(lay.n_workers):
            for s in range(lay.slots):
                if self._buf[lay.slot_off(w, s)] == ST_POSTED:
                    n += 1
        return n

    # -- scan/claim/dispatch loop --------------------------------------

    def _scan_posted(self) -> List[Tuple[int, int]]:
        lay = self._layout
        out = []
        for w in range(lay.n_workers):
            for s in range(lay.slots):
                if self._buf[lay.slot_off(w, s)] == ST_POSTED:
                    out.append((w, s))
        return out

    def _loop(self) -> None:
        while self._run:
            try:
                self._sock.recv(64)
            except socket.timeout:
                pass
            except OSError:
                if not self._run:
                    return
            try:
                self._round()
            except Exception:  # noqa: BLE001 — the loop must survive
                _ERRS_C.labels("round_error").inc()

    def _round(self) -> None:
        posted = self._scan_posted()
        if not posted:
            return
        # MicroBatcher-style gather window: after a concurrent round,
        # give stragglers (clients mid-return from the last batch) one
        # short window to re-post before sealing this round's groups
        if (self._gather_window_s > 0.0 and self._last_round >= 2
                and len(posted) < min(self._last_round, self._max_batch)):
            time.sleep(self._gather_window_s)
            posted = self._scan_posted()
        lay = self._layout
        vec_groups: Dict[str, List[Tuple[int, int, dict]]] = {}
        calls: List[Tuple[int, int, dict]] = []
        now = time.time()
        claimed = 0
        for w, s in posted:
            off = lay.slot_off(w, s)
            hdr = _read_hdr(self._buf, off)
            op, req_len, k = hdr[1], hdr[5], hdr[7]
            if op == OP_VEC:
                head = struct.unpack_from("<HHI", self._buf,
                                          off + _HDR_SIZE)
                key_len, ctx_len, dims = head
                base = off + _HDR_SIZE + 8
                key = bytes(self._buf[base:base + key_len]
                            ).decode("utf-8")
                ctx = None
                if ctx_len:
                    ctx = _tracing.unpack_context(bytes(
                        self._buf[base + key_len:
                                  base + key_len + ctx_len]
                    ).decode("utf-8", errors="replace"))
                with self._busy_lock:
                    if self._vec_busy.get(key):
                        # leader/rider: a dispatch for this key is in
                        # flight — the rider stays POSTED and joins the
                        # NEXT batch, which drains every waiter at once
                        continue
                group = vec_groups.setdefault(key, [])
                if len(group) >= self._max_batch:
                    # group sealed at max_batch: the overflow rider
                    # stays POSTED (unclaimed) and rides the next
                    # round — claiming it here would orphan the slot
                    continue
                item = {"off": off, "k": k, "dims": dims,
                        "vec_off": base + key_len + ctx_len,
                        "t_post": hdr[8], "worker": w, "ctx": ctx,
                        # ring-carried admission context (ISSUE 15):
                        # the rider's absolute budget and lane survive
                        # the worker -> plane hop in the slot header
                        "deadline": hdr[13] or None,
                        "lane": _adm.LANE_FROM_CODE.get(
                            hdr[3], _adm.LANE_INTERACTIVE)}
                group.append((w, s, item))
            else:
                req = bytes(self._buf[off + _HDR_SIZE:
                                      off + _HDR_SIZE + req_len])
                calls.append((w, s, {"off": off, "req": req,
                                     "t_post": hdr[8], "worker": w,
                                     "deadline": hdr[13] or None,
                                     "lane": _adm.LANE_FROM_CODE.get(
                                         hdr[3],
                                         _adm.LANE_INTERACTIVE)}))
            self._buf[off] = ST_CLAIMED
            claimed += 1
        self._last_round = max(claimed, 1)
        for key, group in vec_groups.items():
            with self._busy_lock:
                self._vec_busy[key] = True
            _REQS_C.labels("vec").inc(len(group))
            self._pool.submit(self._run_vec_group, key, group, now)
        for w, s, item in calls:
            _REQS_C.labels("call").inc()
            self._pool.submit(self._run_call, w, s, item, now)

    # -- dispatch bodies -----------------------------------------------

    def _respond(self, off: int, hdr, ok: int, doc: Any,
                 t_claim: float, t0: float, t1: float, batch: int,
                 worker: int) -> None:
        lay = self._layout
        raw = pickle.dumps(doc, protocol=5)
        resp_kind = RESP_INLINE
        if len(raw) > lay.payload_bytes:
            # spill: the ring carries a path, the file carries the data
            path = os.path.join(
                self.sock_dir, f"spill-{uuid.uuid4().hex[:16]}.bin")
            with open(path, "wb") as f:
                f.write(raw)
            raw = path.encode("utf-8")
            resp_kind = RESP_SPILL
            _ERRS_C.labels("spill").inc()
        self._buf[off + _HDR_SIZE:off + _HDR_SIZE + len(raw)] = raw
        _HDR.pack_into(self._buf, off, ST_CLAIMED, hdr[1], ok, resp_kind,
                       hdr[4], hdr[5], len(raw), hdr[7],
                       hdr[8], t_claim, t0, t1, batch, 0)
        self._buf[off] = ST_DONE
        _ring_doorbell(
            self._wake, os.path.join(self.sock_dir, f"worker{worker}.sock"))

    def _shed_expired(self, item: dict, t_claim: float) -> None:
        """Respond to a rider whose budget expired before the plane
        could dispatch it: an explicit DeadlineExceeded (the worker
        maps it onto its surface's honest error), recorded under the
        rider's PROPAGATED trace so the ledger/journal shed record
        carries the originating trace id (ISSUE 15)."""
        hdr = _read_hdr(self._buf, item["off"])

        def _record():
            # the rider's propagated tenant binds the shed verdict
            # (ISSUE 18): the per-tenant shed/served counters on the
            # shared plane attribute to the flooder, not __other__
            with _tenant.scope_from_context(item.get("ctx")):
                _adm.record_deadline_miss("broker", "ring", item["lane"])

        if item.get("ctx"):
            with _tracing.propagated_trace("broker.shed", item["ctx"],
                                           surface="broker"):
                _record()
        else:
            _record()
        now = time.time()
        self._respond(item["off"], hdr, 0,
                      ("DeadlineExceeded",
                       "deadline budget expired on the ring", 504),
                      t_claim, now, now, 1, item["worker"])

    def _run_vec_group(self, key: str,
                       group: List[Tuple[int, int, dict]],
                       t_claim: float) -> None:
        try:
            now = time.time()
            live = []
            for w, s, item in group:
                if item.get("deadline") and now >= item["deadline"]:
                    self._shed_expired(item, t_claim)
                else:
                    live.append((w, s, item))
            group = live
            if not group:
                return
            b = len(group)
            _BATCH_H.observe(b)
            # zero-copy gather off the ring: each rider's embedding is
            # viewed in place; a dims mismatch fails the stack and
            # drops to the per-rider poison-isolation replay below
            rows = [np.frombuffer(self._buf, dtype=np.float32,
                                  count=item["dims"],
                                  offset=item["vec_off"])
                    for _w, _s, item in group]
            queries = np.stack(rows)
            k_max = pow2_bucket(max(max(item["k"] for _w, _s, item
                                        in group), 1))
            bucket = pow2_bucket(b)
            if bucket != b:
                pad = np.broadcast_to(queries[0],
                                      (bucket - b,) + queries.shape[1:])
                queries = np.concatenate([queries, pad], axis=0)
            t0 = time.time()
            _audit.consume_batch_tier()
            _audit.consume_fleet_node()
            # the LEADER's trace context (first rider that carried one)
            # binds the plane-side dispatch: degrade records and spans
            # minted inside join the leader's trace — the MicroBatcher
            # precedent (the leader's dispatch story is the batch's)
            lead_ctx = next((item["ctx"] for _w, _s, item in group
                             if item.get("ctx")), None)
            # ring-carried admission context binds the dispatch: the
            # group's tightest budget and best lane govern any nested
            # coalescing below the plane entry (ISSUE 15)
            dls = [item["deadline"] for _w, _s, item in group
                   if item.get("deadline")]
            group_dl = min(dls) if dls else None
            group_lane = min(
                (item["lane"] for _w, _s, item in group),
                key=lambda ln: _adm.lane_rank(ln))
            # the riders' tenant mix (propagated in each slot's packed
            # trace ctx) binds the dispatch AND the serve recording:
            # padded-dispatch cost splits across riders by tenant and
            # the n=b serve distributes the same way (ISSUE 18)
            rider_tenants = [(item.get("ctx") or {}).get("tenant")
                             for _w, _s, item in group]
            with _tenant.batch_scope(rider_tenants):
                # ISSUE 20: cost priced below this seam credits the
                # broker_vec serving kind, and the sampled bracket pins
                # t1 to device completion before record_dispatch
                with _adm.deadline_scope(group_dl), \
                        _adm.lane_scope(group_lane), \
                        _device.dispatch_scope("broker_vec"):
                    # the plane prices the PADDED batch; the padding-
                    # efficiency join needs the real rider count
                    _device.note_real_rows(float(b))
                    if lead_ctx is not None:
                        attrs = {"key": key, "batch": b,
                                 "surface": "broker", "lane": group_lane}
                        if group_dl is not None:
                            attrs["deadline_ms"] = round(
                                (group_dl - t0) * 1e3, 1)
                        with _tracing.propagated_trace(
                                "broker.vec", lead_ctx, **attrs):
                            results = self._vec_dispatch(key, queries,
                                                         k_max)
                    else:
                        results = self._vec_dispatch(key, queries, k_max)
                    _device.maybe_sync(results)
                t1 = time.time()
                tier = _audit.consume_batch_tier()
                # fleet-routed reads stamp the chosen node (ISSUE 13):
                # the FleetRouter notes which replica served this
                # thread's dispatch; the stamp rides every response
                node = _audit.consume_fleet_node()
                record_dispatch("broker_vec", bucket, k_max, t1 - t0)
                # rider-accurate tier attribution (ISSUE 10) for the
                # ring path: the direct batched dispatch bypasses a
                # MicroBatcher so the broker, as the standing leader,
                # records one serve per rider on the shared plane —
                # each worker's merged scrape then carries the tier
                # mix exactly once
                _audit.record_served("vector", tier or "host", n=b)
            for idx, (_w, _s, item) in enumerate(group):
                hdr = _read_hdr(self._buf, item["off"])
                hits = results[idx]
                k = item["k"]
                doc = {"hits": list(hits[:k] if k < k_max else hits),
                       "tier": tier}
                if node:
                    doc["node"] = node
                if item.get("ctx"):
                    doc["spans"] = _vec_span_docs(
                        item["t_post"], t_claim, t0, t1, b, tier, node,
                        deadline=item.get("deadline"),
                        lane=item.get("lane"))
                self._respond(item["off"], hdr, 1, doc, t_claim, t0, t1,
                              b, item["worker"])
        except Exception as exc:  # noqa: BLE001 — poison isolation
            _ERRS_C.labels("vec_dispatch").inc()
            # replay each rider alone so only the poisoned request
            # observes its error (MicroBatcher discipline)
            for _w, _s, item in group:
                if item.get("deadline") \
                        and time.time() >= item["deadline"]:
                    # the failed batch consumed this rider's budget
                    self._shed_expired(item, t_claim)
                    continue
                hdr = _read_hdr(self._buf, item["off"])
                try:
                    q1 = np.frombuffer(
                        self._buf, dtype=np.float32, count=item["dims"],
                        offset=item["vec_off"]).reshape(1, -1)
                    kb = pow2_bucket(max(item["k"], 1))
                    t0 = time.time()
                    _audit.consume_batch_tier()
                    _audit.consume_fleet_node()
                    with _tenant.batch_scope(
                            [(item.get("ctx") or {}).get("tenant")]):
                        if item.get("ctx") is not None:
                            with _tracing.propagated_trace(
                                    "broker.vec", item["ctx"], key=key,
                                    batch=1, surface="broker"):
                                res = self._vec_dispatch(
                                    key, np.array(q1), kb)[0]
                        else:
                            res = self._vec_dispatch(key, np.array(q1),
                                                     kb)[0]
                        t1 = time.time()
                        tier = _audit.consume_batch_tier()
                        node = _audit.consume_fleet_node()
                        _audit.record_served("vector", tier or "host")
                    doc = {"hits": list(res[:item["k"]]), "tier": tier}
                    if node:
                        doc["node"] = node
                    if item.get("ctx"):
                        doc["spans"] = _vec_span_docs(
                            item["t_post"], t_claim, t0, t1, 1, tier,
                            node, deadline=item.get("deadline"),
                            lane=item.get("lane"))
                    self._respond(item["off"], hdr, 1, doc, t_claim,
                                  t0, t1, 1, item["worker"])
                except Exception as single:  # noqa: BLE001
                    self._respond(
                        item["off"], hdr, 0,
                        _remote_error_doc(single), t_claim,
                        time.time(), time.time(), 1, item["worker"])
            del exc
        finally:
            with self._busy_lock:
                self._vec_busy[key] = False

    def _run_call(self, w: int, s: int, item: dict,
                  t_claim: float) -> None:
        off = item["off"]
        hdr = _read_hdr(self._buf, off)
        try:
            req = pickle.loads(item["req"])
            target_name, method, args, kwargs = req[:4]
            ctx = req[4] if len(req) > 4 else None
            if item.get("deadline") and time.time() >= item["deadline"]:
                # rider budget spent before the op could run (ISSUE 15)
                item.setdefault("ctx", ctx)
                self._shed_expired(item, t_claim)
                return
            obj = self._targets[target_name]
            fn = obj
            for part in method.split("."):
                fn = getattr(fn, part)
            t0 = time.time()
            _audit.set_last_served(None)
            pspan = None
            with _audit.collect_degrades() as degrades, \
                    _adm.deadline_scope(item.get("deadline")), \
                    _adm.lane_scope(item.get("lane")
                                    or _adm.LANE_INTERACTIVE), \
                    _tenant.scope_from_context(ctx):
                # the ring-carried admission context binds the op: a
                # nested MicroBatcher/convoy ride below inherits the
                # rider's budget and lane (ISSUE 15)
                if ctx is not None and ctx.get("trace_id"):
                    # PROPAGATED trace (ISSUE 13): the op executes
                    # under the rider's trace id, so degrade records
                    # minted here carry it across the boundary, and
                    # plane-side child spans export back in meta. A
                    # tenant-only ctx (untraced rider) binds the scope
                    # above but must NOT mint spans — untraced in,
                    # untraced out
                    attrs = {"target": target_name, "op": method,
                             "surface": "broker"}
                    if item.get("deadline"):
                        attrs["deadline_ms"] = round(
                            (item["deadline"] - t0) * 1e3, 1)
                    with _tracing.propagated_trace(
                            "plane.call", ctx, **attrs) as pspan:
                        result = fn(*args, **kwargs)
                else:
                    result = fn(*args, **kwargs)
            t1 = time.time()
            meta = {"tier": _audit.last_served(),
                    "degrades": list(degrades)}
            if isinstance(pspan, _tracing.Span):
                # telemetry disabled plane-side returns a _NullSpan —
                # serve untraced rather than fail the op on export
                meta["spans"] = [_tracing.export_span(pspan)]
            self._respond(off, hdr, 1, {"result": result, "meta": meta},
                          t_claim, t0, t1, 1, item["worker"])
        except Exception as exc:  # noqa: BLE001 — delivered per-request
            _ERRS_C.labels("call_error").inc()
            self._respond(off, hdr, 0, _remote_error_doc(exc), t_claim,
                          time.time(), time.time(), 1, item["worker"])


def _vec_span_docs(t_post: float, t_claim: float, t0: float, t1: float,
                   batch: int, tier: Optional[str],
                   node: Optional[str],
                   deadline: Optional[float] = None,
                   lane: Optional[str] = None) -> List[Dict[str, Any]]:
    """Plane-side span records for ONE OP_VEC rider — the exported
    tree the worker grafts into its live trace so `/admin/traces` on
    the ingress worker shows the full wire -> ring -> coalesce ->
    device.dispatch chain with original timing. The ring.claim span
    carries the rider's remaining budget AT the ring crossing and the
    dispatch span its remaining budget AT the dispatch decision
    (ISSUE 15 acceptance: the deadline is visible at every hop)."""
    claim_attrs: Dict[str, Any] = {"surface": "broker"}
    dispatch_attrs: Dict[str, Any] = {"surface": "broker",
                                      "batch": batch,
                                      "kind": "broker_vec"}
    if lane:
        claim_attrs["lane"] = lane
    if deadline:
        claim_attrs["deadline_ms"] = round((deadline - t_post) * 1e3, 1)
        dispatch_attrs["deadline_ms"] = round((deadline - t0) * 1e3, 1)
    if tier:
        dispatch_attrs["tier"] = tier
    if node:
        dispatch_attrs["fleet_node"] = node
    return [
        {"name": "ring.claim", "t0": t_post, "t1": t_claim,
         "attrs": claim_attrs, "children": []},
        {"name": "plane.coalesce", "t0": t_claim, "t1": t0,
         "attrs": {"surface": "broker"}, "children": []},
        {"name": "device.dispatch", "t0": t0, "t1": t1,
         "attrs": dispatch_attrs, "children": []},
    ]


def _remote_error_doc(exc: Exception) -> Tuple[str, str, int]:
    return (type(exc).__name__, str(exc),
            int(getattr(exc, "status", 400) or 400))
