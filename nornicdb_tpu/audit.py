"""Append-only compliance audit log (GDPR/HIPAA/FISMA/SOC2).

Reference: pkg/audit/audit.go:1-30 — JSON lines, append-only, retention
window, queryable. Each entry is one JSON object per line; the file is
only ever appended (compliance requirement), retention rewrites
atomically.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, Iterator, List, Optional

# event categories (reference: audit.go event types)
AUTH = "auth"
DATA_READ = "data_read"
DATA_WRITE = "data_write"
DATA_DELETE = "data_delete"
ADMIN_ACTION = "admin"
GDPR = "gdpr"


@dataclass
class AuditEvent:
    timestamp_ms: int
    category: str
    action: str
    actor: str = ""
    database: str = ""
    target: str = ""
    success: bool = True
    details: Dict[str, Any] = field(default_factory=dict)


class AuditLog:
    """Thread-safe append-only JSONL audit log."""

    def __init__(self, path: Optional[str] = None, enabled: bool = True,
                 retention_days: int = 0):
        self.path = path
        self.enabled = enabled
        self.retention_days = retention_days
        self._lock = threading.Lock()
        self._mem: List[AuditEvent] = []  # in-memory ring when no path
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def record(self, category: str, action: str, actor: str = "",
               database: str = "", target: str = "", success: bool = True,
               **details: Any) -> Optional[AuditEvent]:
        if not self.enabled:
            return None
        ev = AuditEvent(
            timestamp_ms=int(time.time() * 1000), category=category,
            action=action, actor=actor, database=database, target=target,
            success=success, details=details,
        )
        line = json.dumps(asdict(ev), separators=(",", ":"))
        with self._lock:
            if self.path:
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line + "\n")
            else:
                self._mem.append(ev)
                if len(self._mem) > 100_000:
                    del self._mem[:50_000]
        return ev

    def events(self, category: Optional[str] = None, actor: Optional[str] = None,
               since_ms: int = 0) -> Iterator[AuditEvent]:
        for ev in self._iter_all():
            if category and ev.category != category:
                continue
            if actor and ev.actor != actor:
                continue
            if since_ms and ev.timestamp_ms < since_ms:
                continue
            yield ev

    def _iter_all(self) -> Iterator[AuditEvent]:
        if self.path and os.path.exists(self.path):
            with open(self.path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        d = json.loads(line)
                        yield AuditEvent(**d)
                    except (json.JSONDecodeError, TypeError):
                        continue  # a torn tail line must not kill queries
        else:
            with self._lock:
                batch = list(self._mem)
            yield from batch

    def apply_retention(self, now_ms: Optional[int] = None) -> int:
        """Drop entries older than the retention window. Returns removed
        count. Atomic rewrite (tmp + rename)."""
        if not self.retention_days:
            return 0
        cutoff = (now_ms or int(time.time() * 1000)) - self.retention_days * 86_400_000
        removed = 0
        if self.path and os.path.exists(self.path):
            keep: List[str] = []
            with self._lock:
                with open(self.path, "r", encoding="utf-8") as f:
                    for line in f:
                        try:
                            if json.loads(line).get("timestamp_ms", 0) >= cutoff:
                                keep.append(line)
                            else:
                                removed += 1
                        except json.JSONDecodeError:
                            removed += 1
                tmp = self.path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    f.writelines(keep)
                os.replace(tmp, self.path)
        else:
            with self._lock:
                before = len(self._mem)
                self._mem = [e for e in self._mem if e.timestamp_ms >= cutoff]
                removed = before - len(self._mem)
        return removed
