"""nornicdb_tpu — a TPU-native graph database framework.

A brand-new framework with the capabilities of NornicDB (Neo4j-compatible
graph store + hybrid BM25/vector search), designed TPU-first:

- Storage: composable engine decorators (Memory/Disk -> WAL -> Async ->
  Namespaced), mirroring the contract of the reference's storage layer
  (reference: pkg/storage/types.go:363-422).
- Device data plane: JAX/XLA/Pallas kernels over capacity-padded
  HBM-resident embedding matrices (cosine top-k, k-means, graph
  aggregations) replacing the reference's Metal/CUDA/Vulkan/OpenCL
  backends (reference: pkg/gpu).
- Search: BM25 + brute-force/HNSW vector search + RRF hybrid fusion
  (reference: pkg/search).
- Query: Cypher engine with streaming fast paths (reference: pkg/cypher).
- Models: flax bge-m3-style encoder served with jit/pjit over a device
  mesh (reference: pkg/embed + pkg/localllm, llama.cpp path).
"""

__version__ = "0.1.0"

from nornicdb_tpu.db import DB, open  # noqa: F401,E402  (public facade)
