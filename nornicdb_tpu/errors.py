"""Common error types (reference: pkg/storage/types.go error vars)."""


class NornicError(Exception):
    """Base class for all nornicdb_tpu errors."""


class NotFoundError(NornicError, KeyError):
    """Node or edge not found."""


class AlreadyExistsError(NornicError):
    """Node or edge with this ID already exists."""


class ConstraintViolationError(NornicError):
    """Schema constraint violated."""


class ClosedError(NornicError):
    """Operation on a closed engine/DB."""


class CypherSyntaxError(NornicError):
    """Cypher query failed to parse."""


class CypherRuntimeError(NornicError):
    """Cypher query failed during execution."""


class WALCorruptionError(NornicError):
    """WAL segment failed checksum/parse validation."""
