"""Auto-relationship inference engine.

Reference: pkg/inference — Engine (inference.go:219), OnStoreBestOfChunks
(:544, similarity via injected vector search), OnAccess co-access windows
(:778), SuggestTransitive (:835), cooldown table (cooldown.go), evidence
buffer (evidence.go). Suggested edges are created best-effort with typed
provenance properties, exactly like the reference's Store() wiring
(db.go:1997-2016).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from nornicdb_tpu.storage.types import Edge, Engine, Node

SIMILAR_TO = "SIMILAR_TO"
CO_ACCESSED_WITH = "CO_ACCESSED_WITH"
RELATES_TO = "RELATES_TO"


@dataclass
class Suggestion:
    from_id: str
    to_id: str
    rel_type: str
    confidence: float
    reason: str


class InferenceEngine:
    def __init__(
        self,
        storage: Engine,
        search_service=None,
        similarity_threshold: float = 0.75,
        max_links_per_store: int = 3,
        cooldown_s: float = 300.0,
        min_confidence: float = 0.5,
    ):
        self.storage = storage
        self.search = search_service
        self.similarity_threshold = similarity_threshold
        self.max_links_per_store = max_links_per_store
        self.cooldown_s = cooldown_s
        self.min_confidence = min_confidence
        self._cooldown: Dict[Tuple[str, str], float] = {}
        self._lock = threading.Lock()
        self.created_count = 0

    # -- cooldown (reference: cooldown.go) --------------------------------

    def _on_cooldown(self, a: str, b: str) -> bool:
        key = (min(a, b), max(a, b))
        with self._lock:
            t = self._cooldown.get(key)
            if t is not None and time.time() - t < self.cooldown_s:
                return True
            self._cooldown[key] = time.time()
            return False

    def _already_linked(self, a: str, b: str) -> bool:
        for e in self.storage.get_node_edges(a):
            if b in (e.start_node, e.end_node):
                return True
        return False

    # -- on store: similarity links (reference: OnStoreBestOfChunks :544) --

    def on_store(self, node: Node) -> List[Suggestion]:
        """Suggest (and create) SIMILAR_TO edges for a newly stored node.
        Uses best-of-chunks similarity when chunk embeddings exist."""
        if self.search is None:
            return []
        query_vectors: List[List[float]] = []
        if node.chunk_embeddings:
            query_vectors = list(node.chunk_embeddings)
        elif node.embedding is not None:
            query_vectors = [node.embedding]
        if not query_vectors:
            return []
        # best-of-chunks: keep each candidate's best similarity over chunks
        best: Dict[str, float] = {}
        for qv in query_vectors:
            for nid, score in self.search.vector_search_candidates(
                qv, k=self.max_links_per_store * 3
            ):
                if nid == node.id:
                    continue
                if score > best.get(nid, -1.0):
                    best[nid] = score
        suggestions: List[Suggestion] = []
        for nid, score in sorted(best.items(), key=lambda kv: -kv[1]):
            if len(suggestions) >= self.max_links_per_store:
                break
            if score < self.similarity_threshold:
                continue
            if self._on_cooldown(node.id, nid) or self._already_linked(node.id, nid):
                continue
            sug = Suggestion(node.id, nid, SIMILAR_TO, float(score), "similarity")
            if self._create(sug):
                suggestions.append(sug)
        return suggestions

    # -- on access: co-access links (reference: OnAccess :778) --------------

    def on_access(self, temporal_tracker, node_id: str, min_count: int = 3) -> List[Suggestion]:
        out: List[Suggestion] = []
        for other, count in temporal_tracker.co_accessed(node_id):
            if count < min_count:
                continue
            if self._on_cooldown(node_id, other) or self._already_linked(node_id, other):
                continue
            conf = min(0.5 + count / 20.0, 0.95)
            sug = Suggestion(node_id, other, CO_ACCESSED_WITH, conf, "co-access")
            if self._create(sug):
                out.append(sug)
        return out

    # -- transitive (reference: SuggestTransitive :835) ---------------------

    def suggest_transitive(self, node_id: str, limit: int = 5) -> List[Suggestion]:
        """A-[SIMILAR]->B-[SIMILAR]->C implies A~C (not auto-created —
        lower confidence; the caller decides)."""
        out: List[Suggestion] = []
        first_hop = set()
        for e in self.storage.get_node_edges(node_id):
            other = e.end_node if e.start_node == node_id else e.start_node
            if e.type in (SIMILAR_TO, RELATES_TO):
                first_hop.add(other)
        seen = set(first_hop) | {node_id}
        for mid in first_hop:
            for e in self.storage.get_node_edges(mid):
                far = e.end_node if e.start_node == mid else e.start_node
                if far in seen or e.type not in (SIMILAR_TO, RELATES_TO):
                    continue
                seen.add(far)
                out.append(
                    Suggestion(node_id, far, RELATES_TO, 0.4, f"transitive via {mid}")
                )
                if len(out) >= limit:
                    return out
        return out

    # -- edge creation ------------------------------------------------------

    def _create(self, sug: Suggestion) -> bool:
        if sug.confidence < self.min_confidence:
            return False
        edge = Edge(
            id=str(uuid.uuid4()),
            type=sug.rel_type,
            start_node=sug.from_id,
            end_node=sug.to_id,
            properties={
                "confidence": sug.confidence,
                "inferred": True,
                "reason": sug.reason,
            },
        )
        try:
            self.storage.create_edge(edge)
            self.created_count += 1
            return True
        except KeyError:
            return False
