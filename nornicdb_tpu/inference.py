"""Auto-relationship inference engine.

Reference: pkg/inference — Engine (inference.go:219), OnStoreBestOfChunks
(:544, similarity via injected vector search), OnAccess co-access windows
(:778), SuggestTransitive (:835), cooldown table (cooldown.go), evidence
buffer (evidence.go). Suggested edges are created best-effort with typed
provenance properties, exactly like the reference's Store() wiring
(db.go:1997-2016).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from nornicdb_tpu.storage.types import Edge, Engine, Node

SIMILAR_TO = "SIMILAR_TO"
CO_ACCESSED_WITH = "CO_ACCESSED_WITH"
RELATES_TO = "RELATES_TO"


@dataclass
class Suggestion:
    from_id: str
    to_id: str
    rel_type: str
    confidence: float
    reason: str


class InferenceEngine:
    def __init__(
        self,
        storage: Engine,
        search_service=None,
        similarity_threshold: float = 0.75,
        max_links_per_store: int = 3,
        cooldown_s: float = 300.0,
        min_confidence: float = 0.5,
        evidence: Optional["EvidenceBuffer"] = None,
        qc: Optional["HeimdallQC"] = None,
    ):
        self.storage = storage
        self.search = search_service
        self.similarity_threshold = similarity_threshold
        self.max_links_per_store = max_links_per_store
        self.cooldown_s = cooldown_s
        self.min_confidence = min_confidence
        # optional gates ahead of edge creation (reference: evidence.go
        # buffer + heimdall_qc.go batch review)
        self.evidence = evidence
        self.qc = qc
        self._cooldown: Dict[Tuple[str, str], float] = {}
        self._lock = threading.Lock()
        self.created_count = 0
        # ISSUE 19: a BackgroundDevicePlane attaches itself here; when
        # present, on_store_batch rides its background-lane candidate
        # generation instead of per-node interactive-path searches
        self.device_plane = None

    # -- cooldown (reference: cooldown.go) --------------------------------

    def _on_cooldown(self, a: str, b: str) -> bool:
        """Check AND arm the cooldown (creation paths)."""
        key = (min(a, b), max(a, b))
        with self._lock:
            t = self._cooldown.get(key)
            if t is not None and time.time() - t < self.cooldown_s:
                return True
            self._cooldown[key] = time.time()
            return False

    def _peek_cooldown(self, a: str, b: str) -> bool:
        """Cooldown state without arming it (pure)."""
        key = (min(a, b), max(a, b))
        with self._lock:
            t = self._cooldown.get(key)
            return t is not None and time.time() - t < self.cooldown_s

    def _already_linked(self, a: str, b: str) -> bool:
        for e in self.storage.get_node_edges(a):
            if b in (e.start_node, e.end_node):
                return True
        return False

    # -- on store: similarity links (reference: OnStoreBestOfChunks :544) --

    def on_store(self, node: Node) -> List[Suggestion]:
        """Suggest (and create) SIMILAR_TO edges for a newly stored node.
        Uses best-of-chunks similarity when chunk embeddings exist."""
        if self.search is None:
            return []
        query_vectors: List[List[float]] = []
        if node.chunk_embeddings:
            query_vectors = list(node.chunk_embeddings)
        elif node.embedding is not None:
            query_vectors = [node.embedding]
        if not query_vectors:
            return []
        # best-of-chunks: keep each candidate's best similarity over chunks
        best: Dict[str, float] = {}
        for qv in query_vectors:
            for nid, score in self.search.vector_search_candidates(
                qv, k=self.max_links_per_store * 3
            ):
                if nid == node.id:
                    continue
                if score > best.get(nid, -1.0):
                    best[nid] = score
        candidates: List[Suggestion] = []
        for nid, score in sorted(best.items(), key=lambda kv: -kv[1]):
            if len(candidates) >= self.max_links_per_store:
                break
            if score < self.similarity_threshold:
                continue
            if self._on_cooldown(node.id, nid) or self._already_linked(node.id, nid):
                continue
            candidates.append(
                Suggestion(node.id, nid, SIMILAR_TO, float(score), "similarity"))
        if self.qc is not None and candidates:
            candidates = self.qc.review_batch(self.storage, candidates)
        suggestions: List[Suggestion] = []
        for sug in candidates:
            if self._create(sug):
                suggestions.append(sug)
        return suggestions

    def on_store_batch(self, nodes: List[Node]) -> Dict[str, List[Suggestion]]:
        """Batched similarity inference (ISSUE 19): candidate generation
        for the WHOLE batch of newly stored nodes rides the background
        device plane — one background-lane pass through the existing
        quantized ANN tiers — then each node runs the same
        threshold/cooldown/QC/create pipeline as :meth:`on_store`.
        Parity with the per-node path holds by construction (same
        search service, same filters); without a plane, or when the
        plane degrades, the per-node path serves."""
        if self.search is None:
            return {n.id: [] for n in nodes}
        plane = self.device_plane
        per_node: Dict[str, List[List[float]]] = {}
        items: List[Tuple[str, List[float]]] = []
        for node in nodes:
            if node.chunk_embeddings:
                qvs = list(node.chunk_embeddings)
            elif node.embedding is not None:
                qvs = [node.embedding]
            else:
                qvs = []
            per_node[node.id] = qvs
            for j, qv in enumerate(qvs):
                items.append((f"{node.id}\x00{j}", qv))
        cands = None
        if plane is not None and items:
            cands = plane.infer_candidates(
                items, k=self.max_links_per_store * 3)
        if cands is None:
            return {n.id: self.on_store(n) for n in nodes}
        out: Dict[str, List[Suggestion]] = {}
        for node in nodes:
            best: Dict[str, float] = {}
            for j in range(len(per_node[node.id])):
                for nid, score in cands.get(f"{node.id}\x00{j}", []):
                    if nid == node.id:
                        continue
                    if score > best.get(nid, -1.0):
                        best[nid] = score
            candidates: List[Suggestion] = []
            for nid, score in sorted(best.items(), key=lambda kv: -kv[1]):
                if len(candidates) >= self.max_links_per_store:
                    break
                if score < self.similarity_threshold:
                    continue
                if self._on_cooldown(node.id, nid) \
                        or self._already_linked(node.id, nid):
                    continue
                candidates.append(Suggestion(
                    node.id, nid, SIMILAR_TO, float(score), "similarity"))
            if self.qc is not None and candidates:
                candidates = self.qc.review_batch(self.storage, candidates)
            out[node.id] = [s for s in candidates if self._create(s)]
        return out

    # -- on access: co-access links (reference: OnAccess :778) --------------

    def on_access(self, temporal_tracker, node_id: str, min_count: int = 3) -> List[Suggestion]:
        out: List[Suggestion] = []
        for other, count in temporal_tracker.co_accessed(node_id):
            if count < min_count:
                continue
            conf = min(0.5 + count / 20.0, 0.95)
            if self._already_linked(node_id, other):
                continue
            if self.evidence is not None:
                # buffer the signal; only a threshold crossing proceeds.
                # TemporalTracker.session is a property returning
                # (session_id, nodes); tolerate method-style trackers too.
                sess = getattr(temporal_tracker, "session", None)
                if callable(sess):
                    sess = sess()
                session = str(sess[0]) if isinstance(sess, tuple) else "s0"
                ready = self.evidence.add(node_id, other, CO_ACCESSED_WITH,
                                          conf, signal="coaccess",
                                          session=session)
                if ready is None:
                    continue
                if self._peek_cooldown(node_id, other):
                    # crossing landed inside a cooldown window: keep the
                    # accumulated evidence instead of dropping it
                    self.evidence.restore(ready)
                    continue
                conf = min(0.95, ready.score_avg)
            if self._on_cooldown(node_id, other):
                continue
            sug = Suggestion(node_id, other, CO_ACCESSED_WITH, conf, "co-access")
            if self._create(sug):
                out.append(sug)
        return out

    # -- transitive (reference: SuggestTransitive :835) ---------------------

    def suggest_transitive(self, node_id: str, limit: int = 5) -> List[Suggestion]:
        """A-[SIMILAR]->B-[SIMILAR]->C implies A~C (not auto-created —
        lower confidence; the caller decides)."""
        out: List[Suggestion] = []
        first_hop = set()
        for e in self.storage.get_node_edges(node_id):
            other = e.end_node if e.start_node == node_id else e.start_node
            if e.type in (SIMILAR_TO, RELATES_TO):
                first_hop.add(other)
        seen = set(first_hop) | {node_id}
        for mid in first_hop:
            for e in self.storage.get_node_edges(mid):
                far = e.end_node if e.start_node == mid else e.start_node
                if far in seen or e.type not in (SIMILAR_TO, RELATES_TO):
                    continue
                seen.add(far)
                out.append(
                    Suggestion(node_id, far, RELATES_TO, 0.4, f"transitive via {mid}")
                )
                if len(out) >= limit:
                    return out
        return out

    # -- edge creation ------------------------------------------------------

    def _create(self, sug: Suggestion) -> bool:
        if sug.confidence < self.min_confidence:
            return False
        edge = Edge(
            id=str(uuid.uuid4()),
            type=sug.rel_type,
            start_node=sug.from_id,
            end_node=sug.to_id,
            properties={
                "confidence": sug.confidence,
                "inferred": True,
                "reason": sug.reason,
            },
        )
        try:
            self.storage.create_edge(edge)
            self.created_count += 1
            return True
        except KeyError:
            return False


# -- evidence buffer ------------------------------------------------------


@dataclass
class EvidenceThreshold:
    """When accumulated evidence is sufficient to materialize an edge
    (reference: evidence.go:141-147)."""

    min_count: int = 3
    min_score: float = 1.5
    min_sessions: int = 1
    max_age_s: float = 7 * 86400.0


@dataclass
class Evidence:
    """Accumulated signals for one potential edge
    (reference: evidence.go:128-139)."""

    src: str
    dst: str
    label: str
    count: int = 0
    score_sum: float = 0.0
    first_ts: float = 0.0
    last_ts: float = 0.0
    sessions: set = None  # type: ignore[assignment]
    signals: list = None  # type: ignore[assignment]

    @property
    def score_avg(self) -> float:
        return self.score_sum / self.count if self.count else 0.0


class EvidenceBuffer:
    """Accumulates relationship signals before materialization, so a
    single weak signal never creates an edge (reference:
    evidence.go:148-200 EvidenceBuffer; wired ahead of edge creation the
    way the reference buffers Auto-TLP suggestions)."""

    def __init__(self, thresholds: Optional[Dict[str, EvidenceThreshold]] = None,
                 default: Optional[EvidenceThreshold] = None):
        self._entries: Dict[Tuple[str, str, str], Evidence] = {}
        self._thresholds = thresholds or {}
        self._default = default or EvidenceThreshold()
        self._lock = threading.Lock()
        self.total_added = 0
        self.total_materialized = 0
        self.total_expired = 0

    def set_threshold(self, label: str, threshold: EvidenceThreshold) -> None:
        with self._lock:
            self._thresholds[label] = threshold

    def _threshold(self, label: str) -> EvidenceThreshold:
        return self._thresholds.get(label, self._default)

    def add(self, src: str, dst: str, label: str, score: float,
            signal: str = "similarity", session: str = "",
            at: Optional[float] = None) -> Optional[Evidence]:
        """Record one signal; returns the Evidence iff it just crossed
        its threshold (caller materializes the edge)."""
        at = time.time() if at is None else at
        key = (src, dst, label)
        th = self._threshold(label)
        with self._lock:
            ev = self._entries.get(key)
            if ev is not None and at - ev.first_ts > th.max_age_s:
                del self._entries[key]
                self.total_expired += 1
                ev = None
            if ev is None:
                ev = Evidence(src=src, dst=dst, label=label, first_ts=at,
                              last_ts=at, sessions=set(), signals=[])
                self._entries[key] = ev
            before = self._sufficient(ev, th)
            ev.count += 1
            ev.score_sum += score
            ev.last_ts = at
            if session:
                ev.sessions.add(session)
            if signal not in ev.signals:
                ev.signals.append(signal)
            self.total_added += 1
            if not before and self._sufficient(ev, th):
                self.total_materialized += 1
                del self._entries[key]
                return ev
            return None

    def restore(self, ev: Evidence) -> None:
        """Put crossed-but-unconsumed evidence back (e.g. the edge
        creation was deferred by a cooldown)."""
        with self._lock:
            self._entries[(ev.src, ev.dst, ev.label)] = ev
            self.total_materialized -= 1

    @staticmethod
    def _sufficient(ev: Evidence, th: EvidenceThreshold) -> bool:
        return (ev.count >= th.min_count
                and ev.score_sum >= th.min_score
                and len(ev.sessions or ()) >= th.min_sessions)

    def expire(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        with self._lock:
            doomed = [
                k for k, ev in self._entries.items()
                if now - ev.first_ts > self._threshold(ev.label).max_age_s
            ]
            for k in doomed:
                del self._entries[k]
            self.total_expired += len(doomed)
            return len(doomed)

    def stats(self) -> Dict[str, float]:
        with self._lock:
            rate = (self.total_materialized / self.total_added
                    if self.total_added else 0.0)
            return {
                "entries": len(self._entries),
                "added": self.total_added,
                "materialized": self.total_materialized,
                "expired": self.total_expired,
                "materialize_rate": round(rate, 4),
            }


# -- Heimdall QC ----------------------------------------------------------


class HeimdallQC:
    """SLM review of suggested edges before creation (reference:
    heimdall_qc.go:196 HeimdallQC.ReviewBatch — approve/reject/retype).

    ``generate_fn(prompt) -> str`` is any Heimdall generator (the JAX
    decoder, an HTTP backend, or a stub). The prompt asks for one verdict
    letter per suggestion; unparseable output fails open (all approved),
    matching the reference's fail-open posture for QC outages."""

    def __init__(self, generate_fn, min_confidence_to_skip: float = 0.9,
                 cache_ttl_s: float = 300.0):
        self.generate = generate_fn
        self.min_confidence_to_skip = min_confidence_to_skip
        self.cache_ttl_s = cache_ttl_s
        self._cache: Dict[str, Tuple[float, List[bool]]] = {}
        self._lock = threading.Lock()
        self.batches = 0
        self.suggestions_in = 0
        self.suggestions_out = 0
        self.cache_hits = 0
        self.errors = 0

    def _describe(self, storage: Engine, node_id: str) -> str:
        try:
            n = storage.get_node(node_id)
        except KeyError:
            return node_id
        content = str(n.properties.get("content", ""))[:80]
        return f"{'/'.join(n.labels)}: {content or node_id}"

    def review_batch(self, storage: Engine,
                     suggestions: List[Suggestion]) -> List[Suggestion]:
        """Returns the approved subset. High-confidence suggestions skip
        review; the rest are judged in one generation call.

        Runs on the BACKGROUND admission lane (ISSUE 15): inference
        review (a generation call + storage reads) must never convoy
        interactive traffic through shared machinery."""
        from nornicdb_tpu import admission as _adm

        with _adm.lane_scope(_adm.LANE_BACKGROUND):
            return self._review_batch_background(storage, suggestions)

    def _review_batch_background(
            self, storage: Engine,
            suggestions: List[Suggestion]) -> List[Suggestion]:
        self.batches += 1
        self.suggestions_in += len(suggestions)
        skip = [s for s in suggestions
                if s.confidence >= self.min_confidence_to_skip]
        to_review = [s for s in suggestions
                     if s.confidence < self.min_confidence_to_skip]
        if not to_review:
            self.suggestions_out += len(skip)
            return skip
        lines = [
            f"{i + 1}. ({self._describe(storage, s.from_id)}) "
            f"-[{s.rel_type}]-> ({self._describe(storage, s.to_id)}) "
            f"confidence={s.confidence:.2f} reason={s.reason}"
            for i, s in enumerate(to_review)
        ]
        prompt = (
            "Review proposed graph relationships. Answer with one letter "
            "per line, Y to approve or N to reject:\n" + "\n".join(lines)
            + "\nAnswers:"
        )
        key = prompt
        now = time.time()
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None and now - hit[0] < self.cache_ttl_s:
                self.cache_hits += 1
                verdicts = hit[1]
            else:
                verdicts = None
        if verdicts is None:
            try:
                reply = self.generate(prompt)
                # one verdict per line: first standalone Y/N token of each
                # non-empty line (prose like an echoed "Answers:" header
                # must not contribute stray letters)
                letters = []
                for line in reply.splitlines():
                    token = line.strip().upper()[:1]
                    if token in ("Y", "N"):
                        letters.append(token)
                if len(letters) < len(to_review):
                    raise ValueError("short verdict")
                verdicts = [c == "Y" for c in letters[: len(to_review)]]
                with self._lock:
                    if len(self._cache) >= 256:
                        # drop expired, then oldest
                        for k in [k for k, (t, _) in self._cache.items()
                                  if now - t >= self.cache_ttl_s]:
                            del self._cache[k]
                        while len(self._cache) >= 256:
                            del self._cache[next(iter(self._cache))]
                    self._cache[key] = (now, verdicts)
            except Exception:
                self.errors += 1
                # fail open but do NOT cache: the next identical batch
                # must retry QC once the model recovers
                verdicts = [True] * len(to_review)
        approved = skip + [s for s, ok in zip(to_review, verdicts) if ok]
        self.suggestions_out += len(approved)
        return approved
