"""Tenant truth: end-to-end per-tenant attribution (ISSUE 18).

ROADMAP item 5's actuator — per-tenant weighted fair queueing and
cost-priced quotas — needs the serving stack to *see* tenants first
(the PR 7 -> PR 15 pattern: load-truth observability before the
admission actuator). This module is that identity layer:

- **Resolution** at every ingress: the ``X-Nornic-Tenant`` HTTP header
  (or ``x-nornic-tenant`` gRPC metadata) wins; a tenant PROPAGATED in
  the trace context (``X-Nornic-Trace`` / broker slot) counts as
  explicit too; otherwise the multidb namespace (``/db/{name}/...``,
  default DB elsewhere); qdrant ops refine a non-explicit tenant from
  the collection->tenant mapping (``NORNICDB_TENANT_COLLECTIONS``,
  the ``tenant__collection`` prefix convention, else the collection
  name itself).
- **A contextvar cell** carried across the executor hop exactly like
  the trace context and the deadline budget. The cell is one shared
  mutable object, so a refinement made inside a ``copy_context()``-run
  executor thread (where the collection name first becomes known) is
  visible to the ingress scope that records the request.
- **Cardinality-capped label registry** (PR 5 precedent): past
  ``NORNICDB_TENANT_MAX`` distinct tenants, new names fold into
  ``__other__`` and tick ``nornicdb_tenant_folded_total`` — client-
  chosen header values can never blow up the exposition.
- **Per-tenant families**: requests, request latency, served tier,
  degrades, sheds, and the cumulative cost meter (FLOPs/bytes/queries
  — the billing surface the quota PR will price against).
- **The leader->rider batch channel** (``audit.note_batch_tier``
  precedent): a batch leader binds the riders' tenant mix around the
  dispatch so ``obs.cost.record_query_cost`` splits the PADDED
  dispatch cost across riders by tenant.
- **Noisy-neighbor detector**: a rolling window of per-tenant cost;
  while the admission posture is >= degrade, a tenant holding more
  than ``NORNICDB_TENANT_NOISY_SHARE`` of the window's cost emits one
  advisory ``noisy_neighbor`` journal event with evidence (share,
  window totals, posture). No actuation — that is the next PR.
- **Rollups**: :func:`tenants_summary` (top-K by cost/qps/p99/shed)
  serves ``GET /admin/tenants``, joins ``/admin/fleet`` and
  ``/admin/telemetry``, and rides SLO flight-recorder dumps. It reads
  a ``dump_state``-shaped family map, so the wire-plane worker can
  feed it the MERGED local+plane state (exactly-once discipline).

Per-request functions here (:func:`resolve`, :func:`refine`,
:func:`record_served`, :func:`record_cost`) are lint-registered hot
paths — config is env-read once (``cfg``/``reload``), never on the
request path.
"""

from __future__ import annotations

import contextvars
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from nornicdb_tpu.obs import events as _events
from nornicdb_tpu.obs import metrics as _m
from nornicdb_tpu.obs import tracing as _tracing
from nornicdb_tpu.obs.metrics import REGISTRY

# the HTTP header an explicit tenant rides in (gRPC: the lowercase
# metadata key — gRPC metadata keys are always lowercase on the wire)
TENANT_HEADER = "X-Nornic-Tenant"
GRPC_METADATA_KEY = "x-nornic-tenant"

# the namespace fallback when nothing resolves (the multidb default DB
# is the caller's namespace; surfaces without one land here)
DEFAULT_TENANT = "default"
# fold target past the registry cap (PR 5 / obs.metrics `__other__`)
OTHER_TENANT = "__other__"
# a record produced OUTSIDE any tenant scope (internal/background
# work) — the attribution-completeness metric counts these
UNATTRIBUTED = "__unattributed__"

# client-reachable header values must look like code-chosen names
# before they land in metric labels or admin surfaces
_TENANT_RE = re.compile(r"^[\w.-]{1,64}$")


# ---------------------------------------------------------------------------
# cached configuration (env read once; per-request paths read the dict)
# ---------------------------------------------------------------------------

_cfg_lock = threading.Lock()
_cfg: Optional[Dict[str, Any]] = None


def _load_cfg() -> Dict[str, Any]:
    from nornicdb_tpu.config import env_float, env_int, env_str

    cmap: Dict[str, str] = {}
    for part in env_str("TENANT_COLLECTIONS", "").split(","):
        if ":" not in part:
            continue
        coll, ten = part.split(":", 1)
        coll, ten = coll.strip(), ten.strip()
        if coll and _TENANT_RE.match(ten):
            cmap[coll] = ten
    return {
        # distinct tenant label values before folding into __other__
        "max_tenants": max(1, env_int("TENANT_MAX", 64)),
        # rollup size at /admin/tenants (top-K by cost)
        "top_k": max(1, env_int("TENANT_TOP_K", 20)),
        # noisy-neighbor rolling window + advisory thresholds
        "noisy_window_s": max(1.0, env_float("TENANT_NOISY_WINDOW_S",
                                             30.0)),
        "noisy_share": min(1.0, max(0.0, env_float("TENANT_NOISY_SHARE",
                                                   0.5))),
        "noisy_cooldown_s": max(0.0, env_float("TENANT_NOISY_COOLDOWN_S",
                                               30.0)),
        # evidence floor: below this much windowed cost the detector
        # stays silent (an idle box has no neighbors to be noisy to)
        "noisy_min_flops": max(0.0, env_float("TENANT_NOISY_MIN_FLOPS",
                                              1e6)),
        # explicit collection->tenant assignments ("coll:tenant,...")
        "collection_map": cmap,
    }


def cfg() -> Dict[str, Any]:
    global _cfg
    c = _cfg
    if c is None:
        with _cfg_lock:
            if _cfg is None:
                _cfg = _load_cfg()
            c = _cfg
    return c


def reload() -> None:
    """Drop the cached env config AND the registry/detector state
    (tests; the metric counters themselves are monotone and stay)."""
    global _cfg
    with _cfg_lock:
        _cfg = None
    with _reg_lock:
        _known.clear()
    DETECTOR.reset()
    _RATES.reset()


# ---------------------------------------------------------------------------
# the tenant context cell
# ---------------------------------------------------------------------------


class _Cell:
    """One request's tenant identity. A single MUTABLE object shared by
    every context copy of the request (executor hops run under
    ``contextvars.copy_context()`` — a plain contextvar set inside the
    copy would never reach the ingress scope that records the request;
    mutating the shared cell does)."""

    __slots__ = ("tenant", "explicit")

    def __init__(self, tenant: Optional[str], explicit: bool) -> None:
        self.tenant = tenant
        self.explicit = explicit


_ctx_cell: "contextvars.ContextVar[Optional[_Cell]]" = \
    contextvars.ContextVar("nornicdb_tenant", default=None)


def current_tenant() -> Optional[str]:
    """The resolved tenant of the current request, or None outside any
    tenant scope. Cheap: one contextvar read + one attribute read."""
    cell = _ctx_cell.get()
    return cell.tenant if cell is not None else None


def current_label() -> str:
    """The METRIC label for the current context: the admitted (cap-
    folded) tenant, or ``__unattributed__`` outside any scope."""
    cell = _ctx_cell.get()
    if cell is None or not cell.tenant:
        return UNATTRIBUTED
    return _admit(cell.tenant)


class _TenantScope:
    __slots__ = ("_cell", "_token")

    def __init__(self, cell: _Cell) -> None:
        self._cell = cell
        self._token = None

    def __enter__(self) -> _Cell:
        self._token = _ctx_cell.set(self._cell)
        return self._cell

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _ctx_cell.reset(self._token)


def tenant_scope(tenant: Optional[str],
                 explicit: bool = False) -> _TenantScope:
    """Bind a tenant for the dynamic extent of a request (the
    ``lane_scope`` pattern). ``tenant=None`` still binds a cell so a
    later :func:`refine` (qdrant collection mapping) can fill it."""
    return _TenantScope(_Cell(tenant, explicit and tenant is not None))


def scope_from_context(ctx: Optional[Dict[str, str]]) -> _TenantScope:
    """A scope from a propagated trace context dict (broker ring /
    ``X-Nornic-Trace``): the origin node already resolved the tenant,
    so it binds as explicit."""
    t = (ctx or {}).get("tenant")
    return _TenantScope(_Cell(t, bool(t)))


def refine(candidate: Optional[str]) -> None:
    """Late-bind a DERIVED tenant (qdrant collection mapping, a route
    that learns its namespace mid-parse). An explicit tenant (header,
    metadata, propagated) always wins; a derived one fills the gap.
    Mutates the shared cell, so refinement inside an executor hop is
    visible at the ingress scope."""
    if not candidate:
        return
    cell = _ctx_cell.get()
    if cell is None:
        # no scope at all (direct library use): stay unattributed — a
        # bare contextvar set here would outlive the request in a
        # long-lived caller context (no scope exit resets it) and
        # silently attribute every LATER unscoped op to this tenant
        return
    if not cell.explicit:
        cell.tenant = candidate


def resolve(header_value: Optional[str],
            ctx: Optional[Dict[str, str]],
            namespace: Optional[str]) -> Tuple[Optional[str], bool]:
    """Ingress resolution order: explicit header > tenant propagated in
    the trace context > multidb namespace > :data:`DEFAULT_TENANT`.
    Returns ``(tenant, explicit)``. A malformed header value is
    DROPPED (charset-validated — it becomes a label and an admin
    surface string), falling through to the namespace."""
    if header_value:
        h = str(header_value).strip()
        if _TENANT_RE.match(h):
            return h, True
    t = (ctx or {}).get("tenant")
    if t:
        return t, True
    if namespace and _TENANT_RE.match(str(namespace)):
        return str(namespace), False
    return DEFAULT_TENANT, False


def tenant_for_collection(collection: str) -> Optional[str]:
    """qdrant collection -> tenant: the explicit map
    (``NORNICDB_TENANT_COLLECTIONS``) wins; a ``tenant__collection``
    name yields its prefix; otherwise the collection IS the tenant
    (per-collection namespacing, capped by the registry like any
    client-chosen value)."""
    if not collection:
        return None
    c = cfg()
    mapped = c["collection_map"].get(collection)
    if mapped:
        return mapped
    if "__" in collection:
        prefix = collection.split("__", 1)[0]
        if prefix and _TENANT_RE.match(prefix):
            return prefix
    return collection if _TENANT_RE.match(collection) else None


# ---------------------------------------------------------------------------
# cardinality-capped tenant registry (PR 5 fold-to-__other__ precedent)
# ---------------------------------------------------------------------------

_reg_lock = threading.Lock()
_known: Dict[str, None] = {}

_FOLDED_C = REGISTRY.counter(
    "nornicdb_tenant_folded_total",
    "Tenant names folded into __other__ past NORNICDB_TENANT_MAX")

REGISTRY.gauge(
    "nornicdb_tenant_registry_size",
    "Distinct tenant label values admitted (cap: NORNICDB_TENANT_MAX)",
    fn=lambda: float(len(_known)))


def _admit(name: str) -> str:
    """The label a tenant name materializes under: itself while the
    registry has room, ``__other__`` past the cap. Known names stay
    stable forever (dict membership is the fast path — no lock)."""
    if name in _known:
        return name
    if name in (OTHER_TENANT, UNATTRIBUTED):
        return name
    with _reg_lock:
        if name in _known:
            return name
        if len(_known) >= cfg()["max_tenants"]:
            _FOLDED_C.inc()
            return OTHER_TENANT
        _known[name] = None
        return name


def known_tenants() -> List[str]:
    return list(_known)


# ---------------------------------------------------------------------------
# per-tenant metric families (declared in lint/config.py
# TENANT_FAMILIES — the nornic-lint tenant-label rule)
# ---------------------------------------------------------------------------

_T_REQ_C = REGISTRY.counter(
    "nornicdb_tenant_requests_total",
    "Requests attributed per tenant (served + shed), by surface",
    labels=("tenant", "surface"))
_T_LAT_H = REGISTRY.histogram(
    "nornicdb_tenant_request_seconds",
    "Request wall time per tenant, by surface",
    labels=("tenant", "surface"))
_T_SERVED_C = REGISTRY.counter(
    "nornicdb_tenant_served_tier_total",
    "Serving-ladder rung that answered, per tenant",
    labels=("tenant", "surface", "tier"))
_T_DEGRADE_C = REGISTRY.counter(
    "nornicdb_tenant_degrade_total",
    "Serving-ladder step-downs attributed per tenant",
    labels=("tenant", "surface", "reason"))
_T_SHED_C = REGISTRY.counter(
    "nornicdb_tenant_shed_total",
    "Admission sheds attributed per tenant",
    labels=("tenant", "surface", "reason"))
_T_FLOPS_C = REGISTRY.counter(
    "nornicdb_tenant_cost_flops_total",
    "Cumulative priced dispatch FLOPs attributed per tenant (batched "
    "dispatches split the padded cost across riders by tenant)",
    labels=("tenant",))
_T_BYTES_C = REGISTRY.counter(
    "nornicdb_tenant_cost_bytes_total",
    "Cumulative priced dispatch bytes attributed per tenant",
    labels=("tenant",))
_T_QUERIES_C = REGISTRY.counter(
    "nornicdb_tenant_cost_queries_total",
    "Priced queries attributed per tenant (real pre-pad counts)",
    labels=("tenant",))
_T_DEVICE_S_C = REGISTRY.counter(
    "nornicdb_tenant_device_seconds_total",
    "MEASURED device dispatch wall seconds attributed per tenant "
    "(ISSUE 20: metering in seconds, not just analytic FLOPs; batched "
    "dispatches split wall time across riders by tenant)",
    labels=("tenant",))


# ---------------------------------------------------------------------------
# the leader->rider tenant mix channel (audit.note_batch_tier pattern)
# ---------------------------------------------------------------------------

_tls = threading.local()


class _BatchScope:
    """Bind a batch's tenant mix on the LEADER thread around the
    dispatch: ``record_query_cost`` calls inside split the padded cost
    across the mix; ``record_served(n=b)`` distributes serves the same
    way. Nests (restores the previous mix on exit) — a fused dispatch
    that re-enters a nested coalescer keeps the outer mix."""

    __slots__ = ("_mix", "_prev")

    def __init__(self, mix: Dict[str, int]) -> None:
        self._mix = mix

    def __enter__(self) -> Dict[str, int]:
        self._prev = getattr(_tls, "batch_mix", None)
        _tls.batch_mix = self._mix
        return self._mix

    def __exit__(self, *exc) -> None:
        _tls.batch_mix = self._prev


def batch_scope(tenants: List[Optional[str]]) -> _BatchScope:
    """Scope for a leader dispatching ``tenants``' riders (one entry
    per rider; None = unattributed). Labels are admitted (cap-folded)
    here, once per batch, not per record."""
    mix: Dict[str, int] = {}
    for t in tenants:
        label = _admit(t) if t else UNATTRIBUTED
        mix[label] = mix.get(label, 0) + 1
    return _BatchScope(mix)


def batch_mix() -> Optional[Dict[str, int]]:
    return getattr(_tls, "batch_mix", None)


# ---------------------------------------------------------------------------
# recording hooks (called from obs.audit / obs.cost / admission)
# ---------------------------------------------------------------------------


def record_served(surface: str, tier: str,
                  seconds: Optional[float] = None, n: int = 1) -> None:
    """Per-tenant side of ``audit.record_served``: requests + served
    tier (+ latency when known). Under an active batch mix the ``n``
    serves distribute across the riders' tenants; otherwise the
    current context's tenant takes all ``n``."""
    if not _m.enabled():
        return
    mix = getattr(_tls, "batch_mix", None)
    if mix:
        total = sum(mix.values()) or 1
        for t, c in mix.items():
            share = n * c / total
            _T_REQ_C.labels(t, surface).inc(share)
            _T_SERVED_C.labels(t, surface, tier).inc(share)
            _RATES.note(t, share)
        if seconds is not None:
            for t in mix:
                _T_LAT_H.labels(t, surface).observe(seconds)
        return
    t = current_label()
    _T_REQ_C.labels(t, surface).inc(n)
    _T_SERVED_C.labels(t, surface, tier).inc(n)
    _RATES.note(t, n)
    if seconds is not None:
        _T_LAT_H.labels(t, surface).observe(seconds)


def record_degrade(surface: str, reason: str) -> None:
    if not _m.enabled():
        return
    _T_DEGRADE_C.labels(current_label(), surface, reason).inc()


def record_shed(surface: str, reason: str) -> None:
    if not _m.enabled():
        return
    _T_SHED_C.labels(current_label(), surface, reason).inc()


def record_cost(queries: float, flops: float, bytes_: float) -> None:
    """Per-tenant side of ``obs.cost.record_query_cost``: split the
    padded-dispatch cost across the active batch mix by rider count
    (the leader->rider channel), else attribute it whole to the
    current context's tenant. Feeds the noisy-neighbor window."""
    if not _m.enabled():
        return
    mix = getattr(_tls, "batch_mix", None)
    if mix:
        total = sum(mix.values()) or 1
        for t, c in mix.items():
            frac = c / total
            f = flops * frac
            _T_FLOPS_C.labels(t).inc(f)
            _T_BYTES_C.labels(t).inc(bytes_ * frac)
            _T_QUERIES_C.labels(t).inc(queries * frac)
            DETECTOR.note(t, f)
        return
    t = current_label()
    _T_FLOPS_C.labels(t).inc(flops)
    _T_BYTES_C.labels(t).inc(bytes_)
    _T_QUERIES_C.labels(t).inc(queries)
    DETECTOR.note(t, flops)


def record_device_seconds(seconds: float) -> None:
    """Per-tenant side of the measured dispatch bracket (ISSUE 20):
    split one dispatch's wall seconds across the active batch mix by
    rider count — the bill in device time, not analytic FLOPs. Outside
    a mix the current context's tenant pays whole."""
    if not _m.enabled():
        return
    mix = getattr(_tls, "batch_mix", None)
    if mix:
        total = sum(mix.values()) or 1
        for t, c in mix.items():
            _T_DEVICE_S_C.labels(t).inc(seconds * c / total)
        return
    _T_DEVICE_S_C.labels(current_label()).inc(seconds)


# ---------------------------------------------------------------------------
# request-rate window (the qps column of the rollup)
# ---------------------------------------------------------------------------


class _RateWindow:
    """Two-bucket per-tenant request rate: O(1) per note, qps derived
    from the closed previous bucket (a full bucket of signal) plus the
    live one — no unbounded deque under a flood."""

    BUCKET_S = 10.0

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._t0 = 0.0
        self._cur: Dict[str, float] = {}
        self._prev: Dict[str, float] = {}

    def note(self, tenant: str, n: float = 1.0) -> None:
        now = time.time()
        with self._lock:
            if now - self._t0 >= self.BUCKET_S:
                self._prev = self._cur if now - self._t0 < \
                    2 * self.BUCKET_S else {}
                self._cur = {}
                self._t0 = now
            self._cur[tenant] = self._cur.get(tenant, 0.0) + n

    def rates(self) -> Dict[str, float]:
        now = time.time()
        with self._lock:
            live_s = max(now - self._t0, 1e-3)
            if live_s >= 2 * self.BUCKET_S:
                return {}
            out: Dict[str, float] = {}
            span = min(live_s, self.BUCKET_S) + (
                self.BUCKET_S if self._prev else 0.0)
            for t in set(self._cur) | set(self._prev):
                total = self._cur.get(t, 0.0) + self._prev.get(t, 0.0)
                out[t] = total / max(span, 1e-3)
            return out

    def reset(self) -> None:
        with self._lock:
            self._cur = {}
            self._prev = {}
            self._t0 = 0.0


_RATES = _RateWindow()


# ---------------------------------------------------------------------------
# noisy-neighbor detector (advisory; actuation is the next PR)
# ---------------------------------------------------------------------------

# injected by admission.py at import (provider pattern — this module
# must not import the actuator): returns the posture LEVEL (index into
# admission.POSTURES; >= 1 means degrade or worse)
_posture_provider: Optional[Callable[[], int]] = None


def set_posture_provider(fn: Callable[[], int]) -> None:
    global _posture_provider
    _posture_provider = fn


class NoisyNeighborDetector:
    """Rolling-window per-tenant cost share. While the admission
    posture is >= degrade, the tenant holding more than
    ``noisy_share`` of the window's priced FLOPs emits ONE advisory
    ``noisy_neighbor`` journal event per cooldown, with evidence: its
    share, windowed flops, the window total, qps, and the posture that
    armed the check. Costs attributed to ``__other__`` or
    ``__unattributed__`` never accuse anyone."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ring: "deque[Tuple[float, str, float]]" = deque()
        self._totals: Dict[str, float] = {}
        self._last_emit: Dict[str, float] = {}
        self.emitted = 0

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._totals.clear()
            self._last_emit.clear()

    def _prune(self, now: float, window_s: float) -> None:
        ring, totals = self._ring, self._totals
        while ring and ring[0][0] < now - window_s:
            _ts, t, f = ring.popleft()
            left = totals.get(t, 0.0) - f
            if left <= 1e-9:
                totals.pop(t, None)
            else:
                totals[t] = left

    def note(self, tenant: str, flops: float) -> None:
        if flops <= 0.0:
            return
        c = cfg()
        now = time.time()
        with self._lock:
            self._ring.append((now, tenant, flops))
            self._totals[tenant] = self._totals.get(tenant, 0.0) + flops
            self._prune(now, c["noisy_window_s"])
            level = _posture_provider() if _posture_provider else 0
            if level < 1:
                return
            total = sum(self._totals.values())
            if total < c["noisy_min_flops"]:
                return
            top, top_f = max(self._totals.items(), key=lambda kv: kv[1])
            share = top_f / total
            if share < c["noisy_share"] \
                    or top in (OTHER_TENANT, UNATTRIBUTED):
                return
            if now - self._last_emit.get(top, 0.0) \
                    < c["noisy_cooldown_s"]:
                return
            self._last_emit[top] = now
            self.emitted += 1
            evidence = {
                "tenant": top,
                "cost_share": round(share, 4),
                "window_s": c["noisy_window_s"],
                "window_flops": round(top_f, 1),
                "window_total_flops": round(total, 1),
                "qps": round(_RATES.rates().get(top, 0.0), 2),
                "posture_level": level,
            }
        # journal write outside the window lock (the journal has its
        # own lock; never hold two)
        _events.record_event("noisy_neighbor", surface="admission",
                             reason="cost_share", detail=evidence)

    def snapshot(self) -> Dict[str, Any]:
        c = cfg()
        now = time.time()
        with self._lock:
            self._prune(now, c["noisy_window_s"])
            total = sum(self._totals.values())
            shares = {t: round(f / total, 4)
                      for t, f in self._totals.items()} if total else {}
            return {
                "window_s": c["noisy_window_s"],
                "share_threshold": c["noisy_share"],
                "window_total_flops": round(total, 1),
                "shares": shares,
                "emitted": self.emitted,
            }


DETECTOR = NoisyNeighborDetector()


# ---------------------------------------------------------------------------
# rollups — /admin/tenants, /admin/fleet, /admin/telemetry, SLO dumps
# ---------------------------------------------------------------------------


def _quantile_from_snapshot(snap: Dict[str, Any],
                            q: float) -> Optional[float]:
    """Bucket-interpolated quantile over a dump_state histogram
    snapshot (the obs.fleet math, over the same wire shape)."""
    total = snap.get("count", 0)
    if not total:
        return None
    bounds = snap["buckets"]
    rank = q * total
    cum = 0.0
    for i, c in enumerate(snap["counts"]):
        prev = cum
        cum += c
        if cum >= rank:
            if i >= len(bounds):
                return bounds[-1]
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            if c == 0:
                return hi
            return lo + (hi - lo) * (rank - prev) / c
    return bounds[-1] if bounds else None


def _fam_children(state: Dict[str, Dict], name: str) -> Dict:
    fam = state.get(name)
    return fam["children"] if fam else {}


def attribution_completeness(
        state: Optional[Dict[str, Dict]] = None) -> Optional[float]:
    """Share of attributed requests carrying a REAL tenant (not
    ``__unattributed__``) — the truth metric the multi-tenant bench
    sentinel gates ABSOLUTELY at 1.0. None when no requests were
    recorded at all."""
    if state is None:
        state = {f["name"]: f for f in _m.dump_state()}
    total = attributed = 0.0
    for key, v in _fam_children(
            state, "nornicdb_tenant_requests_total").items():
        total += v
        if key[0] != UNATTRIBUTED:
            attributed += v
    if total <= 0.0:
        return None
    return attributed / total


def tenants_summary(state: Optional[Dict[str, Dict]] = None,
                    top: Optional[int] = None) -> Dict[str, Any]:
    """The ``GET /admin/tenants`` payload: per-tenant requests, qps,
    p99, served-tier mix, sheds, degrades and the cumulative cost
    meter — top-K by windowed+cumulative cost. ``state`` accepts a
    merged ``dump_state`` family map (wire-plane workers pass
    local+plane merged state so per-tenant counters appear exactly
    once); None reads the local registry."""
    local = state is None
    if state is None:
        state = {f["name"]: f for f in _m.dump_state()}
    c = cfg()
    k = top or c["top_k"]
    docs: Dict[str, Dict[str, Any]] = {}

    def doc(t: str) -> Dict[str, Any]:
        return docs.setdefault(t, {"tenant": t})

    for key, v in _fam_children(
            state, "nornicdb_tenant_requests_total").items():
        d = doc(key[0])
        d["requests"] = d.get("requests", 0.0) + v
    for key, v in _fam_children(
            state, "nornicdb_tenant_served_tier_total").items():
        d = doc(key[0]).setdefault("tiers", {})
        d[key[2]] = d.get(key[2], 0.0) + v
    for key, v in _fam_children(
            state, "nornicdb_tenant_shed_total").items():
        d = doc(key[0])
        d["shed"] = d.get("shed", 0.0) + v
        reasons = d.setdefault("shed_reasons", {})
        reasons[key[2]] = reasons.get(key[2], 0.0) + v
    for key, v in _fam_children(
            state, "nornicdb_tenant_degrade_total").items():
        d = doc(key[0])
        d["degrades"] = d.get("degrades", 0.0) + v
    for name, field in (("nornicdb_tenant_cost_flops_total", "flops"),
                        ("nornicdb_tenant_cost_bytes_total", "bytes"),
                        ("nornicdb_tenant_cost_queries_total",
                         "queries"),
                        ("nornicdb_tenant_device_seconds_total",
                         "device_seconds")):
        for key, v in _fam_children(state, name).items():
            d = doc(key[0]).setdefault("cost", {})
            d[field] = d.get(field, 0.0) + v
    for key, snap in _fam_children(
            state, "nornicdb_tenant_request_seconds").items():
        if not isinstance(snap, dict) or not snap.get("count"):
            continue
        d = doc(key[0])
        best = d.get("_lat")
        if best is None or snap.get("count", 0) > best.get("count", 0):
            d["_lat"] = snap
    rates = _RATES.rates()
    total_flops = sum(d.get("cost", {}).get("flops", 0.0)
                      for d in docs.values())
    for t, d in docs.items():
        lat = d.pop("_lat", None)
        if lat is not None:
            p99 = _quantile_from_snapshot(lat, 0.99)
            p50 = _quantile_from_snapshot(lat, 0.5)
            d["p50_ms"] = None if p50 is None else round(p50 * 1e3, 3)
            d["p99_ms"] = None if p99 is None else round(p99 * 1e3, 3)
        if t in rates:
            d["qps"] = round(rates[t], 2)
        if total_flops > 0.0 and "cost" in d:
            d["cost_share"] = round(
                d["cost"].get("flops", 0.0) / total_flops, 4)

    def rank(d: Dict[str, Any]) -> Tuple[float, float]:
        return (d.get("cost", {}).get("flops", 0.0),
                d.get("requests", 0.0))

    ordered = sorted(docs.values(), key=rank, reverse=True)
    out: Dict[str, Any] = {
        "cap": c["max_tenants"],
        "known": len(_known),
        "tenants": ordered[:k],
        "total": len(ordered),
        "attribution_completeness": attribution_completeness(state),
        "noisy_neighbor": DETECTOR.snapshot(),
    }
    if not local:
        # qps/noisy window are process-local; flag the merged view so
        # an operator reads the cumulative columns as fleet-wide and
        # the windowed ones as this node's
        out["merged"] = True
    return out


# tenant propagation: the trace context carries the tenant across the
# broker ring and the X-Nornic-Trace node hop (pack_context field 4);
# the journal stamps it on every incident event. Providers registered
# here (not in tracing/events) so those modules stay importable
# without the tenant layer.
_tracing.set_tenant_provider(current_tenant)
_events.set_tenant_provider(current_tenant)
