"""Per-query device cost accounting: what does one search *cost*?

The roadmap's admission control (item 4) and replica routing (item 3)
both need to reason about load in resource units, not just QPS — a
brute matmul over a 10M-row matrix and a 16-iteration graph walk are
wildly different answers to "one query". This module prices every
batched device dispatch from its KNOWN shapes at dispatch time:

- brute cosine top-k: a ``[B, D] x [D, C]`` matmul — ``2*B*C*D`` FLOPs,
  the matrix + queries + scores moved once;
- CAGRA walk: the wide seed round plus ``iters`` frontier expansions of
  ``width * degree`` candidate distance evaluations per query;
- device BM25: the CSR gather/segment-sum over the batch's unique-term
  postings (nnz) plus the ``[B, U] x [U, C]`` idf-weighted matmul;
- fused hybrid: lexical + vector tier + the RRF fuse, composed from
  the pieces above.

Costs land in three counters labeled ``{kind, index}`` (index = the
structure's resource-registration name, so aggregation follows the
same identity as the memory/freshness gauges — per service database or
per qdrant collection):

- ``nornicdb_query_cost_flops_total``
- ``nornicdb_query_cost_bytes_total`` (device bytes touched)
- ``nornicdb_query_cost_queries_total`` (REAL queries served, pre-pad)

FLOPs/bytes are priced at the PADDED shapes (the device executes the
pow2 bucket, not the request) while queries count the real batch —
``cost_summary()``'s flops-per-query therefore includes padding waste,
which is exactly what a router deciding where to send one more query
needs to see. Estimates are arithmetic-only (no memory-hierarchy
model): stable units for relative pricing, not a roofline claim.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from nornicdb_tpu.obs import metrics as _m
from nornicdb_tpu.obs import tenant as _tenant
from nornicdb_tpu.obs.metrics import REGISTRY, Registry

_F32 = 4  # bytes

_FLOPS_C = REGISTRY.counter(
    "nornicdb_query_cost_flops_total",
    "Estimated device FLOPs spent, by dispatch kind and index",
    labels=("kind", "index"))
_BYTES_C = REGISTRY.counter(
    "nornicdb_query_cost_bytes_total",
    "Estimated device bytes touched, by dispatch kind and index",
    labels=("kind", "index"))
_QUERIES_C = REGISTRY.counter(
    "nornicdb_query_cost_queries_total",
    "Real (pre-padding) queries priced, by dispatch kind and index",
    labels=("kind", "index"))


def pricing_enabled() -> bool:
    """Gate for call sites: skip the pricing arithmetic (unique-term
    sets, stats lookups) entirely when telemetry is off, not just the
    counter increments — the zero-overhead discipline obs documents."""
    return _m.enabled()


# -- pricing functions (pure) ------------------------------------------------


def price_brute(b: int, rows: int, d: int) -> Tuple[float, float]:
    """(flops, bytes) of one brute cosine top-k dispatch: [b,d]x[d,rows]
    matmul over the capacity-padded matrix."""
    flops = 2.0 * b * rows * d
    bytes_ = _F32 * (rows * d + b * d + b * rows)
    return flops, bytes_


def price_walk(b: int, d: int, iters: int, width: int, degree: int,
               itopk: int, n_seeds: int = 1024) -> Tuple[float, float]:
    """(flops, bytes) of one batched CAGRA greedy walk: the wide seed
    round then ``iters`` expansions of ``width*degree`` distance evals,
    each a d-dim dot product, plus the itopk pool maintenance."""
    evals = float(n_seeds + iters * width * degree)
    flops = b * (evals * 2.0 * d + iters * itopk * 2.0)
    bytes_ = _F32 * b * (evals * d + iters * degree * width)
    return flops, bytes_


def price_int8_coarse(b: int, rows: int, d: int) -> Tuple[float, float]:
    """(flops, bytes) of one int8 coarse top-k dispatch: the same
    [b,d]x[d,rows] arithmetic as the float32 matmul (codes cast to f32
    chunk-by-chunk in cache — the cast never lands in HBM), but the
    matrix moves ONE byte per element (+ the per-row f32 scales) — the
    4x HBM win the compression exists for shows up on the same axis."""
    flops = 2.0 * b * rows * d
    bytes_ = (rows * d  # int8 codes
              + _F32 * rows  # per-row scales
              + _F32 * (b * d + b * rows))  # f32 queries + scores
    return flops, bytes_


def price_pq_adc(b: int, rows: int, m: int, n_codes: int,
                 d_sub: int) -> Tuple[float, float]:
    """(flops, bytes) of one PQ ADC dispatch: the per-subspace
    [b, n_codes] table matmuls plus the gather+sum over the uint8 code
    columns — bytes are dominated by the m*rows code bytes, which is
    the entire point."""
    flops = 2.0 * b * m * n_codes * d_sub + 1.0 * b * m * rows
    bytes_ = (m * rows  # uint8 codes
              + _F32 * (m * n_codes * d_sub  # codebooks
                        + b * m * d_sub  # query subvectors
                        + b * rows))  # scores
    return flops, bytes_


def price_tiered_route(b: int, parts: int, d: int) -> Tuple[float, float]:
    """(flops, bytes) of the host-side cluster routing matmul: one
    [b,d]x[d,parts] centroid scoring per batch — the tiny price that
    buys skipping every unprobed partition's codes entirely."""
    flops = 2.0 * b * parts * d
    bytes_ = _F32 * (parts * d + b * d + b * parts)
    return flops, bytes_


def price_rerank(b: int, pool: int, d: int) -> Tuple[float, float]:
    """(flops, bytes) of the exact rerank over a gathered candidate
    pool: one [b,d]x[d,pool] float32 matmul over rows gathered from the
    host source of truth (counted as bytes moved — the gather IS the
    cost the overfetch knob trades against recall)."""
    flops = 2.0 * b * pool * d
    bytes_ = _F32 * (b * pool * d + b * d + b * pool)
    return flops, bytes_


def price_walk_quant(b: int, d: int, iters: int, width: int,
                     degree: int, itopk: int, head_dims: int, keep: int,
                     n_seeds: int = 1024) -> Tuple[float, float]:
    """(flops, bytes) of one QUANTIZED CAGRA walk: the seed round reads
    full int8 rows, each iteration gathers ``width*degree`` candidate
    HEADS (head_dims int8 each — the PCA prefilter) and only ``keep``
    full int8 rows; the host-side exact rerank of the pool is priced
    separately (``price_rerank``)."""
    m = float(width * degree)
    flops = b * (n_seeds * 2.0 * d
                 + iters * (m * 2.0 * head_dims + keep * 2.0 * d
                            + itopk * 2.0))
    bytes_ = b * (n_seeds * d  # int8 seed rows
                  + iters * (m * head_dims + keep * d  # int8 gathers
                             + _F32 * m))  # adjacency/scale columns
    return flops, bytes_


def price_walk_pq(b: int, d: int, iters: int, width: int, degree: int,
                  itopk: int, m: int, n_codes: int,
                  n_seeds: int = 1024) -> Tuple[float, float]:
    """(flops, bytes) of one PQ CAGRA walk (ISSUE 17 satellite): one
    per-query ADC table einsum ([m, n_codes] dots of d/m dims), then
    the seed round and each iteration's ``width*degree`` candidates
    cost ``m`` uint8 code gathers + table adds apiece; the host exact
    rerank of the pool is priced separately (``price_rerank``)."""
    cand = float(iters * width * degree)
    d_sub = d / max(m, 1)
    flops = b * (2.0 * m * n_codes * d_sub  # ADC tables
                 + (n_seeds + cand) * m  # table-lookup adds
                 + iters * itopk * 2.0)  # pool maintenance
    bytes_ = b * (m * (n_seeds + cand)  # uint8 code gathers
                  + _F32 * (m * n_codes + cand))  # tables + adjacency
    return flops, bytes_


def price_chain_topk(b: int, f: int, kp: int) -> Tuple[float, float]:
    """(flops, bytes) of one device graph chain-top-k dispatch
    (query/device_graph.py): per anchor, a width-``f`` CSR friend
    gather, ``f*kp`` strip-head rank gathers, and the top-k merge over
    the ``f*kp`` composite keys. Gather-dominated: flops are the merge
    comparisons, bytes the int32 index/rank/neighbor traffic."""
    width = float(f * kp)
    flops = b * (2.0 * width + width)  # top-k compares + key composition
    bytes_ = 4.0 * b * (2 + 2 * f + 3 * width)
    return flops, bytes_


def price_graph_agg(e1: int, e2: int, n: int) -> Tuple[float, float]:
    """(flops, bytes) of one strip-aggregation build dispatch: the
    terminal-degree segment-sum over ``e2`` edges, the weighted group
    segment-sum over ``e1``, and the lexicographic distinct-pair pass
    (sort ~ e1*log2(e1))."""
    import math

    lg = math.log2(max(e1, 2))
    flops = 2.0 * e2 + 3.0 * e1 + e1 * lg
    bytes_ = 4.0 * (3 * e1 + 2 * e2 + 3 * n)
    return flops, bytes_


def price_cooc_gram(m: int, a: int, bcols: int) -> Tuple[float, float]:
    """(flops, bytes) of one co-occurrence Gram contraction
    ``[a, m] x [m, b]`` over the padded incidence matrices."""
    flops = 2.0 * m * a * bcols
    bytes_ = _F32 * (m * a + m * bcols + a * bcols)
    return flops, bytes_


def price_traverse_rank(b: int, frontier: int, d: int,
                        kp: int) -> Tuple[float, float]:
    """(flops, bytes) of one fused traverse-then-rank dispatch: the
    frontier expansion gathers, the ``[b, frontier, d]`` vector gather
    + cosine dot, and the top-k over frontier scores."""
    flops = b * (frontier * 2.0 * d + 2.0 * frontier + kp * 2.0)
    bytes_ = _F32 * b * (frontier * d + d + 2 * frontier)
    return flops, bytes_


def price_upsert(n_points: int, d: int) -> Tuple[float, float]:
    """(flops, bytes) of one bulk vector upsert (ISSUE 18): the
    normalize pass (~2 flops/dim) over ``n_points`` rows plus the rows
    moved host->device twice (staging + index append). Write traffic
    was unpriced before this; a bulk-upserting tenant looked free to
    the cost meter while monopolizing the device."""
    flops = 2.0 * n_points * d
    bytes_ = 2.0 * _F32 * n_points * d
    return flops, bytes_


def price_decay_sweep(m: int) -> Tuple[float, float]:
    """(flops, bytes) of one background decay sweep dispatch
    (background/device_plane.py): ~10 elementwise ops per node over
    seven f32 input columns and three output columns, priced at the
    padded bucket ``m``."""
    flops = 10.0 * m
    bytes_ = _F32 * 10.0 * m
    return flops, bytes_


def price_linkpredict(b: int, f1: int, f2: int,
                      kp: int) -> Tuple[float, float]:
    """(flops, bytes) of one background link-prediction dispatch: per
    seed, the ``f1*f2`` two-hop candidate expansion, the sort over the
    expansion (``W*log2(W)`` compares), the segment reduction, and the
    top-``kp`` selection; bytes are the int32/f32 gather traffic over
    the expansion."""
    import math

    w = float(f1 * f2)
    lg = math.log2(max(w, 2.0))
    flops = b * (w * lg + 4.0 * w + 2.0 * kp)
    bytes_ = 4.0 * b * (f1 + 3.0 * w + 2.0 * kp)
    return flops, bytes_


def price_fastrp(n: int, edges: int, dim: int,
                 iters: int) -> Tuple[float, float]:
    """(flops, bytes) of one background FastRP dispatch: ``iters``
    neighbor-mean propagations (one ``dim``-wide segment-sum over both
    edge directions apiece) plus the per-iteration row normalization
    over ``n`` rows."""
    flops = iters * (2.0 * 2.0 * edges * dim + 5.0 * n * dim)
    bytes_ = _F32 * iters * (2.0 * edges * dim + 3.0 * n * dim)
    return flops, bytes_


def price_bm25(b: int, nnz: int, unique_terms: int,
               rows: int) -> Tuple[float, float]:
    """(flops, bytes) of one device-BM25 scoring dispatch: tf/idf math +
    segment-sum over the batch's unique-term postings (nnz), then the
    [b, U] x [U, rows] idf-weighted score matmul."""
    flops = 8.0 * nnz + 2.0 * b * max(unique_terms, 1) * rows
    bytes_ = _F32 * (2 * nnz + b * max(unique_terms, 1) + b * rows)
    return flops, bytes_


# -- recording ---------------------------------------------------------------


def cost_name(obj: Any) -> str:
    """The structure's resource-accounting identity (stamped by
    ``obs.resources.register``), or 'unregistered'."""
    return getattr(obj, "_obs_resource_name", None) or "unregistered"


def set_observer(fn) -> None:
    """Register the per-record cost observer (obs/device.py, ISSUE 20):
    called as ``fn(kind, queries, flops, bytes_)`` so calibration can
    join analytic cost against measured dispatch seconds."""
    global _observer
    _observer = fn


_observer = None


def record_query_cost(kind: str, index: str, queries: int,
                      flops: float, bytes_: float) -> None:
    """Record one priced dispatch. ``queries`` is the REAL batch size
    (pre-padding); flops/bytes are the padded program's."""
    if not _m.enabled():
        return
    _FLOPS_C.labels(kind, index).inc(flops)
    _BYTES_C.labels(kind, index).inc(bytes_)
    _QUERIES_C.labels(kind, index).inc(queries)
    # per-tenant metering (ISSUE 18): under an active batch mix the
    # padded-dispatch cost splits across riders by tenant (the
    # leader->rider channel); else the current context's tenant pays
    _tenant.record_cost(queries, flops, bytes_)
    obs_fn = _observer
    if obs_fn is not None:
        obs_fn(kind, queries, flops, bytes_)


def cost_summary(registry: Optional[Registry] = None
                 ) -> List[Dict[str, Any]]:
    """Aggregated cost-per-query per (kind, index): the telemetry that
    admission control / replica routing consume. Scrape-time only."""
    reg = registry if registry is not None else REGISTRY
    fams = {
        "flops": reg.get("nornicdb_query_cost_flops_total"),
        "bytes": reg.get("nornicdb_query_cost_bytes_total"),
        "queries": reg.get("nornicdb_query_cost_queries_total"),
    }
    if any(f is None for f in fams.values()):
        return []
    children = {name: fam.children() for name, fam in fams.items()}
    out: List[Dict[str, Any]] = []
    for key in sorted(children["queries"]):
        kind, index = key
        queries = children["queries"][key].value
        if queries <= 0:
            continue
        flops = (children["flops"].get(key).value
                 if key in children["flops"] else 0.0)
        byts = (children["bytes"].get(key).value
                if key in children["bytes"] else 0.0)
        out.append({
            "kind": kind, "index": index, "queries": int(queries),
            "flops_total": flops, "bytes_total": byts,
            "flops_per_query": round(flops / queries, 1),
            "bytes_per_query": round(byts / queries, 1),
        })
    return out
