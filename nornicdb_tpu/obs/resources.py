"""Resource & freshness accounting: device memory and staleness gauges.

PR 2/4 left three device-resident structures in HBM — the brute-force
matrix, the CAGRA graph (+ reorder maps), and the device-BM25 CSR
columns — with zero operational visibility into their footprint or how
far behind the live indexes their snapshots run. This module closes
that: any index/queue object registers itself here (weakly — a dropped
collection's series disappear instead of lingering at their last
value), and a registry collector derives labeled gauges on every
scrape from each object's ``resource_stats()``:

- ``nornicdb_index_device_bytes{family,index}`` / ``_host_bytes`` —
  per-index accelerator / host-mirror footprint;
- ``nornicdb_index_rows`` / ``_capacity`` / ``_dead_fraction`` —
  liveness vs the padded slot space (compaction pressure);
- ``nornicdb_index_changelog_depth`` / ``_changelog_cap`` — how close
  the read-your-writes changelog is to overrun (overrun degrades the
  device path to host-exact serving);
- ``nornicdb_index_mutation_gap`` — mutation generations between the
  live index and the device snapshot it serves from;
- ``nornicdb_index_rebuild_in_flight`` / ``_rebuild_backlog_seconds``
  — background rebuild state and how long the backlog has been open;
- ``nornicdb_queue_depth{queue}`` — live MicroBatcher queue depth;
- ``nornicdb_compile_cache_entries{kind}`` — distinct compiled (B, k)
  buckets per dispatch kind (obs/dispatch.py's shape universe).

``/readyz`` (api/http_server.py) reads the same ``snapshot()`` to
decide readiness: pending rebuilds, near-overrun changelogs and
saturated queues degrade the node before they degrade answers.

Everything is scrape-time work: the hot path pays nothing; each
``resource_stats()`` is one short lock hold on its index.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

from nornicdb_tpu.obs import dispatch as _dispatch
from nornicdb_tpu.obs.metrics import REGISTRY

# gauge key -> (metric family name, stat key); every stat an index
# reports under one of these keys becomes a labeled gauge series
_INDEX_GAUGES: Tuple[Tuple[str, str], ...] = (
    ("nornicdb_index_device_bytes", "device_bytes"),
    ("nornicdb_index_host_bytes", "host_bytes"),
    ("nornicdb_index_rows", "rows"),
    ("nornicdb_index_capacity", "capacity"),
    ("nornicdb_index_dead_fraction", "dead_fraction"),
    ("nornicdb_index_changelog_depth", "changelog_depth"),
    ("nornicdb_index_changelog_cap", "changelog_cap"),
    ("nornicdb_index_mutation_gap", "mutation_gap"),
    ("nornicdb_index_rebuild_in_flight", "rebuild_in_flight"),
    ("nornicdb_index_rebuild_backlog_seconds", "rebuild_backlog_s"),
    ("nornicdb_index_quant_device_bytes", "quant_device_bytes"),
    ("nornicdb_index_compression_ratio", "compression_ratio"),
    ("nornicdb_index_partitions", "partitions"),
    ("nornicdb_index_resident_partitions", "resident_partitions"),
    ("nornicdb_index_tiered_device_bytes", "tiered_device_bytes"),
    ("nornicdb_index_disk_bytes", "disk_bytes"),
)

_HELP = {
    "nornicdb_index_device_bytes":
        "Device-resident bytes held by this index structure",
    "nornicdb_index_host_bytes":
        "Host-side bytes of the index's mirrors/tables",
    "nornicdb_index_rows": "Live rows in the index",
    "nornicdb_index_capacity": "Padded slot capacity of the index",
    "nornicdb_index_dead_fraction":
        "Tombstoned fraction of used slots (compaction pressure)",
    "nornicdb_index_changelog_depth":
        "Entries currently held in the read-your-writes changelog",
    "nornicdb_index_changelog_cap":
        "Changelog length cap (overrun degrades to host-exact serving)",
    "nornicdb_index_mutation_gap":
        "Mutation generations between live index and device snapshot",
    "nornicdb_index_rebuild_in_flight":
        "1 while a background snapshot/graph rebuild is running",
    "nornicdb_index_rebuild_backlog_seconds":
        "Age of the open background-rebuild backlog",
    "nornicdb_index_quant_device_bytes":
        "Device bytes of the index's quantized (int8/PQ) plane",
    "nornicdb_index_compression_ratio":
        "float32 bytes replaced / quantized device bytes",
    "nornicdb_index_partitions":
        "k-means partitions in the tiered plane's corpus layout",
    "nornicdb_index_resident_partitions":
        "Partitions currently holding a device slab (LRU residency)",
    "nornicdb_index_tiered_device_bytes":
        "Device bytes of the tiered plane's resident PQ slabs",
    "nornicdb_index_disk_bytes":
        "On-disk bytes of the cold partition spill store",
}

_lock = threading.Lock()
# (family, name) -> weakref to the registered object
_objects: Dict[Tuple[str, str], "weakref.ref[Any]"] = {}
# gauge series previously materialized by the collector, so series
# whose object died are removed from the exposition, not frozen
_live_series: Dict[str, set] = {}


def register(family: str, name: str, obj: Any) -> None:
    """Track one index/queue object for resource accounting. The object
    must expose ``resource_stats() -> dict`` (indexes) or
    ``queue_depth() -> int`` (queues). Registration replaces any prior
    object under the same (family, name) — index reloads re-register —
    and re-registering the SAME object is a no-op, so a second wire
    worker booting over shared structures (ISSUE 11) can never churn
    the weakref or momentarily drop the series from a racing scrape."""
    with _lock:
        prior = _objects.get((str(family), str(name)))
        if prior is not None and prior() is obj:
            return
    try:
        # stamp the registration identity so the cost accounting
        # (obs/cost.py) labels per-dispatch prices with the same name
        # as the memory/freshness gauges; best-effort (slotted or
        # foreign objects simply price as 'unregistered')
        obj._obs_resource_name = str(name)
    except Exception:  # noqa: BLE001
        pass
    with _lock:
        _objects[(str(family), str(name))] = weakref.ref(obj)


def unregister(family: str, name: str) -> None:
    with _lock:
        _objects.pop((str(family), str(name)), None)


def _live_objects() -> List[Tuple[str, str, Any]]:
    dead: List[Tuple[str, str]] = []
    out: List[Tuple[str, str, Any]] = []
    with _lock:
        for (family, name), ref in _objects.items():
            obj = ref()
            if obj is None:
                dead.append((family, name))
            else:
                out.append((family, name, obj))
        for key in dead:
            _objects.pop(key, None)
    return out


def snapshot() -> List[Dict[str, Any]]:
    """Per-object resource/freshness stats for every live registered
    structure — the JSON the admin surface, /readyz and bench.py read.
    A failing stats call yields an ``error`` entry, never a raise."""
    out: List[Dict[str, Any]] = []
    for family, name, obj in _live_objects():
        entry: Dict[str, Any] = {"family": family, "index": name}
        try:
            if hasattr(obj, "resource_stats"):
                entry.update(obj.resource_stats())
            elif hasattr(obj, "queue_depth"):
                entry["queue_depth"] = obj.queue_depth()
                entry["max_batch"] = getattr(obj, "_max_batch", None)
        except Exception as exc:  # noqa: BLE001 — scrape must not fail
            entry["error"] = f"{type(exc).__name__}: {exc}"[:200]
        out.append(entry)
    return out


def update_gauges(registry=None) -> None:
    """Collector body: derive every resource gauge from the live
    objects. Registered on the process registry, so each /metrics
    scrape (and each explicit ``run_collectors``) reflects the current
    structures; series of dead objects are dropped."""
    reg = registry if registry is not None else REGISTRY
    seen: Dict[str, set] = {}

    def set_gauge(metric: str, labels: Tuple[str, ...], value) -> None:
        if value is None:
            return
        fam = reg.gauge(metric, _HELP.get(metric, ""),
                        labels=("family", "index")
                        if metric.startswith("nornicdb_index_")
                        else (("queue",) if metric == "nornicdb_queue_depth"
                              else ("kind",)))
        fam.labels(*labels).set(float(value))
        seen.setdefault(metric, set()).add(labels)

    for entry in snapshot():
        family, name = entry["family"], entry["index"]
        if "queue_depth" in entry and "rows" not in entry:
            set_gauge("nornicdb_queue_depth", (name,),
                      entry["queue_depth"])
            continue
        for metric, key in _INDEX_GAUGES:
            if key in entry:
                set_gauge(metric, (family, name), entry.get(key))
    for kind, count in _dispatch.bucket_counts().items():
        set_gauge("nornicdb_compile_cache_entries", (kind,), count)

    # retire series whose object vanished since the last collection
    # (tracked only for the process registry; private test registries
    # are throwaway and must not disturb the shared bookkeeping)
    if reg is REGISTRY:
        global _live_series
        for metric, keys in _live_series.items():
            fam = reg.get(metric)
            if fam is None:
                continue
            for stale in keys - seen.get(metric, set()):
                fam.remove(stale)
        _live_series = seen


_HELP["nornicdb_queue_depth"] = \
    "Live pending requests in a MicroBatcher queue"
_HELP["nornicdb_compile_cache_entries"] = \
    "Distinct compiled (B, k) buckets per dispatch kind"

REGISTRY.add_collector(update_gauges)
