"""Per-request stage attribution: where did the latency actually go?

The surface latency histograms (PR 3) record ONE end-to-end number per
request; a p99 spike there cannot say whether the request was slow
because it *queued* (coalesce wait behind a busy batch leader) or
because it *computed* (device dispatch). This module splits every
request into the stages the serving path already times:

- ``parse`` — wire bytes -> request message (protobuf ``FromString``,
  HTTP ``json.loads``);
- ``coalesce_wait`` — enqueue into a MicroBatcher/BatchCoalescer until
  the batch leader sealed our batch (the QUEUE-DELAY component);
- ``device_dispatch`` — the shared batched device call (each rider
  attributes the full interval: that is the latency it experienced);
- ``merge`` — post-dispatch truncation/result delivery;
- ``apply`` — a write convoy's merged storage apply;
- ``serialize`` — response message -> wire bytes.

Each lands in ``nornicdb_request_stage_seconds{surface,stage}``
(surface is a bounded, code-chosen name: ``grpc``, ``http``,
``service:vector``, ``service:hybrid``, ``qdrant``,
``qdrant:upsert_convoy``) and the same intervals already ride each
request's trace as spans, so one slow trace and the fleet-wide
histogram tell the same story.

``stage_summary()`` derives the QUEUEING FRACTION per surface —
coalesce-wait seconds over total attributed seconds — the single
number that answers "slow because queued or slow because compute".
Served in ``/admin/telemetry`` (``stages``) and in every SLO
flight-recorder dump.
"""

from __future__ import annotations

from typing import Dict, Optional

from nornicdb_tpu.obs import metrics as _m
from nornicdb_tpu.obs.metrics import LATENCY_BUCKETS, REGISTRY, Registry

# canonical stage names (call sites may add new ones; the catalog in
# docs/observability.md documents the family, not each stage value)
STAGE_PARSE = "parse"
STAGE_COALESCE_WAIT = "coalesce_wait"
STAGE_DISPATCH = "device_dispatch"
STAGE_MERGE = "merge"
STAGE_APPLY = "apply"
STAGE_SERIALIZE = "serialize"

# queue-delay stages for the queueing-fraction rollup
_QUEUE_STAGES = (STAGE_COALESCE_WAIT,)

_STAGE_H = REGISTRY.histogram(
    "nornicdb_request_stage_seconds",
    "Per-request latency attribution by serving stage",
    labels=("surface", "stage"), buckets=LATENCY_BUCKETS)


def record_stage(surface: str, stage: str, seconds: float) -> None:
    """One stage interval of one request. Negative intervals (clock
    skew between the enqueue stamp and a leader stamp) clamp to 0."""
    if not _m.enabled():
        return
    _STAGE_H.labels(surface, stage).observe(
        seconds if seconds > 0.0 else 0.0)


def stage_summary(registry: Optional[Registry] = None) -> Dict[str, Dict]:
    """Per-surface stage decomposition from the stage histograms:

    ``{surface: {"stages": {stage: {"count", "total_ms", "mean_ms"}},
                 "queueing_fraction": wait_s / total_s | None}}``

    Scrape-time work only — reads histogram sums, never the hot path.
    """
    reg = registry if registry is not None else REGISTRY
    fam = reg.get("nornicdb_request_stage_seconds")
    out: Dict[str, Dict] = {}
    if fam is None:
        return out
    for key, child in sorted(fam.children().items()):
        surface, stage = key
        snap = child.snapshot()
        if not snap["count"]:
            continue
        doc = out.setdefault(
            surface, {"stages": {}, "queueing_fraction": None})
        doc["stages"][stage] = {
            "count": snap["count"],
            "total_ms": round(snap["sum"] * 1e3, 3),
            "mean_ms": round(snap["sum"] / snap["count"] * 1e3, 4),
        }
    for doc in out.values():
        total = sum(s["total_ms"] for s in doc["stages"].values())
        if total > 0:
            waited = sum(doc["stages"][s]["total_ms"]
                         for s in _QUEUE_STAGES if s in doc["stages"])
            doc["queueing_fraction"] = round(waited / total, 4)
    return out
