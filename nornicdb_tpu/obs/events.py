"""Unified incident timeline: one causally-ordered event journal.

ISSUE 13. Incidents on a serving fleet — tier degrades, replica
drains/re-admissions, failovers, quarantine step-downs, SLO breaches —
were scattered across per-concern ledgers (the degrade ring, the
router's drain state, failover counters, flight-recorder files) with
no single stream an operator could replay to answer "what happened, in
what order". This module is that stream: a bounded ring of structured
event records, each stamped with a process-monotone sequence number
(assigned under the ring lock, so journal order IS observation order)
and linked to the originating request's trace id when one is active —
including trace ids PROPAGATED across the broker ring or an HTTP hop
(obs/tracing.py), so a degrade on the device plane joins the wire
worker's trace in the timeline.

Served at ``GET /admin/events`` (api/http_server.py), merged across
worker/plane processes by the worker's own ``/admin/events`` route
(api/wire_plane.py), and included in every SLO flight-recorder dump
(``kind: events``).

Producers (wired in this PR):

- ``degrade`` — every :func:`obs.audit.record_degrade` (and broker
  replays, marked ``via: broker``);
- ``drain`` / ``admit`` — fleet-router rotation transitions
  (api/fleet_router.py records the transition, never the steady state);
- ``failover`` — a replica promoted to primary
  (replication/read_fleet.py);
- ``fence_rejected`` — a replica refused a stale-epoch WAL batch;
- ``quarantine`` / ``quarantine_lift`` — the shadow-parity auditor
  stepping a tier down / recovering it (obs/audit.py);
- ``slo_breach`` — a breach-triggered flight-recorder dump
  (obs/slo.py).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from nornicdb_tpu.obs import metrics as _m
from nornicdb_tpu.obs.metrics import REGISTRY
from nornicdb_tpu.obs.tracing import current_trace_id

# tenant stamping (ISSUE 18): obs/tenant.py registers its resolver so
# every journal event carries the active request's tenant — this
# module stays importable without the tenant layer.
_tenant_provider = None


def set_tenant_provider(fn) -> None:
    global _tenant_provider
    _tenant_provider = fn

# the documented event-kind vocabulary — scripts/check_metrics_catalog
# lints each value against docs/observability.md (tier/reason
# precedent, ISSUE 10)
KINDS: Tuple[str, ...] = (
    "degrade",          # a serving ladder step-down (the degrade ledger)
    "drain",            # a replica left the read rotation
    "admit",            # a replica (re)joined the read rotation
    "failover",         # a standby promoted to primary
    "fence_rejected",   # a stale-epoch stream batch was refused
    "quarantine",       # the parity auditor stepped a tier down
    "quarantine_lift",  # the quarantined tier recovered
    "slo_breach",       # a breach-triggered flight-recorder dump
    "shed",             # admission rejected a query (429/exhausted)
                        # or failed it fast past its deadline budget
    "posture",          # the admission posture transitioned
    "lease_grant",      # a replica at the primary watermark was leased
                        # for read-your-writes routing (ISSUE 16)
    "lease_lapse",      # a leader lease expired or was revoked
    "noisy_neighbor",   # one tenant held over the cost-share threshold
                        # of the rolling window while posture >= degrade
                        # (advisory, ISSUE 18 — no actuation)
    "recompile",        # a compile observed after the dispatch kind was
                        # warm: bucket churn at serve time (ISSUE 20)
)

_EVENTS_C = REGISTRY.counter(
    "nornicdb_events_total",
    "Incident-timeline events recorded, by kind",
    labels=("kind",))


def _ring_capacity() -> int:
    try:
        return max(16, int(os.environ.get("NORNICDB_EVENT_RING", "1024")))
    except ValueError:
        return 1024


class EventJournal:
    """Bounded, monotonically-ordered ring of incident events.

    ``record`` assigns the sequence number and appends under ONE lock,
    so two racing producers can never interleave seq order vs ring
    order — the stream replays causally even under 16-thread churn
    (pinned by tests/test_fleet_truth.py). Records are plain dicts,
    fully JSON-able."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self.capacity = capacity or _ring_capacity()
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self.recorded = 0

    def record(self, kind: str, node: str = "", surface: str = "",
               reason: str = "", detail: Optional[Dict[str, Any]] = None,
               trace_id: Optional[str] = None) -> Dict[str, Any]:
        """Append one event. ``trace_id`` defaults to the active trace
        (including one propagated across a process boundary); ``seq``
        is assigned under the ring lock. Never raises, never blocks
        beyond the one short lock hold."""
        if trace_id is None:
            trace_id = current_trace_id()
        rec: Dict[str, Any] = {
            "ts": round(time.time(), 6),
            "kind": str(kind),
        }
        if node:
            rec["node"] = str(node)
        if surface:
            rec["surface"] = str(surface)
        if reason:
            rec["reason"] = str(reason)
        if trace_id:
            rec["trace_id"] = str(trace_id)
        if _tenant_provider is not None:
            tenant = _tenant_provider()
            if tenant:
                rec["tenant"] = str(tenant)
        if detail:
            rec["detail"] = dict(detail)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            self.recorded += 1
        if _m.enabled():
            _EVENTS_C.labels(rec["kind"]).inc()
        return rec

    def snapshot(self, limit: int = 100,
                 kind: Optional[str] = None) -> List[Dict[str, Any]]:
        """The most recent ``limit`` events in STREAM order (ascending
        seq — the timeline reads top-to-bottom), optionally filtered by
        kind."""
        with self._lock:
            items = list(self._ring)
        if kind is not None:
            items = [r for r in items if r["kind"] == kind]
        return items[-max(0, limit):]

    def by_kind(self) -> Dict[str, int]:
        with self._lock:
            items = list(self._ring)
        out: Dict[str, int] = {}
        for rec in items:
            out[rec["kind"]] = out.get(rec["kind"], 0) + 1
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


JOURNAL = EventJournal()


def record_event(kind: str, node: str = "", surface: str = "",
                 reason: str = "", detail: Optional[Dict[str, Any]] = None,
                 trace_id: Optional[str] = None) -> None:
    """Module-level convenience over the process journal; a disabled
    telemetry layer records nothing."""
    if not _m.enabled():
        return
    JOURNAL.record(kind, node=node, surface=surface, reason=reason,
                   detail=detail, trace_id=trace_id)


def event_snapshot(limit: int = 100,
                   kind: Optional[str] = None) -> List[Dict[str, Any]]:
    return JOURNAL.snapshot(limit=limit, kind=kind)


def event_summary() -> Dict[str, Any]:
    """The ``/admin/events`` envelope (the caller appends the ring)."""
    return {
        "recorded": JOURNAL.recorded,
        "capacity": JOURNAL.capacity,
        "by_kind": JOURNAL.by_kind(),
    }
