"""Fleet telemetry aggregator: one merged view over many registries.

ISSUE 13. The multi-worker wire plane (PR 11) taught each WORKER to
merge the shared device plane's metrics into its own scrape, and the
read fleet (PR 12) put per-node gauges in one shared registry for
in-process topologies — but there was no single surface that answers
"what is the FLEET doing" across processes and hosts. This module is
that surface: named telemetry *sources* (each a zero-arg callable
returning an ``obs.metrics.dump_state`` snapshot — the broker's
``metrics_state`` plane op, a remote node's ``GET /admin/fleet/state``,
or any custom feed) merge with the local registry under the exact
``render_merged`` discipline (counters/histograms sum, remote gauges
win) and serve:

- ``GET /admin/fleet`` — the summary: per-source health, wire worker
  count, per-replica lag/apply-delay truth (``lag_ops`` AND the
  ISSUE 13 ``nornicdb_replication_apply_delay_seconds`` p50/p99 in
  milliseconds — seconds-not-ops), failover counts, the merged
  served-tier mix, and the local incident-timeline rollup;
- ``GET /admin/fleet/state`` — this node's ``dump_state`` in a
  JSON-safe shape, the scrape endpoint remote aggregators pull.

A failing source reports an error string in the summary and
contributes nothing — a dead replica can never break the admin
surface.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from nornicdb_tpu.obs import events as _events
from nornicdb_tpu.obs import metrics as _m
from nornicdb_tpu.obs.metrics import REGISTRY, dump_state, merge_states

_lock = threading.Lock()
_sources: Dict[str, Callable[[], List[Dict]]] = {}

REGISTRY.gauge(
    "nornicdb_fleet_sources",
    "Remote telemetry sources registered with the fleet aggregator",
    fn=lambda: float(len(_sources)))


def register_source(name: str, fn: Callable[[], List[Dict]]) -> None:
    """Register one remote telemetry source. ``fn`` returns a
    ``dump_state``-shaped list (or raises — the summary then carries
    the error). Re-registering a name replaces the prior source."""
    with _lock:
        _sources[str(name)] = fn


def unregister_source(name: str) -> None:
    with _lock:
        _sources.pop(str(name), None)


def http_state_source(base_url: str, timeout_s: float = 2.0,
                      auth: Optional[str] = None
                      ) -> Callable[[], List[Dict]]:
    """Source over a remote node's ``GET /admin/fleet/state`` —
    the multi-host feed (RemoteReplica topologies)."""
    url = base_url.rstrip("/") + "/admin/fleet/state"

    def fetch() -> List[Dict]:
        import json
        import urllib.request

        req = urllib.request.Request(
            url, headers={"Authorization": auth} if auth else {})
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            doc = json.loads(resp.read() or b"{}")
        return state_from_jsonable(doc.get("state") or [])

    return fetch


# -- JSON-safe transport shape ----------------------------------------------
#
# dump_state children are keyed by label-value TUPLES — fine over the
# broker's pickle, not representable as JSON object keys. The HTTP
# transport flattens children to [labels, value] pairs.


def state_to_jsonable(state: List[Dict]) -> List[Dict]:
    out: List[Dict] = []
    for fam in state:
        out.append({
            "name": fam["name"], "kind": fam["kind"],
            "help": fam["help"], "labels": list(fam["labels"]),
            "children": [[list(key), value]
                         for key, value in fam["children"].items()],
        })
    return out


def state_from_jsonable(doc: List[Dict]) -> List[Dict]:
    out: List[Dict] = []
    for fam in doc:
        children: Dict[Tuple[str, ...], Any] = {}
        for key, value in fam.get("children", ()):
            if isinstance(value, dict) and value.get("exemplars"):
                value = {**value,
                         "exemplars": [tuple(e) if e else None
                                       for e in value["exemplars"]]}
            children[tuple(key)] = value
        out.append({"name": fam["name"], "kind": fam["kind"],
                    "help": fam.get("help", ""),
                    "labels": tuple(fam.get("labels", ())),
                    "children": children})
    return out


# -- fleet admission posture (ISSUE 16) -------------------------------------
#
# Cross-NODE posture propagation rides the telemetry the aggregator
# already pulls: every node's ``nornicdb_admission_posture`` gauge
# carries its LOCAL posture; the sweep below takes the max across every
# registered source and feeds it to the local AdmissionController as a
# posture source — an overloaded replica tightens the primary's
# admission verdict (and vice versa) without a new control protocol.

_plock = threading.Lock()
_pstate: Dict[str, Any] = {"level": 0, "at": 0.0, "busy": False}


def _sweep_remote_posture() -> int:
    """Max peer posture level across every source's state dump. Slow
    (remote HTTP fetches) — never called on a request path directly;
    see :func:`remote_posture`."""
    with _lock:
        sources = dict(_sources)
    level = 0
    for _name, fn in sources.items():
        try:
            state = fn() or []
        except Exception:  # noqa: BLE001 — a dead peer is not overload
            continue
        for fam in state:
            if fam.get("name") != "nornicdb_admission_posture":
                continue
            for v in fam.get("children", {}).values():
                try:
                    level = max(level, int(float(v)))
                except (TypeError, ValueError):
                    pass
    with _plock:
        _pstate["level"] = level
        _pstate["at"] = time.time()
        _pstate["busy"] = False
    return level


def refresh_remote_posture() -> Tuple[int, float]:
    """Synchronous sweep (tests pin propagation with this; admin
    surfaces may too): (max peer level, age 0)."""
    with _plock:
        _pstate["busy"] = True
    return _sweep_remote_posture(), 0.0


def remote_posture(ttl_s: float = 5.0) -> Optional[Tuple[int, float]]:
    """(max peer posture level, age_s) from the last sweep — the
    AdmissionController posture-source shape. NON-BLOCKING: a stale
    cache kicks one background sweep and returns the stale value (whose
    age the controller's TTL check then ignores); the request path
    never waits on a peer's HTTP surface."""
    now = time.time()
    kick = False
    with _plock:
        at = _pstate["at"]
        if (now - at) > ttl_s and not _pstate["busy"]:
            _pstate["busy"] = True
            kick = True
        level = _pstate["level"]
    if kick:
        threading.Thread(target=_sweep_remote_posture, daemon=True,
                         name="fleet-posture").start()
    if at <= 0.0:
        return None
    return int(level), now - at


def posture_source(ttl_s: Optional[float] = None
                   ) -> Callable[[], Optional[Tuple[int, float]]]:
    """A posture source over the aggregator, for
    ``admission.CONTROLLER.add_posture_source``. ``ttl_s`` defaults to
    the controller's own ``NORNICDB_FLEET_POSTURE_TTL_S``."""

    def source() -> Optional[Tuple[int, float]]:
        t = ttl_s
        if t is None:
            from nornicdb_tpu import admission

            t = admission.cfg()["fleet_posture_ttl_s"]
        return remote_posture(t)

    return source


# -- aggregation ------------------------------------------------------------


def fleet_state(registry=None) -> Tuple[Dict[str, Dict], Dict[str, str]]:
    """(merged family map, per-source status). The local registry is
    always one side of the merge; each registered source contributes
    its snapshot or an error entry."""
    reg = registry if registry is not None else REGISTRY
    with _lock:
        sources = dict(_sources)
    remote_states: List[List[Dict]] = []
    status: Dict[str, str] = {}
    for name, fn in sources.items():
        try:
            state = fn()
            remote_states.append(state or [])
            status[name] = "ok"
        except Exception as exc:  # noqa: BLE001 — summary must render
            status[name] = f"error: {type(exc).__name__}: {exc}"[:200]
    return merge_states(dump_state(reg), remote_states), status


def render_fleet(registry=None,
                 openmetrics: bool = False) -> str:
    """Merged Prometheus exposition across every source — one scrape
    for the whole fleet."""
    merged, _status = fleet_state(registry)
    return _m.render_state(merged, openmetrics=openmetrics)


def _quantile_from_snapshot(snap: Dict[str, Any],
                            q: float) -> Optional[float]:
    """Bucket-interpolated quantile over a merged histogram snapshot
    (same math as Histogram.quantile, but over the wire shape)."""
    total = snap.get("count", 0)
    if not total:
        return None
    bounds = snap["buckets"]
    rank = q * total
    cum = 0.0
    for i, c in enumerate(snap["counts"]):
        prev = cum
        cum += c
        if cum >= rank:
            if i >= len(bounds):
                return bounds[-1]
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            if c == 0:
                return hi
            return lo + (hi - lo) * (rank - prev) / c
    return bounds[-1] if bounds else None


def _children(merged: Dict[str, Dict], name: str) -> Dict:
    fam = merged.get(name)
    return fam["children"] if fam else {}


def fleet_summary(registry=None) -> Dict[str, Any]:
    """The ``GET /admin/fleet`` payload: one JSON answer to "what is
    the fleet doing", derived from the merged state."""
    merged, status = fleet_state(registry)
    replicas: Dict[str, Dict[str, Any]] = {}
    for key, v in _children(merged, "nornicdb_replica_lag_ops").items():
        replicas.setdefault(key[0], {})["lag_ops"] = v
    for key, v in _children(merged,
                            "nornicdb_replica_applied_seq").items():
        replicas.setdefault(key[0], {})["applied_seq"] = v
    for key, v in _children(merged,
                            "nornicdb_replica_catching_up").items():
        replicas.setdefault(key[0], {})["catching_up"] = bool(v)
    for key, v in _children(merged, "nornicdb_replica_admitted").items():
        replicas.setdefault(key[0], {})["admitted"] = bool(v)
    # seconds-not-ops (ISSUE 13): per-node replication apply delay —
    # "lag 400 ops" becomes "p99 replay delay 38 ms"
    for key, snap in _children(
            merged, "nornicdb_replication_apply_delay_seconds").items():
        if not isinstance(snap, dict) or not snap.get("count"):
            continue
        node = replicas.setdefault(key[0], {})
        node["apply_delay_ms"] = {
            "count": snap["count"],
            "p50": _ms(_quantile_from_snapshot(snap, 0.5)),
            "p99": _ms(_quantile_from_snapshot(snap, 0.99)),
        }
    failovers = {key[0]: v for key, v in
                 _children(merged, "nornicdb_fleet_failover_total").items()
                 if v}
    tiers: Dict[str, Dict[str, float]] = {}
    for key, v in _children(merged, "nornicdb_served_tier_total").items():
        if v:
            tiers.setdefault(key[0], {})[key[1]] = v
    workers = None
    for _key, v in _children(merged, "nornicdb_wire_workers").items():
        workers = v
    # per-tenant truth over the SAME merged state (ISSUE 18): the
    # fleet view answers "which tenant is doing this to us" with the
    # identical exactly-once merge discipline as the series above
    from nornicdb_tpu.obs import tenant as _tenant

    return {
        "sources": status,
        "families": len(merged),
        "workers": workers,
        "replicas": replicas,
        "failovers": failovers,
        "tiers": tiers,
        "tenants": _tenant.tenants_summary(state=merged),
        "events": _events.event_summary(),
    }


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1e3, 3)
