"""Low-overhead metric primitives + Prometheus text exposition.

Dependency-free (stdlib only) so every layer — storage, search, wire —
can record without import cycles. Three metric kinds:

- :class:`Counter` — monotone float, lock-striped by thread id so N
  handler threads incrementing one hot counter don't serialize on a
  single lock (the reference surfaces run 8-16 worker threads).
- :class:`Gauge` — last-write-wins scalar, or callback-backed for
  values that are cheaper to read on scrape than to maintain (node
  counts, cache sizes).
- :class:`Histogram` — fixed upper-bound buckets with the full
  Prometheus exposition contract (``_bucket`` with ``le`` labels
  including ``+Inf``, ``_sum``, ``_count``) and bucket-interpolated
  quantile estimation for the bench/admin summaries.

Metrics are registered in a :class:`Registry`; label sets materialize
child series on first use (``labels(...)``) keyed by the label-value
tuple, so the hot path after the first request is one dict probe + one
striped add. ``set_enabled(False)`` turns every record call into a
no-op branch — the overhead-guard test measures the delta.
"""

from __future__ import annotations

import os
import threading
import time
from bisect import bisect_left
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_STRIPES = 8

_enabled = True

# label-cardinality cap per metric family: past this many materialized
# children, new label-value tuples fold into one "__other__" series and
# tick the dropped-labels counter — per-collection labels (multidb
# churn, qdrant collections) can then never blow up the exposition
_DEFAULT_MAX_LABEL_CHILDREN = 256


def default_max_label_children() -> int:
    try:
        return int(os.environ.get("NORNICDB_OBS_MAX_LABELS",
                                  _DEFAULT_MAX_LABEL_CHILDREN))
    except ValueError:
        return _DEFAULT_MAX_LABEL_CHILDREN


def set_enabled(value: bool) -> None:
    """Process-wide kill switch. Record calls become a single branch;
    already-registered metrics keep their accumulated values."""
    global _enabled
    _enabled = value


def enabled() -> bool:
    return _enabled


# -- exemplars ---------------------------------------------------------------
#
# Histograms optionally remember, per bucket, the trace id of the most
# recent observation that landed there — so a p99 spike on a dashboard
# links to a concrete trace in /admin/traces. The trace id comes from a
# provider callback (registered by obs/tracing at import; metrics stays
# importable standalone). Exemplars surface ONLY in the OpenMetrics
# exposition (content-negotiated at /metrics); the classic Prometheus
# text stays byte-identical with tagging on or off.

_exemplar_provider: Optional[Callable[[], Optional[str]]] = None


def _env_exemplars_default() -> bool:
    return os.environ.get("NORNICDB_OBS_EXEMPLARS", "1").lower() \
        not in ("0", "false", "off")


_exemplars_enabled = _env_exemplars_default()


def set_exemplar_provider(fn: Optional[Callable[[], Optional[str]]]) -> None:
    global _exemplar_provider
    _exemplar_provider = fn


def set_exemplars_enabled(value: bool) -> None:
    """Runtime toggle (initial state from NORNICDB_OBS_EXEMPLARS,
    default on). Off = observe() skips the provider call entirely."""
    global _exemplars_enabled
    _exemplars_enabled = bool(value)


def exemplars_enabled() -> bool:
    return _exemplars_enabled


# request-latency buckets (seconds): 50us floor (cache-hit wire replies
# land there) to 10s ceiling, roughly x2-x2.5 steps — 17 buckets
LATENCY_BUCKETS: Tuple[float, ...] = (
    50e-6, 100e-6, 250e-6, 500e-6,
    1e-3, 2.5e-3, 5e-3, 10e-3, 25e-3, 50e-3, 100e-3, 250e-3, 500e-3,
    1.0, 2.5, 5.0, 10.0,
)

# batch/queue-size buckets: powers of two, matching the pow2 compile
# bucketing of the device dispatch path
SIZE_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(names: Sequence[str], values: Sequence[str],
                extra: Optional[Tuple[str, str]] = None) -> str:
    parts = [f'{n}="{_escape_label(str(v))}"'
             for n, v in zip(names, values)]
    if extra is not None:
        parts.append(f'{extra[0]}="{extra[1]}"')
    return "{" + ",".join(parts) + "}" if parts else ""


class Counter:
    """Monotone counter, lock-striped across threads."""

    __slots__ = ("_locks", "_values")

    def __init__(self) -> None:
        self._locks = [threading.Lock() for _ in range(_STRIPES)]
        self._values = [0.0] * _STRIPES

    def inc(self, value: float = 1.0) -> None:
        if not _enabled:
            return
        s = threading.get_ident() % _STRIPES
        with self._locks[s]:
            self._values[s] += value

    @property
    def value(self) -> float:
        return sum(self._values)


class Gauge:
    """Last-write-wins scalar, or callback-backed (read on scrape)."""

    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self, fn: Optional[Callable[[], float]] = None) -> None:
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn = fn

    def set(self, value: float) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value = float(value)

    def inc(self, value: float = 1.0) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — scrape must never fail
                return 0.0
        return self._value


class Histogram:
    """Fixed-bucket histogram. ``observe`` is a bisect + one locked
    bucket increment; cumulative counts are computed at render time."""

    __slots__ = ("_bounds", "_lock", "_counts", "_sum", "_count",
                 "_exemplars")

    def __init__(self, buckets: Sequence[float] = LATENCY_BUCKETS) -> None:
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket")
        self._bounds = bounds
        self._lock = threading.Lock()
        self._counts = [0] * (len(bounds) + 1)  # last slot = +Inf
        self._sum = 0.0
        self._count = 0
        # per-bucket (trace_id, value, ts) of the latest traced
        # observation; allocated lazily on the first tagged observe so
        # untraced histograms pay nothing
        self._exemplars: Optional[List[Optional[Tuple[str, float, float]]]] \
            = None

    def observe(self, value: float) -> None:
        if not _enabled:
            return
        i = bisect_left(self._bounds, value)
        tid = None
        if _exemplars_enabled and _exemplar_provider is not None:
            tid = _exemplar_provider()
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            if tid is not None:
                if self._exemplars is None:
                    self._exemplars = [None] * len(self._counts)
                self._exemplars[i] = (tid, value, time.time())

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            counts = list(self._counts)
            return {"buckets": list(self._bounds), "counts": counts,
                    "sum": self._sum, "count": self._count}

    def exemplars(self) -> List[Optional[Tuple[str, float, float]]]:
        """Per-bucket (trace_id, value, ts) or None — same slot order as
        ``snapshot()['counts']`` (+Inf last)."""
        with self._lock:
            if self._exemplars is None:
                return [None] * len(self._counts)
            return list(self._exemplars)

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-interpolated quantile estimate (Prometheus
        histogram_quantile semantics); None when empty."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return None
        rank = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            prev_cum = cum
            cum += c
            if cum >= rank:
                if i >= len(self._bounds):  # +Inf bucket: clamp to top
                    return self._bounds[-1]
                lo = self._bounds[i - 1] if i > 0 else 0.0
                hi = self._bounds[i]
                if c == 0:
                    return hi
                return lo + (hi - lo) * (rank - prev_cum) / c
        return self._bounds[-1]


class _Family:
    """One metric name with 0+ label dimensions; children materialize
    per label-value tuple, capped at ``max_children`` distinct tuples —
    overflow folds into one ``__other__`` series (and ticks the
    registry's dropped-labels counter) so client-driven label values
    can never grow the exposition without bound."""

    def __init__(self, name: str, kind: str, help_text: str,
                 label_names: Tuple[str, ...],
                 make: Callable[[], object],
                 max_children: Optional[int] = None,
                 on_drop: Optional[Callable[[str], None]] = None) -> None:
        self.name = name
        self.kind = kind
        self.help = help_text
        self.label_names = label_names
        self._make = make
        self._max_children = max_children
        self._on_drop = on_drop
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not label_names:
            self._children[()] = make()

    @property
    def _overflow_key(self) -> Tuple[str, ...]:
        return ("__other__",) * len(self.label_names)

    def labels(self, *values: object):
        key = tuple(str(v) for v in values)
        if len(key) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {key}")
        child = self._children.get(key)
        if child is None:
            dropped = False
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    cap = self._max_children
                    if (cap is not None and key != self._overflow_key
                            and len(self._children) >= cap):
                        # fold: the overflow child is exempt from the
                        # cap so it can always materialize
                        key = self._overflow_key
                        child = self._children.get(key)
                        if child is None:
                            child = self._children[key] = self._make()
                        dropped = True
                    else:
                        child = self._children[key] = self._make()
                        dropped = False
            if dropped and self._on_drop is not None:
                self._on_drop(self.name)
        return child

    def remove(self, key: Tuple[str, ...]) -> None:
        """Drop one child series (used by gauge collectors whose label
        source — an index, a queue — has been garbage-collected, so the
        exposition doesn't carry dead series forever)."""
        with self._lock:
            self._children.pop(tuple(str(v) for v in key), None)

    def child(self):
        """The unlabeled child (only valid for label-less families)."""
        return self._children[()]

    def _maybe_child(self):
        return self._children.get(())

    # convenience passthroughs for label-less families
    def inc(self, value: float = 1.0) -> None:
        self.child().inc(value)

    def set(self, value: float) -> None:
        self.child().set(value)

    def observe(self, value: float) -> None:
        self.child().observe(value)

    @property
    def value(self) -> float:
        return self.child().value

    def quantile(self, q: float):
        """None (not a raise) on a labeled family with no unlabeled
        child or an empty histogram — percentile math over new/idle
        series must degrade to nulls, never to a 500."""
        child = self._maybe_child()
        return None if child is None else child.quantile(q)

    def snapshot(self):
        child = self._maybe_child()
        if child is None:
            return {"buckets": [], "counts": [], "sum": 0.0, "count": 0}
        return child.snapshot()

    def children(self) -> Dict[Tuple[str, ...], object]:
        with self._lock:
            return dict(self._children)

    def render(self, out: List[str]) -> None:
        out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        for key, child in sorted(self.children().items()):
            if self.kind == "histogram":
                snap = child.snapshot()
                cum = 0
                for bound, c in zip(snap["buckets"], snap["counts"]):
                    cum += c
                    lbl = _fmt_labels(self.label_names, key,
                                      ("le", _fmt_float(bound)))
                    out.append(f"{self.name}_bucket{lbl} {cum}")
                cum += snap["counts"][-1]
                lbl = _fmt_labels(self.label_names, key, ("le", "+Inf"))
                out.append(f"{self.name}_bucket{lbl} {cum}")
                base = _fmt_labels(self.label_names, key)
                out.append(f"{self.name}_sum{base} {_fmt_float(snap['sum'])}")
                out.append(f"{self.name}_count{base} {snap['count']}")
            else:
                lbl = _fmt_labels(self.label_names, key)
                out.append(f"{self.name}{lbl} {_fmt_float(child.value)}")

    def render_openmetrics(self, out: List[str]) -> None:
        """OpenMetrics exposition of this family. Differences from the
        classic text: counter families are named WITHOUT the ``_total``
        suffix in TYPE/HELP (samples keep it, per the OM spec), bucket
        ``le`` values are canonical floats, and histogram bucket lines
        carry ``# {trace_id=...} value ts`` exemplars when tagged."""
        name = self.name
        if self.kind == "counter":
            base = name[:-6] if name.endswith("_total") else name
            out.append(f"# TYPE {base} counter")
            if self.help:
                out.append(f"# HELP {base} {self.help}")
            sample = base + "_total" if name.endswith("_total") else name
            for key, child in sorted(self.children().items()):
                lbl = _fmt_labels(self.label_names, key)
                out.append(f"{sample}{lbl} {_fmt_float(child.value)}")
            return
        out.append(f"# TYPE {name} {self.kind}")
        if self.help:
            out.append(f"# HELP {name} {self.help}")
        for key, child in sorted(self.children().items()):
            if self.kind == "histogram":
                snap = child.snapshot()
                exemplars = child.exemplars()
                cum = 0
                bounds = list(snap["buckets"]) + [None]  # None = +Inf
                for i, bound in enumerate(bounds):
                    cum += snap["counts"][i]
                    le = "+Inf" if bound is None else repr(float(bound))
                    lbl = _fmt_labels(self.label_names, key, ("le", le))
                    line = f"{name}_bucket{lbl} {cum}"
                    ex = exemplars[i]
                    if ex is not None:
                        tid, val, ts = ex
                        line += (f' # {{trace_id="{_escape_label(tid)}"}}'
                                 f" {_fmt_float(val)} {ts:.3f}")
                    out.append(line)
                base_l = _fmt_labels(self.label_names, key)
                out.append(
                    f"{name}_sum{base_l} {_fmt_float(snap['sum'])}")
                out.append(f"{name}_count{base_l} {snap['count']}")
            else:
                lbl = _fmt_labels(self.label_names, key)
                out.append(f"{name}{lbl} {_fmt_float(child.value)}")


def _fmt_float(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class Registry:
    """Named metric families; ``render()`` emits the Prometheus text
    exposition. get-or-create is idempotent so call sites can resolve
    their metrics lazily without coordinating registration order.

    ``max_label_children`` caps the per-family label cardinality
    (default from ``NORNICDB_OBS_MAX_LABELS``); overflow folds into an
    ``__other__`` series counted by
    ``nornicdb_metric_labels_dropped_total{metric=...}``.

    Collectors (``add_collector``) run at the start of every
    ``render()`` — callback hooks for gauge families whose values are
    derived on scrape (index memory/freshness accounting, SLO burn
    rates) rather than maintained on the hot path."""

    def __init__(self, max_label_children: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        self._collectors: List[Callable[[], None]] = []
        self.max_label_children = (
            default_max_label_children() if max_label_children is None
            else max_label_children)
        self.started_at = time.time()

    def _note_dropped(self, metric_name: str) -> None:
        # bounded by the number of families, so this family itself can
        # never meaningfully overflow its own cap
        self.counter(
            "nornicdb_metric_labels_dropped_total",
            "Label tuples folded into __other__ by the cardinality cap",
            labels=("metric",)).labels(metric_name).inc()

    def _get_or_create(self, name: str, kind: str, help_text: str,
                       label_names: Tuple[str, ...],
                       make: Callable[[], object]) -> _Family:
        fam = self._families.get(name)
        if fam is not None:
            if fam.kind != kind:
                raise ValueError(
                    f"metric {name} already registered as {fam.kind}")
            return fam
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, kind, help_text, label_names, make,
                              max_children=self.max_label_children,
                              on_drop=self._note_dropped)
                self._families[name] = fam
            return fam

    def counter(self, name: str, help_text: str = "",
                labels: Sequence[str] = ()) -> _Family:
        return self._get_or_create(name, "counter", help_text,
                                   tuple(labels), Counter)

    def gauge(self, name: str, help_text: str = "",
              labels: Sequence[str] = (),
              fn: Optional[Callable[[], float]] = None) -> _Family:
        return self._get_or_create(name, "gauge", help_text,
                                   tuple(labels), lambda: Gauge(fn))

    def histogram(self, name: str, help_text: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> _Family:
        return self._get_or_create(name, "histogram", help_text,
                                   tuple(labels),
                                   lambda: Histogram(buckets))

    def get(self, name: str) -> Optional[_Family]:
        return self._families.get(name)

    def families(self) -> List[_Family]:
        with self._lock:
            return list(self._families.values())

    def add_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:  # noqa: BLE001 — a scrape must never fail
                pass

    def render(self, extra_gauges: Optional[Dict[str, float]] = None) -> str:
        self.run_collectors()
        out: List[str] = []
        for fam in sorted(self.families(), key=lambda f: f.name):
            fam.render(out)
        for name, value in sorted((extra_gauges or {}).items()):
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name} {_fmt_float(value)}")
        return "\n".join(out) + "\n"

    OPENMETRICS_CONTENT_TYPE = (
        "application/openmetrics-text; version=1.0.0; charset=utf-8")

    def render_openmetrics(
            self, extra_gauges: Optional[Dict[str, float]] = None) -> str:
        """OpenMetrics 1.0 exposition (exemplars included, ``# EOF``
        terminated). Served at /metrics under content negotiation; the
        classic ``render()`` text is untouched by exemplar tagging."""
        self.run_collectors()
        out: List[str] = []
        for fam in sorted(self.families(), key=lambda f: f.name):
            fam.render_openmetrics(out)
        for name, value in sorted((extra_gauges or {}).items()):
            out.append(f"# TYPE {name} gauge")
            out.append(f"{name} {_fmt_float(value)}")
        out.append("# EOF")
        return "\n".join(out) + "\n"


# the process-wide registry every layer records into; tests that need
# isolation construct private Registry instances instead
REGISTRY = Registry()


def get_registry() -> Registry:
    return REGISTRY


def dump_state(registry: Optional[Registry] = None) -> List[Dict]:
    """Picklable snapshot of every family (collectors run first) — the
    device plane ships this across the broker so each frontend
    worker's /metrics scrape can include the shared-plane series
    exactly once (ISSUE 11). Shape per family: ``{"name", "kind",
    "help", "labels", "children": {label_tuple: float |
    histogram-snapshot}}``."""
    reg = registry if registry is not None else REGISTRY
    reg.run_collectors()
    out: List[Dict] = []
    for fam in reg.families():
        children: Dict[Tuple[str, ...], object] = {}
        for key, child in fam.children().items():
            if fam.kind == "histogram":
                snap = child.snapshot()
                # exemplars ride the snapshot so a worker's OpenMetrics
                # scrape can still join a shared-plane p99 bucket to a
                # trace — without this the plane's bucket exemplars are
                # silently dropped at the merge (ISSUE 13 satellite)
                ex = child.exemplars()
                if any(e is not None for e in ex):
                    snap = {**snap, "exemplars": ex}
                children[key] = snap
            else:
                children[key] = float(child.value)
        out.append({"name": fam.name, "kind": fam.kind, "help": fam.help,
                    "labels": tuple(fam.label_names),
                    "children": children})
    return out


def merge_states(local_state: List[Dict],
                 remote_states: Sequence[List[Dict]]) -> Dict[str, Dict]:
    """Merge ``dump_state`` snapshots under the multi-worker "exactly
    once" contract (counters/histograms SUM per label tuple, remote
    gauges win on conflict, union otherwise). Shared by
    :func:`render_merged` (a worker's /metrics scrape) and the fleet
    telemetry aggregator (obs/fleet.py)."""
    merged: Dict[str, Dict] = {}
    for fam_state in local_state:
        merged[fam_state["name"]] = {
            **fam_state, "children": dict(fam_state["children"])}
    for state in remote_states:
        for fam in state:
            mine = merged.get(fam["name"])
            if mine is None or mine["kind"] != fam["kind"]:
                merged[fam["name"]] = {
                    **fam, "children": dict(fam["children"])}
                continue
            for key, rv in fam["children"].items():
                lv = mine["children"].get(key)
                if lv is None:
                    mine["children"][key] = rv
                elif fam["kind"] == "counter":
                    mine["children"][key] = float(lv) + float(rv)
                elif fam["kind"] == "gauge":
                    mine["children"][key] = rv  # shared plane wins
                else:  # histogram: sum counts when bounds agree
                    if lv["buckets"] == rv["buckets"]:
                        mine["children"][key] = {
                            "buckets": lv["buckets"],
                            "counts": [a + b for a, b in
                                       zip(lv["counts"], rv["counts"])],
                            "sum": lv["sum"] + rv["sum"],
                            "count": lv["count"] + rv["count"],
                            "exemplars": _merge_exemplars(
                                lv.get("exemplars"),
                                rv.get("exemplars"),
                                len(lv["counts"])),
                        }
                    else:
                        mine["children"][key] = rv
    return merged


def _merge_exemplars(a, b, n: int):
    """Per-bucket newest-wins exemplar merge; None when neither side
    tagged anything (keeps the merged snapshot lean)."""
    if not a and not b:
        return None
    out = []
    for i in range(n):
        ea = a[i] if a and i < len(a) else None
        eb = b[i] if b and i < len(b) else None
        if ea is not None and eb is not None:
            out.append(ea if ea[2] >= eb[2] else eb)
        else:
            out.append(ea if ea is not None else eb)
    return out


def render_merged(remote_states: Sequence[List[Dict]],
                  registry: Optional[Registry] = None,
                  extra_gauges: Optional[Dict[str, float]] = None,
                  openmetrics: bool = False) -> str:
    """Prometheus exposition of the LOCAL registry merged with remote
    ``dump_state`` snapshots. Merge discipline (the "exactly once"
    contract of the multi-worker wire plane):

    - counters and histograms SUM per label tuple — a family the
      worker registered at import but never observed contributes 0, so
      the shared plane's series appear once with the true value;
    - gauges: the remote (shared-plane) value wins on a label-tuple
      conflict — index memory/freshness/compile-universe gauges are
      owned by the device plane, a worker-local zero must not mask
      them — and union otherwise.

    ``openmetrics=True`` renders the OpenMetrics 1.0 exposition
    instead (counter TYPE sans ``_total``, ``# EOF``, and bucket
    exemplars — newest wins per bucket across the merged sides), so a
    worker scrape under content negotiation keeps the shared plane's
    trace-id exemplar joins (ISSUE 13 satellite).
    """
    reg = registry if registry is not None else REGISTRY
    merged = merge_states(dump_state(reg), remote_states)
    return render_state(merged, extra_gauges=extra_gauges,
                        openmetrics=openmetrics)


def render_state(merged: Dict[str, Dict],
                 extra_gauges: Optional[Dict[str, float]] = None,
                 openmetrics: bool = False) -> str:
    """Render a merged family map (:func:`merge_states`) as the classic
    or OpenMetrics text exposition."""
    out: List[str] = []
    for name in sorted(merged):
        fam = merged[name]
        label_names = tuple(fam["labels"])
        if openmetrics and fam["kind"] == "counter":
            base = name[:-6] if name.endswith("_total") else name
            out.append(f"# TYPE {base} counter")
            if fam["help"]:
                out.append(f"# HELP {base} {fam['help']}")
        else:
            if openmetrics:
                out.append(f"# TYPE {name} {fam['kind']}")
                if fam["help"]:
                    out.append(f"# HELP {name} {fam['help']}")
            else:
                out.append(f"# HELP {name} {fam['help']}")
                out.append(f"# TYPE {name} {fam['kind']}")
        for key in sorted(fam["children"]):
            val = fam["children"][key]
            if fam["kind"] == "histogram":
                exemplars = val.get("exemplars") if openmetrics else None
                cum = 0
                bounds = list(val["buckets"]) + [None]  # None = +Inf
                for i, bound in enumerate(bounds):
                    cum += val["counts"][i]
                    if openmetrics:
                        le = ("+Inf" if bound is None
                              else repr(float(bound)))
                    else:
                        le = ("+Inf" if bound is None
                              else _fmt_float(bound))
                    lbl = _fmt_labels(label_names, key, ("le", le))
                    line = f"{name}_bucket{lbl} {cum}"
                    ex = (exemplars[i] if exemplars
                          and i < len(exemplars) else None)
                    if ex is not None:
                        tid, v, ts = ex
                        line += (f' # {{trace_id="{_escape_label(tid)}"}}'
                                 f" {_fmt_float(v)} {ts:.3f}")
                    out.append(line)
                base_l = _fmt_labels(label_names, key)
                out.append(f"{name}_sum{base_l} {_fmt_float(val['sum'])}")
                out.append(f"{name}_count{base_l} {val['count']}")
            else:
                lbl = _fmt_labels(label_names, key)
                out.append(f"{name}{lbl} {_fmt_float(val)}")
    for name, value in sorted((extra_gauges or {}).items()):
        out.append(f"# TYPE {name} gauge")
        out.append(f"{name} {_fmt_float(value)}")
    if openmetrics:
        out.append("# EOF")
    return "\n".join(out) + "\n"


def latency_summary(registry: Optional[Registry] = None,
                    quantiles: Sequence[float] = (0.5, 0.95, 0.99),
                    include_empty: bool = False,
                    ) -> Dict[str, Dict[str, float]]:
    """p50/p95/p99 (ms) + count for every ``*_seconds`` histogram
    series — one flat dict keyed ``name{label=value,...}``. Shared by
    the /admin/telemetry endpoint and bench.py's percentile stage.

    ``include_empty=True`` also lists series with zero observations
    (count 0, null percentiles) — brand-new histograms must read as
    nulls on the admin surface, never raise or silently vanish."""
    out: Dict[str, Dict[str, float]] = {}
    reg = registry if registry is not None else REGISTRY
    for fam in reg.families():
        if fam.kind != "histogram" or not fam.name.endswith("_seconds"):
            continue
        children = sorted(fam.children().items())
        if not children and include_empty:
            out[fam.name] = {"count": 0}
            for qv in quantiles:
                out[fam.name][f"p{int(qv * 100)}_ms"] = None
            continue
        for key, child in children:
            snap = child.snapshot()
            if not snap["count"] and not include_empty:
                continue
            series = fam.name + _fmt_labels(fam.label_names, key)
            entry: Dict[str, float] = {"count": snap["count"]}
            for qv in quantiles:
                est = child.quantile(qv) if snap["count"] else None
                entry[f"p{int(qv * 100)}_ms"] = (
                    None if est is None else round(est * 1e3, 3))
            out[series] = entry
    return out
