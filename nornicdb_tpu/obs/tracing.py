"""Contextvar span tracing + slow-request ring buffer.

One request = one root :class:`Span`; layers underneath open child
spans (``span("coalesce.wait")``) or graft already-timed intervals
(``attach_span`` — the MicroBatcher leader times the device dispatch
once and every rider of that batch grafts the same interval into its
own trace). The current span rides a ``contextvars.ContextVar``, so it
crosses the grpc.aio event-loop -> executor-thread boundary whenever
the caller runs the work under ``contextvars.copy_context()`` (the aio
wire layer does).

Completed root spans land in the process-wide :class:`TraceBuffer` — a
bounded ring holding the most recent requests slower than
``NORNICDB_OBS_SLOW_MS`` (default 0: every request qualifies, the ring
bound keeps memory flat). The HTTP admin surface exposes it at
``/admin/traces``.

Cross-process propagation (ISSUE 13): a trace minted in a wire worker
must not die at the shared-memory ring or an HTTP hop to a replica.
:func:`trace_context` captures the active trace as a compact dict,
:func:`pack_context`/:func:`unpack_context` move it over a wire seam
(a few bytes in a broker slot header, or the ``X-Nornic-Trace`` HTTP
header), and :func:`propagated_trace` opens a root span on the REMOTE
side bound to the propagated trace id instead of minting a new one —
so degrade records, exemplars and ring entries produced over there
join the originating request's trace. The remote side exports its
span tree (:func:`export_span`) in the response and the originating
side grafts it (:func:`attach_span_tree`) into the live root, so
``/admin/traces`` on the ingress worker shows the full
wire -> ring -> coalesce -> device.dispatch -> merge chain.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

from nornicdb_tpu.obs import metrics as _m

# trace-id generation: a per-process random prefix + monotone counter.
# Cheaper than uuid4 on the hot path (every request mints one) and
# unique across processes with overwhelming probability — the id only
# needs to join a /metrics exemplar to a ring entry on the same node.
_TRACE_PREFIX = os.urandom(4).hex()
_trace_seq = itertools.count(1)


def _new_trace_id() -> str:
    return f"{_TRACE_PREFIX}{next(_trace_seq):08x}"


class Span:
    __slots__ = ("name", "t0", "t1", "attrs", "children", "trace_id")

    def __init__(self, name: str, t0: Optional[float] = None,
                 **attrs: Any) -> None:
        self.name = name
        self.t0 = time.time() if t0 is None else t0
        self.t1: Optional[float] = None
        self.attrs: Dict[str, Any] = attrs
        self.children: List["Span"] = []
        # set on ROOT spans only (trace()); None on children
        self.trace_id: Optional[str] = None

    def finish(self, t1: Optional[float] = None) -> None:
        self.t1 = time.time() if t1 is None else t1

    @property
    def duration_ms(self) -> float:
        end = self.t1 if self.t1 is not None else time.time()
        return (end - self.t0) * 1e3

    def annotate(self, **attrs: Any) -> None:
        self.attrs.update(attrs)

    def to_dict(self) -> Dict[str, Any]:
        doc = {
            "name": self.name,
            "start_ms": round(self.t0 * 1e3, 3),
            "duration_ms": round(self.duration_ms, 3),
            "attrs": dict(self.attrs),
            "children": [c.to_dict() for c in self.children],
        }
        if self.trace_id is not None:
            doc["trace_id"] = self.trace_id
        return doc

    def span_names(self) -> List[str]:
        """Flattened names, depth-first — test/diagnostic helper."""
        out = [self.name]
        for c in self.children:
            out.extend(c.span_names())
        return out


_current: contextvars.ContextVar[Optional[Span]] = contextvars.ContextVar(
    "nornicdb_obs_span", default=None)
# the ROOT span's trace id, visible to every layer under it (exemplar
# tagging reads this on histogram observes without walking the tree)
_current_tid: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "nornicdb_obs_trace_id", default=None)


def current_trace_id() -> Optional[str]:
    """Trace id of the active request, or None outside any trace — the
    exemplar provider the metrics layer reads on histogram observes."""
    return _current_tid.get()


class TraceBuffer:
    """Bounded ring of completed root spans, slowest-aware snapshot."""

    def __init__(self, capacity: int = 256,
                 slow_ms: Optional[float] = None) -> None:
        if slow_ms is None:
            try:
                slow_ms = float(os.environ.get("NORNICDB_OBS_SLOW_MS", "0"))
            except ValueError:
                slow_ms = 0.0
        self.capacity = capacity
        self.slow_ms = slow_ms
        self._lock = threading.Lock()
        self._ring: List[Span] = []
        self._pos = 0
        self.recorded = 0

    def record(self, root: Span) -> None:
        if root.duration_ms < self.slow_ms:
            return
        with self._lock:
            if len(self._ring) < self.capacity:
                self._ring.append(root)
            else:
                self._ring[self._pos] = root
                self._pos = (self._pos + 1) % self.capacity
            self.recorded += 1

    def snapshot(self, limit: int = 50,
                 name: Optional[str] = None) -> List[Dict[str, Any]]:
        """Most recent first (ties to the ring write order), converted
        to plain dicts outside the lock."""
        with self._lock:
            spans = list(self._ring)
        if name is not None:
            spans = [s for s in spans if s.name == name
                     or s.attrs.get("method") == name]
        spans.sort(key=lambda s: s.t0, reverse=True)
        return [s.to_dict() for s in spans[:limit]]

    def slowest(self, limit: int = 10) -> List[Dict[str, Any]]:
        with self._lock:
            spans = list(self._ring)
        spans.sort(key=lambda s: s.duration_ms, reverse=True)
        return [s.to_dict() for s in spans[:limit]]

    def clear(self) -> None:
        with self._lock:
            self._ring = []
            self._pos = 0


TRACES = TraceBuffer()


def current_span() -> Optional[Span]:
    return _current.get()


class _ActiveSpan:
    """Context manager binding a span as the contextvar current.

    ``tid`` pins a PROPAGATED trace id (minted in another process) on a
    root span instead of minting a fresh one — the cross-process
    propagation path (:func:`propagated_trace`)."""

    __slots__ = ("span", "_token", "_root", "_tid_token", "_tid")

    def __init__(self, span: Span, root: bool,
                 tid: Optional[str] = None) -> None:
        self.span = span
        self._root = root
        self._token = None
        self._tid_token = None
        self._tid = tid

    def __enter__(self) -> Span:
        self._token = _current.set(self.span)
        if self._root:
            self.span.trace_id = self._tid or _new_trace_id()
            self._tid_token = _current_tid.set(self.span.trace_id)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> None:
        self.span.finish()
        if exc_type is not None:
            self.span.attrs.setdefault("error", f"{exc_type.__name__}")
        _current.reset(self._token)
        if self._root:
            _current_tid.reset(self._tid_token)
            TRACES.record(self.span)


class _NullSpan:
    """No-op stand-in when tracing is disabled or there is no active
    trace to attach a child to."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass

    def annotate(self, **attrs: Any) -> None:
        pass


_NULL = _NullSpan()


def trace(name: str, **attrs: Any):
    """Open a ROOT span (one per request). On exit it is recorded into
    the slow-request ring."""
    if not _m.enabled():
        return _NULL
    return _ActiveSpan(Span(name, **attrs), root=True)


def span(name: str, **attrs: Any):
    """Open a child of the current span; no-op when no trace is active
    (layers stay instrumented without requiring a surface above them)."""
    if not _m.enabled():
        return _NULL
    parent = _current.get()
    if parent is None:
        return _NULL
    child = Span(name, **attrs)
    parent.children.append(child)
    return _ActiveSpan(child, root=False)


def attach_span(name: str, t0: float, t1: float, **attrs: Any) -> None:
    """Graft an already-timed interval into the current trace — used
    when the timing was captured by another thread (the batch leader's
    device dispatch) but belongs in this request's story."""
    if not _m.enabled():
        return
    parent = _current.get()
    if parent is None:
        return
    child = Span(name, t0=t0, **attrs)
    child.t1 = t1
    parent.children.append(child)


def annotate(**attrs: Any) -> None:
    cur = _current.get()
    if cur is not None:
        cur.attrs.update(attrs)


# -- cross-process trace propagation (ISSUE 13) ------------------------------

# the HTTP header carrying a packed trace context across node hops
# (FleetRouter -> RemoteReplica; any reverse proxy can forward it)
TRACE_HEADER = "X-Nornic-Trace"

# tenant propagation rides the trace context (ISSUE 18): obs/tenant.py
# registers its resolver here so trace_context() carries the tenant
# across the ring slot header and the X-Nornic-Trace hop WITHOUT this
# module importing the tenant layer.
_tenant_provider = None


def set_tenant_provider(fn) -> None:
    global _tenant_provider
    _tenant_provider = fn


def trace_context() -> Optional[Dict[str, str]]:
    """The active trace as a compact propagation dict
    (``{"trace_id", "surface", "span"[, "tenant"]}``), or None outside
    any trace. Cheap: two contextvar reads + one small dict — safe on
    the per-request wire path (no trace -> no allocation beyond the
    gets)."""
    tid = _current_tid.get()
    if tid is None:
        return None
    ctx: Dict[str, str] = {"trace_id": tid}
    cur = _current.get()
    if cur is not None:
        ctx["span"] = cur.name
        surface = cur.attrs.get("surface") or cur.attrs.get("transport")
        if surface:
            ctx["surface"] = str(surface)
    if _tenant_provider is not None:
        tenant = _tenant_provider()
        if tenant:
            ctx["tenant"] = str(tenant)
    return ctx


def pack_context(ctx: Optional[Dict[str, str]]) -> str:
    """``trace_id|surface|span[|tenant]`` — the one wire format for
    both the broker ring slots and the ``X-Nornic-Trace`` HTTP header.
    The tenant field is appended only when present, so pre-18 peers
    (which split to 3) keep parsing the prefix unchanged."""
    if not ctx or not ctx.get("trace_id"):
        return ""
    fields = [ctx.get("trace_id", ""), ctx.get("surface", ""),
              ctx.get("span", "")]
    if ctx.get("tenant"):
        fields.append(ctx["tenant"])
    return "|".join(fields)


_TID_RE = re.compile(r"^[0-9a-fA-F]{8,64}$")
_FIELD_RE = re.compile(r"^[\w.:/-]{1,64}$")
# tenant names: header-reachable, so tighter than span fields (no
# slash/colon — must match obs.tenant's label charset)
_TENANT_RE = re.compile(r"^[\w.-]{1,64}$")


def unpack_context(packed: Optional[str]) -> Optional[Dict[str, str]]:
    """Inverse of :func:`pack_context`; None on empty/garbage input
    (a missing or malformed context degrades to an unlinked local
    trace, never an error). Fields are charset-validated — the HTTP
    header is client-reachable, and an arbitrary string must not land
    in span attrs shown on the admin surface: trace ids must look like
    the hex ids this process mints, surface/span names like code-
    chosen identifiers."""
    if not packed:
        return None
    parts = (str(packed).split("|") + ["", "", ""])[:4]
    if not _TID_RE.match(parts[0]):
        return None
    ctx = {"trace_id": parts[0].lower()}
    if parts[1] and _FIELD_RE.match(parts[1]):
        ctx["surface"] = parts[1]
    if parts[2] and _FIELD_RE.match(parts[2]):
        ctx["span"] = parts[2]
    if parts[3] and _TENANT_RE.match(parts[3]):
        ctx["tenant"] = parts[3]
    return ctx


def propagated_trace(name: str, ctx: Optional[Dict[str, str]],
                     **attrs: Any):
    """Open a root span bound to a PROPAGATED trace context: the span
    records into this process's ring like any root (so the device
    plane's own ``/admin/traces`` shows plane-side chains), but carries
    the ORIGINATING request's trace id — degrade records, exemplar
    tags and child spans opened under it all join that trace. Falls
    back to a normal :func:`trace` root when no context came across
    the seam."""
    if not _m.enabled():
        return _NULL
    if not ctx or not ctx.get("trace_id"):
        return _ActiveSpan(Span(name, **attrs), root=True)
    span = Span(name, remote=True, **attrs)
    if ctx.get("span"):
        span.attrs.setdefault("parent_span", ctx["span"])
    if ctx.get("surface"):
        span.attrs.setdefault("origin_surface", ctx["surface"])
    return _ActiveSpan(span, root=True, tid=ctx["trace_id"])


def export_span(span: Span) -> Dict[str, Any]:
    """Wire-shape export (raw ``t0``/``t1`` floats, not the rendered
    ``to_dict``) so a remote side can graft the tree with original
    timing intact."""
    return {
        "name": span.name,
        "t0": span.t0,
        "t1": span.t1 if span.t1 is not None else time.time(),
        "attrs": dict(span.attrs),
        "children": [export_span(c) for c in span.children],
    }


def _span_from_export(doc: Dict[str, Any]) -> Span:
    t0 = float(doc.get("t0", 0.0) or 0.0)
    span = Span(str(doc.get("name", "remote")), t0=t0)
    span.attrs.update(doc.get("attrs") or {})
    span.t1 = float(doc.get("t1", t0) or t0)
    for child in doc.get("children", ()) or ():
        span.children.append(_span_from_export(child))
    return span


def attach_span_tree(doc: Optional[Dict[str, Any]]) -> None:
    """Graft an exported remote span tree into the current trace —
    the worker-side half of the ring/HTTP propagation: the plane's
    ``ring.claim``/``plane.coalesce``/``device.dispatch`` spans land
    as children of the live root. No-op without an active trace or
    on malformed input (propagation must never fail a request)."""
    if not _m.enabled() or not doc:
        return
    parent = _current.get()
    if parent is None:
        return
    try:
        parent.children.append(_span_from_export(doc))
    except (TypeError, ValueError):
        pass


# exemplar wiring: histograms ask "what trace is observing right now?"
# via this provider. Registered here (not in metrics.py) because
# metrics must stay importable without tracing.
_m.set_exemplar_provider(current_trace_id)
