"""Device-truth calibration plane (ISSUE 20): measured dispatch
timing, cost-model calibration, and device-memory reconciliation.

Every observability layer before this one was host-side or analytic:
PR 7 prices dispatches from padded shapes (obs/cost.py), PR 3's
compile-universe instrument folds compile into first-call wall time
(obs/dispatch.py), and the ``nornicdb_index_device_bytes`` gauges are
shape-derived assertions, not measurements. This module closes the
loop three ways:

1. **Measured service-time models.** Every ``record_dispatch`` feeds a
   per-(kind, pow2-batch-bucket) EWMA of steady-state execute seconds.
   Steady updates are sampled (``NORNICDB_DEVICE_TIMING_SAMPLE``) so
   the 2x+1ms overhead guard holds; first calls always record. The
   steady-state estimate subtracts out of first-call wall time, fixing
   the PR 3 conflation — ``nornicdb_device_compile_seconds`` is the
   calibrated compile split, and a compile appearing after a kind is
   warm is an *unexpected recompile* (counter + ``recompile`` journal
   event): bucket churn caught as an incident, not a latency mystery.

2. **Calibration.** Measurements join PR 7's analytic FLOPs/bytes into
   effective FLOPs/s, bytes/s and padding efficiency (real rows /
   padded rows) per kind — the roofline view (arxiv 2602.16719 splits
   these kernels into compute- vs bandwidth-bound regimes; effective
   rates tell them apart on this box) served at ``GET /admin/device``.
   Cost recorded while a :func:`dispatch_scope` is active credits the
   *serving* dispatch kind (a brute plane priced under a MicroBatcher
   credits ``microbatch``), so the join divides like with like.

3. **Device-memory ledger.** The shape-derived gauges are reconciled
   against the JAX backend's own live-buffer accounting
   (``memory_stats()['bytes_in_use']`` on an accelerator,
   ``jax.live_arrays()`` on the CPU backend). Sustained drift past
   ``NORNICDB_DEVICE_MEM_DRIFT_BYTES`` is a leak verdict with its own
   metric family and a /readyz reason.

The payoff actuates PR 15's named headroom: :func:`predict_ms` gives
admission a calibrated per-query cost estimate — confidence-gated
(below ``NORNICDB_DEVICE_MIN_SAMPLES`` it returns None and admission
falls back to queue-wait-only, never a guess) so at posture >= degrade
a predicted-over-budget query sheds up front (``admission_cost``)
instead of occupying a device slot.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from nornicdb_tpu.config import env_float, env_int
from nornicdb_tpu.obs import metrics as _m
from nornicdb_tpu.obs.metrics import REGISTRY

_lock = threading.Lock()
_tls = threading.local()

# ---------------------------------------------------------------------------
# metric families
# ---------------------------------------------------------------------------

_COMPILE_S_G = REGISTRY.gauge(
    "nornicdb_device_compile_seconds",
    "Calibrated compile time per bucket: first-call wall time minus "
    "the steady-state execute estimate (set once the bucket's EWMA is "
    "confident)", labels=("kind", "b", "k"))
_RECOMPILE_C = REGISTRY.counter(
    "nornicdb_device_unexpected_recompile_total",
    "Compiles observed after the kind was warm (bucket churn at serve "
    "time)", labels=("kind",))
_EFF_FLOPS_G = REGISTRY.gauge(
    "nornicdb_device_eff_flops_per_s",
    "Effective FLOPs/s per dispatch kind: analytic padded-shape FLOPs "
    "over measured execute seconds", labels=("kind",))
_EFF_BYTES_G = REGISTRY.gauge(
    "nornicdb_device_eff_bytes_per_s",
    "Effective bytes/s per dispatch kind: analytic padded-shape bytes "
    "over measured execute seconds", labels=("kind",))
_PAD_EFF_G = REGISTRY.gauge(
    "nornicdb_device_padding_efficiency",
    "Real rows / padded rows per dispatch kind (1.0 = no pow2-pad "
    "waste)", labels=("kind",))
_MEM_LEDGER_G = REGISTRY.gauge(
    "nornicdb_device_mem_ledger_bytes",
    "Shape-derived device bytes: what the resource accounting claims "
    "is resident")
_MEM_BACKEND_G = REGISTRY.gauge(
    "nornicdb_device_mem_backend_bytes",
    "Backend-reported device bytes (memory_stats bytes_in_use, or the "
    "live-array sum on the CPU backend)")
_MEM_DRIFT_G = REGISTRY.gauge(
    "nornicdb_device_mem_drift_bytes",
    "backend - ledger: positive means bytes the accounting cannot "
    "name (the leak direction)")
_MEM_LEAK_C = REGISTRY.counter(
    "nornicdb_device_mem_leak_total",
    "Sustained-drift episodes: |drift| stayed past the bound for the "
    "full detection window")

# ---------------------------------------------------------------------------
# cached configuration (env read once; per-request paths read the dict)
# ---------------------------------------------------------------------------

_cfg_lock = threading.Lock()
_cfg: Optional[Dict[str, Any]] = None


def _load_cfg() -> Dict[str, Any]:
    sample = env_float("DEVICE_TIMING_SAMPLE", 1.0)
    sample = min(max(sample, 0.0), 1.0)
    return {
        # fraction of steady-state dispatches that update the EWMA (and
        # pay the explicit block_until_ready at seams that use
        # maybe_sync); internally a 1-in-N tick so the decision is a
        # modulo, not an RNG draw
        "sample_every": 0 if sample <= 0.0 else max(1, round(1.0 / sample)),
        "ewma_alpha": env_float("DEVICE_EWMA_ALPHA", 0.2),
        # predict_ms confidence gate: below this many steady samples
        # the model abstains (admission falls back to queue-wait-only)
        "min_samples": env_int("DEVICE_MIN_SAMPLES", 8),
        # dispatches per kind after which a new (b, k) shape counts as
        # an unexpected recompile
        "recompile_warmup": env_int("DEVICE_RECOMPILE_WARMUP", 32),
        "mem_drift_bytes": env_int("DEVICE_MEM_DRIFT_BYTES", 64 << 20),
        "mem_drift_s": env_float("DEVICE_MEM_DRIFT_S", 60.0),
    }


def cfg() -> Dict[str, Any]:
    global _cfg
    c = _cfg
    if c is None:
        with _cfg_lock:
            if _cfg is None:
                _cfg = _load_cfg()
            c = _cfg
    return c


def reload() -> None:
    """Drop the cached env-derived config (tests; admin flags)."""
    global _cfg
    with _cfg_lock:
        _cfg = None


# ---------------------------------------------------------------------------
# per-kind / per-bucket state
# ---------------------------------------------------------------------------

# kind -> {"dispatches", "top_dispatches", "measured_s", "padded_rows",
#          "real_rows", "flops", "bytes"}
_kinds: Dict[str, Dict[str, float]] = {}
# (kind, b) -> {"n": steady samples ingested, "ewma_s": execute est}
_models: Dict[Tuple[str, int], Dict[str, float]] = {}
# (kind, b, k) -> first-call wall seconds (the conflated compile+execute)
_first: Dict[Tuple[str, int, int], float] = {}
_tick = 0

# memory-ledger episode state
_drift_since: Optional[float] = None
_leak_flagged = False
_backend_probe: Optional[Callable[[], Optional[float]]] = None


def _kind_entry(kind: str) -> Dict[str, float]:
    e = _kinds.get(kind)
    if e is None:
        e = {"dispatches": 0, "top_dispatches": 0, "measured_s": 0.0,
             "padded_rows": 0, "real_rows": 0.0, "flops": 0.0,
             "bytes": 0.0}
        _kinds[kind] = e
    return e


# ---------------------------------------------------------------------------
# the record_dispatch seam
# ---------------------------------------------------------------------------


class _DispatchScope:
    __slots__ = ("_kind", "_prev")

    def __init__(self, kind: str) -> None:
        self._kind = kind

    def __enter__(self) -> "_DispatchScope":
        self._prev = getattr(_tls, "scope", None)
        _tls.scope = self._kind
        return self

    def __exit__(self, *exc) -> None:
        _tls.scope = self._prev


def dispatch_scope(kind: str) -> _DispatchScope:
    """Bind the *serving* dispatch kind around a batched dispatch:
    cost priced inside the scope credits ``kind`` (a brute plane under
    a MicroBatcher prices as ``microbatch``), and inner
    ``record_dispatch`` calls are tagged nested so coverage counts
    top-level serving kinds only. Outermost scope wins."""
    return _DispatchScope(kind)


def maybe_sync(result: Any = None) -> bool:
    """The sampled timing bracket: decide whether THIS dispatch is a
    calibration sample and, when it is, block on the result so the
    caller's ``t1`` measures device completion, not enqueue. Callers
    that materialize results to host anyway pay nothing extra; the
    decision is stashed thread-locally for the ``record_dispatch``
    observer to consume."""
    global _tick
    if not _m.enabled():
        return False
    every = cfg()["sample_every"]
    if every <= 0:
        _tls.sampled = False
        return False
    with _lock:
        _tick += 1
        sampled = (_tick % every) == 0
    _tls.sampled = sampled
    if sampled and result is not None:
        try:
            import jax

            jax.block_until_ready(result)
        except Exception:  # noqa: BLE001 — host-only results are fine
            pass
    return sampled


def _consume_sample_decision() -> Optional[bool]:
    s = getattr(_tls, "sampled", None)
    if s is not None:
        _tls.sampled = None
    return s


def observe_dispatch(kind: str, b: int, k: int, seconds: float,
                     first: bool) -> None:
    """Observer registered with obs.dispatch: every recorded dispatch
    lands here (telemetry already gated by the caller)."""
    global _tick
    c = cfg()
    scope = getattr(_tls, "scope", None)
    nested = scope is not None and scope != kind
    recompile = False
    with _lock:
        e = _kind_entry(kind)
        warm = e["dispatches"] >= c["recompile_warmup"]
        e["dispatches"] += 1
        e["measured_s"] += seconds
        e["padded_rows"] += int(b)
        if not nested:
            e["top_dispatches"] += 1
        key = (kind, int(b))
        mdl = _models.get(key)
        if mdl is None:
            mdl = {"n": 0, "ewma_s": 0.0}
            _models[key] = mdl
        if first:
            _first[(kind, int(b), int(k))] = seconds
            recompile = warm
        else:
            sampled = _consume_sample_decision()
            if sampled is None:
                every = c["sample_every"]
                if every > 0:
                    _tick += 1
                    sampled = (_tick % every) == 0
                else:
                    sampled = False
            if sampled:
                if mdl["n"] == 0:
                    mdl["ewma_s"] = seconds
                else:
                    a = c["ewma_alpha"]
                    mdl["ewma_s"] += a * (seconds - mdl["ewma_s"])
                mdl["n"] += 1
    if recompile:
        _RECOMPILE_C.labels(kind).inc()
        from nornicdb_tpu.obs import events as _events

        _events.record_event(
            "recompile", surface=kind, reason="bucket_churn",
            detail={"b": int(b), "k": int(k),
                    "first_call_ms": round(seconds * 1e3, 3)})
    # per-tenant device-seconds (ISSUE 20 satellite): the measured wall
    # time splits across the batch riders by tenant, the same rider-mix
    # channel the FLOPs meter uses
    from nornicdb_tpu.obs import tenant as _tenant

    _tenant.record_device_seconds(seconds)


def note_real_rows(rows: float) -> None:
    """Pin the REAL (pre-padding) rider count for the cost about to be
    priced under the active :func:`dispatch_scope`. The self-aligned
    device modules price ``queries`` pre-padding already; a coalescer
    hands its inner plane the PADDED array, so without this note the
    padding-efficiency join would read the pad rows as real work."""
    _tls.real_rows = rows


def note_cost(kind: str, queries: float, flops: float,
              bytes_: float) -> None:
    """Observer registered with obs.cost: analytic cost credited to the
    active dispatch scope (the serving kind) or, absent one, to the
    cost kind itself (the self-aligned device modules)."""
    credit = getattr(_tls, "scope", None) or kind
    rr = getattr(_tls, "real_rows", None)
    if rr is not None:
        _tls.real_rows = None
    with _lock:
        e = _kind_entry(credit)
        e["flops"] += flops
        e["bytes"] += bytes_
        e["real_rows"] += queries if rr is None else rr


# ---------------------------------------------------------------------------
# prediction (the admission consumer)
# ---------------------------------------------------------------------------


def predict_ms(kind: str, b: int) -> Optional[float]:
    """Calibrated steady-state service-time estimate for one dispatch
    of ``kind`` at batch bucket ``b`` — or None below the confidence
    floor (the caller must fall back, never guess). Per-request hot
    path: one dict read under the lock, no env access."""
    min_n = cfg()["min_samples"]
    with _lock:
        mdl = _models.get((kind, int(b)))
        if mdl is None or mdl["n"] < min_n:
            return None
        return mdl["ewma_s"] * 1e3


# ---------------------------------------------------------------------------
# calibration summaries
# ---------------------------------------------------------------------------


def _kind_doc_locked(kind: str, min_n: int) -> Dict[str, Any]:
    e = _kinds[kind]
    compile_s = 0.0
    compile_shapes = 0
    for (fk, fb, fkk), first_s in _first.items():
        if fk != kind:
            continue
        mdl = _models.get((fk, fb))
        if mdl is not None and mdl["n"] >= min_n:
            compile_s += max(first_s - mdl["ewma_s"], 0.0)
            compile_shapes += 1
    execute_s = max(e["measured_s"] - compile_s, 0.0)
    flops, byts = e["flops"], e["bytes"]
    eff_flops = flops / execute_s if flops > 0 and execute_s > 0 else None
    eff_bytes = byts / execute_s if byts > 0 and execute_s > 0 else None
    pad_eff = (min(e["real_rows"] / e["padded_rows"], 1.0)
               if e["padded_rows"] and e["real_rows"] else None)
    buckets = {}
    for (mk, mb), mdl in _models.items():
        if mk != kind:
            continue
        buckets[str(mb)] = {
            "samples": mdl["n"],
            "execute_ms": (round(mdl["ewma_s"] * 1e3, 4)
                           if mdl["n"] else None),
            "confident": mdl["n"] >= min_n,
        }
    return {
        "dispatches": int(e["dispatches"]),
        "top_dispatches": int(e["top_dispatches"]),
        "measured_s": round(e["measured_s"], 6),
        "compile_s_est": round(compile_s, 6),
        "compile_shapes_split": compile_shapes,
        "execute_s": round(execute_s, 6),
        "flops": flops,
        "bytes": byts,
        "eff_flops_per_s": eff_flops,
        "eff_bytes_per_s": eff_bytes,
        "padding_efficiency": (round(pad_eff, 4)
                               if pad_eff is not None else None),
        "buckets": buckets,
    }


def _calibrated(doc: Dict[str, Any]) -> bool:
    return (doc["eff_flops_per_s"] is not None
            and doc["padding_efficiency"] is not None
            and any(bk["confident"] for bk in doc["buckets"].values()))


def calibration_summary() -> Dict[str, Any]:
    """Per-kind roofline view + the coverage verdict the sentinel
    gates: every top-level served dispatch kind must carry effective
    FLOPs/s and padding efficiency."""
    min_n = cfg()["min_samples"]
    with _lock:
        kinds = {k: _kind_doc_locked(k, min_n) for k in sorted(_kinds)}
    served = [k for k, d in kinds.items() if d["top_dispatches"] > 0]
    calibrated = [k for k in served if _calibrated(kinds[k])]
    coverage = (len(calibrated) / len(served)) if served else 1.0
    return {
        "kinds": kinds,
        "served_kinds": served,
        "calibrated_kinds": calibrated,
        "calibration_coverage": round(coverage, 4),
        "unexpected_recompiles": int(sum(
            ch.value for ch in _RECOMPILE_C.children().values())),
        "min_samples": min_n,
        "sample_every": cfg()["sample_every"],
    }


# ---------------------------------------------------------------------------
# device-memory ledger
# ---------------------------------------------------------------------------


def set_backend_probe(
        fn: Optional[Callable[[], Optional[float]]]) -> None:
    """Override the backend live-bytes probe (tests inject drift; a
    remote-backend deployment can plug its own accounting)."""
    global _backend_probe
    _backend_probe = fn


def ledger_bytes() -> float:
    """Shape-derived device bytes: every ``*device_bytes`` stat the
    resource accounting carries (brute/quant/tiered slabs, graph
    snapshots, background plane)."""
    from nornicdb_tpu.obs import resources as _resources

    total = 0.0
    for entry in _resources.snapshot():
        for key, val in entry.items():
            if not isinstance(val, (int, float)):
                continue
            if key == "device_bytes" or key.endswith("_device_bytes"):
                total += float(val)
    return total


def backend_bytes() -> Optional[float]:
    """The backend's own accounting: ``memory_stats()`` bytes-in-use on
    a real accelerator; the live-array sum on the CPU backend (which
    has no HBM ledger). None when no probe works — reconciliation
    abstains rather than reporting a fake zero drift."""
    probe = _backend_probe
    if probe is not None:
        return probe()
    try:
        import jax

        dev = jax.local_devices()[0]
        stats = getattr(dev, "memory_stats", lambda: None)()
        if stats and stats.get("bytes_in_use"):
            return float(stats["bytes_in_use"])
        live = getattr(jax, "live_arrays", None)
        if live is None:
            return None
        return float(sum(int(x.nbytes) for x in live()))
    except Exception:  # noqa: BLE001 — no backend, no verdict
        return None


def reconcile(now: Optional[float] = None) -> Dict[str, Any]:
    """One ledger pass: publish the three gauges and run the sustained
    -drift leak detector. |drift| must sit past the bound for the full
    window before the episode counts — a transient allocation burst
    (mid-rebuild double residency) is not a leak."""
    global _drift_since, _leak_flagged
    c = cfg()
    now = time.time() if now is None else now
    ledger = ledger_bytes()
    backend = backend_bytes()
    drift = (backend - ledger) if backend is not None else None
    _MEM_LEDGER_G.set(ledger)
    if backend is not None:
        _MEM_BACKEND_G.set(backend)
        _MEM_DRIFT_G.set(drift)
    sustained_s = 0.0
    if drift is not None and abs(drift) > c["mem_drift_bytes"]:
        if _drift_since is None:
            _drift_since = now
        sustained_s = now - _drift_since
        if sustained_s >= c["mem_drift_s"] and not _leak_flagged:
            _leak_flagged = True
            _MEM_LEAK_C.inc()
    else:
        _drift_since = None
        _leak_flagged = False
    return {
        "ledger_bytes": int(ledger),
        "backend_bytes": None if backend is None else int(backend),
        "drift_bytes": None if drift is None else int(drift),
        "bound_bytes": int(c["mem_drift_bytes"]),
        "window_s": c["mem_drift_s"],
        "sustained_s": round(sustained_s, 3),
        "leak_suspected": bool(_leak_flagged),
    }


# ---------------------------------------------------------------------------
# the admin payload + scrape-time collector
# ---------------------------------------------------------------------------


def device_summary() -> Dict[str, Any]:
    """The ``GET /admin/device`` payload: calibration roofline, compile
    split, and the memory ledger in one document."""
    cal = calibration_summary()
    cal["memory"] = reconcile()
    return cal


def _collect() -> None:
    """Scrape-time publication: calibrated gauges + the memory ledger.
    Runs on every /metrics render (the resources.update_gauges
    precedent) — never on the request path."""
    if not _m.enabled():
        return
    min_n = cfg()["min_samples"]
    with _lock:
        kinds = {k: _kind_doc_locked(k, min_n) for k in _kinds}
        firsts = dict(_first)
        models = {k: dict(v) for k, v in _models.items()}
    for kind, doc in kinds.items():
        if doc["eff_flops_per_s"] is not None:
            _EFF_FLOPS_G.labels(kind).set(doc["eff_flops_per_s"])
        if doc["eff_bytes_per_s"] is not None:
            _EFF_BYTES_G.labels(kind).set(doc["eff_bytes_per_s"])
        if doc["padding_efficiency"] is not None:
            _PAD_EFF_G.labels(kind).set(doc["padding_efficiency"])
    # the calibrated compile split (the PR 3 conflation, fixed): only
    # shapes whose bucket has a confident steady-state estimate
    for (kind, b, k), first_s in firsts.items():
        mdl = models.get((kind, b))
        if mdl is not None and mdl["n"] >= min_n:
            _COMPILE_S_G.labels(kind, b, k).set(
                max(first_s - mdl["ewma_s"], 0.0))
    try:
        reconcile()
    except Exception:  # noqa: BLE001 — a probe failure must not fail scrape
        pass


REGISTRY.add_collector(_collect)


def reset() -> None:
    """Test/bench helper: forget models, joins and ledger episode state
    (registry counters keep their monotone totals)."""
    global _tick, _drift_since, _leak_flagged
    with _lock:
        _kinds.clear()
        _models.clear()
        _first.clear()
        _tick = 0
    _drift_since = None
    _leak_flagged = False


# hook registration: dispatch/cost call these per record; device.py
# imports them (not vice versa) so obs/__init__'s import order stays
# dispatch -> cost -> tenant -> device with no cycle
from nornicdb_tpu.obs import cost as _cost  # noqa: E402
from nornicdb_tpu.obs import dispatch as _dispatch  # noqa: E402

_dispatch.set_observer(observe_dispatch)
_cost.set_observer(note_cost)
