"""Serving-tier truth: per-query tier attribution, the unified degrade
ledger, and the online shadow-parity auditor (ISSUE 10).

The device serving stack degrades along multi-rung ladders — quantized
-> float32 -> host for vectors (ISSUE 8), walk -> brute-fused -> host
for hybrid (ISSUE 6), device-graph -> host (ISSUE 9) — but until now a
live node never recorded *which rung actually answered a query*, *why*
degradations happened, or *whether device answers still matched the
host reference* under real traffic. This module is that trust layer;
the replica fleet (ROADMAP item 3) and the admission controller (item
4) both consume it.

Three parts:

1. **Per-query tier attribution.** A canonical tier taxonomy (`TIERS`)
   shared by every serving path. Each served query increments
   ``nornicdb_served_tier_total{surface,tier}``, observes its wall time
   into ``nornicdb_served_tier_seconds{surface,tier}`` and annotates
   its trace span with ``served_by``. Batched paths propagate the tier
   leader -> riders through a thread-local channel
   (:func:`note_batch_tier` set inside the dispatch,
   :func:`consume_batch_tier` read by the MicroBatcher leader, stamped
   onto every rider) so attribution is **rider-accurate**: the fused
   hybrid decode stamps per-ROW tiers, so one rider whose live-filter
   forced a host re-fuse counts ``host`` while its batch-mates keep
   their device tier.

2. **Unified degrade ledger.** :func:`record_degrade` replaces the
   scattered free-form ``*_events_total{event=degrade_*}`` semantics
   with one structured record — (surface, from_tier, to_tier,
   normalized reason, index identity, snapshot/generation versions) —
   kept in a bounded ring served at ``/admin/degrades``, grafted into
   the owning trace as a zero-width ``degrade`` span, counted in
   ``nornicdb_degrade_total`` and included in every SLO flight-recorder
   dump. The legacy per-module event counters keep their old label
   values as aliases; ``REASONS`` is the one documented vocabulary and
   ``normalize_reason`` maps every legacy event value onto it.

3. **Online shadow-parity auditor.** An env-gated background sampler
   (``NORNICDB_AUDIT_SAMPLE=1/256``-style rate plus the absolute QPS
   budget ``NORNICDB_AUDIT_MAX_QPS``) captures a copy of device-served
   queries and re-executes them on the host reference path on a worker
   thread — never on the hot path; a full queue drops the sample,
   never blocks a dispatch. Parity per tier (rank-parity for exact
   tiers, recall@k for statistical ones) feeds
   ``nornicdb_parity_ratio{surface,tier}`` and
   ``nornicdb_audit_{sampled,mismatch,dropped}_total``; a per-sample
   floor miss dumps a self-contained repro record (query, both answer
   sets, all snapshot versions) through the PR 5 flight recorder; a
   sustained parity-floor breach surfaces in ``/readyz`` reasons and —
   with ``NORNICDB_AUDIT_QUARANTINE=1`` (default off) — quarantines the
   offending tier down its existing ladder (:func:`tier_allowed`),
   re-probing after ``NORNICDB_AUDIT_QUARANTINE_S`` so the tier
   recovers once the breach clears.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from nornicdb_tpu.obs import events as _events
from nornicdb_tpu.obs import metrics as _m
from nornicdb_tpu.obs import tenant as _tenant
from nornicdb_tpu.obs.metrics import LATENCY_BUCKETS, REGISTRY
from nornicdb_tpu.obs.tracing import annotate, attach_span, current_trace_id

# ---------------------------------------------------------------------------
# canonical tier taxonomy
# ---------------------------------------------------------------------------

# host-resident serving (the exhaustive reference path, HNSW/IVF host
# indexes, the host Cypher executor): shared across surfaces
TIER_HOST = "host"
# answers served straight from a response/result cache — no index of
# any rung executed. Counted so the under-load tier mix stays truthful
# (a steady-state wire workload is mostly this); never shadow-audited
# (the cache generation machinery already guarantees freshness).
TIER_CACHED = "cached"
# queries admission control REJECTED (429 / RESOURCE_EXHAUSTED) or
# failed fast past their deadline budget (ISSUE 15): counted in the
# tier mix so the under-load serve accounting sums to offered work —
# a shed query is an answered query (honest backpressure), just not a
# ranked one. Never shadow-audited; never a ladder rung.
TIER_SHED = "shed"

# per-surface device ladders, best rung first. These are the ONLY legal
# `tier` label values — the catalog lint checks each against
# docs/observability.md.
TIERS: Dict[str, Tuple[str, ...]] = {
    "vector": ("vector_walk_quant", "vector_walk_f32", "vector_tiered",
               "vector_int8", "vector_pq", "vector_brute_f32",
               TIER_HOST, TIER_CACHED),
    "hybrid": ("hybrid_walk_quant", "hybrid_walk_f32",
               "hybrid_brute_int8", "hybrid_brute_pq",
               "hybrid_brute_f32", TIER_HOST, TIER_CACHED),
    "graph": ("graph_chain_device", "graph_traverse_rank_device",
              TIER_HOST),
    # ISSUE 19: background device plane (decay / link prediction /
    # FastRP) — no statistical floor, so the exact contract (1.0)
    # applies: every guard trip degrades to host, never a wrong answer
    "background": ("background_device", TIER_HOST),
}

ALL_TIERS: Tuple[str, ...] = tuple(sorted(
    {t for tiers in TIERS.values() for t in tiers} | {TIER_SHED}))

# parity contracts per tier (host is the reference; never audited).
# Exact tiers must reproduce the host ranking bit-for-bit (rank-parity
# floor 1.0); statistical tiers carry the documented recall floors the
# sentinel already gates (walk parity / quant recall >= 0.95).
STATISTICAL_FLOORS: Dict[str, float] = {
    "vector_walk_quant": 0.95,
    "vector_walk_f32": 0.95,
    "vector_tiered": 0.95,
    "vector_int8": 0.95,
    "vector_pq": 0.95,
    "hybrid_walk_quant": 0.95,
    "hybrid_walk_f32": 0.95,
    "hybrid_brute_int8": 0.95,
    "hybrid_brute_pq": 0.95,
}

EXACT_TIERS: Tuple[str, ...] = tuple(sorted(
    t for t in ALL_TIERS
    if t not in (TIER_HOST, TIER_CACHED, TIER_SHED)
    and t not in STATISTICAL_FLOORS))


def tier_floor(tier: str) -> float:
    """Parity floor for a tier: documented statistical floor, else the
    exact contract (1.0)."""
    return STATISTICAL_FLOORS.get(tier, 1.0)


# ---------------------------------------------------------------------------
# normalized degrade-reason vocabulary
# ---------------------------------------------------------------------------

# the one documented reason vocabulary (catalog lint checks each value
# against docs/observability.md). Legacy per-module event label values
# stay as aliases on their original counters; the ledger and
# nornicdb_degrade_total speak only these.
REASONS: Tuple[str, ...] = (
    "changelog_overrun",   # read-your-writes changelog trimmed past marker
    "compaction",          # slot space remapped under the snapshot
    "overflow",            # lexical plan exceeded the CSR plan bounds
    "pending_build",       # first/background build not yet landed
    "underfill",           # live-filtering left a row short of candidates
    "itopk_exceeded",      # requested depth exceeds the walk pool
    "shard_mismatch",      # snapshot/graph disagree on mesh layout
    "unshardable",         # capacity not divisible across the mesh
    "vec_race",            # join map lost a race with a concurrent write
    "rerank_race",         # compaction landed mid exact-rerank gather
    "exactness",           # f32/int32 integer-exactness bound exceeded
    "rank_overflow",       # composite merge key would overflow int32
    "stale_snapshot",      # versioned snapshot invalidated by a write
    "min_batch",           # auto mode: batch below coalescible demand
    "live_filter",         # tombstone correction forced a host re-fuse
    "error",               # caught exception on the device path
    "quarantine",          # shadow-parity auditor stepped the tier down
    "broker_timeout",      # shared device plane missed the rider deadline
    "replica_lag",         # read replica behind the lag threshold drained
    "replica_drain",       # replica drained: parity/rebuild/unreachable
    "deadline",            # request budget expired before/while queued
    "shed",                # admission control rejected the request
    "admission",           # admission posture forced the tier down
    "admission_cost",      # calibrated predicted cost exceeded the
                           # remaining deadline budget (ISSUE 20)
    "tiered_cold",         # probe hit a non-resident partition: host scan
    "paging_race",         # residency churned while a dispatch was in flight
)

# legacy event label value -> normalized reason. One table so the old
# names remain greppable aliases of exactly one documented reason.
_LEGACY_REASONS: Dict[str, str] = {
    # hybrid_fused_events_total
    "host_fallback_changelog": "changelog_overrun",
    "host_fallback_compaction": "compaction",
    "host_fallback_overflow": "overflow",
    "host_fallback_vec_race": "vec_race",
    "host_fallback_unshardable": "unshardable",
    "walk_pending_build": "pending_build",
    "walk_fallback_itopk": "itopk_exceeded",
    "walk_fallback_shards": "shard_mismatch",
    "walk_fallback_changelog": "changelog_overrun",
    "walk_underfill_brute": "underfill",
    "walk_quarantined": "quarantine",
    "quant_pending_build": "pending_build",
    "quant_fallback_compaction": "compaction",
    "quant_fallback_changelog": "changelog_overrun",
    "quant_fallback_vec_race": "vec_race",
    "quant_underfill_f32": "underfill",
    "quant_quarantined": "quarantine",
    # quant_events_total
    "degrade_compaction": "compaction",
    "degrade_changelog": "changelog_overrun",
    "degrade_rerank_race": "rerank_race",
    "degrade_underfill": "underfill",
    "degrade_error": "error",
    "degrade_quarantine": "quarantine",
    # cagra_events_total
    "exact_fallback_itopk": "itopk_exceeded",
    "exact_fallback_changelog": "changelog_overrun",
    "exact_fallback_underfill": "underfill",
    "exact_fallback_quarantine": "quarantine",
    # device_bm25_events_total
    "host_fallback_pending": "pending_build",
    # tiered_events_total
    "degrade_paging_race": "paging_race",
    "cold_scan": "tiered_cold",
    # device_graph_events_total
    "degrade_stale": "stale_snapshot",
    "degrade_exactness": "exactness",
    "degrade_rank_overflow": "rank_overflow",
    "batch_below_min_b": "min_batch",
}


def normalize_reason(event: str) -> str:
    """Normalized reason for a legacy event label value; values already
    in the vocabulary pass through, unknowns map to ``error``."""
    if event in REASONS:
        return event
    return _LEGACY_REASONS.get(event, "error")


# ---------------------------------------------------------------------------
# tier attribution metrics
# ---------------------------------------------------------------------------

_SERVED_C = REGISTRY.counter(
    "nornicdb_served_tier_total",
    "Queries answered, by serving surface and ladder tier",
    labels=("surface", "tier"))
_SERVED_H = REGISTRY.histogram(
    "nornicdb_served_tier_seconds",
    "Per-query wall time by serving surface and ladder tier",
    labels=("surface", "tier"), buckets=LATENCY_BUCKETS)
# the PR 7 stage attribution split by tier: the coalesce/dispatch/merge
# intervals of tier-attributed requests, keyed by the tier that served
# (bounded label set — the taxonomy above)
_TIER_STAGE_H = REGISTRY.histogram(
    "nornicdb_tier_stage_seconds",
    "Per-request stage attribution split by serving tier",
    labels=("tier", "stage"), buckets=LATENCY_BUCKETS)
_DEGRADE_C = REGISTRY.counter(
    "nornicdb_degrade_total",
    "Tier degradations by surface, ladder edge and normalized reason",
    labels=("surface", "from_tier", "to_tier", "reason"))
_PARITY_G = REGISTRY.gauge(
    "nornicdb_parity_ratio",
    "Shadow-audit device/host parity ratio per tier (rolling window)",
    labels=("surface", "tier"))
_SAMPLED_C = REGISTRY.counter(
    "nornicdb_audit_sampled_total",
    "Shadow-parity samples completed per tier",
    labels=("surface", "tier"))
_MISMATCH_C = REGISTRY.counter(
    "nornicdb_audit_mismatch_total",
    "Shadow-parity samples below the tier's floor",
    labels=("surface", "tier"))
_DROPPED_C = REGISTRY.counter(
    "nornicdb_audit_dropped_total",
    "Shadow-parity samples dropped (queue full / budget exhausted)",
    labels=("reason",))


def served_counter(surface: str, tier: str):
    """The materialized child counter for one (surface, tier) — hot
    paths that cannot afford a labels() probe per query (the ~50us host
    chain fast path) cache this at import and call ``.inc()``."""
    return _SERVED_C.labels(surface, tier)


def record_served(surface: str, tier: str, seconds: Optional[float] = None,
                  n: int = 1) -> None:
    """Count one (or ``n``) served queries on a tier, observe the wall
    time when known, and stamp ``served_by`` on the active trace span.
    No-op under :func:`suppress_attribution` (a nested sub-dispatch of
    an already-counted query)."""
    if not _m.enabled() or getattr(_tls, "suppress", False):
        return
    _SERVED_C.labels(surface, tier).inc(n)
    if seconds is not None:
        _SERVED_H.labels(surface, tier).observe(seconds)
    # the per-tenant side rides the same chokepoint (ISSUE 18): under
    # an active batch mix the n serves distribute across the riders'
    # tenants, else the current context's tenant takes them
    _tenant.record_served(surface, tier, seconds=seconds, n=n)
    annotate(served_by=tier)


def record_tier_stages(tier: str, wait_s: float, dispatch_s: float,
                       merge_s: float) -> None:
    """The PR 7 stage split attributed to the tier that served."""
    if not _m.enabled():
        return
    _TIER_STAGE_H.labels(tier, "coalesce_wait").observe(max(wait_s, 0.0))
    _TIER_STAGE_H.labels(tier, "device_dispatch").observe(
        max(dispatch_s, 0.0))
    _TIER_STAGE_H.labels(tier, "merge").observe(max(merge_s, 0.0))


def tier_mix() -> Dict[str, Dict[str, float]]:
    """Served-tier counts per surface — the tier mix /admin/telemetry
    and the bench load stage report."""
    out: Dict[str, Dict[str, float]] = {}
    for (surface, tier), child in _SERVED_C.children().items():
        v = child.value
        if v:
            out.setdefault(surface, {})[tier] = v
    return out


def tier_counts() -> Dict[str, float]:
    """Flat ``surface:tier -> count`` snapshot (delta-friendly shape
    for the bench sweep's per-point tier-mix probe)."""
    return {f"{surface}:{tier}": child.value
            for (surface, tier), child in _SERVED_C.children().items()
            if child.value}


# -- the leader->rider tier channel ------------------------------------------
#
# Batched dispatch functions (the device index code) know which ladder
# rung actually served a batch; the MicroBatcher leader thread runs
# them and the riders need the verdict. The dispatch notes the tier in
# a thread-local; the leader consumes it after the call and stamps it
# onto every rider's request object; each rider then records itself
# (counter + histogram + span) in its own thread — rider-accurate
# counting with zero cross-thread coordination beyond the stamp.

_tls = threading.local()


def note_batch_tier(tier: str) -> None:
    """Called by a batched dispatch path: this batch was served by
    ``tier``. Last note wins (a fallback overwrites the tier it fell
    back from)."""
    _tls.batch_tier = tier


def consume_batch_tier() -> Optional[str]:
    """Read-and-clear the current thread's batch tier note."""
    tier = getattr(_tls, "batch_tier", None)
    _tls.batch_tier = None
    return tier


def set_last_served(tier: Optional[str]) -> None:
    """Rider-side: the tier that served this thread's latest batched
    query (stamped by the MicroBatcher) — read by sampling call sites
    that sit above the batcher."""
    _tls.last_served = tier


class _SuppressAttribution:
    """Context manager: sub-dispatches inside an already-attributed
    query (the host hybrid path's nested vector ride) must not count a
    second serve — one user query, one tier-mix increment."""

    __slots__ = ("_prev",)

    def __enter__(self):
        self._prev = getattr(_tls, "suppress", False)
        _tls.suppress = True
        return self

    def __exit__(self, *exc):
        _tls.suppress = self._prev


def suppress_attribution() -> _SuppressAttribution:
    return _SuppressAttribution()


def last_served() -> Optional[str]:
    return getattr(_tls, "last_served", None)


# -- the fleet-node channel (ISSUE 13) ---------------------------------------
#
# Same discipline as the batch-tier channel: the FleetRouter knows which
# replica served a coalesced dispatch, the broker (running the dispatch
# on its pool thread) needs the verdict to stamp the riders' span
# records and response docs — a note in a thread-local, read-and-clear
# by the dispatcher after the call.


def note_fleet_node(node: str) -> None:
    """Called by the fleet router when a replica served this thread's
    dispatch (``primary`` on local fallback)."""
    _tls.fleet_node = node


def consume_fleet_node() -> Optional[str]:
    node = getattr(_tls, "fleet_node", None)
    _tls.fleet_node = None
    return node


# ---------------------------------------------------------------------------
# unified degrade ledger
# ---------------------------------------------------------------------------


def _ring_capacity() -> int:
    try:
        return max(16, int(os.environ.get("NORNICDB_DEGRADE_RING", "512")))
    except ValueError:
        return 512


class DegradeLedger:
    """Bounded ring of structured degrade records, newest last."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self.capacity = capacity or _ring_capacity()
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self.recorded = 0

    def record(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(rec)
            self.recorded += 1

    def snapshot(self, limit: int = 100) -> List[Dict[str, Any]]:
        """Most recent first."""
        with self._lock:
            items = list(self._ring)
        return list(reversed(items))[:max(0, limit)]

    def by_reason(self) -> Dict[str, int]:
        with self._lock:
            items = list(self._ring)
        out: Dict[str, int] = {}
        for rec in items:
            out[rec["reason"]] = out.get(rec["reason"], 0) + 1
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


LEDGER = DegradeLedger()


def record_degrade(surface: str, from_tier: str, to_tier: str,
                   reason: str, index: str = "",
                   versions: Optional[Dict[str, Any]] = None) -> None:
    """One structured degrade record: counted, ring-buffered, and
    grafted into the owning trace as a zero-width ``degrade`` span.
    ``reason`` may be a legacy event label value — it is normalized
    onto the documented vocabulary. Never raises; never blocks."""
    if not _m.enabled():
        return
    r = normalize_reason(reason)
    _DEGRADE_C.labels(surface, from_tier, to_tier, r).inc()
    now = time.time()
    rec: Dict[str, Any] = {
        "ts": round(now, 6),
        "surface": surface,
        "from_tier": from_tier,
        "to_tier": to_tier,
        "reason": r,
        "index": index,
    }
    if versions:
        rec["versions"] = dict(versions)
    tid = current_trace_id()
    if tid is not None:
        rec["trace_id"] = tid
    tenant = _tenant.current_tenant()
    if tenant:
        rec["tenant"] = tenant
    _tenant.record_degrade(surface, r)
    LEDGER.record(rec)
    # a broker op capture in flight on this thread (ISSUE 11): the
    # record also ships back to the frontend worker that owns the
    # query, so its /admin/degrades stays truthful across the
    # process boundary
    collector = getattr(_tls, "degrade_collector", None)
    if collector is not None:
        collector.append(dict(rec))
    # graft into the owning trace: a degraded request's span tree
    # answers "why was this served from a lower rung" on its own
    attach_span("degrade", now, now, surface=surface,
                from_tier=from_tier, to_tier=to_tier, reason=r)
    # and into the unified incident timeline (ISSUE 13) — trace-linked
    # through the same (possibly propagated) trace id
    _events.record_event("degrade", node=index, surface=surface,
                         reason=r, trace_id=tid,
                         detail={"from_tier": from_tier,
                                 "to_tier": to_tier})


class _DegradeCollector:
    """Thread-local capture of degrade records produced while a broker
    op executes on a device-plane pool thread — the records ride the
    op's response back to the frontend worker (ISSUE 11)."""

    __slots__ = ("_prev", "records")

    def __init__(self) -> None:
        self.records: List[Dict[str, Any]] = []

    def __enter__(self) -> List[Dict[str, Any]]:
        self._prev = getattr(_tls, "degrade_collector", None)
        _tls.degrade_collector = self.records
        return self.records

    def __exit__(self, *exc) -> None:
        _tls.degrade_collector = self._prev


def collect_degrades() -> _DegradeCollector:
    return _DegradeCollector()


def replay_degrade(rec: Dict[str, Any]) -> None:
    """Frontend-worker side of the boundary crossing: append a degrade
    record relayed from the device plane to THIS process's ledger ring
    (marked ``via: broker``). The counter is NOT re-incremented — the
    worker's /metrics aggregation already carries the shared plane's
    ``nornicdb_degrade_total`` exactly once. The record's ``trace_id``
    — stamped plane-side under the PROPAGATED context (ISSUE 13) — is
    kept, so a broker-crossing degrade joins its trace in this
    worker's ledger exactly like a local one. The incident-timeline
    event is NOT re-recorded either: the plane's ``record_degrade``
    already journaled it, and the worker's merged ``/admin/events``
    view carries the plane journal — a second record here would
    double-count the one incident (same exactly-once discipline as
    the counter)."""
    if not _m.enabled():
        return
    LEDGER.record({**rec, "via": "broker"})


def degrade_snapshot(limit: int = 100) -> List[Dict[str, Any]]:
    return LEDGER.snapshot(limit)


def degrade_summary() -> Dict[str, Any]:
    return {
        "recorded": LEDGER.recorded,
        "capacity": LEDGER.capacity,
        "by_reason": LEDGER.by_reason(),
    }


# ---------------------------------------------------------------------------
# online shadow-parity auditor
# ---------------------------------------------------------------------------


def _parse_rate(spec: str) -> float:
    """``1/256`` | float | ``0``/``off`` (disabled) | ``on``/``default``
    (the documented default 1/256)."""
    s = (spec or "").strip().lower()
    if s in ("", "0", "off", "false", "none"):
        return 0.0
    if s in ("on", "default", "true"):
        return 1.0 / 256.0
    try:
        if "/" in s:
            num, _, den = s.partition("/")
            return max(0.0, min(1.0, float(num) / max(float(den), 1e-9)))
        return max(0.0, min(1.0, float(s)))
    except ValueError:
        return 0.0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class ShadowAuditor:
    """Background device/host parity sampler.

    ``maybe_sample`` is the only hot-path entry: a modulo check on a
    per-tier counter, a token-bucket budget probe, and a non-blocking
    queue append — a full queue or an exhausted budget drops the
    sample (counted), never blocks the serving dispatch. The worker
    thread re-executes the captured query on the caller-provided host
    reference closure, scores parity, updates the gauges/windows, and
    on a per-sample floor miss writes a self-contained repro record
    through the SLO flight recorder."""

    def __init__(
        self,
        rate: Optional[float] = None,
        max_qps: Optional[float] = None,
        window: Optional[int] = None,
        min_samples: Optional[int] = None,
        queue_cap: int = 256,
        dump_interval_s: Optional[float] = None,
        quarantine_s: Optional[float] = None,
    ) -> None:
        self._rate_override = rate
        self._max_qps = max_qps
        self._window_n = window
        self._min_samples = min_samples
        self._queue_cap = queue_cap
        self._dump_interval_s = dump_interval_s
        self._quarantine_s = quarantine_s
        self._lock = threading.Lock()
        self._queue: deque = deque()
        self._have_work = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self._seq: Dict[Tuple[str, str], int] = {}
        # token bucket for the absolute QPS budget (starts full)
        self._tokens: Optional[float] = None
        self._tokens_t = time.time()
        # per (surface, tier): rolling parity window
        self._windows: Dict[Tuple[str, str], deque] = {}
        self._blocked_until: Dict[str, float] = {}
        self._last_dump_t = 0.0
        self._quarantine_override: Optional[bool] = None
        self.sampled = 0
        self.mismatches = 0
        self.dumps: List[str] = []

    # -- config (env read per call so tests/bench can flip at runtime) ----

    def sample_rate(self) -> float:
        if self._rate_override is not None:
            return self._rate_override
        return _parse_rate(os.environ.get("NORNICDB_AUDIT_SAMPLE", "0"))

    def set_sample_rate(self, rate: Optional[float]) -> None:
        """Runtime override (None = back to the env)."""
        self._rate_override = rate

    def max_qps(self) -> float:
        if self._max_qps is not None:
            return self._max_qps
        return max(0.1, _env_float("NORNICDB_AUDIT_MAX_QPS", 50.0))

    def window_n(self) -> int:
        if self._window_n is not None:
            return self._window_n
        try:
            return max(4, int(os.environ.get("NORNICDB_AUDIT_WINDOW", "64")))
        except ValueError:
            return 64

    def min_samples(self) -> int:
        if self._min_samples is not None:
            return self._min_samples
        try:
            return max(1, int(os.environ.get(
                "NORNICDB_AUDIT_MIN_SAMPLES", "8")))
        except ValueError:
            return 8

    def quarantine_enabled(self) -> bool:
        if self._quarantine_override is not None:
            return self._quarantine_override
        return os.environ.get("NORNICDB_AUDIT_QUARANTINE", "0").lower() \
            in ("1", "true", "on", "yes")

    def set_quarantine(self, enabled: Optional[bool]) -> None:
        self._quarantine_override = enabled

    def quarantine_s(self) -> float:
        if self._quarantine_s is not None:
            return self._quarantine_s
        return _env_float("NORNICDB_AUDIT_QUARANTINE_S", 30.0)

    def dump_interval_s(self) -> float:
        if self._dump_interval_s is not None:
            return self._dump_interval_s
        return _env_float("NORNICDB_AUDIT_DUMP_INTERVAL_S", 60.0)

    # -- hot path ---------------------------------------------------------

    def maybe_sample(
        self,
        surface: str,
        tier: str,
        device_ids: Sequence[Any],
        k: int,
        ref: Callable[[], Sequence[Any]],
        versions: Optional[Dict[str, Any]] = None,
        query: Optional[Dict[str, Any]] = None,
        versions_now: Optional[Callable[[], Dict[str, Any]]] = None,
    ) -> bool:
        """Capture one device-served query for shadow re-execution.
        ``ref`` is a zero-arg closure computing the host reference
        answer (ranked ids) off the hot path. ``versions_now`` re-reads
        the same version dict at replay time: if a write moved the
        indexes between sampling and the reference run (before OR
        during it), the sample is dropped as ``stale`` instead of being
        scored — a concurrent upsert must never read as a device
        mismatch. Returns True when the sample was enqueued. Never
        blocks, never raises."""
        if not _m.enabled() or tier in (TIER_HOST, TIER_CACHED):
            return False
        if getattr(_tls, "in_audit", False):
            return False  # the reference path must never re-sample
        rate = self.sample_rate()
        if rate <= 0.0:
            return False
        key = (surface, tier)
        with self._lock:
            n = self._seq.get(key, 0)
            self._seq[key] = n + 1
            interval = max(1, int(round(1.0 / rate)))
            if n % interval != 0:
                return False
            # absolute QPS budget: token bucket refilled on the fly
            now = time.time()
            cap = self.max_qps()
            tokens = cap if self._tokens is None else self._tokens
            self._tokens = min(cap, tokens
                               + (now - self._tokens_t) * cap)
            self._tokens_t = now
            if self._tokens < 1.0:
                _DROPPED_C.labels("budget").inc()
                return False
            self._tokens -= 1.0
            if len(self._queue) >= self._queue_cap:
                _DROPPED_C.labels("queue_full").inc()
                return False
            self._queue.append({
                "surface": surface,
                "tier": tier,
                "k": int(k),
                "device_ids": list(device_ids),
                "ref": ref,
                "versions": dict(versions or {}),
                "versions_now": versions_now,
                "query": query,
                "trace_id": current_trace_id(),
                "ts": now,
            })
        self._ensure_worker()
        self._have_work.set()
        return True

    # -- worker -----------------------------------------------------------

    def _ensure_worker(self) -> None:
        w = self._worker
        if w is not None and w.is_alive():
            return
        with self._lock:
            if self._worker is not None and self._worker.is_alive():
                return
            t = threading.Thread(target=self._run, name="shadow-audit",
                                 daemon=True)
            self._worker = t
            t.start()

    def _run(self) -> None:
        # lazy: admission imports this module; at worker start the
        # cycle is long resolved. Shadow replays ride the REPLAY lane
        # (ISSUE 15) so reference re-executions seal behind interactive
        # traffic in any coalescer they touch.
        from nornicdb_tpu import admission as _adm_lane

        _tls.in_audit = True
        _adm_lane.lane_scope(_adm_lane.LANE_REPLAY).__enter__()
        while True:
            self._have_work.wait(timeout=1.0)
            item = None
            with self._lock:
                if self._queue:
                    item = self._queue.popleft()
                else:
                    self._have_work.clear()
            if item is None:
                continue
            try:
                self._process(item)
            except Exception:  # noqa: BLE001 — the auditor never crashes
                pass

    def flush(self, timeout_s: float = 5.0) -> None:
        """Drain the queue (tests / bench summaries)."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._lock:
                if not self._queue:
                    return
            self._ensure_worker()
            self._have_work.set()
            time.sleep(0.005)

    @staticmethod
    def parity_of(device_ids: Sequence[Any], host_ids: Sequence[Any],
                  k: int, exact: bool) -> float:
        """Rank-parity (exact tiers) or recall@k (statistical tiers) of
        a device answer vs the host reference, both ranked id lists.

        Entries may be ``(id, score)`` pairs. For EXACT tiers that
        enables tie-aware rank parity: a position matches when the ids
        agree OR the scores are identical and the device id belongs to
        the host's same-score tie group — the device contract is "same
        scores, same membership at every score level", and a padded-
        batch dispatch may legitimately permute rows WITHIN an exact
        tie relative to the b=1 replay (ISSUE 11: surfaced by the
        wire-plane load run; ids-only exact samples keep the strict
        positional contract). Statistical tiers always compare ids."""

        def _pair(x):
            if isinstance(x, (tuple, list)) and len(x) == 2:
                return x[0], float(x[1])
            return x, None

        kk = min(k, len(host_ids)) if host_ids else 0
        if kk == 0:
            # host found nothing: the device agreeing (also nothing)
            # is parity 1, anything extra is a mismatch
            return 1.0 if not list(device_ids)[:k] else 0.0
        d = [_pair(x) for x in list(device_ids)[:kk]]
        h = [_pair(x) for x in list(host_ids)[:kk]]
        if exact:
            host_full = [_pair(x) for x in host_ids]
            tie_groups: Dict[float, set] = {}
            for hid, hs in host_full:
                if hs is not None:
                    tie_groups.setdefault(hs, set()).add(hid)
            # a tie group the host list was truncated INSIDE (its last
            # entry carries the group's score) has unobservable
            # membership beyond the cutoff: score equality is all the
            # sample can check there
            tail_score = host_full[-1][1] if host_full else None
            same = 0
            for (di, ds), (hi, hs) in zip(d, h):
                if di == hi:
                    same += 1
                elif ds is not None and hs is not None and ds == hs \
                        and (di in tie_groups.get(ds, ())
                             or ds == tail_score):
                    same += 1
            return same / kk
        return len({i for i, _ in d} & {i for i, _ in h}) / kk

    def _process(self, item: Dict[str, Any]) -> None:
        surface, tier = item["surface"], item["tier"]
        vnow = item.get("versions_now")

        def _stale() -> bool:
            if vnow is None:
                return False
            try:
                return dict(vnow()) != item["versions"]
            except Exception:  # noqa: BLE001 — treat as moved on
                return True

        # a write that landed between sampling and replay makes the
        # live reference incomparable to the captured device answer:
        # drop (counted), never score a correct answer as a mismatch
        if _stale():
            _DROPPED_C.labels("stale").inc()
            return
        try:
            host_ids = list(item["ref"]() or [])
        except Exception as exc:  # noqa: BLE001
            # a failed reference execution is not a device mismatch —
            # count the sample dropped and move on
            _DROPPED_C.labels("ref_error").inc()
            del exc
            return
        if _stale():  # a write landed DURING the reference run
            _DROPPED_C.labels("stale").inc()
            return
        exact = tier in EXACT_TIERS
        parity = self.parity_of(item["device_ids"], host_ids,
                                item["k"], exact)
        floor = tier_floor(tier)
        key = (surface, tier)
        with self._lock:
            win = self._windows.get(key)
            if win is None or win.maxlen != self.window_n():
                win = deque(win or (), maxlen=self.window_n())
                self._windows[key] = win
            win.append(parity)
            ratio = sum(win) / len(win)
            self.sampled += 1
        _SAMPLED_C.labels(surface, tier).inc()
        _PARITY_G.labels(surface, tier).set(ratio)
        if parity < floor - 1e-9:
            with self._lock:
                self.mismatches += 1
            _MISMATCH_C.labels(surface, tier).inc()
            self._dump_mismatch(item, host_ids, parity, floor)
        if self.quarantine_enabled():
            if len(win) >= self.min_samples() and ratio < floor - 1e-9:
                with self._lock:
                    # timeline records the step-down TRANSITION only,
                    # not every sample that extends an open quarantine
                    fresh_block = self._blocked_until.get(tier, 0.0) \
                        <= time.time()
                    self._blocked_until[tier] = (
                        time.time() + self.quarantine_s())
                if fresh_block:
                    _events.record_event(
                        "quarantine", surface=surface, node=tier,
                        reason="parity_breach",
                        trace_id=item.get("trace_id"),
                        detail={"ratio": round(ratio, 4),
                                "floor": floor})
            elif ratio >= floor - 1e-9:
                # the rolling window recovered: the breach has cleared,
                # so the quarantine lifts immediately (probation-window
                # samples wrote the recovery; don't serve degraded for
                # the rest of the block)
                with self._lock:
                    lifted = self._blocked_until.pop(tier, None)
                if lifted is not None:
                    _events.record_event(
                        "quarantine_lift", surface=surface, node=tier,
                        reason="parity_recovered",
                        detail={"ratio": round(ratio, 4)})

    def _dump_mismatch(self, item: Dict[str, Any],
                       host_ids: List[Any], parity: float,
                       floor: float) -> None:
        """Self-contained repro record through the PR 5 flight
        recorder: query, both answer sets, every snapshot version —
        enough to re-run the comparison without the live node.
        Rate-limited; best-effort (a failed dump never fails the
        audit)."""
        now = time.time()
        with self._lock:
            if now - self._last_dump_t < self.dump_interval_s():
                return
            self._last_dump_t = now
        record = {
            "surface": item["surface"],
            "tier": item["tier"],
            "k": item["k"],
            "parity": round(parity, 6),
            "floor": floor,
            "device_ids": _jsonable_ids(item["device_ids"]),
            "host_ids": _jsonable_ids(host_ids),
            "versions": item["versions"],
            "query": item.get("query"),
            "trace_id": item.get("trace_id"),
            "sampled_ts": item["ts"],
        }
        try:
            from nornicdb_tpu.obs import slo as _slo

            path = _slo.get_engine().dump(
                reason=f"parity_mismatch:{item['tier']}",
                extra=[{"kind": "parity_repro", "record": record}])
            with self._lock:
                self.dumps.append(path)
        except Exception:  # noqa: BLE001
            pass

    # -- status / gating --------------------------------------------------

    def parity_breaches(self) -> List[Dict[str, Any]]:
        """Tiers whose rolling parity sits below their floor with
        enough samples — the /readyz reasons feed."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            items = list(self._windows.items())
            min_n = self.min_samples()
        for (surface, tier), win in items:
            if len(win) < min_n:
                continue
            ratio = sum(win) / len(win)
            floor = tier_floor(tier)
            if ratio < floor - 1e-9:
                out.append({"surface": surface, "tier": tier,
                            "ratio": round(ratio, 4), "floor": floor})
        return out

    def tier_allowed(self, tier: str) -> bool:
        """False while quarantine is enabled and the tier sits inside
        its quarantine window — callers step the query down the tier's
        existing ladder. After the window the tier re-probes: fresh
        samples either re-trip the quarantine or heal the parity
        window, so recovery is automatic once the breach clears."""
        if not self.quarantine_enabled():
            return True
        until = self._blocked_until.get(tier)
        if until is None:
            return True
        if time.time() >= until:
            return True  # probation: serve again, let samples decide
        return False

    def summary(self) -> Dict[str, Any]:
        """The /admin/telemetry ``parity`` block."""
        tiers: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            items = list(self._windows.items())
            blocked = dict(self._blocked_until)
            queue_depth = len(self._queue)
        now = time.time()
        for (surface, tier), win in items:
            ratio = (sum(win) / len(win)) if win else None
            floor = tier_floor(tier)
            tiers[f"{surface}:{tier}"] = {
                "parity": None if ratio is None else round(ratio, 4),
                "floor": floor,
                "samples": len(win),
                "breached": (ratio is not None
                             and len(win) >= self.min_samples()
                             and ratio < floor - 1e-9),
                "quarantined": (self.quarantine_enabled()
                                and blocked.get(tier, 0.0) > now),
            }
        return {
            "enabled": self.sample_rate() > 0.0,
            "sample_rate": self.sample_rate(),
            "max_qps": self.max_qps(),
            "quarantine": self.quarantine_enabled(),
            "sampled": self.sampled,
            "mismatches": self.mismatches,
            "queue_depth": queue_depth,
            "tiers": tiers,
        }

    def reset(self) -> None:
        """Test helper: forget windows, quarantine state and queue."""
        with self._lock:
            self._queue.clear()
            self._windows.clear()
            self._blocked_until.clear()
            self._seq.clear()
            self.sampled = 0
            self.mismatches = 0
            self.dumps = []
            self._last_dump_t = 0.0
            self._tokens = None
            self._tokens_t = time.time()


def _jsonable_ids(ids: Sequence[Any]) -> List[Any]:
    out = []
    for i in ids:
        if isinstance(i, (tuple, list)) and len(i) == 2:
            # (id, score) pair from a tie-aware exact sample
            i = [i[0] if isinstance(i[0], (str, int)) else str(i[0]),
                 float(i[1])]
        try:
            json.dumps(i)
            out.append(i)
        except (TypeError, ValueError):
            out.append(str(i))
    return out


AUDITOR = ShadowAuditor()


def maybe_sample(surface: str, tier: str, device_ids: Sequence[Any],
                 k: int, ref: Callable[[], Sequence[Any]],
                 versions: Optional[Dict[str, Any]] = None,
                 query: Optional[Dict[str, Any]] = None,
                 versions_now: Optional[Callable[[], Dict[str, Any]]]
                 = None) -> bool:
    return AUDITOR.maybe_sample(surface, tier, device_ids, k, ref,
                                versions=versions, query=query,
                                versions_now=versions_now)


def sampling_active() -> bool:
    """Cheap pre-gate for hot call sites: skip building the sample's
    id lists/closures entirely while auditing is off."""
    return _m.enabled() and AUDITOR.sample_rate() > 0.0


def tier_allowed(tier: str) -> bool:
    return AUDITOR.tier_allowed(tier)


# -- admission-posture tier forcing (ISSUE 15) --------------------------------
#
# The admission controller (nornicdb_tpu/admission.py) degrades along
# the existing serving ladders BEFORE it rejects work: under a degrade-
# or-worse posture the expensive device rungs (walk/quant/graph) step
# down to brute/host exactly like a parity quarantine would, through
# the same per-ladder gate sites — one registered hook, so audit stays
# import-light and admission stays optional.

_ADMISSION_GATE: Callable[[str], bool] = lambda tier: True


def set_admission_gate(fn: Callable[[str], bool]) -> None:
    global _ADMISSION_GATE
    _ADMISSION_GATE = fn


def admission_allows(tier: str) -> bool:
    """True unless the admission posture is holding this tier down its
    ladder (ledger reason ``admission`` at the gate sites — distinct
    from the auditor's ``quarantine``)."""
    try:
        return _ADMISSION_GATE(tier)
    except Exception:  # noqa: BLE001 — a broken gate must not fail serving
        return True


def parity_breaches() -> List[Dict[str, Any]]:
    return AUDITOR.parity_breaches()


def audit_summary() -> Dict[str, Any]:
    return AUDITOR.summary()
