"""Unified telemetry for the serving stack (ISSUE 3 + ISSUE 5).

Dependency-free counters/gauges/histograms with real Prometheus
exposition, contextvar span tracing with a slow-request ring buffer,
and the device-dispatch compile-universe instrument. Every hot layer
records into the process-wide ``REGISTRY``/``TRACES``; the HTTP server
renders them at ``/metrics`` and ``/admin/traces``.

The operability layer on top (ISSUE 5): ``obs/resources.py`` derives
per-index device-memory and freshness-lag gauges on scrape from weakly
registered index/queue objects (the same snapshot gates ``/readyz``),
and ``obs/slo.py`` computes multi-window SLO burn rates over the
latency histograms with a breach-triggered JSONL flight recorder.

The load-truth layer (ISSUE 7): ``obs/stages.py`` attributes each
request's latency to serving stages (queue wait vs device compute),
``obs/cost.py`` prices every device dispatch in FLOPs/bytes per query,
and the histograms optionally tag bucket observations with the current
trace id — exposed as OpenMetrics exemplars under content negotiation
at ``/metrics``.

Overhead discipline: a record call is a branch + dict probe + striped
add (counters) or bisect + locked bucket increment (histograms); spans
allocate one small object each; resource/SLO work happens only at
scrape time. ``set_enabled(False)`` no-ops the whole layer —
tests/test_observability.py pins the instrumented:bare ratio.
"""

from nornicdb_tpu.obs.dispatch import (
    compile_universe,
    declare_kind,
    record_dispatch,
)
from nornicdb_tpu.obs.metrics import (
    LATENCY_BUCKETS,
    REGISTRY,
    SIZE_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    enabled,
    exemplars_enabled,
    get_registry,
    latency_summary,
    set_enabled,
    set_exemplars_enabled,
)
from nornicdb_tpu.obs import audit  # noqa: F401 — registers tier families
from nornicdb_tpu.obs import cost  # noqa: F401 — registers cost counters
from nornicdb_tpu.obs import device  # noqa: F401 — registers calibration
from nornicdb_tpu.obs import events  # noqa: F401 — registers event counter
from nornicdb_tpu.obs import fleet  # noqa: F401 — registers sources gauge
from nornicdb_tpu.obs import resources  # noqa: F401 — registers collector
from nornicdb_tpu.obs import slo  # noqa: F401 — registers collector
from nornicdb_tpu.obs import stages  # noqa: F401 — registers stage family
from nornicdb_tpu.obs import tenant  # noqa: F401 — registers tenant families
from nornicdb_tpu.obs.audit import (
    audit_summary,
    degrade_snapshot,
    degrade_summary,
    maybe_sample,
    parity_breaches,
    record_degrade,
    record_served,
    tier_allowed,
    tier_mix,
)
from nornicdb_tpu.obs.cost import cost_summary, record_query_cost
from nornicdb_tpu.obs.device import (
    calibration_summary,
    device_summary,
    predict_ms,
)
from nornicdb_tpu.obs.events import (
    event_snapshot,
    event_summary,
    record_event,
)
from nornicdb_tpu.obs.fleet import (
    fleet_summary,
    http_state_source,
    register_source as register_fleet_source,
    unregister_source as unregister_fleet_source,
)
from nornicdb_tpu.obs.resources import register as register_resource
from nornicdb_tpu.obs.resources import snapshot as resource_snapshot
from nornicdb_tpu.obs.slo import SloEngine
from nornicdb_tpu.obs.slo import get_engine as get_slo_engine
from nornicdb_tpu.obs.stages import record_stage, stage_summary
from nornicdb_tpu.obs.tenant import (
    TENANT_HEADER,
    current_tenant,
    tenant_scope,
    tenants_summary,
)
from nornicdb_tpu.obs.tracing import (
    TRACE_HEADER,
    TRACES,
    Span,
    TraceBuffer,
    annotate,
    attach_span,
    attach_span_tree,
    current_span,
    current_trace_id,
    export_span,
    pack_context,
    propagated_trace,
    span,
    trace,
    trace_context,
    unpack_context,
)

__all__ = [
    "LATENCY_BUCKETS",
    "REGISTRY",
    "SIZE_BUCKETS",
    "TRACE_HEADER",
    "TRACES",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "SloEngine",
    "Span",
    "TraceBuffer",
    "annotate",
    "attach_span",
    "attach_span_tree",
    "audit",
    "audit_summary",
    "calibration_summary",
    "compile_universe",
    "cost",
    "cost_summary",
    "device",
    "device_summary",
    "predict_ms",
    "current_span",
    "current_trace_id",
    "degrade_snapshot",
    "degrade_summary",
    "enabled",
    "event_snapshot",
    "event_summary",
    "events",
    "exemplars_enabled",
    "export_span",
    "fleet",
    "fleet_summary",
    "http_state_source",
    "get_registry",
    "get_slo_engine",
    "latency_summary",
    "maybe_sample",
    "pack_context",
    "parity_breaches",
    "propagated_trace",
    "record_degrade",
    "record_dispatch",
    "record_event",
    "record_query_cost",
    "record_served",
    "record_stage",
    "register_fleet_source",
    "register_resource",
    "resource_snapshot",
    "resources",
    "set_enabled",
    "set_exemplars_enabled",
    "slo",
    "span",
    "stage_summary",
    "stages",
    "TENANT_HEADER",
    "current_tenant",
    "tenant",
    "tenant_scope",
    "tenants_summary",
    "tier_allowed",
    "tier_mix",
    "trace",
    "trace_context",
    "unpack_context",
    "unregister_fleet_source",
]
