"""Unified telemetry for the serving stack (ISSUE 3).

Dependency-free counters/gauges/histograms with real Prometheus
exposition, contextvar span tracing with a slow-request ring buffer,
and the device-dispatch compile-universe instrument. Every hot layer
records into the process-wide ``REGISTRY``/``TRACES``; the HTTP server
renders them at ``/metrics`` and ``/admin/traces``.

Overhead discipline: a record call is a branch + dict probe + striped
add (counters) or bisect + locked bucket increment (histograms); spans
allocate one small object each. ``set_enabled(False)`` no-ops the whole
layer — tests/test_observability.py pins the instrumented:bare ratio.
"""

from nornicdb_tpu.obs.dispatch import (
    compile_universe,
    record_dispatch,
)
from nornicdb_tpu.obs.metrics import (
    LATENCY_BUCKETS,
    REGISTRY,
    SIZE_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    enabled,
    get_registry,
    latency_summary,
    set_enabled,
)
from nornicdb_tpu.obs.tracing import (
    TRACES,
    Span,
    TraceBuffer,
    annotate,
    attach_span,
    current_span,
    span,
    trace,
)

__all__ = [
    "LATENCY_BUCKETS",
    "REGISTRY",
    "SIZE_BUCKETS",
    "TRACES",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "Span",
    "TraceBuffer",
    "annotate",
    "attach_span",
    "compile_universe",
    "current_span",
    "enabled",
    "get_registry",
    "latency_summary",
    "record_dispatch",
    "set_enabled",
    "span",
    "trace",
]
