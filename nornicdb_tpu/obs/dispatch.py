"""Device-dispatch instrumentation: the XLA compile universe, observed.

PR 1/2 bounded the compile universe by padding every device call to
power-of-two (B, k) buckets (microbatch.pow2_bucket) — but nothing
showed whether the bound held in production. This module records every
batched device dispatch by (kind, B, k): the FIRST call at a shape is
its compile (JAX compiles on first trace; its wall time includes the
compile), later calls are steady-state dispatches. ``/metrics`` then
exposes the real compile universe as labeled series, and bucket churn
(new shapes appearing at serve time) is visible as compile-counter
growth instead of mystery latency spikes.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from nornicdb_tpu.obs import metrics as _m
from nornicdb_tpu.obs.metrics import REGISTRY

_lock = threading.Lock()
# the device-truth calibration plane (obs/device.py, ISSUE 20)
# registers itself here; every recorded dispatch is forwarded. Held as
# a module global (not an import) so this module stays importable
# first in the obs package.
_observer: Optional[Callable[[str, int, int, float, bool], None]] = None
# (kind, b, k) -> {"dispatches": int, "first_call_s": float,
#                  "total_s": float}
_shapes: Dict[Tuple[str, int, int], Dict[str, Any]] = {}
# kinds announced by their owning module at import — the compile-cache
# accounting carries these series from process start (a dashboard can
# tell "tier exists, zero traffic" from "tier doesn't exist")
_declared: set = set()

_DISPATCH_C = REGISTRY.counter(
    "nornicdb_device_dispatch_total",
    "Batched device dispatches by compile bucket",
    labels=("kind", "b", "k"))
_COMPILE_C = REGISTRY.counter(
    "nornicdb_device_compile_total",
    "First-touch compiles by dispatch kind", labels=("kind",))
_LATENCY_H = REGISTRY.histogram(
    "nornicdb_device_dispatch_seconds",
    "Device dispatch wall time (first call includes compile)",
    labels=("kind",))
_FIRST_G = REGISTRY.gauge(
    "nornicdb_device_first_call_seconds",
    "Wall time of the first call per bucket: compile AND execute "
    "conflated (the calibrated split is nornicdb_device_compile_seconds)",
    labels=("kind", "b", "k"))


def set_observer(
        fn: Optional[Callable[[str, int, int, float, bool], None]]) -> None:
    """Register the per-dispatch observer (obs/device.py): called as
    ``fn(kind, b, k, seconds, first)`` after this module's own
    recording, outside its lock."""
    global _observer
    _observer = fn


def declare_kind(kind: str) -> None:
    """Pre-register a dispatch kind in the compile universe. The shape
    table still fills lazily on first dispatch; declaring only seeds
    ``bucket_counts`` (-> ``nornicdb_compile_cache_entries{kind=...}``)
    with a zero entry so the series exists before first traffic."""
    with _lock:
        _declared.add(kind)


def record_dispatch(kind: str, b: int, k: int, seconds: float) -> None:
    """Record one batched device call at pow2-bucketed shape (b, k)."""
    if not _m.enabled():
        return
    key = (kind, int(b), int(k))
    first = False
    with _lock:
        entry = _shapes.get(key)
        if entry is None:
            first = True
            entry = {"dispatches": 0, "first_call_s": seconds,
                     "total_s": 0.0}
            _shapes[key] = entry
        entry["dispatches"] += 1
        entry["total_s"] += seconds
    _DISPATCH_C.labels(kind, b, k).inc()
    _LATENCY_H.labels(kind).observe(seconds)
    if first:
        _COMPILE_C.labels(kind).inc()
        _FIRST_G.labels(kind, b, k).set(seconds)
    obs_fn = _observer
    if obs_fn is not None:
        obs_fn(kind, int(b), int(k), seconds, first)


def compile_universe() -> List[Dict[str, Any]]:
    """Every (kind, B, k) shape seen since process start — the admin
    view of how many distinct XLA programs serving has paid for."""
    with _lock:
        items = sorted(_shapes.items())
    return [
        {"kind": kind, "b": b, "k": k,
         "dispatches": e["dispatches"],
         "first_call_ms": round(e["first_call_s"] * 1e3, 3),
         "mean_ms": round(e["total_s"] / max(e["dispatches"], 1) * 1e3, 4)}
        for (kind, b, k), e in items
    ]


def bucket_counts() -> Dict[str, int]:
    """Distinct compiled (B, k) buckets per dispatch kind — the size of
    each compile cache. The resource accounting layer exposes this as
    ``nornicdb_compile_cache_entries{kind=...}``; growth at serve time
    is the bucket-churn signal the sentinel gates on."""
    with _lock:
        out: Dict[str, int] = {kind: 0 for kind in sorted(_declared)}
        for (kind, _b, _k) in _shapes:
            out[kind] = out.get(kind, 0) + 1
    return out


def reset() -> None:
    """Test helper: forget the shape universe (registry counters keep
    their monotone totals)."""
    with _lock:
        _shapes.clear()
