"""SLO engine: multi-window burn rates over the latency histograms.

PR 3 gave every surface a latency histogram; this layer turns those
cumulative histograms into operable SLO state. Each *objective* is a
(histogram family, latency threshold, target fraction): "99% of HTTP
requests complete within 250ms". Good/total counts are read straight
from the existing bucket counts (the threshold snaps to the nearest
bucket bound at or below it, so no new instrumentation rides the hot
path), sampled into a small in-memory ring on every ``tick()`` —
scrape-driven, no background thread — and differenced over rolling
windows (default 5m fast / 1h slow).

The **burn rate** of a window is ``bad_fraction / (1 - target)``: 1.0
burns exactly the whole error budget over the SLO period, 14.4 on the
fast window is the classic page-now threshold. A breach (fast-window
burn >= ``breach_fast`` with enough traffic, or slow-window burn >=
``breach_slow``) triggers the **flight recorder**: one JSONL file with
the metrics snapshot, latency summary, resource accounting and the
slow-trace ring — the forensic state that is gone by the time a human
reads the alert — rate-limited to one dump per ``dump_interval_s``.

Configuration (env):

- ``NORNICDB_SLO_HTTP`` / ``_GRPC`` / ``_BOLT``: ``"<threshold_ms>:
  <target>"`` (e.g. ``"100:0.999"``), or ``"off"`` to disable one
  objective.
- ``NORNICDB_SLO_WINDOWS``: comma-separated window seconds
  (default ``"300,3600"``).
- ``NORNICDB_OBS_DUMP_DIR``: flight-recorder directory (default
  ``<tmp>/nornicdb-flightrec``).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, List, Optional, Tuple

from nornicdb_tpu.obs import metrics as _m
from nornicdb_tpu.obs.metrics import REGISTRY, Registry


@dataclass(frozen=True)
class Objective:
    name: str           # short surface name ("http", "grpc", ...)
    family: str         # latency histogram family in the registry
    threshold_s: float  # a request at or under this latency is "good"
    target: float       # fraction of requests that must be good

    @property
    def budget(self) -> float:
        return max(1.0 - self.target, 1e-9)


_DEFAULT_OBJECTIVES: Tuple[Tuple[str, str, float, float], ...] = (
    ("http", "nornicdb_http_request_seconds", 0.25, 0.99),
    ("grpc", "nornicdb_grpc_request_seconds", 0.1, 0.99),
    ("bolt", "nornicdb_bolt_request_seconds", 0.25, 0.99),
)


def _objectives_from_env() -> List[Objective]:
    out: List[Objective] = []
    for name, family, thr, target in _DEFAULT_OBJECTIVES:
        spec = os.environ.get(f"NORNICDB_SLO_{name.upper()}", "")
        if spec.strip().lower() == "off":
            continue
        if spec:
            try:
                thr_ms, _, tgt = spec.partition(":")
                # parse BOTH fields before applying either — a spec
                # with a valid threshold but junk target must keep the
                # whole default objective, not half of it
                new_thr = float(thr_ms) / 1e3
                new_target = float(tgt) if tgt else target
                thr, target = new_thr, new_target
            except ValueError:
                pass  # malformed spec: keep the default objective
        out.append(Objective(name, family, thr, target))
    return out


def _windows_from_env() -> Tuple[float, ...]:
    spec = os.environ.get("NORNICDB_SLO_WINDOWS", "")
    if spec:
        try:
            ws = tuple(sorted(float(x) for x in spec.split(",") if x))
            if ws:
                return ws
        except ValueError:
            pass
    return (300.0, 3600.0)


def default_dump_dir() -> str:
    return os.environ.get(
        "NORNICDB_OBS_DUMP_DIR",
        os.path.join(tempfile.gettempdir(), "nornicdb-flightrec"))


class SloEngine:
    """Rolling burn-rate computation + breach-triggered flight dumps.

    Thread-safe; all work happens in ``tick()``/``status()`` (called
    from the scrape/admin/readyz paths), never on a request path."""

    def __init__(
        self,
        registry: Optional[Registry] = None,
        objectives: Optional[List[Objective]] = None,
        windows: Optional[Tuple[float, ...]] = None,
        breach_fast: float = 14.4,
        breach_slow: float = 6.0,
        min_requests: int = 30,
        dump_dir: Optional[str] = None,
        dump_interval_s: float = 300.0,
        sample_min_interval_s: float = 1.0,
    ):
        self.registry = registry if registry is not None else REGISTRY
        self.objectives = (objectives if objectives is not None
                           else _objectives_from_env())
        self.windows = windows if windows is not None else _windows_from_env()
        self.breach_fast = breach_fast
        self.breach_slow = breach_slow
        self.min_requests = min_requests
        self.dump_dir = dump_dir or default_dump_dir()
        self.dump_interval_s = dump_interval_s
        self._sample_min_interval_s = sample_min_interval_s
        self._lock = threading.Lock()
        # objective name -> deque of (t, total, good)
        self._samples: Dict[str, Deque[Tuple[float, int, int]]] = {
            o.name: deque() for o in self.objectives}
        self._last_sample_t = 0.0
        self._last_dump_t = 0.0
        self.dumps: List[str] = []

    # -- counting ---------------------------------------------------------

    def _counts(self, obj: Objective) -> Tuple[int, int]:
        """(total, good) across every child of the objective's family.
        Good = observations in buckets whose bound <= threshold (the le
        contract: observe() lands a value in the first bound >= it)."""
        fam = self.registry.get(obj.family)
        if fam is None or fam.kind != "histogram":
            return 0, 0
        total = good = 0
        for _key, child in fam.children().items():
            snap = child.snapshot()
            total += snap["count"]
            for bound, c in zip(snap["buckets"], snap["counts"]):
                if bound <= obj.threshold_s:
                    good += c
                else:
                    break
        return total, good

    def tick(self, now: Optional[float] = None) -> None:
        """Append one (t, total, good) sample per objective; prune past
        the longest window. Rate-limited so a scrape storm can't bloat
        the rings. Runs the breach check afterwards."""
        now = time.time() if now is None else now
        with self._lock:
            if now - self._last_sample_t < self._sample_min_interval_s:
                return
            self._last_sample_t = now
            horizon = max(self.windows) * 1.25
            for obj in self.objectives:
                total, good = self._counts(obj)
                ring = self._samples[obj.name]
                ring.append((now, total, good))
                while ring and ring[0][0] < now - horizon:
                    ring.popleft()
        self.maybe_dump(now=now)

    # -- burn rates -------------------------------------------------------

    def _window_stats(self, obj: Objective, window: float,
                      now: float) -> Dict[str, Any]:
        ring = self._samples[obj.name]
        if not ring:
            return {"window_s": window, "total": 0, "bad": 0,
                    "bad_fraction": None, "burn_rate": None,
                    "complete": False}
        t_now, tot_now, good_now = ring[-1]
        start = None
        for t, tot, good in ring:
            if t >= now - window:
                break
            start = (t, tot, good)
        if start is None:
            start = ring[0]
        t0, tot0, good0 = start
        total = tot_now - tot0
        bad = total - (good_now - good0)
        if total <= 0:
            return {"window_s": window, "total": 0, "bad": 0,
                    "bad_fraction": None, "burn_rate": None,
                    "complete": (t_now - t0) >= window * 0.9}
        frac = bad / total
        return {
            "window_s": window,
            "total": total,
            "bad": bad,
            "bad_fraction": round(frac, 6),
            "burn_rate": round(frac / obj.budget, 3),
            # a window is complete once the ring actually spans it —
            # early-life burn rates are reported but flagged partial
            "complete": (t_now - t0) >= window * 0.9,
        }

    def status(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Budgets + per-window burn rates per objective, and the
        breach verdict — the /admin/slo payload."""
        now = time.time() if now is None else now
        out: Dict[str, Any] = {"objectives": {}, "breached": []}
        with self._lock:
            for obj in self.objectives:
                ring = self._samples[obj.name]
                tot_now, good_now = (ring[-1][1], ring[-1][2]) if ring \
                    else (0, 0)
                win = [self._window_stats(obj, w, now)
                       for w in self.windows]
                breach = self._breached(win)
                doc = {
                    "family": obj.family,
                    "threshold_ms": round(obj.threshold_s * 1e3, 3),
                    "target": obj.target,
                    "error_budget": round(obj.budget, 6),
                    "total": tot_now,
                    "bad_total": tot_now - good_now,
                    "windows": win,
                    "breached": breach,
                }
                out["objectives"][obj.name] = doc
                if breach:
                    out["breached"].append(obj.name)
        out["dump_dir"] = self.dump_dir
        out["dumps"] = list(self.dumps[-5:])
        return out

    def _breached(self, window_stats: List[Dict[str, Any]]) -> bool:
        if not window_stats:
            return False
        fast = window_stats[0]
        if (fast["burn_rate"] is not None
                and fast["total"] >= self.min_requests
                and fast["burn_rate"] >= self.breach_fast):
            return True
        for slow in window_stats[1:]:
            if (slow["burn_rate"] is not None
                    and slow["total"] >= self.min_requests
                    and slow["complete"]
                    and slow["burn_rate"] >= self.breach_slow):
                return True
        return False

    def breached(self, now: Optional[float] = None) -> List[str]:
        return self.status(now=now)["breached"]

    # -- flight recorder --------------------------------------------------

    def maybe_dump(self, now: Optional[float] = None) -> Optional[str]:
        """Write a flight-recorder dump when any objective is breached,
        at most once per ``dump_interval_s``. Returns the path written
        (or None)."""
        now = time.time() if now is None else now
        status = self.status(now=now)
        breached = status["breached"]
        if not breached:
            return None
        with self._lock:
            if now - self._last_dump_t < self.dump_interval_s:
                return None
            self._last_dump_t = now
        # pass the already-computed status through — this path runs on
        # every tick while degraded, so don't walk the histograms twice
        return self.dump(reason=f"slo_breach:{','.join(breached)}",
                         now=now, status=status)

    def dump(self, reason: str = "manual",
             now: Optional[float] = None,
             status: Optional[Dict[str, Any]] = None,
             extra: Optional[List[Dict[str, Any]]] = None) -> str:
        """One JSONL flight record: meta, SLO status, metrics/latency/
        resource snapshots, the degrade ledger + parity state, and the
        slow-trace ring — everything needed to reconstruct the breach
        after the fact. ``extra`` appends caller records (the shadow
        auditor's self-contained parity repro rides here)."""
        from nornicdb_tpu.obs import audit as _audit
        from nornicdb_tpu.obs import events as _events
        from nornicdb_tpu.obs import resources as _resources
        from nornicdb_tpu.obs import stages as _stages
        from nornicdb_tpu.obs.dispatch import compile_universe
        from nornicdb_tpu.obs.tracing import TRACES

        now = time.time() if now is None else now
        os.makedirs(self.dump_dir, exist_ok=True)
        path = os.path.join(self.dump_dir,
                            f"flightrec-{int(now * 1e3)}.jsonl")
        lines: List[Dict[str, Any]] = [
            {"kind": "meta", "ts": now, "reason": reason},
            {"kind": "slo", "status": (status if status is not None
                                       else self.status(now=now))},
            {"kind": "latency",
             "summary": _m.latency_summary(self.registry,
                                           include_empty=True)},
            # stage decomposition + queueing fraction: a breach record
            # must answer "queued or compute?" without a live node
            {"kind": "stages",
             "summary": _stages.stage_summary(self.registry)},
            {"kind": "resources", "snapshot": _resources.snapshot()},
            {"kind": "compile_universe", "shapes": compile_universe()},
            # which ladder rung served, what degraded and why, and the
            # device/host parity state at breach time (ISSUE 10)
            {"kind": "tiers", "mix": _audit.tier_mix()},
            {"kind": "degrades",
             "summary": _audit.degrade_summary(),
             "ring": _audit.degrade_snapshot(limit=50)},
            {"kind": "parity", "summary": _audit.audit_summary()},
            # the unified incident timeline (ISSUE 13): drains,
            # failovers, quarantines and degrades in causal order,
            # trace-linked — the breach's backstory in one stream
            {"kind": "events", "summary": _events.event_summary(),
             "ring": _events.event_snapshot(limit=100)},
        ]
        # the admission actuator's state at breach time (ISSUE 15):
        # posture, per-lane depth/drain, deadline misses, shed totals —
        # what the scheduler was DOING about the breach. Lazy import:
        # slo must stay importable without the actuator.
        try:
            from nornicdb_tpu.admission import scheduler_summary

            lines.append({"kind": "scheduler",
                          "summary": scheduler_summary()})
        except Exception:  # noqa: BLE001 — dump must never fail on extras
            pass
        # who was doing it to us (ISSUE 18): the per-tenant rollup —
        # top-K by cost, sheds, p99 — plus the noisy-neighbor
        # detector's window at breach time. Same lazy discipline.
        try:
            from nornicdb_tpu.obs.tenant import tenants_summary

            lines.append({"kind": "tenants",
                          "summary": tenants_summary()})
        except Exception:  # noqa: BLE001 — dump must never fail on extras
            pass
        # what the DEVICE was doing (ISSUE 20): the calibrated roofline
        # per dispatch kind + the memory-ledger verdict at breach time
        # — was the breach compute-bound, bandwidth-bound, padding
        # waste, or a capacity story gone wrong. Same lazy discipline.
        try:
            from nornicdb_tpu.obs.device import device_summary

            lines.append({"kind": "device",
                          "summary": device_summary()})
        except Exception:  # noqa: BLE001 — dump must never fail on extras
            pass
        for rec in (extra or []):
            lines.append(rec)
        for trace in TRACES.slowest(limit=20):
            lines.append({"kind": "trace", "trace": trace})
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for line in lines:
                f.write(json.dumps(line, default=str) + "\n")
        os.replace(tmp, path)
        self.dumps.append(path)
        if reason.startswith("slo_breach"):
            # an automatic breach dump IS an incident: timeline it
            _events.record_event("slo_breach", reason=reason,
                                 detail={"path": path})
        return path


_engine: Optional[SloEngine] = None
_engine_lock = threading.Lock()


def get_engine() -> SloEngine:
    """The process-wide engine over the shared REGISTRY, created lazily
    (env read at first use). Tests build private SloEngine instances."""
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = SloEngine()
        return _engine


def _collect() -> None:
    get_engine().tick()


REGISTRY.add_collector(_collect)
