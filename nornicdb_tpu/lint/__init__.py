"""nornic-lint: AST-driven invariant suite over the whole package.

The reference engine keeps a 255k-LoC concurrent codebase honest with
the race detector and 397 test files; this port grew the same class of
hand-enforced invariants — pow2 compile buckets, snapshot version
re-checks, the normalized degrade vocabulary, lock-guarded freshness
counters, ~51 env knobs — but until ISSUE 14 only the metrics catalog
was machine-checked. ``nornicdb_tpu.lint`` turns the rest into a static
gate wired into tier-1 (``scripts/nornic_lint.py``; default-suite test
in ``tests/test_lint.py``).

Five passes (see each module's docstring for rules):

- ``jit-hygiene``       host syncs / env reads / unbucketed dispatch
                        shapes in jit-traced code (jit_hygiene.py)
- ``lock-discipline``   single-writer heuristic: attributes written
                        under ``with self._lock`` must never be written
                        outside it (lock_discipline.py)
- ``degrade-contract``  ``record_degrade`` reason vocabulary + per-
                        module post-dispatch version re-checks
                        (degrade_contract.py)
- ``env-knob-catalog``  every NORNICDB_* read documented; per-request
                        env reads on registered hot paths flagged
                        (env_catalog.py)
- ``metrics-catalog``   the pre-existing scripts/check_metrics_catalog
                        drift lint, folded in (metrics_catalog.py)

Grandfathered findings live in a committed baseline
(``scripts/nornic_lint_baseline.json``) keyed by line-stable
fingerprints; ``--update-baseline`` regenerates it. Inline escape
hatches (``# lint: unguarded-ok`` and friends) suppress individual
findings at the source line — see docs/static_analysis.md.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, asdict
from typing import Dict, List, Optional, Sequence

__all__ = [
    "Finding",
    "PASSES",
    "pass_names",
    "run_passes",
    "load_baseline",
    "save_baseline",
    "apply_baseline",
    "DEFAULT_BASELINE",
]

DEFAULT_BASELINE = os.path.join("scripts", "nornic_lint_baseline.json")


@dataclass
class Finding:
    """One lint violation.

    ``fingerprint`` deliberately excludes the line number: baselined
    findings must survive unrelated edits above them. ``detail`` is the
    stable discriminator inside a context (attribute name, knob name,
    offending call text).
    """

    pass_name: str
    rule: str
    path: str  # repo-relative
    line: int
    context: str = ""  # dotted qualname of the enclosing def/class
    detail: str = ""
    message: str = ""

    def fingerprint(self) -> str:
        return "|".join(
            (self.pass_name, self.rule, self.path, self.context,
             self.detail))

    def to_dict(self) -> Dict:
        return asdict(self)

    def render(self) -> str:
        ctx = f" [{self.context}]" if self.context else ""
        return (f"{self.path}:{self.line}:{ctx} {self.rule}: "
                f"{self.message}")


# ---------------------------------------------------------------------------
# pass registry
# ---------------------------------------------------------------------------

def _load_passes():
    from nornicdb_tpu.lint import (
        degrade_contract,
        env_catalog,
        jit_hygiene,
        lock_discipline,
        metrics_catalog,
    )

    return {
        "jit-hygiene": jit_hygiene,
        "lock-discipline": lock_discipline,
        "degrade-contract": degrade_contract,
        "env-knob-catalog": env_catalog,
        "metrics-catalog": metrics_catalog,
    }


class _PassRegistry:
    """Lazy pass table: importing ``nornicdb_tpu.lint`` must stay cheap
    (the metrics pass imports the serving modules on *run*, not on
    import)."""

    def __init__(self):
        self._passes = None

    def _table(self):
        if self._passes is None:
            self._passes = _load_passes()
        return self._passes

    def names(self) -> List[str]:
        return list(self._table().keys())

    def get(self, name: str):
        return self._table()[name]

    def items(self):
        return self._table().items()


PASSES = _PassRegistry()


def pass_names() -> List[str]:
    return PASSES.names()


def pass_descriptions() -> Dict[str, str]:
    """First docstring line of each pass module — ``--list-passes``."""
    out = {}
    for name, mod in PASSES.items():
        doc = (mod.__doc__ or "").strip().splitlines()
        out[name] = doc[0] if doc else ""
    return out


def run_passes(
    root: str,
    passes: Optional[Sequence[str]] = None,
    tree=None,
) -> List[Finding]:
    """Run the selected passes (default: all) over the package rooted
    at ``root`` and return raw findings — baseline not yet applied,
    escape hatches already honored (suppression is a property of the
    source, not of the run)."""
    from nornicdb_tpu.lint.astutil import load_package

    selected = list(passes) if passes else pass_names()
    unknown = [p for p in selected if p not in pass_names()]
    if unknown:
        raise ValueError(f"unknown lint pass(es): {unknown}; "
                         f"known: {pass_names()}")
    if tree is None:
        tree = load_package(root)
    findings: List[Finding] = []
    for name in selected:
        findings.extend(PASSES.get(name).run(tree))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.detail))
    return findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, int]:
    """fingerprint -> grandfathered count. Missing file = empty
    baseline (a fresh checkout lints strictly)."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def save_baseline(
    path: str,
    findings: Sequence[Finding],
    extra: Optional[Dict[str, int]] = None,
) -> Dict:
    """Write the baseline for ``findings``; ``extra`` carries
    fingerprint counts to preserve verbatim (a subset-pass CLI update
    keeps the unselected passes' grandfathered entries through it)."""
    counts: Dict[str, int] = dict(extra or {})
    for f in findings:
        fp = f.fingerprint()
        counts[fp] = counts.get(fp, 0) + 1
    data = {
        "version": 1,
        "generated_by": "scripts/nornic_lint.py --update-baseline",
        "findings": {k: counts[k] for k in sorted(counts)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")
    return data


def apply_baseline(
    findings: Sequence[Finding], baseline: Dict[str, int]
) -> List[Finding]:
    """Findings NOT covered by the baseline. Counted per fingerprint:
    a second violation with the same fingerprint (new unguarded write
    of the same attribute in the same method) is fresh even though the
    first is grandfathered."""
    budget = dict(baseline)
    fresh: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
        else:
            fresh.append(f)
    return fresh
