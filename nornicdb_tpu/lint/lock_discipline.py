"""Lock discipline: the single-writer heuristic over ``with self._lock``
sites.

Rule ``unguarded-write``: within one class, an instance attribute that
is ever written under a ``with self.<lock>:`` block must never be
written outside one. This is exactly the invariant the ~526 existing
lock sites enforce by convention (freshness counters, snapshot maps,
changelogs): one writer discipline, guarded reads optional.

What counts as holding the lock:

- lexically inside a ``with`` whose context expression is a ``self``
  attribute (or local name) containing "lock", "cv", "cond" or
  "mutex" — ``with self._lock:``, ``with self._inflight_lock:``,
  multi-item withs included;
- the enclosing method's name ends in ``_locked`` (the repo convention
  for "caller holds the lock");
- the write is in ``__init__`` / ``__new__`` / ``__del__`` /
  ``close``-like teardown (object not yet / no longer shared).

Escape hatch: ``# lint: unguarded-ok`` on (or one line above) the
write — for deliberate racy-but-benign writes (monotonic hint flags,
cached gate bits). Say why in the surrounding comment.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from nornicdb_tpu.lint import Finding
from nornicdb_tpu.lint import config as cfg
from nornicdb_tpu.lint.astutil import (
    ModuleInfo,
    PackageTree,
    ancestors,
    dotted,
    qualname,
    suppressed,
)

PASS = "lock-discipline"

_LOCK_NAME_RE = re.compile(r"lock|cv\b|cond|mutex", re.IGNORECASE)
# methods where unguarded writes are constructor/teardown-safe
_EXEMPT_METHODS = ("__init__", "__new__", "__del__", "__exit__",
                   "close", "shutdown", "stop")
_LOCKED_SUFFIX = "_locked"


def _is_lock_ctx(expr: ast.AST) -> bool:
    """True for ``self._lock`` / bare ``lock``-ish names, including
    ``self._lock.acquire_timeout()``-style wrapped managers."""
    name = dotted(expr)
    if not name:
        return False
    last = name.split(".")[-1]
    if last in ("acquire", "read_lock", "write_lock"):
        segs = name.split(".")
        last = segs[-2] if len(segs) > 1 else last
    return bool(_LOCK_NAME_RE.search(last))


def _under_lock(node: ast.AST) -> bool:
    """Lexically inside a ``with <lock>:`` block. Method-name
    conventions (``*_locked``, ``__init__``) are handled separately as
    *exempt* — they neither establish an attribute as lock-guarded nor
    get flagged."""
    for anc in ancestors(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                if _is_lock_ctx(item.context_expr):
                    return True
    return False


def _method_of(node: ast.AST) -> Optional[str]:
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc.name
    return None


@dataclass
class _Write:
    attr: str
    line: int
    guarded: bool
    exempt: bool   # __init__-class method
    node: ast.AST


def _self_attr_target(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _class_writes(cls: ast.ClassDef) -> List[_Write]:
    writes: List[_Write] = []
    for node in ast.walk(cls):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
            # tuple unpacking: a, self.x = ...
            flat: List[ast.AST] = []
            for t in targets:
                if isinstance(t, (ast.Tuple, ast.List)):
                    flat.extend(t.elts)
                else:
                    flat.append(t)
            targets = flat
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for tgt in targets:
            attr = _self_attr_target(tgt)
            if attr is None:
                continue
            # writes inside a NESTED class belong to that class
            owner = None
            for anc in ancestors(node):
                if isinstance(anc, ast.ClassDef):
                    owner = anc
                    break
            if owner is not cls:
                continue
            method = _method_of(node) or ""
            writes.append(_Write(
                attr=attr, line=node.lineno,
                guarded=_under_lock(node),
                exempt=method in _EXEMPT_METHODS
                or method.endswith(_LOCKED_SUFFIX),
                node=node))
    return writes


def run(tree: PackageTree) -> List[Finding]:
    findings: List[Finding] = []
    for mod in tree.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            writes = _class_writes(node)
            guarded_attrs = {w.attr for w in writes if w.guarded
                             and not w.exempt}
            if not guarded_attrs:
                continue
            for w in writes:
                if w.attr not in guarded_attrs or w.guarded \
                        or w.exempt:
                    continue
                if suppressed(mod, w.line, cfg.HATCH_LOCK):
                    continue
                findings.append(Finding(
                    pass_name=PASS, rule="unguarded-write",
                    path=mod.rel, line=w.line,
                    context=f"{node.name}."
                            f"{_method_of(w.node) or '<class>'}",
                    detail=w.attr,
                    message=(f"self.{w.attr} is written under "
                             f"{node.name}'s lock elsewhere but "
                             f"unguarded here (single-writer "
                             f"discipline)")))
    return findings
