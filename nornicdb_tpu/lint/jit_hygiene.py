"""JIT hygiene: host syncs, env reads and unbucketed dispatch shapes in
jax-traced code.

Rules
-----
``host-sync-item``
    ``x.item()`` inside a traced body — a device->host transfer (and a
    trace error on an actual tracer). The repo's contract is that
    results cross the boundary once, in the dispatcher, never inside
    the compiled program.
``host-sync-coercion``
    ``float(x)`` / ``int(x)`` / ``bool(x)`` on a non-static expression
    inside a traced body. Static-looking args (shape/ndim/dtype/len
    arithmetic, literals) are exempt — those fold at trace time.
``host-sync-numpy``
    ``np.asarray(...)`` / ``np.array(...)`` inside a traced body: a
    silent device sync when handed a tracer. Static shape math through
    numpy is fine and recognized via the same exemption.
``env-read-in-jit``
    ``os.environ`` / ``os.getenv`` (or an ``_env_*`` helper) inside a
    traced body — a host call baked into trace, re-read never.
``unbucketed-dispatch``
    A ``record_dispatch(kind, bucket, ...)`` whose bucket argument
    provably bypasses ``pow2_bucket`` (a raw ``len()``/``.shape``
    expression or a local assigned from one). The (kind, bucket) pair
    keys the compile-universe accounting; raw sizes there mean a
    recompile per distinct shape. Snapshot/attribute lookups are
    trusted — capacities are bucketed at build.

Escape hatch: ``# lint: jit-ok`` on (or one line above) the flagged
line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from nornicdb_tpu.lint import Finding
from nornicdb_tpu.lint import config as cfg
from nornicdb_tpu.lint.astutil import (
    ModuleInfo,
    PackageTree,
    call_name,
    dotted,
    enclosing_function,
    is_env_read_node,
    qualname,
    short_src,
    suppressed,
    traced_function_names,
)

PASS = "jit-hygiene"

_NUMPY_ROOTS = ("np", "numpy", "onp")
_NUMPY_SYNC_ATTRS = ("asarray", "array")
_COERCIONS = ("float", "int", "bool")


def _static_names(fdef: ast.AST) -> Set[str]:
    """Local names provably bound to trace-static values: assigned
    (only) from shape/len/literal expressions, including tuple
    unpacking from ``.shape`` (``b, d = x.shape``)."""
    static: Set[str] = set()
    tainted: Set[str] = set()
    for _ in range(2):  # two passes: let b = a + 1 see a's verdict
        for node in ast.walk(fdef):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if isinstance(tgt, (ast.Tuple, ast.List)):
                    names = [e.id for e in tgt.elts
                             if isinstance(e, ast.Name)]
                    if _expr_static(node.value, static) \
                            and len(names) == len(tgt.elts):
                        static.update(n for n in names
                                      if n not in tainted)
                    else:
                        tainted.update(names)
                        static.difference_update(names)
                elif isinstance(tgt, ast.Name):
                    if _expr_static(node.value, static):
                        if tgt.id not in tainted:
                            static.add(tgt.id)
                    else:
                        tainted.add(tgt.id)
                        static.discard(tgt.id)
    return static


def _expr_static(node: ast.AST, static_names: Set[str]) -> bool:
    """Expression that folds at trace time: literals, shape/ndim/
    dtype/len arithmetic, and names already proven static."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return node.id in static_names
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
                "shape", "ndim", "dtype", "itemsize"):
            return True
        if isinstance(sub, ast.Call):
            fname = call_name(sub)
            if fname == "len" or fname.endswith(".bit_length"):
                return True
        if isinstance(sub, ast.Name) and sub.id in static_names:
            return True
    return False


def _numpy_sync_call(call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Attribute) \
            and func.attr in _NUMPY_SYNC_ATTRS:
        root = dotted(func.value)
        return root in _NUMPY_ROOTS
    return False


def _check_traced_body(
    mod: ModuleInfo, fdef: ast.AST, findings: List[Finding],
    seen: Set[int],
) -> None:
    ctx = qualname(fdef)
    static = _static_names(fdef)
    for node in ast.walk(fdef):
        if id(node) in seen:
            continue
        rule = None
        detail = ""
        if isinstance(node, ast.Call):
            fname = call_name(node)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                rule, detail = "host-sync-item", short_src(mod, node)
            elif fname in _COERCIONS and len(node.args) == 1 \
                    and not _expr_static(node.args[0], static):
                rule = "host-sync-coercion"
                detail = short_src(mod, node)
            elif _numpy_sync_call(node) and node.args \
                    and not _expr_static(node.args[0], static):
                rule = "host-sync-numpy"
                detail = short_src(mod, node)
        if rule is None and is_env_read_node(node):
            rule, detail = "env-read-in-jit", short_src(mod, node)
        if rule is not None:
            seen.add(id(node))
            if suppressed(mod, node.lineno, cfg.HATCH_JIT):
                continue
            findings.append(Finding(
                pass_name=PASS, rule=rule, path=mod.rel,
                line=node.lineno, context=ctx, detail=detail,
                message=f"{detail} in jit-traced code"))


# ---------------------------------------------------------------------------
# unbucketed-dispatch
# ---------------------------------------------------------------------------

def _expr_mentions(node: ast.AST, names) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and call_name(sub) in names:
            return True
        if isinstance(sub, ast.Name) and sub.id in names:
            return True
    return False


def _raw_size_expr(node: ast.AST) -> bool:
    """Provably a raw (unbucketed) size: built from len()/.shape
    without a pow2 helper anywhere in the expression."""
    if _expr_mentions(node, cfg.POW2_HELPERS):
        return False
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        # a pow2 literal IS a bucket (the b=1 poison-isolation
        # replays); any other literal is exactly the hazard
        v = node.value
        return not (v > 0 and (v & (v - 1)) == 0)
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and call_name(sub) == "len":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "shape":
            return True
    return False


def _local_assignments(
    fdef: ast.AST,
) -> Dict[str, List[ast.AST]]:
    out: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(fdef):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.setdefault(tgt.id, []).append(node.value)
        elif isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Name):
            out.setdefault(node.target.id, []).append(node.value)
    return out


def _check_dispatch_buckets(
    mod: ModuleInfo, findings: List[Finding],
) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = call_name(node)
        if fname.split(".")[-1] not in cfg.DISPATCH_RECORDERS:
            continue
        if len(node.args) < 2:
            continue
        bucket = node.args[1]
        bad = False
        if _raw_size_expr(bucket):
            bad = True
        elif isinstance(bucket, ast.Name):
            fdef = enclosing_function(node)
            if fdef is not None:
                assigns = _local_assignments(fdef).get(bucket.id, [])
                if assigns and all(_raw_size_expr(a)
                                   for a in assigns):
                    bad = True
        if bad and not suppressed(mod, node.lineno, cfg.HATCH_JIT):
            fdef = enclosing_function(node)
            findings.append(Finding(
                pass_name=PASS, rule="unbucketed-dispatch",
                path=mod.rel, line=node.lineno,
                context=qualname(fdef) if fdef is not None else "",
                detail=short_src(mod, bucket),
                message=(f"dispatch bucket {short_src(mod, bucket)!r} "
                         f"bypasses pow2_bucket — every distinct "
                         f"shape is its own XLA compile")))


def run(tree: PackageTree) -> List[Finding]:
    findings: List[Finding] = []
    for mod in tree.modules.values():
        traced = traced_function_names(mod)
        seen: Set[int] = set()
        # dedupe: a def reachable under several traced names is
        # checked once (seen carries node ids across bodies)
        checked: Set[int] = set()
        for fdef in traced.values():
            if isinstance(fdef, ast.Pass) or id(fdef) in checked:
                continue
            checked.add(id(fdef))
            _check_traced_body(mod, fdef, findings, seen)
        _check_dispatch_buckets(mod, findings)
    return findings
