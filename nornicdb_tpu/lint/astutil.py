"""Shared AST plumbing for the lint passes.

One parse of the package per run: ``load_package`` returns a
``PackageTree`` of ``ModuleInfo`` (ast + source lines + parent links);
passes walk it read-only. Helpers here encode the repo idioms the
passes share — what counts as an env read, what counts as a jit
wrapper, how escape-hatch comments suppress a finding, and qualname
computation for line-stable fingerprints.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

PACKAGE = "nornicdb_tpu"

# directories never linted (generated protobuf stubs, vendored UI)
_SKIP_PARTS = ("__pycache__",)
_SKIP_SUFFIXES = ("_pb2.py", "_pb2_grpc.py")


@dataclass
class ModuleInfo:
    rel: str                 # repo-relative path, forward slashes
    path: str                # absolute path
    tree: ast.Module
    lines: List[str]         # raw source lines (no trailing newline)

    @property
    def modname(self) -> str:
        """Dotted module name: nornicdb_tpu/search/cagra.py ->
        nornicdb_tpu.search.cagra"""
        mod = self.rel[:-3] if self.rel.endswith(".py") else self.rel
        mod = mod.replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        return mod


@dataclass
class PackageTree:
    root: str                       # repo root
    modules: Dict[str, ModuleInfo]  # rel -> info

    def by_modname(self, modname: str) -> Optional[ModuleInfo]:
        for m in self.modules.values():
            if m.modname == modname:
                return m
        return None


def _link_parents(tree: ast.Module) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._lint_parent = node  # type: ignore[attr-defined]


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "_lint_parent", None)


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = parent(node)
    while cur is not None:
        yield cur
        cur = parent(cur)


def enclosing_function(node: ast.AST):
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def qualname(node: ast.AST) -> str:
    """Dotted path of enclosing defs/classes, innermost last. Stable
    under unrelated edits — the fingerprint context."""
    parts: List[str] = []
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            parts.append(anc.name)
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        parts.insert(0, node.name)
    return ".".join(reversed(parts))


def load_package(root: str, package: str = PACKAGE) -> PackageTree:
    modules: Dict[str, ModuleInfo] = {}
    pkg_root = os.path.join(root, package)
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d not in _SKIP_PARTS]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            if any(fn.endswith(s) for s in _SKIP_SUFFIXES):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                src = f.read()
            try:
                tree = ast.parse(src, filename=rel)
            except SyntaxError:
                # a file the interpreter can't parse fails tier-1 long
                # before the lint does; skip rather than crash the run
                continue
            _link_parents(tree)
            modules[rel] = ModuleInfo(
                rel=rel, path=path, tree=tree,
                lines=src.splitlines())
    return PackageTree(root=root, modules=modules)


def parse_sources(root: str, sources: Dict[str, str]) -> PackageTree:
    """A tree from in-memory {rel: source} mappings — the test-fixture
    entry point (tests/test_lint.py lints snippets in isolation)."""
    modules: Dict[str, ModuleInfo] = {}
    for rel, src in sources.items():
        tree = ast.parse(src, filename=rel)
        _link_parents(tree)
        modules[rel] = ModuleInfo(
            rel=rel, path=os.path.join(root, rel), tree=tree,
            lines=src.splitlines())
    return PackageTree(root=root, modules=modules)


def parse_single(root: str, rel: str, src: str) -> PackageTree:
    """One-module convenience wrapper over :func:`parse_sources`."""
    return parse_sources(root, {rel: src})


# ---------------------------------------------------------------------------
# escape hatches
# ---------------------------------------------------------------------------

_HATCH_RE = re.compile(r"#\s*lint:\s*([a-z0-9_,\- ]+)")


def suppressed(mod: ModuleInfo, lineno: int, token: str) -> bool:
    """True when the source line (or the line above — multi-line calls
    put the directive where it fits) carries ``# lint: <token>``."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(mod.lines):
            m = _HATCH_RE.search(mod.lines[ln - 1])
            if m and token in [t.strip()
                               for t in m.group(1).split(",")]:
                return True
    return False


# ---------------------------------------------------------------------------
# source rendering
# ---------------------------------------------------------------------------

def src(mod: ModuleInfo, node: ast.AST) -> str:
    try:
        return ast.get_source_segment(
            "\n".join(mod.lines), node) or ast.dump(node)
    except Exception:
        return ast.dump(node)


def short_src(mod: ModuleInfo, node: ast.AST, limit: int = 80) -> str:
    text = " ".join(src(mod, node).split())
    return text if len(text) <= limit else text[: limit - 3] + "..."


# ---------------------------------------------------------------------------
# name-chain helpers
# ---------------------------------------------------------------------------

def dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression: ``os.environ.get`` ->
    "os.environ.get"; non-name parts render as empty segments."""
    if isinstance(node, ast.Attribute):
        return f"{dotted(node.value)}.{node.attr}"
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return dotted(node.func)
    return ""


def call_name(call: ast.Call) -> str:
    return dotted(call.func)


# ---------------------------------------------------------------------------
# env-read detection (shared by jit-hygiene and env-knob-catalog)
# ---------------------------------------------------------------------------

_ENV_HELPER_RE = re.compile(r"(^|\.)_?env_(int|float|str|bool|s|ms)$")
_KNOB_RE = re.compile(r"^NORNICDB_[A-Z0-9_]+$")


def is_env_read_call(call: ast.Call) -> bool:
    """Call that reads the process environment: ``os.environ.get``,
    ``os.getenv``, ``os.environ.setdefault``, or one of the repo's
    ``_env_int``-style helpers."""
    name = call_name(call)
    if not name:
        return False
    if name.endswith("environ.get") or name.endswith(
            "environ.setdefault"):
        return True
    if name.endswith("getenv"):
        return True
    if _ENV_HELPER_RE.search(name):
        return True
    return False


def is_env_read_node(node: ast.AST) -> bool:
    """Any env-read expression: the calls above, ``os.environ[...]``
    subscripts, and ``"X" in os.environ`` membership tests."""
    if isinstance(node, ast.Call):
        return is_env_read_call(node)
    if isinstance(node, ast.Subscript):
        # ctx matters: os.environ["X"] = v is a WRITE — cataloguing it
        # as a read (or flagging it on a hot path) misdiagnoses
        return isinstance(node.ctx, ast.Load) \
            and dotted(node.value).endswith("environ")
    if isinstance(node, ast.Compare):
        return any(
            dotted(c).endswith("environ") for c in node.comparators)
    return False


_SHORT_KNOB_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")
# config.py's prefix-adding helpers: env_bool("HYBRID_FUSED") reads
# NORNICDB_HYBRID_FUSED. The leading-underscore variants (audit's
# _env_float, broker's _env_int) take FULL names.
_PREFIXING_HELPERS = ("env_str", "env_bool", "env_int", "env_float")


def knob_literal(mod: ModuleInfo, node: ast.AST) -> Optional[str]:
    """The NORNICDB_* knob a read targets, when statically knowable.

    Handles literal first args, module-level str-constant indirection
    (``ENV_VAR = "NORNICDB_X"; os.environ.get(ENV_VAR)``),
    subscript/membership forms, and config.py's prefix-adding helpers
    (``env_bool("HYBRID_FUSED")`` -> NORNICDB_HYBRID_FUSED). Fully
    dynamic names (``ENV_PREFIX + name`` inside config.py itself)
    return None — that generic plumbing is catalogued via the config
    schema, not per-site.
    """
    candidates: List[ast.AST] = []
    prefixing = False
    if isinstance(node, ast.Call):
        prefixing = dotted(node.func).split(".")[-1] \
            in _PREFIXING_HELPERS
        candidates = list(node.args[:1]) + [
            kw.value for kw in node.keywords
            if kw.arg in ("key", "name")]
    elif isinstance(node, ast.Subscript):
        candidates = [node.slice]
    elif isinstance(node, ast.Compare):
        candidates = [node.left]
    for cand in candidates:
        val: Optional[str] = None
        if isinstance(cand, ast.Constant) and isinstance(
                cand.value, str):
            val = cand.value
        elif isinstance(cand, ast.Name):
            val = module_str_constant(mod, cand.id)
        if val is None:
            continue
        if _KNOB_RE.match(val):
            return val
        if prefixing and _SHORT_KNOB_RE.match(val):
            return "NORNICDB_" + val
    return None


def module_str_constant(mod: ModuleInfo, name: str) -> Optional[str]:
    """Value of a module-level ``NAME = "literal"`` assignment."""
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    if isinstance(stmt.value, ast.Constant) and \
                            isinstance(stmt.value.value, str):
                        return stmt.value.value
    return None


# ---------------------------------------------------------------------------
# jit detection (shared by jit-hygiene and degrade-contract)
# ---------------------------------------------------------------------------

def _is_jit_name(node: ast.AST) -> bool:
    """``jax.jit`` / bare ``jit`` imported from jax."""
    name = dotted(node)
    return name == "jit" or name.endswith(".jit")


def _is_jit_factory(node: ast.AST) -> bool:
    """Expression that evaluates to a jit transform:
    ``jax.jit`` itself or ``functools.partial(jax.jit, ...)``."""
    if _is_jit_name(node):
        return True
    if isinstance(node, ast.Call):
        fname = call_name(node)
        if fname.endswith("partial") and node.args \
                and _is_jit_name(node.args[0]):
            return True
    return False


_SHARD_WRAP_RE = re.compile(r"(^|[._])shard_map$")


def traced_function_names(mod: ModuleInfo) -> Dict[str, ast.AST]:
    """Module-local functions that run under jax tracing.

    Seeds: defs decorated with ``jax.jit`` / ``functools.partial(
    jax.jit, ...)``; defs wrapped by assignment (``X = jax.jit(f)`` or
    ``X = functools.partial(jax.jit, ...)(f)``); first args of
    ``*shard_map`` wrapping calls. The closure is taken over the
    module-local call graph: anything a traced function calls is traced
    during trace. Returns name -> def node (includes the *wrapper*
    assignment names so call sites can be recognized).
    """
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)

    traced: Dict[str, ast.AST] = {}

    def mark(name: str, node: Optional[ast.AST] = None) -> None:
        if name not in traced:
            traced[name] = node if node is not None \
                else defs.get(name, ast.Pass())

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_factory(dec):
                    mark(node.name, node)
        elif isinstance(node, ast.Assign):
            val = node.value
            if isinstance(val, ast.Call):
                wrapped: Optional[str] = None
                if _is_jit_factory(val.func) or _SHARD_WRAP_RE.search(
                        call_name(val)):
                    for arg in val.args:
                        if isinstance(arg, ast.Name) \
                                and arg.id in defs:
                            wrapped = arg.id
                            break
                if wrapped:
                    mark(wrapped)
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            # the wrapper name is a traced entry point
                            # at call sites, but has no body of its own
                            mark(tgt.id, defs.get(wrapped))
        elif isinstance(node, ast.Call):
            # fn passed into a shard_map/scan/while_loop combinator
            # inside any traced body is handled by the closure below;
            # top-level shard_map wrapping outside Assign:
            if _SHARD_WRAP_RE.search(call_name(node)):
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name) and arg.id in defs:
                        mark(arg.id)

    # closure over the module-local call graph
    changed = True
    while changed:
        changed = False
        for name in list(traced):
            fdef = defs.get(name)
            if fdef is None or isinstance(fdef, ast.Pass):
                continue
            for node in ast.walk(fdef):
                if isinstance(node, ast.Call):
                    callee = call_name(node)
                    if callee in defs and callee not in traced:
                        traced[callee] = defs[callee]
                        changed = True
                # nested defs inside a traced body are traced
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node.name not in traced:
                    traced[node.name] = node
                    changed = True
    return traced
