"""Declarative registries for the lint passes (the
``IMPORT_TIME_MODULES`` precedent: facts about the codebase the AST
cannot cheaply infer live here, reviewed like code).

Keep these lists in sync when adding serving paths — a module that
installs version-keyed device snapshots belongs in
``SNAPSHOT_MODULES``; a function that runs once per query (not once
per batch) belongs in ``HOT_PATHS``.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# degrade-contract: modules that install version-keyed device snapshots.
# Each must contain at least one function that calls a jit-traced
# program AND re-checks a version/mutation/generation counter after the
# dispatch (the PR 2/4/6/8/9 freshness discipline: a write landing
# mid-dispatch throws the device answer away, never serves it).
# ---------------------------------------------------------------------------
SNAPSHOT_MODULES = {
    "nornicdb_tpu.search.cagra": (
        "CagraIndex._resolve",       # live-stale filter vs built_mutations
        "CagraIndex._delta_block",   # changelog marker vs mutations
    ),
    "nornicdb_tpu.search.device_bm25": (
        "DeviceBM25.delta_block",
        "DeviceBM25.refresh_alive",
    ),
    "nornicdb_tpu.search.device_quant": (
        "QuantizedBrutePlane.search_batch",  # built_compactions re-check
    ),
    "nornicdb_tpu.search.hybrid_fused": (
        "FusedHybrid._walk_context",  # live brute mutations after delta
        "FusedHybrid._graph_rows",
    ),
    "nornicdb_tpu.query.device_graph": (
        "DeviceGraphPlane._chain_batch",  # catalog.version post-dispatch
        "DeviceGraphPlane.traverse_rank",
    ),
    "nornicdb_tpu.search.tiered_store": (
        "TieredStore.search_batch",  # residency_gen re-check after ADC
    ),
    "nornicdb_tpu.background.device_plane": (
        "BackgroundDevicePlane.decay_sweep",      # catalog.version
        "BackgroundDevicePlane.linkpredict_topk",  # etype_versions
        "BackgroundDevicePlane.fastrp",            # etype_versions
    ),
}

# tokens that count as a freshness counter in a post-dispatch re-check
VERSION_TOKENS = ("version", "mutation", "generation", "build_seq",
                  "built_mutations", "compaction", "gen")

# ---------------------------------------------------------------------------
# env-knob-catalog: per-REQUEST functions (run once per query/message,
# not once per coalesced batch or per process). An os.environ read here
# costs ~1 us — 2-8% of the 50 us host chain path (PR 9's measurement).
# Batch-leader and init/build functions deliberately stay off this
# list: their env reads amortize over the whole batch / process.
# Entries are (module-relative path, dotted qualname prefix).
# ---------------------------------------------------------------------------
HOT_PATHS = (
    # vector/hybrid serving front door — once per query
    ("nornicdb_tpu/search/service.py", "SearchService.search"),
    # per-rider coalescer paths (leader-side _run/_run_batch reads
    # amortize over the whole batch; these run per query)
    ("nornicdb_tpu/search/microbatch.py", "MicroBatcher.search"),
    ("nornicdb_tpu/search/microbatch.py", "BatchCoalescer.submit"),
    # per-query device-plane gates (the 50 us host chain path)
    ("nornicdb_tpu/query/device_graph.py",
     "DeviceGraphPlane.maybe_device"),
    ("nornicdb_tpu/query/device_graph.py",
     "DeviceGraphPlane.chain_topk"),
    # single-query search fronts
    ("nornicdb_tpu/search/vector_index.py", "BruteForceIndex.search"),
    ("nornicdb_tpu/search/cagra.py", "CagraIndex.search"),
    # wire-plane per-rider path (ring post/claim runs per request)
    ("nornicdb_tpu/search/broker.py", "BrokerClient.vec_search"),
    ("nornicdb_tpu/search/broker.py", "BrokerClient.call"),
    # fleet read routing — once per read
    ("nornicdb_tpu/api/fleet_router.py", "FleetRouter.pick_read"),
    ("nornicdb_tpu/api/fleet_router.py", "RoutedSearch.search"),
    # multi-process fleet hot paths (ISSUE 16) — http_search routes
    # once per read; _request runs once per remote hop; the frame
    # codecs run once per streamed WAL message. Lease/posture knobs
    # are read once at __init__ and cached.
    ("nornicdb_tpu/api/fleet_router.py", "FleetRouter.http_search"),
    ("nornicdb_tpu/api/fleet_router.py", "FleetRouter.pick_fresh"),
    ("nornicdb_tpu/api/fleet_router.py", "RemoteReplica._request"),
    ("nornicdb_tpu/api/fleet_router.py", "RemoteReplica.search"),
    ("nornicdb_tpu/replication/transport.py", "read_frame"),
    ("nornicdb_tpu/replication/transport.py", "write_frame"),
    ("nornicdb_tpu/replication/transport.py",
     "DualPlaneTransport.request"),
    # tiered plane (ISSUE 17) — route scores centroids once per query
    # batch member; pool sizing runs per dispatch. Build/paging knobs
    # are read once at plane construction and cached.
    ("nornicdb_tpu/search/tiered_store.py", "TieredStore.route"),
    ("nornicdb_tpu/search/tiered_store.py", "TieredStore.pool_for"),
    # admission actuator (ISSUE 15) — deadline mint + verdict run once
    # per request on every ingress; config is cached at first use and
    # these must never read the environment
    ("nornicdb_tpu/admission.py", "AdmissionController.check"),
    ("nornicdb_tpu/admission.py", "AdmissionController.note_enter"),
    ("nornicdb_tpu/admission.py", "AdmissionController.note_exit"),
    ("nornicdb_tpu/admission.py", "mint_deadline"),
    ("nornicdb_tpu/admission.py", "parse_deadline_header"),
    ("nornicdb_tpu/admission.py", "record_shed"),
    ("nornicdb_tpu/admission.py", "lane_rank"),
    # tenant attribution (ISSUE 18) — resolution, refinement and the
    # per-request recording hooks run once per query on every ingress;
    # config is cached at first use and these must never read the
    # environment
    ("nornicdb_tpu/obs/tenant.py", "resolve"),
    ("nornicdb_tpu/obs/tenant.py", "refine"),
    ("nornicdb_tpu/obs/tenant.py", "current_label"),
    ("nornicdb_tpu/obs/tenant.py", "record_served"),
    ("nornicdb_tpu/obs/tenant.py", "record_cost"),
    ("nornicdb_tpu/obs/tenant.py", "_admit"),
    # device-truth calibration (ISSUE 20) — the cost gate runs once
    # per request on the microbatch ingress; predict_ms and the
    # per-dispatch observers run on every dispatch/record. Config is
    # cached at first use (device.cfg / admission.cfg); none of these
    # may read the environment.
    ("nornicdb_tpu/admission.py", "AdmissionController.cost_check"),
    ("nornicdb_tpu/obs/device.py", "predict_ms"),
    ("nornicdb_tpu/obs/device.py", "observe_dispatch"),
    ("nornicdb_tpu/obs/device.py", "note_cost"),
    ("nornicdb_tpu/obs/device.py", "maybe_sync"),
    ("nornicdb_tpu/obs/tenant.py", "record_device_seconds"),
)

# ---------------------------------------------------------------------------
# tenant-families (ISSUE 18): every metric family carrying a ``tenant``
# label must be declared here. The label is the cardinality hazard —
# each family below rides the obs/tenant.py cardinality-capped registry
# (fold past NORNICDB_TENANT_MAX into ``__other__``); a tenant label on
# any OTHER family bypasses that cap and can blow up the scrape. The
# metrics-catalog pass fails on a registered-but-undeclared family
# (undeclared-tenant-family) and on a declared-but-gone entry
# (stale-tenant-family).
# ---------------------------------------------------------------------------
TENANT_FAMILIES = (
    "nornicdb_tenant_requests_total",
    "nornicdb_tenant_request_seconds",
    "nornicdb_tenant_served_tier_total",
    "nornicdb_tenant_degrade_total",
    "nornicdb_tenant_shed_total",
    "nornicdb_tenant_cost_flops_total",
    "nornicdb_tenant_cost_bytes_total",
    "nornicdb_tenant_cost_queries_total",
    # measured device wall seconds (ISSUE 20): the bill in time, not
    # just analytic FLOPs
    "nornicdb_tenant_device_seconds_total",
)

# ---------------------------------------------------------------------------
# jit-hygiene: dispatch-recording calls whose bucket argument must come
# from the pow2 helpers (the compile-universe key; a raw batch size
# here means a recompile per distinct shape).
# ---------------------------------------------------------------------------
DISPATCH_RECORDERS = ("record_dispatch",)
POW2_HELPERS = ("pow2_bucket",)

# ---------------------------------------------------------------------------
# escape-hatch tokens per pass (document new ones in
# docs/static_analysis.md)
# ---------------------------------------------------------------------------
HATCH_LOCK = "unguarded-ok"
HATCH_JIT = "jit-ok"
HATCH_ENV = "env-ok"
HATCH_DEGRADE = "degrade-ok"
