"""Degrade contract: reason vocabulary + post-dispatch version
re-checks.

Rules
-----
``unknown-degrade-reason``
    A ``record_degrade(...)`` whose reason literal is not in
    ``obs.audit``'s vocabulary (``REASONS`` or a ``_LEGACY_REASONS``
    alias). The vocabulary is parsed from ``obs/audit.py``'s AST — the
    lint never imports the serving stack. Reasons that flow through a
    local wrapper (``_ledger(from_tier, reason)``) are resolved one
    call level up: the wrapper's call sites are checked at the
    corresponding argument position.
``dynamic-degrade-reason``
    A reason argument the lint cannot resolve to a literal (computed
    strings, attribute loads). Baseline or rewrite — every reason the
    ledger emits must be auditable against the documented vocabulary.
``missing-version-recheck``
    A module registered in ``config.SNAPSHOT_MODULES`` (it installs
    version-keyed device snapshots) has no function that compares a
    version/mutation/generation counter *after* calling a jit-traced
    program. That re-check is the freshness contract every device
    serving path carries: a write landing mid-dispatch must throw the
    device answer away.

Escape hatch: ``# lint: degrade-ok``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from nornicdb_tpu.lint import Finding
from nornicdb_tpu.lint import config as cfg
from nornicdb_tpu.lint.astutil import (
    ModuleInfo,
    PackageTree,
    call_name,
    enclosing_function,
    qualname,
    short_src,
    suppressed,
)

PASS = "degrade-contract"

_AUDIT_REL = "nornicdb_tpu/obs/audit.py"
# record_degrade(surface, from_tier, to_tier, reason, ...)
_REASON_POS = 3


def vocabulary(tree: PackageTree) -> Set[str]:
    """REASONS tuple values + legacy alias keys, parsed statically
    from obs/audit.py."""
    mod = tree.modules.get(_AUDIT_REL)
    vocab: Set[str] = set()
    if mod is None:
        return vocab
    for node in mod.tree.body:
        tgt_names = []
        if isinstance(node, ast.Assign):
            tgt_names = [t.id for t in node.targets
                         if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name):
            tgt_names = [node.target.id]
            value = node.value
        else:
            continue
        if "REASONS" in tgt_names and isinstance(
                value, (ast.Tuple, ast.List)):
            for elt in value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str):
                    vocab.add(elt.value)
        if "_LEGACY_REASONS" in tgt_names and isinstance(
                value, ast.Dict):
            for key in value.keys:
                if isinstance(key, ast.Constant) and isinstance(
                        key.value, str):
                    vocab.add(key.value)
    return vocab


def _reason_arg(call: ast.Call, pos: int) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "reason":
            return kw.value
    if len(call.args) > pos:
        return call.args[pos]
    return None


def _param_index(fdef, name: str) -> Optional[int]:
    params = [a.arg for a in fdef.args.args]
    if params and params[0] == "self":
        params = params[1:]
    return params.index(name) if name in params else None


def _literal_values(expr: ast.AST) -> Optional[List[str]]:
    """All string literals an expression can evaluate to, or None if
    any branch is non-literal. Handles the conditional-reason idiom:
    ``r = "replica_lag" if cond else "replica_drain"``."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    if isinstance(expr, ast.IfExp):
        a = _literal_values(expr.body)
        b = _literal_values(expr.orelse)
        if a is not None and b is not None:
            return a + b
    return None


def _resolve_local_literals(
    fdef: ast.AST, name: str,
) -> Optional[List[str]]:
    """Literal values a local name is assigned within ``fdef`` — None
    when any assignment is unresolvable (or there are none). A bare
    ``hold = None`` assignment is skipped, not unresolvable: it is the
    no-degrade arm of the guard idiom ``hold = None; if ...: hold =
    "quarantine"; ...; if hold is not None: _ledger(..., hold, ...)``
    (the record call never runs with the None value)."""
    vals: List[str] = []
    for node in ast.walk(fdef):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            if isinstance(node.value, ast.Constant) \
                    and node.value.value is None:
                continue
            v = _literal_values(node.value)
            if v is None:
                return None
            vals.extend(v)
    return vals or None


def _check_reason(
    mod: ModuleInfo,
    call: ast.Call,
    arg: Optional[ast.AST],
    vocab: Set[str],
    findings: List[Finding],
    wrappers: Dict[Tuple[str, str], int],
) -> None:
    """Validate one resolved reason argument; register wrapper params
    for one level of call-site propagation."""
    fdef = enclosing_function(call)
    ctx = qualname(fdef) if fdef is not None else ""
    if arg is None:
        return
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        # hatch honored on the literal's line OR the call line — a
        # multi-line call puts the directive where it fits
        if arg.value not in vocab \
                and not suppressed(mod, arg.lineno, cfg.HATCH_DEGRADE) \
                and not suppressed(mod, call.lineno,
                                   cfg.HATCH_DEGRADE):
            findings.append(Finding(
                pass_name=PASS, rule="unknown-degrade-reason",
                path=mod.rel, line=arg.lineno, context=ctx,
                detail=arg.value,
                message=(f"degrade reason {arg.value!r} is not in "
                         f"audit.normalize_reason's vocabulary")))
        return
    if isinstance(arg, ast.Name) and fdef is not None:
        idx = _param_index(fdef, arg.id)
        if idx is not None:
            # wrapper: validate literals at this function's call sites
            wrappers[(mod.rel, fdef.name)] = idx
            return
        vals = _resolve_local_literals(fdef, arg.id)
        if vals is not None:
            for v in vals:
                if v not in vocab \
                        and not suppressed(mod, arg.lineno,
                                           cfg.HATCH_DEGRADE) \
                        and not suppressed(mod, call.lineno,
                                           cfg.HATCH_DEGRADE):
                    findings.append(Finding(
                        pass_name=PASS,
                        rule="unknown-degrade-reason",
                        path=mod.rel, line=arg.lineno, context=ctx,
                        detail=v,
                        message=(f"degrade reason {v!r} (via local "
                                 f"{arg.id}) is not in the "
                                 f"vocabulary")))
            return
    if not suppressed(mod, call.lineno, cfg.HATCH_DEGRADE):
        findings.append(Finding(
            pass_name=PASS, rule="dynamic-degrade-reason",
            path=mod.rel, line=call.lineno, context=ctx,
            detail=short_src(mod, arg),
            message=(f"degrade reason {short_src(mod, arg)!r} cannot "
                     f"be resolved to a vocabulary literal")))


def _check_wrapper_sites(
    tree: PackageTree,
    wrappers: Dict[Tuple[str, str], int],
    vocab: Set[str],
    findings: List[Finding],
) -> None:
    # wrappers resolve module-locally: two modules may each define a
    # ``_ledger`` with different signatures (hybrid_fused's method vs
    # device_graph's module function) — cross-module matching by bare
    # name would check the wrong argument position
    for mod in tree.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            simple = call_name(node).split(".")[-1]
            idx = wrappers.get((mod.rel, simple))
            if idx is None:
                continue
            arg: Optional[ast.AST] = None
            for kw in node.keywords:
                if kw.arg == "reason":
                    arg = kw.value
            if arg is None and len(node.args) > idx:
                arg = node.args[idx]
            if arg is None:
                continue
            fdef = enclosing_function(node)
            ctx = qualname(fdef) if fdef is not None else ""
            if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str):
                if arg.value not in vocab \
                        and not suppressed(mod, arg.lineno,
                                           cfg.HATCH_DEGRADE) \
                        and not suppressed(mod, node.lineno,
                                           cfg.HATCH_DEGRADE):
                    findings.append(Finding(
                        pass_name=PASS,
                        rule="unknown-degrade-reason",
                        path=mod.rel, line=arg.lineno, context=ctx,
                        detail=arg.value,
                        message=(f"degrade reason {arg.value!r} "
                                 f"(via {simple}) is not in the "
                                 f"vocabulary")))
            elif isinstance(arg, ast.Name) and fdef is not None \
                    and (_param_index(fdef, arg.id) is not None
                         or _resolve_local_literals(fdef, arg.id)
                         is not None):
                # param: two levels of indirection — give up quietly;
                # local literals: check each against the vocabulary
                for v in (_resolve_local_literals(fdef, arg.id)
                          or []):
                    if v not in vocab and not suppressed(
                            mod, node.lineno, cfg.HATCH_DEGRADE):
                        findings.append(Finding(
                            pass_name=PASS,
                            rule="unknown-degrade-reason",
                            path=mod.rel, line=node.lineno,
                            context=ctx, detail=v,
                            message=(f"degrade reason {v!r} (via "
                                     f"{simple}) is not in the "
                                     f"vocabulary")))
            elif not suppressed(mod, node.lineno, cfg.HATCH_DEGRADE):
                findings.append(Finding(
                    pass_name=PASS, rule="dynamic-degrade-reason",
                    path=mod.rel, line=node.lineno, context=ctx,
                    detail=short_src(mod, arg),
                    message=(f"degrade reason {short_src(mod, arg)!r} "
                             f"(via {simple}) cannot be resolved to "
                             f"a vocabulary literal")))


# ---------------------------------------------------------------------------
# missing-version-recheck
# ---------------------------------------------------------------------------

def _mentions_version_token(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Constant) and isinstance(
                sub.value, str):
            name = sub.value
        if name and any(tok in name.lower()
                        for tok in cfg.VERSION_TOKENS):
            return True
    return False


def _recheck_carriers(mod: ModuleInfo) -> Dict[str, bool]:
    """qualname -> "contains a version-token Compare" for every
    function in the module."""
    out: Dict[str, bool] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        has = any(
            isinstance(sub, ast.Compare)
            and _mentions_version_token(sub)
            for sub in ast.walk(node))
        q = qualname(node)
        out[q] = out.get(q, False) or has
    return out


def run(tree: PackageTree) -> List[Finding]:
    findings: List[Finding] = []
    vocab = vocabulary(tree)
    wrappers: Dict[Tuple[str, str], int] = {}
    if vocab:
        for mod in tree.modules.values():
            if mod.rel == _AUDIT_REL:
                continue  # the vocabulary's own module defines it
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and call_name(
                        node).split(".")[-1] == "record_degrade":
                    _check_reason(
                        mod, node, _reason_arg(node, _REASON_POS),
                        vocab, findings, wrappers)
        _check_wrapper_sites(tree, wrappers, vocab, findings)
    for modname, carriers in cfg.SNAPSHOT_MODULES.items():
        mod = tree.by_modname(modname)
        if mod is None:
            findings.append(Finding(
                pass_name=PASS, rule="missing-version-recheck",
                path=modname.replace(".", "/") + ".py", line=1,
                detail=modname,
                message=(f"{modname} is registered in SNAPSHOT_MODULES"
                         f" but does not exist — update the registry "
                         f"in nornicdb_tpu/lint/config.py")))
            continue
        present = _recheck_carriers(mod)
        for carrier in carriers:
            if present.get(carrier, False):
                continue
            findings.append(Finding(
                pass_name=PASS, rule="missing-version-recheck",
                path=mod.rel, line=1, context=carrier,
                detail=f"{modname}:{carrier}",
                message=(f"{carrier} is the registered post-dispatch "
                         f"freshness re-check for {modname} but "
                         f"{'has lost its version-counter compare' if carrier in present else 'does not exist'}"
                         f" — restore the re-check or update "
                         f"SNAPSHOT_MODULES in lint/config.py")))
    return findings
