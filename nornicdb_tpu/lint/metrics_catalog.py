"""Metric-catalog drift lint: every import-time metric family must be
documented (the scripts/check_metrics_catalog.py logic, folded into the
nornic-lint framework as its fifth pass — ISSUE 14).

``docs/observability.md`` is the operator-facing catalog of the
``nornicdb_*`` metric families plus the serving-truth vocabularies
(dispatch kinds, canonical tiers, normalized degrade reasons, event
kinds). This module imports every module that registers families at
import time, then reports drift. The standalone CLI
(``scripts/check_metrics_catalog.py``) is a thin shim over this module
— its verdict shape and the ``tests/test_load_truth.py`` entry points
are unchanged.

Unlike the AST passes this one imports the serving stack at *run*
time; it is the reason ``nornic_lint`` reports runtime drift a pure
parse cannot see (a family registered under a computed name still
lands in the process registry).
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import re
import sys
from typing import List

# modules that register metric families at import time (module-level
# REGISTRY.counter/histogram/gauge calls). Keep in sync by grepping:
#   grep -rn "REGISTRY\.\(counter\|histogram\|gauge\)(" nornicdb_tpu
IMPORT_TIME_MODULES = (
    "nornicdb_tpu.obs",            # dispatch, stages, cost families
    "nornicdb_tpu.obs.events",     # incident-timeline counter (ISSUE 13)
    "nornicdb_tpu.obs.fleet",      # fleet-aggregator sources gauge
    "nornicdb_tpu.admission",      # shed/deadline/lane families (ISSUE 15)
    "nornicdb_tpu.search.microbatch",
    "nornicdb_tpu.search.broker",  # wire-plane broker families (ISSUE 11)
    "nornicdb_tpu.search.service",
    "nornicdb_tpu.search.cagra",
    "nornicdb_tpu.search.device_bm25",
    "nornicdb_tpu.search.device_quant",
    "nornicdb_tpu.search.tiered_store",  # tiered paging events (ISSUE 17)
    "nornicdb_tpu.search.hybrid_fused",
    "nornicdb_tpu.query.device_graph",
    "nornicdb_tpu.storage.wal",
    "nornicdb_tpu.api.bolt",
    "nornicdb_tpu.api.http_server",
    "nornicdb_tpu.api.qdrant_official_grpc",
    "nornicdb_tpu.api.fleet_router",       # read-fleet router (ISSUE 12)
    "nornicdb_tpu.replication.read_fleet",  # replica lag/failover gauges
    # ISSUE 16: the multi-process fleet modules register no families of
    # their own *today*, but they carry the streaming/posture hot paths
    # — importing them here means any family they grow is caught by
    # this lint the moment it appears, not when the docs drift.
    "nornicdb_tpu.replication.transport",   # dual-plane WAL streaming
    "nornicdb_tpu.replication.fleet_proc",  # subprocess replica fleet
    "nornicdb_tpu.obs.tenant",  # per-tenant attribution (ISSUE 18)
    # ISSUE 19: background device plane — jobs counter + bg_* dispatch
    # kinds registered at import
    "nornicdb_tpu.background.device_plane",
    # ISSUE 20: device-truth calibration plane — compile split,
    # roofline gauges, recompile counter, memory-ledger families
    "nornicdb_tpu.obs.device",
)

_PREFIX = "nornicdb_"

PASS = "metrics-catalog"


def _expand_braces(text: str) -> str:
    """Expand one level of ``name_{a,b,c}_suffix`` doc shorthand into
    the literal metric names so the substring match sees them."""
    pattern = re.compile(r"(\w*)\{([\w,]+)\}(\w*)")
    out = [text]
    for m in pattern.finditer(text):
        head, alts, tail = m.group(1), m.group(2), m.group(3)
        for alt in alts.split(","):
            out.append(f"{head}{alt}{tail}")
    return "\n".join(out)


def registered_families():
    from nornicdb_tpu.obs import REGISTRY

    for mod in IMPORT_TIME_MODULES:
        importlib.import_module(mod)
    return sorted(f.name for f in REGISTRY.families())


def _documented(expanded: str, name: str) -> bool:
    # word-boundary match: a plain substring test would let e.g. a
    # new nornicdb_stage_seconds family ride inside the documented
    # nornicdb_request_stage_seconds — the exact drift class this
    # lint exists to catch (underscores are word chars, so \b only
    # matches at the full-name edges)
    return re.search(rf"\b{re.escape(name)}\b", expanded) is not None


def missing_from_catalog(doc_text: str, families) -> list:
    expanded = _expand_braces(doc_text)
    missing = []
    for name in families:
        short = name[len(_PREFIX):] if name.startswith(_PREFIX) else name
        if not _documented(expanded, short) \
                and not _documented(expanded, name):
            missing.append(name)
    return missing


def declared_dispatch_kinds():
    """Dispatch kinds announced via obs.declare_kind at import time —
    the compile-cache vocabulary the docs must carry."""
    from nornicdb_tpu.obs.dispatch import bucket_counts

    return sorted(bucket_counts().keys())


def tier_vocabulary():
    """(canonical tier names, normalized degrade reasons) from the
    serving-truth taxonomy (obs/audit.py)."""
    from nornicdb_tpu.obs import audit

    return sorted(audit.ALL_TIERS), sorted(audit.REASONS)


def event_kinds():
    """Incident-timeline event kinds (obs/events.py, ISSUE 13) — the
    /admin/events vocabulary the catalog must carry."""
    from nornicdb_tpu.obs import events

    return sorted(events.KINDS)


def missing_terms(doc_text: str, names) -> list:
    """Vocabulary values (dispatch kinds, tier labels, degrade
    reasons) with no word-boundary mention in the catalog."""
    expanded = _expand_braces(doc_text)
    return [n for n in names if not _documented(expanded, n)]


def tenant_family_drift():
    """(undeclared, stale) — ISSUE 18. A ``tenant`` label is a
    cardinality hazard: every family carrying one must ride the
    capped obs/tenant.py label registry and be declared in
    ``lint.config.TENANT_FAMILIES``. Undeclared = registered family
    with a tenant label but no declaration (the hazard); stale =
    declared name no longer registered (dead declaration)."""
    from nornicdb_tpu.lint.config import TENANT_FAMILIES
    from nornicdb_tpu.obs import REGISTRY

    for mod in IMPORT_TIME_MODULES:
        importlib.import_module(mod)
    carrying = sorted(f.name for f in REGISTRY.families()
                      if "tenant" in f.label_names)
    declared = set(TENANT_FAMILIES)
    undeclared = [n for n in carrying if n not in declared]
    stale = sorted(declared - set(carrying))
    return undeclared, stale


def build_verdict(doc_path: str, repo: str) -> dict:
    """The drift verdict — one dict, shape shared by the standalone
    CLI and the framework pass."""
    families = registered_families()
    with open(doc_path, encoding="utf-8") as f:
        doc_text = f.read()
    missing = missing_from_catalog(doc_text, families)
    # ISSUE 10: the serving-truth vocabularies are part of the catalog
    # contract too — every declared dispatch kind, canonical tier
    # label and normalized degrade reason must be documented
    kinds = declared_dispatch_kinds()
    tiers, reasons = tier_vocabulary()
    events = event_kinds()
    missing_kinds = missing_terms(doc_text, kinds)
    missing_tiers = missing_terms(doc_text, tiers)
    missing_reasons = missing_terms(doc_text, reasons)
    # ISSUE 13: the incident-timeline kinds are catalog contract too —
    # an undocumented /admin/events kind fails the lint like an
    # undocumented tier or reason
    missing_events = missing_terms(doc_text, events)
    # ISSUE 18: tenant-labeled families must be declared in
    # lint.config.TENANT_FAMILIES (the cardinality-cap contract)
    undeclared_tenant, stale_tenant = tenant_family_drift()
    drift = bool(missing or missing_kinds or missing_tiers
                 or missing_reasons or missing_events
                 or undeclared_tenant or stale_tenant)
    return {
        "catalog_lint": True,
        "doc": os.path.relpath(doc_path, repo),
        "families": len(families),
        "dispatch_kinds": len(kinds),
        "tiers": len(tiers),
        "reasons": len(reasons),
        "event_kinds": len(events),
        "missing": missing,
        "missing_kinds": missing_kinds,
        "missing_tiers": missing_tiers,
        "missing_reasons": missing_reasons,
        "missing_events": missing_events,
        "undeclared_tenant": undeclared_tenant,
        "stale_tenant": stale_tenant,
        "verdict": "drift" if drift else "pass",
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Metric-catalog drift lint: every import-time "
                    "metric family must be documented.")
    ap.add_argument("--doc", default=None,
                    help="catalog path (default: docs/observability.md "
                         "next to this repo)")
    ap.add_argument("--list", action="store_true",
                    help="print the import-time families and exit")
    args = ap.parse_args(argv)

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.path.insert(0, repo)
    families = registered_families()
    if args.list:
        print(json.dumps(families, indent=1))
        return 0
    doc_path = args.doc or os.path.join(repo, "docs", "observability.md")
    verdict = build_verdict(doc_path, repo)
    print(json.dumps(verdict))
    return 1 if verdict["verdict"] == "drift" else 0


# ---------------------------------------------------------------------------
# framework pass adapter
# ---------------------------------------------------------------------------

def run(tree) -> List:
    """Fifth nornic-lint pass: the drift verdict above rendered as
    findings (one per missing family / vocabulary term)."""
    from nornicdb_tpu.lint import Finding

    doc_rel = "docs/observability.md"
    doc_path = os.path.join(tree.root, doc_rel)
    if not os.path.exists(doc_path):
        return [Finding(
            pass_name=PASS, rule="missing-catalog-doc", path=doc_rel,
            line=1, detail=doc_rel,
            message=f"{doc_rel} not found")]
    verdict = build_verdict(doc_path, tree.root)
    rules = (
        ("missing", "undocumented-metric-family",
         "metric family {0} has no catalog entry"),
        ("missing_kinds", "undocumented-dispatch-kind",
         "dispatch kind {0} has no catalog entry"),
        ("missing_tiers", "undocumented-tier",
         "serving tier {0} has no catalog entry"),
        ("missing_reasons", "undocumented-degrade-reason",
         "degrade reason {0} has no catalog entry"),
        ("missing_events", "undocumented-event-kind",
         "event kind {0} has no catalog entry"),
    )
    findings = []
    for key, rule, msg in rules:
        for name in verdict[key]:
            findings.append(Finding(
                pass_name=PASS, rule=rule, path=doc_rel, line=1,
                detail=name,
                message=msg.format(name)
                + " in docs/observability.md"))
    # tenant-label declarations anchor to the registry file, not the
    # docs — the fix is an edit to lint/config.py
    cfg_rel = "nornicdb_tpu/lint/config.py"
    for name in verdict["undeclared_tenant"]:
        findings.append(Finding(
            pass_name=PASS, rule="undeclared-tenant-family",
            path=cfg_rel, line=1, detail=name,
            message=f"metric family {name} carries a tenant label but "
                    "is not declared in TENANT_FAMILIES "
                    "(cardinality-cap contract, ISSUE 18)"))
    for name in verdict["stale_tenant"]:
        findings.append(Finding(
            pass_name=PASS, rule="stale-tenant-family",
            path=cfg_rel, line=1, detail=name,
            message=f"TENANT_FAMILIES declares {name} but no such "
                    "family is registered"))
    return findings
