"""Vector-space registry: named vector spaces keyed by scope.

Reference: pkg/vectorspace/registry.go:1-60 — spaces keyed
(db, entity type, vector name, dims, metric) with backend kinds
auto/brute-force/hnsw; chunk vectors get their own space
(ChunkVectorName). The TPU build adds ivf_hnsw / ivfpq backends
(ann_quality.py profiles).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

CHUNK_VECTOR_NAME = "chunks"
DEFAULT_VECTOR_NAME = "embedding"

_BACKENDS = ("auto", "brute", "hnsw", "ivf_hnsw", "ivfpq", "cagra")


@dataclass(frozen=True)
class SpaceKey:
    database: str = "neo4j"
    entity_type: str = "node"
    vector_name: str = DEFAULT_VECTOR_NAME
    dims: int = 0
    metric: str = "cosine"


@dataclass
class VectorSpace:
    key: SpaceKey
    backend: str = "auto"
    index: Any = None  # lazily-built index instance
    _build_lock: Any = field(default_factory=threading.Lock, repr=False)

    def ensure_index(self):
        """Build the backend index on first use (auto resolves through
        the ANN profile). Locked: a concurrent double-build would hand
        two callers different instances and silently lose vectors."""
        with self._build_lock:
            return self._ensure_index_locked()

    def _ensure_index_locked(self):
        if self.index is not None:
            return self.index
        from nornicdb_tpu.search.ann_quality import current_profile
        from nornicdb_tpu.search.vector_index import BruteForceIndex

        kind = self.backend
        if kind == "auto":
            kind = current_profile().index_kind
        if kind == "brute":
            self.index = BruteForceIndex(dims=self.key.dims or None)
        elif kind == "hnsw":
            from nornicdb_tpu.search.hnsw import HNSWIndex

            p = current_profile()
            self.index = HNSWIndex(m=p.hnsw_m,
                                   ef_construction=p.hnsw_ef_construction,
                                   ef_search=p.hnsw_ef_search)
        elif kind == "ivf_hnsw":
            from nornicdb_tpu.search.ivf_hnsw import IVFHNSWIndex

            p = current_profile()
            self.index = IVFHNSWIndex(nprobe=p.nprobe, m=p.hnsw_m,
                                      ef_construction=p.hnsw_ef_construction,
                                      ef_search=p.hnsw_ef_search)
        elif kind == "ivfpq":
            from nornicdb_tpu.search.ivfpq import IVFPQIndex

            import os

            p = current_profile()
            refine = (p.pq_refine and os.environ.get(
                "NORNICDB_VECTOR_PQ_REFINE", "1") != "0")
            self.index = IVFPQIndex(n_subspaces=p.pq_subspaces,
                                    nprobe=p.nprobe,
                                    keep_vectors=refine)
        elif kind == "cagra":
            from nornicdb_tpu.search.ann_quality import cagra_shards_from_env
            from nornicdb_tpu.search.cagra import CagraIndex

            p = current_profile()
            self.index = CagraIndex(
                dims=self.key.dims or None,
                degree=p.cagra_degree, itopk=p.cagra_itopk,
                search_width=p.cagra_width, min_n=p.cagra_min_n,
                n_shards=cagra_shards_from_env(p.cagra_shards))
        else:
            raise ValueError(f"unknown backend {kind!r}")
        return self.index


class VectorSpaceRegistry:
    """Thread-safe registry (reference: registry.go)."""

    def __init__(self):
        self._spaces: Dict[SpaceKey, VectorSpace] = {}
        self._lock = threading.Lock()

    def register(self, key: SpaceKey, backend: str = "auto") -> VectorSpace:
        if backend not in _BACKENDS:
            raise ValueError(f"unknown backend {backend!r}")
        with self._lock:
            sp = self._spaces.get(key)
            if sp is None:
                sp = VectorSpace(key=key, backend=backend)
                self._spaces[key] = sp
            return sp

    def get(self, key: SpaceKey) -> Optional[VectorSpace]:
        with self._lock:
            return self._spaces.get(key)

    def get_or_create(
        self,
        database: str = "neo4j",
        entity_type: str = "node",
        vector_name: str = DEFAULT_VECTOR_NAME,
        dims: int = 0,
        metric: str = "cosine",
        backend: str = "auto",
    ) -> VectorSpace:
        return self.register(
            SpaceKey(database, entity_type, vector_name, dims, metric),
            backend)

    def list(self, database: Optional[str] = None) -> List[SpaceKey]:
        with self._lock:
            return [k for k in self._spaces
                    if database is None or k.database == database]

    def drop(self, key: SpaceKey) -> bool:
        with self._lock:
            return self._spaces.pop(key, None) is not None

    def drop_database(self, database: str) -> int:
        """Drop every space of a database (multi-DB drop path)."""
        with self._lock:
            doomed = [k for k in self._spaces if k.database == database]
            for k in doomed:
                del self._spaces[k]
            return len(doomed)
