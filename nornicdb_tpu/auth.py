"""Authentication & authorization: users, roles, privileges, JWTs,
per-database access control.

Reference: pkg/auth (auth.go JWT auth; roles.go users/roles/privileges/
entitlements; database_access.go per-database access control; auth
cache). JWTs are HS256, implemented over stdlib hmac/hashlib — no
external jwt dependency.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set


class AuthError(Exception):
    pass


class PermissionDenied(AuthError):
    pass


# -- password hashing (PBKDF2, matching the reference's KDF choice) ---------

PBKDF2_ITERS = 600_000  # reference: pkg/encryption PBKDF2 600k iters


def hash_password(password: str, salt: Optional[bytes] = None,
                  iterations: int = PBKDF2_ITERS) -> str:
    salt = salt or secrets.token_bytes(16)
    dk = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, iterations)
    return f"pbkdf2${iterations}${salt.hex()}${dk.hex()}"


def check_password(password: str, stored: str) -> bool:
    try:
        _, iters, salt_hex, dk_hex = stored.split("$")
        dk = hashlib.pbkdf2_hmac("sha256", password.encode(),
                                 bytes.fromhex(salt_hex), int(iters))
        return hmac.compare_digest(dk.hex(), dk_hex)
    except (ValueError, TypeError):
        return False


# -- JWT (HS256) ------------------------------------------------------------


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64url(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def jwt_encode(claims: Dict[str, Any], secret: str) -> str:
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims, separators=(",", ":")).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = hmac.new(secret.encode(), signing_input, hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64url(sig)}"


def jwt_decode(token: str, secret: str, verify_exp: bool = True) -> Dict[str, Any]:
    try:
        header, payload, sig = token.split(".")
    except ValueError:
        raise AuthError("malformed token")
    signing_input = f"{header}.{payload}".encode()
    want = hmac.new(secret.encode(), signing_input, hashlib.sha256).digest()
    if not hmac.compare_digest(want, _unb64url(sig)):
        raise AuthError("bad signature")
    claims = json.loads(_unb64url(payload))
    if verify_exp and "exp" in claims and time.time() > claims["exp"]:
        raise AuthError("token expired")
    return claims


# -- roles & privileges ------------------------------------------------------

# privilege verbs (reference: roles.go privileges/entitlements)
READ = "read"
WRITE = "write"
ADMIN = "admin"
SCHEMA = "schema"

BUILTIN_ROLES: Dict[str, Set[str]] = {
    "admin": {READ, WRITE, ADMIN, SCHEMA},
    "architect": {READ, WRITE, SCHEMA},
    "editor": {READ, WRITE},
    "publisher": {READ, WRITE},
    "reader": {READ},
}


@dataclass
class User:
    username: str
    password_hash: str
    roles: List[str] = field(default_factory=lambda: ["reader"])
    # per-database grants: db -> set of privileges; "*" db = all
    database_access: Dict[str, Set[str]] = field(default_factory=dict)
    suspended: bool = False

    def privileges(self, custom_roles: Dict[str, Set[str]]) -> Set[str]:
        out: Set[str] = set()
        for r in self.roles:
            out |= custom_roles.get(r, BUILTIN_ROLES.get(r, set()))
        return out


class Authenticator:
    """User store + token issuing + per-database RBAC checks."""

    def __init__(self, jwt_secret: Optional[str] = None,
                 token_ttl_seconds: int = 3600,
                 allow_anonymous_reads: bool = False):
        self.jwt_secret = jwt_secret or secrets.token_hex(32)
        self.token_ttl = token_ttl_seconds
        self.allow_anonymous_reads = allow_anonymous_reads
        self._users: Dict[str, User] = {}
        self._roles: Dict[str, Set[str]] = {}
        self._lock = threading.Lock()
        # auth cache: token -> (claims, expiry) (reference: auth cache)
        self._cache: Dict[str, Dict[str, Any]] = {}

    # -- user management -------------------------------------------------

    def create_user(self, username: str, password: str,
                    roles: Optional[List[str]] = None) -> User:
        with self._lock:
            if username in self._users:
                raise AuthError(f"user exists: {username}")
            u = User(username=username, password_hash=hash_password(password),
                     roles=list(roles or ["reader"]))
            self._users[username] = u
            return u

    def delete_user(self, username: str) -> bool:
        with self._lock:
            return self._users.pop(username, None) is not None

    def set_password(self, username: str, password: str) -> None:
        u = self._get_user(username)
        u.password_hash = hash_password(password)

    def suspend_user(self, username: str, suspended: bool = True) -> None:
        self._get_user(username).suspended = suspended

    def _get_user(self, username: str) -> User:
        with self._lock:
            u = self._users.get(username)
        if u is None:
            raise AuthError(f"user not found: {username}")
        return u

    def list_users(self) -> List[str]:
        with self._lock:
            return sorted(self._users)

    # -- roles -----------------------------------------------------------

    def create_role(self, name: str, privileges: Set[str]) -> None:
        with self._lock:
            self._roles[name] = set(privileges)

    def grant_role(self, username: str, role: str) -> None:
        u = self._get_user(username)
        if role not in u.roles:
            u.roles.append(role)

    def revoke_role(self, username: str, role: str) -> None:
        u = self._get_user(username)
        if role in u.roles:
            u.roles.remove(role)

    def grant_database_access(self, username: str, database: str,
                              privileges: Set[str]) -> None:
        u = self._get_user(username)
        u.database_access.setdefault(database, set()).update(privileges)

    def revoke_database_access(self, username: str, database: str) -> None:
        u = self._get_user(username)
        u.database_access.pop(database, None)

    # -- authentication --------------------------------------------------

    def login(self, username: str, password: str) -> str:
        """Verify credentials, return a JWT."""
        u = self._get_user(username)
        if u.suspended:
            raise AuthError("user suspended")
        if not check_password(password, u.password_hash):
            raise AuthError("invalid credentials")
        now = int(time.time())
        claims = {"sub": username, "roles": u.roles, "iat": now,
                  "exp": now + self.token_ttl, "jti": secrets.token_hex(8)}
        return jwt_encode(claims, self.jwt_secret)

    def verify_token(self, token: str) -> Dict[str, Any]:
        cached = self._cache.get(token)
        if cached is not None and time.time() < cached.get("exp", 0):
            claims = cached  # signature/exp already checked
        else:
            claims = jwt_decode(token, self.jwt_secret)
            with self._lock:
                if len(self._cache) > 10_000:
                    self._cache.clear()
                self._cache[token] = claims
        # user status is always re-checked — a cached token must not
        # outlive suspension or deletion
        u = self._get_user(claims.get("sub", ""))
        if u.suspended:
            raise AuthError("user suspended")
        return claims

    # -- authorization ---------------------------------------------------

    def check(self, username: Optional[str], database: str, privilege: str) -> None:
        """Raise PermissionDenied unless the user may do ``privilege`` on
        ``database`` (reference: database_access.go AllowDatabaseAccess)."""
        if username is None:
            if self.allow_anonymous_reads and privilege == READ:
                return
            raise PermissionDenied("authentication required")
        u = self._get_user(username)
        if u.suspended:
            raise PermissionDenied("user suspended")
        with self._lock:
            roles = dict(self._roles)
        privs = u.privileges(roles)
        if ADMIN in privs:
            return
        if u.database_access:
            # per-db grants are authoritative: a listed database allows
            # exactly its granted privileges (a READ-only grant really is
            # read-only even for a WRITE-capable role), and unlisted
            # databases are fenced off entirely
            if database in u.database_access:
                if privilege in u.database_access[database]:
                    return
                raise PermissionDenied(
                    f"privilege {privilege!r} not granted on {database!r}")
            if "*" in u.database_access:
                if privilege in u.database_access["*"]:
                    return
                raise PermissionDenied(
                    f"privilege {privilege!r} not granted on {database!r}")
            raise PermissionDenied(f"no access to database {database!r}")
        if privilege in privs:
            return
        raise PermissionDenied(f"privilege {privilege!r} required")

    def allowed(self, username: Optional[str], database: str, privilege: str) -> bool:
        try:
            self.check(username, database, privilege)
            return True
        except (PermissionDenied, AuthError):
            # unknown/deleted user is a denial, not a crash
            return False


def bootstrap_admin(auth: Authenticator, username: str = "neo4j",
                    password: str = "") -> str:
    """Create the initial admin user (reference: default neo4j admin).
    Returns the password (generated when empty)."""
    password = password or secrets.token_urlsafe(12)
    auth.create_user(username, password, roles=["admin"])
    return password
